"""Fused blockwise (flash-style) attention as a Pallas TPU kernel.

Not in the reference (SURVEY.md §2.2: CNN-only, no attention anywhere) but
first-class here: this is the hot op of the ViT workload (BASELINE.md
config 5) and the per-device block compute of ring attention
(``adapt_tpu.parallel.ring_attention``). A fused kernel keeps the S x S
score matrix out of HBM entirely — scores live in VMEM one (block_q,
block_k) tile at a time with online-softmax accumulation, so memory is
O(S * D) instead of O(S^2) and the matmuls stay on the MXU.

Grid: (batch*heads, S/block_q). Each program holds one q block plus that
(batch, head)'s full K/V in VMEM and loops over k blocks with running
(max, denom, acc) — the standard online softmax recurrence.

Off-TPU the kernel runs through the Pallas interpreter, so tests on the
virtual CPU mesh exercise the same code path; ``attention_reference`` is
the jnp oracle.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = -1e30


def _attn_kernel(
    q_ref, k_ref, v_ref, o_ref, *, block_k, causal, sm_scale, valid_k
):
    q = q_ref[0].astype(jnp.float32)  # (block_q, d)
    block_q, d = q.shape
    seq_k = k_ref.shape[1]
    num_kv = seq_k // block_k
    q_start = pl.program_id(1) * block_q

    m0 = jnp.full((block_q, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)

    def body(j, carry):
        m, l, acc = carry
        k = k_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * block_k, block_k), :].astype(jnp.float32)
        s = (
            jax.lax.dot_general(
                q,
                k,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * sm_scale
        )  # (block_q, block_k)
        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        if valid_k != seq_k:
            # Ragged tail: keys beyond the true sequence are zero padding
            # (ViT's 197 = 14^2 + CLS is the canonical offender) — mask
            # them out of the softmax like causal masks the future.
            s = jnp.where(cols < valid_k, s, _NEG_INF)
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            s = jnp.where(rows >= cols, s, _NEG_INF)
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        l_new = l * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    # Causal: k blocks strictly after this q block contribute nothing.
    if causal:
        upper = jnp.minimum(
            (q_start + block_q + block_k - 1) // block_k, num_kv
        )
    else:
        upper = num_kv
    m, l, acc = jax.lax.fori_loop(0, upper, body, (m0, l0, acc0))
    o_ref[0] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
) -> jax.Array:
    """Fused attention over (batch, heads, seq, head_dim) tensors.

    Differentiable: the forward pass is the Pallas kernel; the backward
    pass recomputes scores with the jnp oracle (pallas_call defines no
    VJP of its own, and recompute-in-backward is the flash-attention
    memory story anyway — nothing S x S is saved between the passes).

    Non-block-divisible sequence lengths (ViT's 197) run the kernel via
    internal zero-padding with key masking; the only oracle fallback left
    is causal ragged-key cross-attention (s_q != s_k), where
    absolute-position masking over padded interiors is ill-defined.
    """
    return _flash_vjp(q, k, v, causal, block_q, block_k)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _flash_vjp(q, k, v, causal, block_q, block_k):
    return _flash_impl(q, k, v, causal, block_q, block_k)


def _flash_fwd(q, k, v, causal, block_q, block_k):
    return _flash_impl(q, k, v, causal, block_q, block_k), (q, k, v)


def _flash_bwd(causal, block_q, block_k, residuals, do):
    q, k, v = residuals
    _, vjp = jax.vjp(
        lambda q_, k_, v_: attention_reference(q_, k_, v_, causal=causal),
        q,
        k,
        v,
    )
    return vjp(do)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k")
)
def _flash_impl(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
) -> jax.Array:
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    block_q = min(block_q, max(s_q, 8))
    block_k = min(block_k, max(s_k, 8))
    # Ragged sequences (ViT's 197) are zero-padded up to whole blocks;
    # padded KEY positions are masked inside the kernel (valid_k), padded
    # QUERY rows compute garbage that is sliced off below. Only degenerate
    # cross-attention raggedness under causal falls back to the oracle
    # (absolute-position masking with padded interior is ill-defined).
    pad_q = (-s_q) % block_q
    pad_k = (-s_k) % block_k
    if causal and pad_k and s_q != s_k:
        return attention_reference(q, k, v, causal=causal)
    if pad_q or pad_k:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0)))

    sm_scale = 1.0 / math.sqrt(d)
    sp_q, sp_k = s_q + pad_q, s_k + pad_k
    qf = q.reshape(b * h, sp_q, d)
    kf = k.reshape(b * h, sp_k, d)
    vf = v.reshape(b * h, sp_k, d)
    kernel = functools.partial(
        _attn_kernel,
        block_k=block_k,
        causal=causal,
        sm_scale=sm_scale,
        valid_k=s_k,
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, sp_q // block_q),
        in_specs=[
            pl.BlockSpec(
                (1, block_q, d), lambda bh, qi: (bh, qi, 0), memory_space=_VMEM
            ),
            pl.BlockSpec(
                (1, sp_k, d), lambda bh, qi: (bh, 0, 0), memory_space=_VMEM
            ),
            pl.BlockSpec(
                (1, sp_k, d), lambda bh, qi: (bh, 0, 0), memory_space=_VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, block_q, d), lambda bh, qi: (bh, qi, 0), memory_space=_VMEM
        ),
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        interpret=jax.default_backend() != "tpu",
    )(qf, kf, vf)
    return out.reshape(b, h, sp_q, d)[:, :, :s_q, :]


_flash_vjp.defvjp(_flash_fwd, _flash_bwd)


def attention_reference(
    q: jax.Array, k: jax.Array, v: jax.Array, causal: bool = False
) -> jax.Array:
    """Pure-jnp oracle: softmax(QK^T / sqrt(d)) V with optional causal mask.

    Causal convention (same as the kernel): query at absolute position i
    attends keys at absolute positions j <= i — top-left aligned, which is
    the identity convention for the self-attention (s_q == s_k) shapes the
    framework uses.
    """
    d = q.shape[-1]
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / math.sqrt(d)
    if causal:
        s_q, s_k = s.shape[-2:]
        mask = jnp.tril(jnp.ones((s_q, s_k), bool))
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(
        q.dtype
    )
