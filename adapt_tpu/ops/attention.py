"""Fused blockwise (flash-style) attention as a Pallas TPU kernel.

Not in the reference (SURVEY.md §2.2: CNN-only, no attention anywhere) but
first-class here: the attention entry point for the ViT workload
(BASELINE.md config 5), the decoder LM, and ring attention's opt-in
long-shard block compute. Dispatch between this kernel and XLA's fused
attention is *measured* (see ``FLASH_SCORE_BYTES_BUDGET`` below): XLA
wins while scores fit, the kernel exists for the long-context regime —
scores live in VMEM one (block_q, block_k) tile at a time with
online-softmax accumulation, so memory is O(S * D) instead of O(S^2) and
the matmuls stay on the MXU.

Grid: (batch*heads, S/block_q, S/block_k), k innermost. Each program
holds ONE q tile and ONE K/V tile in VMEM; K/V stream from HBM block by
block while the running (max, denom, acc) online-softmax state persists
in VMEM scratch — O(block) VMEM at any sequence length. The backward is
the same discipline in reverse: two streaming passes (dQ, then dK/dV)
recompute score blocks against the saved row logsumexp.

Off-TPU the kernel runs through the Pallas interpreter, so tests on the
virtual CPU mesh exercise the same code path; ``attention_reference`` is
the jnp oracle.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

try:
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover
    pltpu = None
    _VMEM = None

DEFAULT_BLOCK_Q = 128
DEFAULT_BLOCK_K = 128
_NEG_INF = -1e30

#: Measured dispatch budget (real v5e chip, artifacts
#: benchmarks/results/r03/{attn_crossover,attn_longseq}.json and the
#: end-to-end ViT A/B in tpu_vit_b16_ab.json): XLA's fused attention
#: beats the Pallas kernel while the materialized f32 score tensor
#: (batch*heads*s_q*s_k*4 bytes) is small — end-to-end ViT-B/16 ran 1.9x
#: faster through XLA (3,360 vs 1,781 img/s) — but score memory grows
#: O(S^2): at 2 GiB+ it crowds out everything else in 16 GiB HBM (and at
#: s=32k, 51.5 GiB, XLA simply OOMs) while the streaming kernel stays
#: O(S*D). Past the budget the throughput data is NON-monotonic, not a
#: clean crossover: attn_longseq.json has flash 5% faster at
#: (1, 12, 8192) = 3 GiB scores but XLA 24% faster again at 16384 =
#: 12 GiB. The dispatch keys on capacity, not that noisy margin: a
#: 12 GiB transient score tensor in 16 GiB HBM leaves nothing for
#: weights/caches/activations in a real serving process (the standalone
#: sweep that survives it has the chip to itself), so past ~2 GiB the
#: O(S*D) kernel wins on headroom even where XLA wins the sweep.
#: ``prefer=`` overrides when sweep throughput is all that matters.
FLASH_SCORE_BYTES_BUDGET = 2 << 30

#: Absolute guard on top of the byte budget: at or past this key length
#: the kernel is used regardless of batch (a tiny-batch long sequence can
#: sneak under the byte budget while still being the regime XLA handles
#: worst).
FLASH_MIN_SEQ = 32768


def scores_over_budget(q_shape, k_shape) -> bool:
    """THE dispatch predicate, shared by forward dispatch, the backward
    branch choice, and ring attention's block_impl="auto" — one place to
    retune so the three can't drift apart. True -> the materialized f32
    score tensor is past the measured budget (or the absolute length
    guard) and the streaming kernel is the right path."""
    b, h, s_q, _ = q_shape
    s_k = k_shape[2]
    return (
        b * h * s_q * s_k * 4 > FLASH_SCORE_BYTES_BUDGET
        or s_k >= FLASH_MIN_SEQ
    )


def _oracle_shape(q_shape, k_shape, causal, block_k) -> bool:
    """The one shape class the kernel itself refuses (mirrors
    ``_flash_impl``'s fallback): causal ragged-key cross-attention,
    where absolute-position masking over padded interiors is
    ill-defined."""
    s_q, s_k = q_shape[2], k_shape[2]
    bk = min(block_k, max(s_k, 8))
    return bool(causal and ((-s_k) % bk) and s_q != s_k)


def _attn_kernel(
    q_ref,
    k_ref,
    v_ref,
    *refs,
    block_k,
    num_kv,
    causal,
    sm_scale,
    valid_k,
    has_vf=False,
    has_shift=False,
    window=None,
):
    """Grid = (batch*heads, q_blocks, k_blocks); the k dimension is the
    innermost (sequential) axis, so only ONE (block_q, d) q tile and ONE
    (block_k, d) K/V tile are VMEM-resident at a time — K/V stream from
    HBM block by block and the online-softmax state (running max, denom,
    accumulator) persists across k steps in VMEM scratch. Per-program
    VMEM is O(block_q * (d + block_k)) regardless of sequence length,
    which is what lets the kernel run 32k+ sequences that OOM both the
    naive full-K/V-in-VMEM layout (scoped-vmem) and XLA's materialized
    S x S scores (HBM) — measured in
    benchmarks/results/r03/attn_longseq.json.

    ``has_vf``: an extra per-(batch, head) scalar input ``vf`` (SMEM)
    masks keys at positions < vf — ragged LEFT padding (the LM's masked
    prefill), so ragged batches stay on the streaming path at long S
    instead of falling back to the materialized oracle. Key blocks
    entirely inside the padding skip their compute.

    ``has_shift``: a traced SMEM scalar offsets the causal diagonal —
    row i attends cols <= i - shift. Striped ring attention's per-step
    blocks (``parallel/ring_attention.py`` layout="striped") are exactly
    shift-0/shift-1 triangles, so every ring step runs this kernel's
    causal skip path instead of an SPMD ``lax.cond`` that computes dead
    blocks anyway."""
    refs = list(refs)
    vf_ref = refs.pop(0) if has_vf else None
    shift_ref = refs.pop(0) if has_shift else None
    o_ref, lse_ref, m_scr, l_scr, acc_scr = refs
    j = pl.program_id(2)
    block_q = q_ref.shape[1]
    q_start = pl.program_id(1) * block_q

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, _NEG_INF, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    def _step():
        q = q_ref[0].astype(jnp.float32)  # (block_q, d)
        k = k_ref[0].astype(jnp.float32)  # (block_k, d)
        v = v_ref[0].astype(jnp.float32)
        s = (
            jax.lax.dot_general(
                q,
                k,
                (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * sm_scale
        )  # (block_q, block_k)
        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        if valid_k != num_kv * block_k:
            # Ragged tail: keys beyond the true sequence are zero padding
            # (ViT's 197 = 14^2 + CLS is the canonical offender) — mask
            # them out of the softmax like causal masks the future.
            s = jnp.where(cols < valid_k, s, _NEG_INF)
        if has_vf:
            # Ragged head: keys before this row's first real token are
            # left padding.
            s = jnp.where(cols >= vf_ref[0], s, _NEG_INF)
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            shift = shift_ref[0] if has_shift else 0
            s = jnp.where(rows >= cols + shift, s, _NEG_INF)
            if window is not None:
                # Sliding band: row i attends cols in (i - window, i].
                s = jnp.where(cols > rows - window, s, _NEG_INF)
        m = m_scr[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )

    # K blocks strictly after this q block (causal), entirely inside the
    # left padding (vf), or entirely behind every row's sliding window
    # contribute nothing — skip their compute entirely (the DMA still
    # lands, the MXU stays idle).
    live = None
    if causal:
        live = (
            j * block_k + (shift_ref[0] if has_shift else 0)
            <= q_start + block_q - 1
        )
        if window is not None:
            # Lowest row's band floor: cols <= q_start - window are dead
            # for every row in the tile.
            live = jnp.logical_and(
                live, (j + 1) * block_k - 1 > q_start - window
            )
    if has_vf:
        past_pad = (j + 1) * block_k > vf_ref[0]
        live = past_pad if live is None else jnp.logical_and(live, past_pad)
    if live is not None:
        pl.when(live)(_step)
    else:
        _step()

    @pl.when(j == num_kv - 1)
    def _emit():
        o_ref[0] = (
            acc_scr[...] / jnp.maximum(l_scr[...], 1e-30)
        ).astype(o_ref.dtype)
        # Per-row logsumexp — the O(S) softmax residual the streaming
        # backward recomputes scores against (saving it is what lets the
        # backward stay O(S*D) instead of keeping S x S probabilities).
        # Stored 8-row-broadcast: TPU lowering needs the last two block
        # dims divisible by (8, 128), so the row vector rides in a
        # (1, 8, block_q) tile (row 0 is read back; x8 on an O(S) tensor
        # is noise next to the O(S*D) tensors).
        lse = (
            m_scr[...] + jnp.log(jnp.maximum(l_scr[...], 1e-30))
        ).reshape(1, 1, -1)
        lse_ref[...] = jnp.broadcast_to(lse, lse_ref.shape)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    prefer: str | None = None,
    valid_from: jax.Array | None = None,
    window: int | None = None,
) -> jax.Array:
    """Fused attention over (batch, heads, seq, head_dim) tensors.

    Dispatch is perf-measured, not dogmatic: while the materialized
    f32 score tensor stays under ``FLASH_SCORE_BYTES_BUDGET`` the XLA
    path wins on the real chip (end-to-end ViT-B/16: 1.9x — artifacts
    ``benchmarks/results/r03/attn_crossover.json`` / ``attn_longseq``);
    past it the streaming Pallas kernel takes over — O(S*D) HBM and
    O(block) VMEM, serving 32k+ sequences where XLA's scores exceed HBM
    outright. ``prefer="pallas"`` or ``"xla"`` forces a path (tests, the
    SP block compute, and the sweeps themselves use this).

    Differentiable at every length: sub-budget shapes recompute the
    backward through the jnp oracle (one materialized pass — fastest
    where scores fit), super-budget shapes run the streaming Pallas
    backward (two passes, dQ then dK/dV, recomputing score blocks
    against the saved row logsumexp) — O(S*D) HBM either direction, so
    long-context gradients survive where a materialized recompute OOMs.

    Non-block-divisible sequence lengths (ViT's 197) run the kernel via
    internal zero-padding with key masking; the only oracle fallback left
    is causal ragged-key cross-attention (s_q != s_k), where
    absolute-position masking over padded interiors is ill-defined.

    ``valid_from`` (b,) masks each row's keys at positions < its value —
    ragged LEFT padding (the LM's masked prefill). The kernel carries the
    mask as a per-(batch, head) SMEM scalar, so ragged batches ride the
    same measured dispatch as dense ones (kernel at long S where the
    materialized oracle would OOM). Fully-padded query rows (position
    < vf) have UNSPECIFIED contents — zeros when every k-block was
    skipped, a uniform V average when the row shares a k-block with live
    keys (which is also what the oracle emits) — no caller may read
    them; valid rows match the oracle exactly.

    ``window`` (requires ``causal``, no ``causal_shift``) bands the
    mask Mistral-style — row i attends (i - window, i] — in BOTH
    directions: the streaming forward and backward mask and
    compute-skip blocks outside the band, so a long windowed prefill
    streams O(S*D) instead of materializing O(S^2) scores.
    """
    if prefer not in (None, "pallas", "xla"):
        raise ValueError(
            f"prefer={prefer!r}: expected None, 'pallas' or 'xla'"
        )
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    if prefer is None:
        prefer = "pallas" if scores_over_budget(q.shape, k.shape) else "xla"
    if prefer == "xla":
        return attention_reference(
            q, k, v, causal=causal, valid_from=valid_from, window=window
        )
    if valid_from is None:
        return _flash_vjp(q, k, v, causal, block_q, block_k, window)
    return _flash_ragged_vjp(
        q, k, v, jnp.asarray(valid_from, jnp.int32), causal, block_q,
        block_k, window,
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_vjp(q, k, v, causal, block_q, block_k, window=None):
    return _flash_impl(q, k, v, causal, block_q, block_k, window=window)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _flash_ragged_vjp(q, k, v, valid_from, causal, block_q, block_k,
                      window=None):
    """valid_from travels as a regular (traced) operand — custom_vjp
    nondiff_argnums may not hold tracers, and the bwd returns None for
    its (integer, gradient-free) cotangent."""
    return _flash_impl(
        q, k, v, causal, block_q, block_k, valid_from=valid_from,
        window=window,
    )


def _flash_ragged_fwd(q, k, v, valid_from, causal, block_q, block_k,
                      window=None):
    if _bwd_streams(q.shape, k.shape, causal, block_q, block_k):
        out, lse = _flash_impl(
            q, k, v, causal, block_q, block_k,
            with_lse=True, valid_from=valid_from, window=window,
        )
        return out, (q, k, v, valid_from, out, lse)
    out = _flash_impl(
        q, k, v, causal, block_q, block_k, valid_from=valid_from,
        window=window,
    )
    return out, (q, k, v, valid_from, None, None)


def _flash_ragged_bwd(causal, block_q, block_k, window, residuals, do):
    q, k, v, valid_from, out, lse = residuals
    if out is None:  # materialized-recompute branch (scores fit)
        _, vjp = jax.vjp(
            lambda q_, k_, v_: attention_reference(
                q_, k_, v_, causal=causal, valid_from=valid_from,
                window=window,
            ),
            q,
            k,
            v,
        )
        return (*vjp(do), None)
    dq, dk, dv = _flash_bwd_impl(
        q, k, v, out, lse, do,
        causal=causal, block_q=block_q, block_k=block_k,
        valid_from=valid_from, window=window,
    )
    return dq, dk, dv, None


_flash_ragged_vjp.defvjp(_flash_ragged_fwd, _flash_ragged_bwd)


def flash_attention_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    causal_shift: jax.Array | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Streaming-kernel attention returning ``(out, lse)`` where ``lse``
    is the per-row logsumexp of the scaled scores, shape (b, h, s_q),
    f32. The lse is what lets partial attention results merge exactly:
    given per-key-block ``(o_j, lse_j)``, the blockwise combine

        m = max(lse_a, lse_b)
        o = (o_a * exp(lse_a - m) + o_b * exp(lse_b - m))
            / (exp(lse_a - m) + exp(lse_b - m))
        lse = m + log(exp(lse_a - m) + exp(lse_b - m))

    reproduces full-softmax attention — the contract ring attention's
    flash block path builds on (``parallel/ring_attention.py``).

    Forward-only: this entry point bypasses the custom-VJP wrapper (an
    lse output would need its own streaming VJP); differentiating
    through it fails at the pallas_call. Use :func:`flash_attention` for
    training paths.

    ``causal_shift`` (traced int scalar, requires ``causal=True``)
    offsets the diagonal: row i attends cols <= i - shift. Rows with no
    live key (i < shift) emit ``lse ~= -inf`` with UNSPECIFIED out
    contents — the merge weight ``exp(lse - m)`` zeroes them, which is
    the neutral element striped ring attention's shift-1 steps rely on.
    """
    return _flash_impl(
        q, k, v, causal, block_q, block_k, with_lse=True,
        causal_shift=causal_shift,
    )


def _bwd_streams(q_shape, k_shape, causal, block_q, block_k) -> bool:
    """Static decision (shapes only) shared by fwd and bwd: does the
    backward run the streaming Pallas passes? False -> one materialized
    jnp-oracle recompute, which is faster wherever scores fit and is the
    only option off pallas-tpu or on the causal ragged-cross-attention
    shape the forward itself oracles."""
    if pltpu is None:  # pragma: no cover — jax builds without pallas-tpu
        return False
    return scores_over_budget(q_shape, k_shape) and not _oracle_shape(
        q_shape, k_shape, causal, block_k
    )


def _flash_fwd(q, k, v, causal, block_q, block_k, window=None):
    # Save the O(S) logsumexp (and keep `out` alive) only when the
    # backward will actually stream; the oracle branch re-derives
    # everything from (q, k, v).
    if _bwd_streams(q.shape, k.shape, causal, block_q, block_k):
        out, lse = _flash_impl(
            q, k, v, causal, block_q, block_k, with_lse=True,
            window=window,
        )
        return out, (q, k, v, out, lse)
    out = _flash_impl(q, k, v, causal, block_q, block_k, window=window)
    return out, (q, k, v, None, None)


def _flash_bwd(causal, block_q, block_k, window, residuals, do):
    q, k, v, out, lse = residuals
    if out is None:  # fwd decided on the materialized-recompute branch
        _, vjp = jax.vjp(
            lambda q_, k_, v_: attention_reference(
                q_, k_, v_, causal=causal, window=window
            ),
            q,
            k,
            v,
        )
        return vjp(do)
    return _flash_bwd_impl(
        q, k, v, out, lse, do,
        causal=causal, block_q=block_q, block_k=block_k, window=window,
    )


@functools.partial(
    jax.jit,
    static_argnames=("causal", "block_q", "block_k", "with_lse", "window"),
)
def _flash_impl(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    block_q: int = DEFAULT_BLOCK_Q,
    block_k: int = DEFAULT_BLOCK_K,
    with_lse: bool = False,
    valid_from: jax.Array | None = None,
    causal_shift: jax.Array | None = None,
    window: int | None = None,
):
    if causal_shift is not None and not causal:
        raise ValueError("causal_shift requires causal=True")
    if window is not None and (not causal or causal_shift is not None):
        raise ValueError("window requires causal=True without causal_shift")
    if pltpu is None:  # pragma: no cover — jax builds without pallas-tpu
        return (
            _reference_with_lse(q, k, v, causal, valid_from, causal_shift,
                                window)
            if with_lse
            else attention_reference(
                q, k, v, causal=causal, valid_from=valid_from,
                causal_shift=causal_shift, window=window,
            )
        )
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    block_q = min(block_q, max(s_q, 8))
    block_k = min(block_k, max(s_k, 8))
    # Ragged sequences (ViT's 197) are zero-padded up to whole blocks;
    # padded KEY positions are masked inside the kernel (valid_k), padded
    # QUERY rows compute garbage that is sliced off below. Only degenerate
    # cross-attention raggedness under causal falls back to the oracle
    # (absolute-position masking with padded interior is ill-defined).
    pad_q = (-s_q) % block_q
    pad_k = (-s_k) % block_k
    if causal and pad_k and s_q != s_k:
        return (
            _reference_with_lse(q, k, v, causal, valid_from, causal_shift,
                                window)
            if with_lse
            else attention_reference(
                q, k, v, causal=causal, valid_from=valid_from,
                causal_shift=causal_shift, window=window,
            )
        )
    if pad_q or pad_k:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0)))

    sm_scale = 1.0 / math.sqrt(d)
    sp_q, sp_k = s_q + pad_q, s_k + pad_k
    num_kv = sp_k // block_k
    qf = q.reshape(b * h, sp_q, d)
    kf = k.reshape(b * h, sp_k, d)
    vf = v.reshape(b * h, sp_k, d)
    kernel = functools.partial(
        _attn_kernel,
        block_k=block_k,
        num_kv=num_kv,
        causal=causal,
        sm_scale=sm_scale,
        valid_k=s_k,
        has_vf=valid_from is not None,
        has_shift=causal_shift is not None,
        window=window,
    )
    on_tpu = jax.default_backend() == "tpu"
    scratch = [
        pltpu.VMEM((block_q, 1), jnp.float32),
        pltpu.VMEM((block_q, 1), jnp.float32),
        pltpu.VMEM((block_q, d), jnp.float32),
    ]
    in_specs = [
        pl.BlockSpec(
            (1, block_q, d),
            lambda bh, qi, kj: (bh, qi, 0),
            memory_space=_VMEM,
        ),
        pl.BlockSpec(
            (1, block_k, d),
            lambda bh, qi, kj: (bh, kj, 0),
            memory_space=_VMEM,
        ),
        pl.BlockSpec(
            (1, block_k, d),
            lambda bh, qi, kj: (bh, kj, 0),
            memory_space=_VMEM,
        ),
    ]
    operands = [qf, kf, vf]
    if valid_from is not None:
        # Per-(batch, head) left-pad scalar rides in SMEM.
        operands.append(
            jnp.repeat(jnp.asarray(valid_from, jnp.int32), h)
        )
        in_specs.append(
            pl.BlockSpec(
                (1,), lambda bh, qi, kj: (bh,), memory_space=pltpu.SMEM
            )
        )
    if causal_shift is not None:
        # One global diagonal-offset scalar in SMEM (traced: striped
        # ring varies it per step without recompiling).
        operands.append(
            jnp.reshape(jnp.asarray(causal_shift, jnp.int32), (1,))
        )
        in_specs.append(
            pl.BlockSpec(
                (1,), lambda bh, qi, kj: (0,), memory_space=pltpu.SMEM
            )
        )
    out, lse = pl.pallas_call(
        kernel,
        # K/V stream one block per innermost grid step; scratch carries
        # the online-softmax state across them (TPU grids iterate
        # sequentially, innermost-fastest, so the state is coherent).
        grid=(b * h, sp_q // block_q, num_kv),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec(
                (1, block_q, d),
                lambda bh, qi, kj: (bh, qi, 0),
                memory_space=_VMEM,
            ),
            pl.BlockSpec(
                (1, 8, block_q),
                lambda bh, qi, kj: (bh, 0, qi),
                memory_space=_VMEM,
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(qf.shape, q.dtype),
            jax.ShapeDtypeStruct((b * h, 8, sp_q), jnp.float32),
        ],
        scratch_shapes=scratch,
        compiler_params=(
            pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")
            )
            if on_tpu and pltpu is not None
            else None
        ),
        interpret=not on_tpu,
    )(*operands)
    out = out.reshape(b, h, sp_q, d)[:, :, :s_q, :]
    if not with_lse:
        return out
    return out, lse[:, 0, :].reshape(b, h, sp_q)[:, :, :s_q]


def _causal_mask(s_q, s_k, causal_shift=None):
    """THE oracle causal mask (row i attends cols <= i - shift) — shared
    by both reference paths so the masking convention cannot fork."""
    if causal_shift is not None:
        return (
            jnp.arange(s_q)[:, None] >= jnp.arange(s_k)[None, :] + causal_shift
        )
    return jnp.tril(jnp.ones((s_q, s_k), bool))


def _reference_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool,
    valid_from: jax.Array | None = None,
    causal_shift: jax.Array | None = None,
    window: int | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Oracle-path ``(out, lse)`` computing the score matrix ONCE (the
    fallback exists because scores are expensive to materialize —
    don't pay for them twice)."""
    d = q.shape[-1]
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / math.sqrt(d)
    if causal:
        s = jnp.where(_causal_mask(*s.shape[-2:], causal_shift), s, _NEG_INF)
    if window is not None:
        s_q, s_k = s.shape[-2:]
        band = (
            jnp.arange(s_k)[None, :] > jnp.arange(s_q)[:, None] - window
        )
        s = jnp.where(band[None, None], s, _NEG_INF)
    if valid_from is not None:
        cols = jnp.arange(s.shape[-1])
        live = cols[None, :] >= valid_from[:, None]
        s = jnp.where(live[:, None, None, :], s, _NEG_INF)
    lse = jax.scipy.special.logsumexp(s, axis=-1)
    p = jnp.exp(s - lse[..., None])
    out = jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(
        q.dtype
    )
    return out, lse


def _bwd_dq_kernel(
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    lse_ref,
    delta_ref,
    *refs,
    block_k,
    num_kv,
    causal,
    sm_scale,
    valid_k,
    has_vf=False,
    window=None,
):
    """dQ pass: grid (bh, q_blocks, k_blocks), K/V streaming innermost;
    dq accumulates in VMEM scratch. Scores recompute blockwise against
    the saved row logsumexp, so nothing S x S ever exists."""
    if has_vf:
        vf_ref, dq_ref, dq_scr = refs
    else:
        dq_ref, dq_scr = refs
    j = pl.program_id(2)
    block_q = q_ref.shape[1]
    q_start = pl.program_id(1) * block_q

    @pl.when(j == 0)
    def _init():
        dq_scr[...] = jnp.zeros(dq_scr.shape, jnp.float32)

    def _step():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0:1, :].T  # (block_q, 1); rows 1-7 are broadcast
        delta = delta_ref[0, 0:1, :].T
        s = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * sm_scale
        )
        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        if valid_k != num_kv * block_k:
            s = jnp.where(cols < valid_k, s, _NEG_INF)
        if has_vf:
            s = jnp.where(cols >= vf_ref[0], s, _NEG_INF)
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            s = jnp.where(rows >= cols, s, _NEG_INF)
            if window is not None:
                s = jnp.where(cols > rows - window, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * sm_scale
        dq_scr[...] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    live = None
    if causal:
        live = j * block_k <= q_start + block_q - 1
        if window is not None:
            live = jnp.logical_and(
                live, (j + 1) * block_k - 1 > q_start - window
            )
    if has_vf:
        past_pad = (j + 1) * block_k > vf_ref[0]
        live = past_pad if live is None else jnp.logical_and(live, past_pad)
    if live is not None:
        pl.when(live)(_step)
    else:
        _step()

    @pl.when(j == num_kv - 1)
    def _emit():
        dq_ref[0] = dq_scr[...].astype(dq_ref.dtype)


def _bwd_dkv_kernel(
    q_ref,
    k_ref,
    v_ref,
    do_ref,
    lse_ref,
    delta_ref,
    *refs,
    block_q,
    num_q,
    causal,
    sm_scale,
    valid_k,
    sp_k,
    has_vf=False,
    window=None,
):
    """dK/dV pass: grid (bh, k_blocks, q_blocks), Q/dO streaming
    innermost; dk/dv accumulate in VMEM scratch."""
    if has_vf:
        vf_ref, dk_ref, dv_ref, dk_scr, dv_scr = refs
    else:
        dk_ref, dv_ref, dk_scr, dv_scr = refs
    i = pl.program_id(2)
    block_k = k_ref.shape[1]
    k_start = pl.program_id(1) * block_k
    q_start = i * block_q

    @pl.when(i == 0)
    def _init():
        dk_scr[...] = jnp.zeros(dk_scr.shape, jnp.float32)
        dv_scr[...] = jnp.zeros(dv_scr.shape, jnp.float32)

    def _step():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        lse = lse_ref[0, 0:1, :].T  # (block_q, 1); rows 1-7 are broadcast
        delta = delta_ref[0, 0:1, :].T
        s = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * sm_scale
        )  # (block_q, block_k)
        cols = k_start + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        if valid_k != sp_k:
            s = jnp.where(cols < valid_k, s, _NEG_INF)
        if has_vf:
            s = jnp.where(cols >= vf_ref[0], s, _NEG_INF)
        if causal:
            rows = q_start + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0
            )
            s = jnp.where(rows >= cols, s, _NEG_INF)
            if window is not None:
                s = jnp.where(cols > rows - window, s, _NEG_INF)
        p = jnp.exp(s - lse)
        dv_scr[...] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        ds = p * (dp - delta) * sm_scale
        dk_scr[...] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    live = None
    if causal:
        # Q blocks entirely before this K block see none of it.
        live = q_start + block_q - 1 >= k_start
        if window is not None:
            # Q blocks entirely past this K block's window: every row i
            # needs a col c with i < c + window.
            live = jnp.logical_and(
                live, q_start < k_start + block_k + window - 1
            )
    if has_vf:
        # A K block entirely inside the left padding gets zero gradient.
        past_pad = k_start + block_k > vf_ref[0]
        live = past_pad if live is None else jnp.logical_and(live, past_pad)
    if live is not None:
        pl.when(live)(_step)
    else:
        _step()

    @pl.when(i == num_q - 1)
    def _emit():
        dk_ref[0] = dk_scr[...].astype(dk_ref.dtype)
        dv_ref[0] = dv_scr[...].astype(dv_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("causal", "block_q", "block_k", "window")
)
def _flash_bwd_impl(
    q, k, v, out, lse, do, *, causal, block_q, block_k, valid_from=None,
    window=None,
):
    """Streaming flash backward: two Pallas passes (dQ, then dK/dV), each
    recomputing score blocks against the saved logsumexp — O(S*D) HBM
    and O(block) VMEM like the forward, so gradients survive sequence
    lengths whose materialized S x S recompute OOMs
    (benchmarks/results/r03/attn_longseq.json documents the forward-side
    wall; this is the backward-side counterpart)."""
    b, h, s_q, d = q.shape
    s_k = k.shape[2]
    block_q = min(block_q, max(s_q, 8))
    block_k = min(block_k, max(s_k, 8))
    pad_q = (-s_q) % block_q
    pad_k = (-s_k) % block_k
    if valid_from is not None:
        # Ragged left padding: a fully-padded q row (position < vf) saved
        # lse ~= -1e30 (everything masked); exp(s - lse) would then be
        # exp(~0) = 1 instead of 0 and the row would pollute dK/dV. Clamp
        # so masked scores stay masked: exp(-1e30 - (-1e20)) == 0, while
        # any row with one live key has lse far above the clamp.
        lse = jnp.maximum(lse, -1e20)
    # delta_i = rowsum(dO_i * O_i): the only extra residual the backward
    # needs, O(S) — computed once outside the kernels.
    delta = jnp.sum(
        do.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
        do = jnp.pad(do, ((0, 0), (0, 0), (0, pad_q), (0, 0)))
        # Padded rows: zero q/do/delta make every contribution vanish;
        # lse=0 keeps exp(s - lse) finite (s is 0 there, p = 1, x 0 = 0).
        lse = jnp.pad(lse, ((0, 0), (0, 0), (0, pad_q)))
        delta = jnp.pad(delta, ((0, 0), (0, 0), (0, pad_q)))
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, 0), (0, pad_k), (0, 0)))

    sm_scale = 1.0 / math.sqrt(d)
    sp_q, sp_k = s_q + pad_q, s_k + pad_k
    num_q, num_kv = sp_q // block_q, sp_k // block_k
    qf = q.reshape(b * h, sp_q, d)
    kf = k.reshape(b * h, sp_k, d)
    vf = v.reshape(b * h, sp_k, d)
    dof = do.reshape(b * h, sp_q, d)
    # 8-row broadcast (TPU block-shape rule; see the forward's lse note).
    lsef = jnp.broadcast_to(
        lse.reshape(b * h, 1, sp_q), (b * h, 8, sp_q)
    )
    deltaf = jnp.broadcast_to(
        delta.reshape(b * h, 1, sp_q), (b * h, 8, sp_q)
    )
    on_tpu = jax.default_backend() == "tpu"
    params = (
        pltpu.CompilerParams(
            dimension_semantics=("parallel", "parallel", "arbitrary")
        )
        if on_tpu
        else None
    )
    q_spec = pl.BlockSpec(
        (1, block_q, d), lambda bh, a, b_: (bh, a, 0), memory_space=_VMEM
    )
    row_spec = pl.BlockSpec(
        (1, 8, block_q), lambda bh, a, b_: (bh, 0, a), memory_space=_VMEM
    )
    kv_spec_dq = pl.BlockSpec(
        (1, block_k, d), lambda bh, a, b_: (bh, b_, 0), memory_space=_VMEM
    )
    vf_operands, vf_specs = [], []
    if valid_from is not None:
        vf_operands = [jnp.repeat(jnp.asarray(valid_from, jnp.int32), h)]
        vf_specs = [
            pl.BlockSpec(
                (1,), lambda bh, a, b_: (bh,), memory_space=pltpu.SMEM
            )
        ]
    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel,
            block_k=block_k,
            num_kv=num_kv,
            causal=causal,
            sm_scale=sm_scale,
            valid_k=s_k,
            has_vf=valid_from is not None,
            window=window,
        ),
        grid=(b * h, num_q, num_kv),
        in_specs=[q_spec, kv_spec_dq, kv_spec_dq, q_spec, row_spec,
                  row_spec, *vf_specs],
        out_specs=q_spec,
        out_shape=jax.ShapeDtypeStruct(qf.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        compiler_params=params,
        interpret=not on_tpu,
    )(qf, kf, vf, dof, lsef, deltaf, *vf_operands)

    q_spec_kv = pl.BlockSpec(
        (1, block_q, d), lambda bh, a, b_: (bh, b_, 0), memory_space=_VMEM
    )
    row_spec_kv = pl.BlockSpec(
        (1, 8, block_q), lambda bh, a, b_: (bh, 0, b_), memory_space=_VMEM
    )
    kv_spec = pl.BlockSpec(
        (1, block_k, d), lambda bh, a, b_: (bh, a, 0), memory_space=_VMEM
    )
    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel,
            block_q=block_q,
            num_q=num_q,
            causal=causal,
            sm_scale=sm_scale,
            valid_k=s_k,
            sp_k=sp_k,
            has_vf=valid_from is not None,
            window=window,
        ),
        grid=(b * h, num_kv, num_q),
        in_specs=[
            q_spec_kv,
            kv_spec,
            kv_spec,
            q_spec_kv,
            row_spec_kv,
            row_spec_kv,
            *vf_specs,
        ],
        out_specs=[kv_spec, kv_spec],
        out_shape=[
            jax.ShapeDtypeStruct(kf.shape, k.dtype),
            jax.ShapeDtypeStruct(vf.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        compiler_params=params,
        interpret=not on_tpu,
    )(qf, kf, vf, dof, lsef, deltaf, *vf_operands)

    dq = dq.reshape(b, h, sp_q, d)[:, :, :s_q, :]
    dk = dk.reshape(b, h, sp_k, d)[:, :, :s_k, :]
    dv = dv.reshape(b, h, sp_k, d)[:, :, :s_k, :]
    return dq, dk, dv


_flash_vjp.defvjp(_flash_fwd, _flash_bwd)


def attention_reference(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    causal: bool = False,
    valid_from: jax.Array | None = None,
    causal_shift: jax.Array | None = None,
    window: int | None = None,
) -> jax.Array:
    """Pure-jnp oracle: softmax(QK^T / sqrt(d)) V with optional masks.

    Causal convention (same as the kernel): query at absolute position i
    attends keys at absolute positions j <= i — top-left aligned, which is
    the identity convention for the self-attention (s_q == s_k) shapes the
    framework uses. ``valid_from`` (b,) additionally masks each row's
    keys at positions < valid_from[row] — left-padding in ragged batches
    (the LM's masked prefill). ``causal_shift`` offsets the causal
    diagonal (row i attends j <= i - shift; see
    :func:`flash_attention_with_lse`). ``window`` (requires ``causal``)
    bands the mask Mistral-style: row i attends j in
    (i - window, i] — the sliding-window LM's full-sequence forward.
    One oracle, one set of masking/precision conventions.
    """
    if window is not None and not causal:
        raise ValueError("window requires causal=True")
    d = q.shape[-1]
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) / math.sqrt(d)
    if causal:
        s = jnp.where(_causal_mask(*s.shape[-2:], causal_shift), s, _NEG_INF)
    if window is not None:
        s_q, s_k = s.shape[-2:]
        band = (
            jnp.arange(s_k)[None, :]
            > jnp.arange(s_q)[:, None] - window
        )
        s = jnp.where(band[None, None], s, _NEG_INF)
    if valid_from is not None:
        cols = jnp.arange(s.shape[-1])
        live = cols[None, :] >= valid_from[:, None]  # (b, s_k)
        s = jnp.where(live[:, None, None, :], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v.astype(jnp.float32)).astype(
        q.dtype
    )
