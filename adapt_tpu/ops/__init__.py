from adapt_tpu.ops.quantize import (
    QuantizedTensor,
    dequantize,
    dequantize_reference,
    quantize,
    quantize_reference,
)
from adapt_tpu.ops.attention import attention_reference, flash_attention

__all__ = [
    "QuantizedTensor",
    "attention_reference",
    "dequantize",
    "dequantize_reference",
    "flash_attention",
    "quantize",
    "quantize_reference",
]
