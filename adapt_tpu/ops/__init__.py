from adapt_tpu.ops.quantize import (
    QuantizedTensor,
    dequantize,
    dequantize_params,
    dequantize_reference,
    quantize,
    quantize_kv_vectors,
    quantize_params,
    quantize_reference,
)
from adapt_tpu.ops.attention import attention_reference, flash_attention
from adapt_tpu.ops.decode_attention import (
    decode_attention,
    decode_attention_reference,
    verify_attention,
)
from adapt_tpu.ops.paged_attention import (
    paged_attention,
    paged_attention_reference,
    paged_chunk_attention,
    paged_chunk_attention_reference,
    paged_verify_attention,
    paged_verify_attention_reference,
    pool_values,
)

__all__ = [
    "QuantizedTensor",
    "attention_reference",
    "decode_attention",
    "decode_attention_reference",
    "dequantize",
    "dequantize_params",
    "dequantize_reference",
    "flash_attention",
    "paged_attention",
    "paged_attention_reference",
    "paged_chunk_attention",
    "paged_chunk_attention_reference",
    "paged_verify_attention",
    "paged_verify_attention_reference",
    "pool_values",
    "quantize",
    "quantize_kv_vectors",
    "quantize_params",
    "quantize_reference",
    "verify_attention",
]
