"""Paged decode attention: KV cache pages + a scalar-prefetched kernel.

Contiguous per-slot KV caches (``runtime/continuous.py``) reserve
``slots x max_len`` positions in HBM whatever the actual request mix —
a short request in a long-context server wastes almost its whole strip.
Paged KV (the vLLM idea, TPU-native here) carves the cache into
fixed-size PAGES in one shared pool; each slot owns just the pages its
live window touches, and a page table maps logical position blocks to
physical pages. Capacity then scales with actual resident tokens, not
with ``slots x max_len``.

The TPU part: attention over a paged cache must NOT gather pages into a
contiguous buffer first (that would write + re-read the whole window,
doubling HBM traffic — the exact cost paging exists to avoid). The
Pallas kernel here streams pages directly: the page table rides as a
SCALAR-PREFETCH operand (``pltpu.PrefetchScalarGridSpec``), and the K/V
``index_map`` consults it to pick each grid step's physical page — the
DMA engine fetches pool blocks in table order while the online-softmax
state carries across them. The kernel body is ``ops/decode_attention``'s
(same masks, same skip of dead blocks past ``index``); only the block
FETCH differs, which is the whole point: one attention discipline, two
memory layouts.

Layouts:
- pool: (num_pages, kv_heads, page_size, head_dim), native dtype
  (bf16/f32). int8 pools are future work — per-vector scale tiles need
  the 1024-chunk trick of ``decode_attention``, which fights the small
  page sizes paging wants; paging and int8 both buy capacity, compose
  them when a workload needs both.
- page table: (slots, pages_per_slot) int32 physical page ids; entries
  past a slot's live window may be ANY valid page id (their positions
  are masked, their blocks' compute skipped — point them at page 0).
- q: (slots, kv_heads, g, head_dim) group-folded, as in
  ``decode_attention``.

``page_size`` must be a lane multiple (128); the grid streams
``pages_per_slot`` blocks of ``page_size`` positions.

No reference analog (SURVEY.md §2.2: the reference is CNN-only) — this
is the framework's own serving-memory frontier.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from adapt_tpu.ops.decode_attention import _decode_kernel, check_head_parity

try:
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover — jax builds without pallas-tpu
    pltpu = None
    _VMEM = None

DEFAULT_PAGE_SIZE = 128


def paged_attention_reference(q, k_pool, v_pool, page_table, index,
                              valid_from=None):
    """jnp oracle: gather each slot's pages into a contiguous window,
    then run the contiguous decode-attention oracle. This is the
    semantics definition AND the materializing schedule the kernel
    exists to beat.

    q (b, kvh, g, hd); pools (num_pages, kvh, P, hd); page_table
    (b, pages_per_slot) int32; index scalar or (b,)."""
    from adapt_tpu.ops.decode_attention import decode_attention_reference

    b = q.shape[0]
    # (b, pages, kvh, P, hd) -> (b, kvh, pages*P, hd)
    def gather(pool):
        g_ = pool[page_table]  # (b, pages, kvh, P, hd)
        g_ = jnp.moveaxis(g_, 2, 1)
        return g_.reshape(b, pool.shape[1], -1, pool.shape[3])

    return decode_attention_reference(
        q, gather(k_pool), gather(v_pool), index, valid_from
    )


@functools.partial(jax.jit, static_argnames=())
def _paged_impl(q, k_pool, v_pool, page_table, index, valid_from):
    b, kvh, g, hd = q.shape
    page = k_pool.shape[2]
    pages_per_slot = page_table.shape[1]
    has_vf = valid_from is not None
    pad_g = (-g) % 8
    if pad_g:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_g), (0, 0)))
    gq = g + pad_g
    qf = q.reshape(b * kvh, gq, hd)
    idx = jnp.repeat(
        jnp.broadcast_to(jnp.asarray(index, jnp.int32).reshape(-1), (b,)),
        kvh,
    )
    sm_scale = 1.0 / (hd ** 0.5)

    # Scalar-prefetch operand 0: the page table, flattened with the idx /
    # valid_from vectors appended is NOT needed — table stays 2-D; the
    # kernel's SMEM scalars (idx, vf) remain ordinary SMEM inputs.
    def q_map(bh, j, table_ref):
        del j, table_ref
        return (bh, 0, 0)

    def kv_map(bh, j, table_ref):
        return (table_ref[bh // kvh, j], bh % kvh, 0, 0)

    def smem_map(bh, j, table_ref):
        del j, table_ref
        return (bh,)

    def out_map(bh, j, table_ref):
        del j, table_ref
        return (bh, 0, 0)

    in_specs = [
        pl.BlockSpec((1, gq, hd), q_map, memory_space=_VMEM),
        pl.BlockSpec((1, 1, page, hd), kv_map, memory_space=_VMEM),
        pl.BlockSpec((1, 1, page, hd), kv_map, memory_space=_VMEM),
        pl.BlockSpec((1,), smem_map, memory_space=pltpu.SMEM),
    ]
    operands = [qf, k_pool, v_pool, idx]
    if has_vf:
        operands.append(jnp.repeat(jnp.asarray(valid_from, jnp.int32), kvh))
        in_specs.append(
            pl.BlockSpec((1,), smem_map, memory_space=pltpu.SMEM)
        )

    kernel = functools.partial(
        _paged_kernel,
        block_k=page,
        num_kv=pages_per_slot,
        sm_scale=sm_scale,
        has_vf=has_vf,
    )
    on_tpu = jax.default_backend() == "tpu"
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * kvh, pages_per_slot),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, gq, hd), out_map, memory_space=_VMEM),
        scratch_shapes=[
            pltpu.VMEM((gq, 1), jnp.float32),
            pltpu.VMEM((gq, 1), jnp.float32),
            pltpu.VMEM((gq, hd), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b * kvh, gq, hd), q.dtype),
        compiler_params=(
            pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary")
            )
            if on_tpu
            else None
        ),
        interpret=not on_tpu,
    )(jnp.asarray(page_table, jnp.int32), *operands)
    return out.reshape(b, kvh, gq, hd)[:, :, :g, :]


def _paged_kernel(table_ref, q_ref, k_ref, v_ref, idx_ref, *refs, block_k,
                  num_kv, sm_scale, has_vf):
    """Scalar-prefetch wrapper: the table ref arrives first (consumed by
    the index_maps, unused in the body) and the K/V tiles arrive as
    (1, 1, page, hd) — drop the head axis and delegate to the contiguous
    decode kernel body (one attention discipline, two layouts)."""
    del table_ref
    _decode_kernel(
        q_ref,
        k_ref.at[:, 0],
        v_ref.at[:, 0],
        idx_ref,
        *refs,
        block_k=block_k,
        num_kv=num_kv,
        sm_scale=sm_scale,
        quantized=False,
        has_vf=has_vf,
    )


def _chunk_kernel(pages_ref, q_ref, k_ref, v_ref, pos0_ref, *refs,
                  block_k, num_kv, sm_scale, chunk, window=None):
    """Chunk-query paged attention: q rows are a CHUNK of positions
    [pos0, pos0 + chunk) (GQA groups folded in, row = member*chunk + p)
    attending the paged window up to each row's own position — the
    per-row causal mask ``col <= pos0 + row % chunk``. One (kv_head)
    program streams the window's pages innermost with online-softmax
    scratch, exactly the decode kernel's discipline with a row-dependent
    diagonal instead of a shared index."""
    del pages_ref  # consumed by the index_maps
    o_ref, m_scr, l_scr, acc_scr = refs
    j = pl.program_id(1)
    gc = q_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, -1e30, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    def _step():
        q = q_ref[0].astype(jnp.float32)  # (gc, hd)
        k = k_ref[0, 0].astype(jnp.float32)  # (block_k, hd)
        v = v_ref[0, 0].astype(jnp.float32)
        s = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * sm_scale
        )  # (gc, block_k)
        rows = jax.lax.broadcasted_iota(jnp.int32, (gc, block_k), 0) % chunk
        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (gc, block_k), 1
        )
        live = cols <= pos0_ref[0] + rows
        if window is not None:
            # Sliding window: row at absolute position p attends
            # (p - window, p].
            live = jnp.logical_and(
                live, cols > pos0_ref[0] + rows - window
            )
        s = jnp.where(live, s, -1e30)
        m = m_scr[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    # Pages entirely past the chunk's last position are dead (the pow2
    # padding's trash pages land here too); under a sliding window so
    # are pages entirely below EVERY row's window (row 0's is lowest).
    live_block = j * block_k <= pos0_ref[0] + chunk - 1
    if window is not None:
        live_block = jnp.logical_and(
            live_block, (j + 1) * block_k - 1 > pos0_ref[0] - window
        )
    pl.when(live_block)(_step)

    @pl.when(j == num_kv - 1)
    def _emit():
        o_ref[0] = (
            acc_scr[...] / jnp.maximum(l_scr[...], 1e-30)
        ).astype(o_ref.dtype)


def paged_chunk_attention_reference(q, k_pool, v_pool, pages, pos0,
                                    chunk: int, window: int | None = None):
    """jnp oracle for the chunk-query kernel: gather the window, mask
    ``col <= pos0 + row % chunk`` (banded by ``window`` when set),
    softmax, weight. q is (1, kv_h, g*C, hd) GROUP-FOLDED (row =
    member*C + position), pages (n,)."""
    kvh, hd = k_pool.shape[1], k_pool.shape[3]
    gather = lambda pool: jnp.moveaxis(pool[pages], 1, 0).reshape(
        1, kvh, -1, hd
    )
    k, v = gather(k_pool), gather(v_pool)
    sm = 1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32))
    s = jnp.einsum(
        "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
    ) * sm
    rows = jnp.arange(q.shape[2]) % chunk
    cols = jnp.arange(k.shape[2])
    live = cols[None, :] <= pos0 + rows[:, None]
    if window is not None:
        live = live & (cols[None, :] > pos0 + rows[:, None] - window)
    s = jnp.where(live[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32)
    ).astype(q.dtype)


@functools.partial(jax.jit, static_argnames=("chunk", "window"))
def _chunk_impl(q, k_pool, v_pool, pages, pos0, chunk, window=None):
    _, kvh, gc, hd = q.shape
    page = k_pool.shape[2]
    n = pages.shape[0]
    pad_g = (-gc) % 8
    if pad_g:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_g), (0, 0)))
    gcp = gc + pad_g
    qf = q.reshape(kvh, gcp, hd)
    pos0v = jnp.reshape(jnp.asarray(pos0, jnp.int32), (1,))

    def q_map(h, j, pages_ref):
        del j, pages_ref
        return (h, 0, 0)

    def kv_map(h, j, pages_ref):
        return (pages_ref[j], h, 0, 0)

    def smem_map(h, j, pages_ref):
        del h, j, pages_ref
        return (0,)

    on_tpu = jax.default_backend() == "tpu"
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(kvh, n),
        in_specs=[
            pl.BlockSpec((1, gcp, hd), q_map, memory_space=_VMEM),
            pl.BlockSpec((1, 1, page, hd), kv_map, memory_space=_VMEM),
            pl.BlockSpec((1, 1, page, hd), kv_map, memory_space=_VMEM),
            pl.BlockSpec((1,), smem_map, memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, gcp, hd), q_map, memory_space=_VMEM),
        scratch_shapes=[
            pltpu.VMEM((gcp, 1), jnp.float32),
            pltpu.VMEM((gcp, 1), jnp.float32),
            pltpu.VMEM((gcp, hd), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _chunk_kernel,
            block_k=page,
            num_kv=n,
            sm_scale=1.0 / (hd ** 0.5),
            chunk=chunk,
            window=window,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((kvh, gcp, hd), q.dtype),
        compiler_params=(
            pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary")
            )
            if on_tpu
            else None
        ),
        interpret=not on_tpu,
    )(jnp.asarray(pages, jnp.int32), qf, k_pool, v_pool, pos0v)
    return out.reshape(1, kvh, gcp, hd)[:, :, :gc, :]


def paged_chunk_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    pages: jax.Array,
    pos0,
    chunk: int,
    prefer: str | None = None,
    window: int | None = None,
) -> jax.Array:
    """Chunk-prefill attention over a paged window, in place — the
    incremental-prefill counterpart of :func:`paged_attention` (no
    gathered strip, no scatter-back; the caller writes the chunk's K/V
    pages first, this reads the window page by page).

    q (1, kv_h, g*chunk, hd) group-folded; ``pages`` (n,) covers the
    whole live window [0, pos0 + chunk) (pow2 padding to the trash page
    is fine — those positions are past every row's mask). Dispatch as
    :func:`paged_attention`: kernel on real TPUs with lane-multiple
    pages, oracle elsewhere."""
    check_head_parity(q.shape[1], k_pool.shape[1])
    page = k_pool.shape[2]
    supported = pltpu is not None and page % 128 == 0
    if prefer is None:
        prefer = (
            "pallas"
            if supported and jax.default_backend() == "tpu"
            else "xla"
        )
    elif prefer not in ("pallas", "xla"):
        raise ValueError(
            f"prefer={prefer!r}: expected None, 'pallas' or 'xla'"
        )
    if prefer == "pallas" and supported:
        return _chunk_impl(q, k_pool, v_pool, pages, pos0, chunk, window)
    return paged_chunk_attention_reference(
        q, k_pool, v_pool, pages, pos0, chunk, window
    )


def paged_verify_attention_reference(q, k_pool, v_pool, page_table, index,
                                     chunk: int, window: int | None = None):
    """jnp oracle for the batched paged VERIFY: gather each slot's pages
    into a contiguous window and run the contiguous verify oracle
    (``ops/decode_attention.verify_attention``) — per-row diagonal
    ``col <= index[b] + row % chunk``. q (b, kv_h, g*chunk, hd)
    group-folded K-major; ``index`` (b,) per-slot base positions
    (negative = dead row, fully masked)."""
    from adapt_tpu.ops.decode_attention import verify_attention

    b = q.shape[0]

    def gather(pool):
        g_ = pool[page_table]  # (b, pages, kvh, P, hd)
        g_ = jnp.moveaxis(g_, 2, 1)
        return g_.reshape(b, pool.shape[1], -1, pool.shape[3])

    return verify_attention(
        q, gather(k_pool), gather(v_pool), index, chunk, window=window
    )


def _verify_kernel(table_ref, q_ref, k_ref, v_ref, idx_ref, *refs,
                   block_k, num_kv, sm_scale, chunk, window=None):
    """Batched chunk-query paged attention: one (batch, kv_head) row of
    K-major verify rows streams ITS page-table row innermost (scalar
    prefetch, as ``_paged_kernel``) with ``_chunk_kernel``'s per-row
    diagonal mask anchored at this slot's OWN base position
    (``idx_ref`` SMEM) — the speculative verify over a paged cache.
    Dead rows (negative index) skip every block and emit zeros."""
    del table_ref  # consumed by the index_maps
    o_ref, m_scr, l_scr, acc_scr = refs
    j = pl.program_id(1)
    gc = q_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, -1e30, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    def _step():
        q = q_ref[0].astype(jnp.float32)  # (gc, hd)
        k = k_ref[0, 0].astype(jnp.float32)  # (block_k, hd)
        v = v_ref[0, 0].astype(jnp.float32)
        s = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * sm_scale
        )  # (gc, block_k)
        rows = jax.lax.broadcasted_iota(jnp.int32, (gc, block_k), 0) % chunk
        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (gc, block_k), 1
        )
        live = cols <= idx_ref[0] + rows
        if window is not None:
            live = jnp.logical_and(live, cols > idx_ref[0] + rows - window)
        s = jnp.where(live, s, -1e30)
        m = m_scr[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    # Pages wholly past this slot's last chunk position are dead (every
    # page, for a negative dead-row index); under a sliding window so
    # are pages wholly below row 0's window.
    live_block = j * block_k <= idx_ref[0] + chunk - 1
    if window is not None:
        live_block = jnp.logical_and(
            live_block, (j + 1) * block_k - 1 > idx_ref[0] - window
        )
    pl.when(live_block)(_step)

    @pl.when(j == num_kv - 1)
    def _emit():
        o_ref[0] = (
            acc_scr[...] / jnp.maximum(l_scr[...], 1e-30)
        ).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("chunk", "window"))
def _verify_impl(q, k_pool, v_pool, page_table, index, chunk, window=None):
    b, kvh, gc, hd = q.shape
    page = k_pool.shape[2]
    pages_per_slot = page_table.shape[1]
    pad_g = (-gc) % 8
    if pad_g:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_g), (0, 0)))
    gcp = gc + pad_g
    qf = q.reshape(b * kvh, gcp, hd)
    idx = jnp.repeat(
        jnp.broadcast_to(jnp.asarray(index, jnp.int32).reshape(-1), (b,)),
        kvh,
    )

    def q_map(bh, j, table_ref):
        del j, table_ref
        return (bh, 0, 0)

    def kv_map(bh, j, table_ref):
        return (table_ref[bh // kvh, j], bh % kvh, 0, 0)

    def smem_map(bh, j, table_ref):
        del j, table_ref
        return (bh,)

    on_tpu = jax.default_backend() == "tpu"
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * kvh, pages_per_slot),
        in_specs=[
            pl.BlockSpec((1, gcp, hd), q_map, memory_space=_VMEM),
            pl.BlockSpec((1, 1, page, hd), kv_map, memory_space=_VMEM),
            pl.BlockSpec((1, 1, page, hd), kv_map, memory_space=_VMEM),
            pl.BlockSpec((1,), smem_map, memory_space=pltpu.SMEM),
        ],
        out_specs=pl.BlockSpec((1, gcp, hd), q_map, memory_space=_VMEM),
        scratch_shapes=[
            pltpu.VMEM((gcp, 1), jnp.float32),
            pltpu.VMEM((gcp, 1), jnp.float32),
            pltpu.VMEM((gcp, hd), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _verify_kernel,
            block_k=page,
            num_kv=pages_per_slot,
            sm_scale=1.0 / (hd ** 0.5),
            chunk=chunk,
            window=window,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b * kvh, gcp, hd), q.dtype),
        compiler_params=(
            pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary")
            )
            if on_tpu
            else None
        ),
        interpret=not on_tpu,
    )(jnp.asarray(page_table, jnp.int32), qf, k_pool, v_pool, idx)
    return out.reshape(b, kvh, gcp, hd)[:, :, :gc, :]


def paged_verify_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    page_table: jax.Array,
    index,
    chunk: int,
    prefer: str | None = None,
    window: int | None = None,
) -> jax.Array:
    """Batched multi-token verify attention over a paged KV cache — the
    speculative-decode counterpart of :func:`paged_attention` (K chunk
    rows per slot, each masked to its own ``index[b] + t`` diagonal;
    the caller has already scattered the chunk's K/V into the pages).

    Dispatch as :func:`paged_attention`: the scalar-prefetch kernel on
    a real TPU with lane-multiple pages (the gather oracle materializes
    every slot's whole window — the traffic paging exists to avoid),
    the oracle everywhere else. Grids and the GQA fold derive from the
    shapes given — the per-shard head count under tensor parallelism —
    so q and pool must carry the same head count
    (``decode_attention.check_head_parity``)."""
    check_head_parity(q.shape[1], k_pool.shape[1])
    page = k_pool.shape[2]
    supported = pltpu is not None and page % 128 == 0
    if prefer is None:
        prefer = (
            "pallas"
            if supported and jax.default_backend() == "tpu"
            else "xla"
        )
    elif prefer not in ("pallas", "xla"):
        raise ValueError(
            f"prefer={prefer!r}: expected None, 'pallas' or 'xla'"
        )
    if prefer == "pallas" and supported:
        return _verify_impl(
            q, k_pool, v_pool, page_table, index, chunk, window
        )
    return paged_verify_attention_reference(
        q, k_pool, v_pool, page_table, index, chunk, window
    )


def paged_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    page_table: jax.Array,
    index,
    valid_from=None,
    prefer: str | None = None,
) -> jax.Array:
    """Decode attention over a paged KV cache.

    ``prefer``: None = auto — the kernel on a real TPU whenever the page
    size is a lane multiple (the gather oracle materializes the whole
    window, the exact traffic paging exists to avoid), the oracle
    everywhere else (off-TPU the kernel only has the Pallas INTERPRETER,
    orders of magnitude slower than XLA's gather — tests opt in with
    ``prefer="pallas"``). ``"pallas"`` / ``"xla"`` force. Grids/folds
    derive from the given (per-shard, under TP) head count — q and pool
    must agree (``decode_attention.check_head_parity``)."""
    check_head_parity(q.shape[1], k_pool.shape[1])
    page = k_pool.shape[2]
    supported = pltpu is not None and page % 128 == 0
    if prefer is None:
        on_tpu = jax.default_backend() == "tpu"
        prefer = "pallas" if (supported and on_tpu) else "xla"
    elif prefer not in ("pallas", "xla"):
        raise ValueError(
            f"prefer={prefer!r}: expected None, 'pallas' or 'xla'"
        )
    if prefer == "pallas" and supported:
        return _paged_impl(q, k_pool, v_pool, page_table, index, valid_from)
    return paged_attention_reference(
        q, k_pool, v_pool, page_table, index, valid_from
    )
