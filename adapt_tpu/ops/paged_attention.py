"""Paged decode attention: KV cache pages + a scalar-prefetched kernel.

Contiguous per-slot KV caches (``runtime/continuous.py``) reserve
``slots x max_len`` positions in HBM whatever the actual request mix —
a short request in a long-context server wastes almost its whole strip.
Paged KV (the vLLM idea, TPU-native here) carves the cache into
fixed-size PAGES in one shared pool; each slot owns just the pages its
live window touches, and a page table maps logical position blocks to
physical pages. Capacity then scales with actual resident tokens, not
with ``slots x max_len``.

The TPU part: attention over a paged cache must NOT gather pages into a
contiguous buffer first (that would write + re-read the whole window,
doubling HBM traffic — the exact cost paging exists to avoid). The
Pallas kernel here streams pages directly: the page table rides as a
SCALAR-PREFETCH operand (``pltpu.PrefetchScalarGridSpec``), and the K/V
``index_map`` consults it to pick each grid step's physical page — the
DMA engine fetches pool blocks in table order while the online-softmax
state carries across them. The kernel body is ``ops/decode_attention``'s
(same masks, same skip of dead blocks past ``index``); only the block
FETCH differs, which is the whole point: one attention discipline, two
memory layouts.

Layouts:
- pool: (num_pages, kv_heads, page_size, head_dim), native dtype
  (bf16/f32). int8 pools are future work — per-vector scale tiles need
  the 1024-chunk trick of ``decode_attention``, which fights the small
  page sizes paging wants; paging and int8 both buy capacity, compose
  them when a workload needs both.
- page table: (slots, pages_per_slot) int32 physical page ids; entries
  past a slot's live window may be ANY valid page id (their positions
  are masked, their blocks' compute skipped — point them at page 0).
- q: (slots, kv_heads, g, head_dim) group-folded, as in
  ``decode_attention``.

``page_size`` must be a lane multiple (128); the grid streams
``pages_per_slot`` blocks of ``page_size`` positions.

No reference analog (SURVEY.md §2.2: the reference is CNN-only) — this
is the framework's own serving-memory frontier.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from adapt_tpu.ops.decode_attention import _decode_kernel

try:
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover — jax builds without pallas-tpu
    pltpu = None
    _VMEM = None

DEFAULT_PAGE_SIZE = 128


def paged_attention_reference(q, k_pool, v_pool, page_table, index,
                              valid_from=None):
    """jnp oracle: gather each slot's pages into a contiguous window,
    then run the contiguous decode-attention oracle. This is the
    semantics definition AND the materializing schedule the kernel
    exists to beat.

    q (b, kvh, g, hd); pools (num_pages, kvh, P, hd); page_table
    (b, pages_per_slot) int32; index scalar or (b,)."""
    from adapt_tpu.ops.decode_attention import decode_attention_reference

    b = q.shape[0]
    # (b, pages, kvh, P, hd) -> (b, kvh, pages*P, hd)
    def gather(pool):
        g_ = pool[page_table]  # (b, pages, kvh, P, hd)
        g_ = jnp.moveaxis(g_, 2, 1)
        return g_.reshape(b, pool.shape[1], -1, pool.shape[3])

    return decode_attention_reference(
        q, gather(k_pool), gather(v_pool), index, valid_from
    )


@functools.partial(jax.jit, static_argnames=())
def _paged_impl(q, k_pool, v_pool, page_table, index, valid_from):
    b, kvh, g, hd = q.shape
    page = k_pool.shape[2]
    pages_per_slot = page_table.shape[1]
    has_vf = valid_from is not None
    pad_g = (-g) % 8
    if pad_g:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_g), (0, 0)))
    gq = g + pad_g
    qf = q.reshape(b * kvh, gq, hd)
    idx = jnp.repeat(
        jnp.broadcast_to(jnp.asarray(index, jnp.int32).reshape(-1), (b,)),
        kvh,
    )
    sm_scale = 1.0 / (hd ** 0.5)

    # Scalar-prefetch operand 0: the page table, flattened with the idx /
    # valid_from vectors appended is NOT needed — table stays 2-D; the
    # kernel's SMEM scalars (idx, vf) remain ordinary SMEM inputs.
    def q_map(bh, j, table_ref):
        del j, table_ref
        return (bh, 0, 0)

    def kv_map(bh, j, table_ref):
        return (table_ref[bh // kvh, j], bh % kvh, 0, 0)

    def smem_map(bh, j, table_ref):
        del j, table_ref
        return (bh,)

    def out_map(bh, j, table_ref):
        del j, table_ref
        return (bh, 0, 0)

    in_specs = [
        pl.BlockSpec((1, gq, hd), q_map, memory_space=_VMEM),
        pl.BlockSpec((1, 1, page, hd), kv_map, memory_space=_VMEM),
        pl.BlockSpec((1, 1, page, hd), kv_map, memory_space=_VMEM),
        pl.BlockSpec((1,), smem_map, memory_space=pltpu.SMEM),
    ]
    operands = [qf, k_pool, v_pool, idx]
    if has_vf:
        operands.append(jnp.repeat(jnp.asarray(valid_from, jnp.int32), kvh))
        in_specs.append(
            pl.BlockSpec((1,), smem_map, memory_space=pltpu.SMEM)
        )

    kernel = functools.partial(
        _paged_kernel,
        block_k=page,
        num_kv=pages_per_slot,
        sm_scale=sm_scale,
        has_vf=has_vf,
    )
    on_tpu = jax.default_backend() == "tpu"
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * kvh, pages_per_slot),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, gq, hd), out_map, memory_space=_VMEM),
        scratch_shapes=[
            pltpu.VMEM((gq, 1), jnp.float32),
            pltpu.VMEM((gq, 1), jnp.float32),
            pltpu.VMEM((gq, hd), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b * kvh, gq, hd), q.dtype),
        compiler_params=(
            pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary")
            )
            if on_tpu
            else None
        ),
        interpret=not on_tpu,
    )(jnp.asarray(page_table, jnp.int32), *operands)
    return out.reshape(b, kvh, gq, hd)[:, :, :g, :]


def _paged_kernel(table_ref, q_ref, k_ref, v_ref, idx_ref, *refs, block_k,
                  num_kv, sm_scale, has_vf):
    """Scalar-prefetch wrapper: the table ref arrives first (consumed by
    the index_maps, unused in the body) and the K/V tiles arrive as
    (1, 1, page, hd) — drop the head axis and delegate to the contiguous
    decode kernel body (one attention discipline, two layouts)."""
    del table_ref
    _decode_kernel(
        q_ref,
        k_ref.at[:, 0],
        v_ref.at[:, 0],
        idx_ref,
        *refs,
        block_k=block_k,
        num_kv=num_kv,
        sm_scale=sm_scale,
        quantized=False,
        has_vf=has_vf,
    )


def paged_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    page_table: jax.Array,
    index,
    valid_from=None,
    prefer: str | None = None,
) -> jax.Array:
    """Decode attention over a paged KV cache.

    ``prefer``: None = auto — the kernel on a real TPU whenever the page
    size is a lane multiple (the gather oracle materializes the whole
    window, the exact traffic paging exists to avoid), the oracle
    everywhere else (off-TPU the kernel only has the Pallas INTERPRETER,
    orders of magnitude slower than XLA's gather — tests opt in with
    ``prefer="pallas"``). ``"pallas"`` / ``"xla"`` force."""
    page = k_pool.shape[2]
    supported = pltpu is not None and page % 128 == 0
    if prefer is None:
        on_tpu = jax.default_backend() == "tpu"
        prefer = "pallas" if (supported and on_tpu) else "xla"
    elif prefer not in ("pallas", "xla"):
        raise ValueError(
            f"prefer={prefer!r}: expected None, 'pallas' or 'xla'"
        )
    if prefer == "pallas" and supported:
        return _paged_impl(q, k_pool, v_pool, page_table, index, valid_from)
    return paged_attention_reference(
        q, k_pool, v_pool, page_table, index, valid_from
    )
