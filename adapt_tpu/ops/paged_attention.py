"""Paged decode attention: KV cache pages + a scalar-prefetched kernel.

Contiguous per-slot KV caches (``runtime/continuous.py``) reserve
``slots x max_len`` positions in HBM whatever the actual request mix —
a short request in a long-context server wastes almost its whole strip.
Paged KV (the vLLM idea, TPU-native here) carves the cache into
fixed-size PAGES in one shared pool; each slot owns just the pages its
live window touches, and a page table maps logical position blocks to
physical pages. Capacity then scales with actual resident tokens, not
with ``slots x max_len``.

The TPU part: attention over a paged cache must NOT gather pages into a
contiguous buffer first (that would write + re-read the whole window,
doubling HBM traffic — the exact cost paging exists to avoid). The
Pallas kernel here streams pages directly: the page table rides as a
SCALAR-PREFETCH operand (``pltpu.PrefetchScalarGridSpec``), and the K/V
``index_map`` consults it to pick each grid step's physical page — the
DMA engine fetches pool blocks in table order while the online-softmax
state carries across them. The kernel body is ``ops/decode_attention``'s
(same masks, same skip of dead blocks past ``index``); only the block
FETCH differs, which is the whole point: one attention discipline, two
memory layouts.

The grid's page axis can additionally FLASH-SPLIT (``split`` on every
dispatcher, ``config.KernelConfig.decode_split``): each (row, split)
grid point streams its own run of the slot's pages with independent
online-softmax scratch and emits unnormalized partials (accumulator +
running max + denominator); a single-pass rescale combine reduces them
— so a long-context slot's KV stream fans across compute units instead
of one sequential page walk. ``split=1`` is the original kernel
bit-exactly; the last split may be ragged (clamped in the index maps,
masked in the kernel).

Layouts:
- pool: (num_pages, kv_heads, page_size, head_dim) in the native dtype
  (bf16/f32), OR an ``(int8 values, f32 scales)`` PAIR of pools —
  values (num_pages, kv_heads, page_size, head_dim) int8, scales
  (num_pages, kv_heads, page_size, 1) f32, one absmax scale per cached
  K/V vector (``ops/quantize.quantize_kv_vectors``, the same scheme as
  the dense int8 strips). int4 pools keep the pair shape with the
  VALUE plane packed two nibbles per int8 lane (width head_dim // 2,
  ``quantize_kv_vectors(..., "int4")``); the kernels detect the packed
  width against q's head_dim and unpack in VMEM, so the HBM stream is
  4-bit. Quantized pools compose paging's
  resident-token capacity with int8's ~2-4x byte shrink: the scale
  plane rides the SAME page table (page id addresses both pools), and
  the kernels stream it as one chunked (page/128, 128) f32 tile per
  page — 4/head_dim of the int8 payload's bytes (one f32 per vector)
  — applying scales to the score/probability
  COLUMNS so the big cache operand stays int8 end to end (dequant fused
  in VMEM, the ``_decode_kernel`` discipline). On REAL TPUs the
  quantized kernel path additionally requires
  ``page % DECODE_BLOCK_K == 0`` so the scale tile fills a full f32
  (8, 128) tile (``_kernel_supported`` — the dense int8 path's
  constraint); smaller quantized pages serve through the XLA oracle
  until a hardware A/B motivates a packed-scale layout. Off-TPU the
  interpreter has no tiling, so CI parity drives the quantized kernel
  bodies at ordinary page sizes.
- page table: (slots, pages_per_slot) int32 physical page ids; entries
  past a slot's live window may be ANY valid page id (their positions
  are masked, their blocks' compute skipped — point them at page 0).
- q: (slots, kv_heads, g, head_dim) group-folded, as in
  ``decode_attention``.

``page_size`` must be a lane multiple (128); the grid streams
``pages_per_slot`` blocks of ``page_size`` positions.

No reference analog (SURVEY.md §2.2: the reference is CNN-only) — this
is the framework's own serving-memory frontier.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from adapt_tpu.ops.decode_attention import (
    DECODE_BLOCK_K,
    _attend_tile,
    _combine_splits,
    _decode_kernel,
    _decode_split_kernel,
    _init_softmax_scratch,
    check_head_parity,
    record_kernel_dispatch,
    resolve_decode_split,
)
from adapt_tpu.ops.quantize import unpack_int4

try:
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover — jax builds without pallas-tpu
    pltpu = None
    _VMEM = None

DEFAULT_PAGE_SIZE = 128


def pool_values(pool):
    """The VALUE array of a pool operand: the int8 member of a
    quantized ``(values, scales)`` pair, the pool itself otherwise —
    the one place shape/head/page derivation looks, so every entry
    point sees through the tuple identically."""
    return pool[0] if isinstance(pool, tuple) else pool


def _split_pools(k_pool, v_pool):
    """Split possibly-quantized pool operands into ``(k_vals, v_vals,
    k_scales, v_scales)`` — scales ``None`` for native pools. THE one
    unpack the three kernel dispatchers share, so a future change to
    the pair representation lands in one place."""
    if isinstance(k_pool, tuple):
        (kv, ks), (vv, vs) = k_pool, v_pool
        return kv, vv, ks, vs
    return k_pool, v_pool, None, None


def _kernel_supported(page: int, quantized: bool) -> bool:
    """Shared pallas-dispatch gate for the three paged kernels. Native
    pools need a lane-multiple page. Quantized pools ALSO need the
    scale tile to satisfy f32 (8, 128) tiling ON HARDWARE: a page
    carries page/128 rows of 128 scales, so real TPUs require
    ``page % DECODE_BLOCK_K == 0`` (the dense int8 path's documented
    constraint — small pages would hand Mosaic a 1-sublane f32 tile);
    smaller quantized pages fall back to the XLA oracle until a
    hardware A/B motivates a packed-scale layout. The INTERPRETER has
    no tiling, so off-TPU the CI parity tests still drive the quantized
    kernel bodies at ordinary page sizes."""
    if pltpu is None or page % 128:
        return False
    if quantized and jax.default_backend() == "tpu":
        return page % DECODE_BLOCK_K == 0
    return True


def paged_attention_reference(q, k_pool, v_pool, page_table, index,
                              valid_from=None):
    """jnp oracle: gather each slot's pages into a contiguous window,
    then run the contiguous decode-attention oracle (which owns the
    quantized score/probability-column scale application — one
    definition, so paged int8 decode matches the dense int8 slot path
    value-for-value). This is the semantics definition AND the
    materializing schedule the kernel exists to beat.

    q (b, kvh, g, hd); pools (num_pages, kvh, P, hd) or ``(int8 values,
    f32 scales)`` pairs; page_table (b, pages_per_slot) int32; index
    scalar or (b,)."""
    from adapt_tpu.ops.decode_attention import decode_attention_reference

    b = q.shape[0]
    # (b, pages, kvh, P, hd) -> (b, kvh, pages*P, hd)
    def gather(pool):
        g_ = pool[page_table]  # (b, pages, kvh, P, hd)
        g_ = jnp.moveaxis(g_, 2, 1)
        return g_.reshape(b, pool.shape[1], -1, pool.shape[3])

    if isinstance(k_pool, tuple):
        cache_k = (gather(k_pool[0]), gather(k_pool[1]))
        cache_v = (gather(v_pool[0]), gather(v_pool[1]))
    else:
        cache_k, cache_v = gather(k_pool), gather(v_pool)
    return decode_attention_reference(
        q, cache_k, cache_v, index, valid_from
    )


@functools.partial(jax.jit, static_argnames=("split",))
def _paged_impl(q, k_pool, v_pool, k_scales, v_scales, page_table, index,
                valid_from, split=1):
    b, kvh, g, hd = q.shape
    page = k_pool.shape[2]
    hdk = k_pool.shape[3]  # head_dim // 2 for packed int4 pools
    quantized = k_scales is not None
    packed = quantized and hdk * 2 == hd
    pages_per_slot = page_table.shape[1]
    has_vf = valid_from is not None
    pad_g = (-g) % 8
    if pad_g:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_g), (0, 0)))
    gq = g + pad_g
    qf = q.reshape(b * kvh, gq, hd)
    idx = jnp.repeat(
        jnp.broadcast_to(jnp.asarray(index, jnp.int32).reshape(-1), (b,)),
        kvh,
    )
    sm_scale = 1.0 / (hd ** 0.5)
    bps = -(-pages_per_slot // split)  # pages per split (last may be ragged)

    def blk(bh, *js):
        if split == 1:
            (j,) = js
            return j
        s_id, j = js
        # Ragged tail clamps to a valid table column (masked in-kernel).
        return jnp.minimum(s_id * bps + j, pages_per_slot - 1)

    # Scalar-prefetch operand 0: the page table, flattened with the idx /
    # valid_from vectors appended is NOT needed — table stays 2-D; the
    # kernel's SMEM scalars (idx, vf) remain ordinary SMEM inputs.
    def q_map(bh, *js_table):
        return (bh, 0, 0)

    def kv_map(bh, *js_table):
        *js, table_ref = js_table
        return (table_ref[bh // kvh, blk(bh, *js)], bh % kvh, 0, 0)

    def smem_map(bh, *js_table):
        return (bh,)

    in_specs = [
        pl.BlockSpec((1, gq, hd), q_map, memory_space=_VMEM),
        pl.BlockSpec((1, 1, page, hdk), kv_map, memory_space=_VMEM),
        pl.BlockSpec((1, 1, page, hdk), kv_map, memory_space=_VMEM),
        pl.BlockSpec((1,), smem_map, memory_space=pltpu.SMEM),
    ]
    operands = [qf, k_pool, v_pool, idx]
    if quantized:
        # (pages, kvh, P, 1) f32 scale pools -> (pages, kvh, P/128,
        # 128) CHUNKED views (position = row*128 + lane — the dense
        # kernel's scale-tile trick, so a >=1024 page fills whole f32
        # (8, 128) tiles on hardware); table-addressed by the SAME
        # scalar-prefetch index_map as the int8 payload, 4/head_dim of
        # its bytes (one f32 per int8 vector).
        for s in (k_scales, v_scales):
            operands.append(
                s.reshape(s.shape[0], kvh, page // 128, 128)
            )
            in_specs.append(
                pl.BlockSpec(
                    (1, 1, page // 128, 128), kv_map, memory_space=_VMEM
                )
            )
    if has_vf:
        operands.append(jnp.repeat(jnp.asarray(valid_from, jnp.int32), kvh))
        in_specs.append(
            pl.BlockSpec((1,), smem_map, memory_space=pltpu.SMEM)
        )

    on_tpu = jax.default_backend() == "tpu"
    scratch = [
        pltpu.VMEM((gq, 1), jnp.float32),
        pltpu.VMEM((gq, 1), jnp.float32),
        pltpu.VMEM((gq, hd), jnp.float32),
    ]
    if split == 1:
        kernel = functools.partial(
            _paged_kernel,
            block_k=page,
            num_kv=pages_per_slot,
            sm_scale=sm_scale,
            quantized=quantized,
            has_vf=has_vf,
            packed=packed,
        )
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b * kvh, pages_per_slot),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, gq, hd), q_map, memory_space=_VMEM),
            scratch_shapes=scratch,
        )
        out = pl.pallas_call(
            kernel,
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((b * kvh, gq, hd), q.dtype),
            compiler_params=(
                pltpu.CompilerParams(
                    dimension_semantics=("parallel", "arbitrary")
                )
                if on_tpu
                else None
            ),
            interpret=not on_tpu,
        )(jnp.asarray(page_table, jnp.int32), *operands)
        return out.reshape(b, kvh, gq, hd)[:, :, :g, :]

    # Flash-decoding split over the slot's page list: each (row, split)
    # streams its own run of table entries and emits partials; the
    # single-pass rescale combine reduces them (dense discipline).
    def part_map(bh, s_id, j, table_ref):
        del j, table_ref
        return (bh, s_id, 0, 0)

    kernel = functools.partial(
        _paged_split_kernel,
        block_k=page,
        num_kv=pages_per_slot,
        bps=bps,
        sm_scale=sm_scale,
        quantized=quantized,
        has_vf=has_vf,
        packed=packed,
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * kvh, split, bps),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((1, 1, gq, hd), part_map, memory_space=_VMEM),
            pl.BlockSpec((1, 1, gq, hd), part_map, memory_space=_VMEM),
            pl.BlockSpec((1, 1, gq, hd), part_map, memory_space=_VMEM),
        ),
        scratch_shapes=scratch,
    )
    o_p, m_p, l_p = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((b * kvh, split, gq, hd), jnp.float32),
            jax.ShapeDtypeStruct((b * kvh, split, gq, hd), jnp.float32),
            jax.ShapeDtypeStruct((b * kvh, split, gq, hd), jnp.float32),
        ),
        compiler_params=(
            pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")
            )
            if on_tpu
            else None
        ),
        interpret=not on_tpu,
    )(jnp.asarray(page_table, jnp.int32), *operands)
    out = _combine_splits(o_p, m_p, l_p, q.dtype)
    return out.reshape(b, kvh, gq, hd)[:, :, :g, :]


def _paged_kernel(table_ref, q_ref, k_ref, v_ref, idx_ref, *refs, block_k,
                  num_kv, sm_scale, quantized, has_vf, packed=False):
    """Scalar-prefetch wrapper: the table ref arrives first (consumed by
    the index_maps, unused in the body) and the K/V tiles arrive as
    (1, 1, page, hd) — drop the head axis and delegate to the contiguous
    decode kernel body (one attention discipline, two layouts).
    Quantized pools add chunked (1, 1, page/128, 128) f32 scale tiles,
    table-addressed like the int8 payload; ``_decode_kernel``'s quantized branch applies
    them to the score/probability columns in VMEM — the fused dequant
    (``packed``: int4 nibble pools, unpacked there too)."""
    del table_ref
    _decode_kernel(
        q_ref,
        k_ref.at[:, 0],
        v_ref.at[:, 0],
        idx_ref,
        *refs,
        block_k=block_k,
        num_kv=num_kv,
        sm_scale=sm_scale,
        quantized=quantized,
        has_vf=has_vf,
        packed=packed,
    )


def _paged_split_kernel(table_ref, q_ref, k_ref, v_ref, idx_ref, *refs,
                        block_k, num_kv, bps, sm_scale, quantized, has_vf,
                        packed=False):
    """Flash-split scalar-prefetch wrapper: grid (b * kv_h, split, bps)
    — drop the table/head axes and delegate to the dense split kernel
    (partial emission + masked ragged tail)."""
    del table_ref
    _decode_split_kernel(
        q_ref,
        k_ref.at[:, 0],
        v_ref.at[:, 0],
        idx_ref,
        *refs,
        block_k=block_k,
        num_kv=num_kv,
        bps=bps,
        sm_scale=sm_scale,
        quantized=quantized,
        has_vf=has_vf,
        packed=packed,
    )


def _chunk_kernel(pages_ref, q_ref, k_ref, v_ref, pos0_ref, *refs,
                  block_k, num_kv, sm_scale, chunk, window=None,
                  quantized=False, packed=False):
    """Chunk-query paged attention: q rows are a CHUNK of positions
    [pos0, pos0 + chunk) (GQA groups folded in, row = member*chunk + p)
    attending the paged window up to each row's own position — the
    per-row causal mask ``col <= pos0 + row % chunk``. One (kv_head)
    program streams the window's pages innermost with online-softmax
    scratch, exactly the decode kernel's discipline with a row-dependent
    diagonal instead of a shared index. Quantized pools add chunked
    (page/128, 128) f32 scale tiles applied to the score/probability
    columns in VMEM (``_decode_kernel``'s fused-dequant discipline;
    ``packed`` int4 pools unpack their nibbles there too)."""
    del pages_ref  # consumed by the index_maps
    refs = list(refs)
    ksc_ref = refs.pop(0) if quantized else None
    vsc_ref = refs.pop(0) if quantized else None
    o_ref, m_scr, l_scr, acc_scr = refs
    j = pl.program_id(1)
    gc = q_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        _init_softmax_scratch(m_scr, l_scr, acc_scr)

    def _step():
        rows = jax.lax.broadcasted_iota(jnp.int32, (gc, block_k), 0) % chunk
        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (gc, block_k), 1
        )
        live = cols <= pos0_ref[0] + rows
        if window is not None:
            # Sliding window: row at absolute position p attends
            # (p - window, p].
            live = jnp.logical_and(
                live, cols > pos0_ref[0] + rows - window
            )
        _attend_tile(
            q_ref[0], k_ref[0, 0], v_ref[0, 0],
            ksc_ref[0, 0].reshape(1, block_k) if quantized else None,
            vsc_ref[0, 0].reshape(1, block_k) if quantized else None,
            live, m_scr, l_scr, acc_scr, sm_scale, packed,
        )

    # Pages entirely past the chunk's last position are dead (the pow2
    # padding's trash pages land here too); under a sliding window so
    # are pages entirely below EVERY row's window (row 0's is lowest).
    live_block = j * block_k <= pos0_ref[0] + chunk - 1
    if window is not None:
        live_block = jnp.logical_and(
            live_block, (j + 1) * block_k - 1 > pos0_ref[0] - window
        )
    pl.when(live_block)(_step)

    @pl.when(j == num_kv - 1)
    def _emit():
        o_ref[0] = (
            acc_scr[...] / jnp.maximum(l_scr[...], 1e-30)
        ).astype(o_ref.dtype)


def paged_chunk_attention_reference(q, k_pool, v_pool, pages, pos0,
                                    chunk: int, window: int | None = None):
    """jnp oracle for the chunk-query kernel: gather the window, mask
    ``col <= pos0 + row % chunk`` (banded by ``window`` when set),
    softmax, weight. q is (1, kv_h, g*C, hd) GROUP-FOLDED (row =
    member*C + position), pages (n,). Quantized ``(values, scales)``
    pool pairs apply scales to the score/probability columns, in
    ``decode_attention_reference``'s op order."""
    quantized = isinstance(k_pool, tuple)
    kv = pool_values(k_pool)
    kvh, hd = kv.shape[1], kv.shape[3]

    def gather(pool):
        return jnp.moveaxis(pool[pages], 1, 0).reshape(
            1, kvh, -1, pool.shape[3]
        )

    sm = 1.0 / jnp.sqrt(jnp.asarray(q.shape[-1], jnp.float32))
    if quantized:
        k, ksc = gather(k_pool[0]), gather(k_pool[1])
        v, vsc = gather(v_pool[0]), gather(v_pool[1])
        if k.shape[-1] * 2 == q.shape[-1]:  # packed int4 nibbles
            k, v = unpack_int4(k), unpack_int4(v)
        s = jnp.einsum(
            "bhqd,bhkd->bhqk",
            q.astype(jnp.float32),
            k.astype(jnp.float32),
        ) * jnp.swapaxes(ksc, 2, 3) * sm
    else:
        k, v = gather(k_pool), gather(v_pool)
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
        ) * sm
    rows = jnp.arange(q.shape[2]) % chunk
    cols = jnp.arange(k.shape[2])
    live = cols[None, :] <= pos0 + rows[:, None]
    if window is not None:
        live = live & (cols[None, :] > pos0 + rows[:, None] - window)
    s = jnp.where(live[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    if quantized:
        p = p * jnp.swapaxes(vsc, 2, 3)
    return jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32)
    ).astype(q.dtype)


@functools.partial(jax.jit, static_argnames=("chunk", "window"))
def _chunk_impl(q, k_pool, v_pool, k_scales, v_scales, pages, pos0, chunk,
                window=None):
    _, kvh, gc, hd = q.shape
    page = k_pool.shape[2]
    hdk = k_pool.shape[3]  # head_dim // 2 for packed int4 pools
    n = pages.shape[0]
    quantized = k_scales is not None
    packed = quantized and hdk * 2 == hd
    pad_g = (-gc) % 8
    if pad_g:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_g), (0, 0)))
    gcp = gc + pad_g
    qf = q.reshape(kvh, gcp, hd)
    pos0v = jnp.reshape(jnp.asarray(pos0, jnp.int32), (1,))

    def q_map(h, j, pages_ref):
        del j, pages_ref
        return (h, 0, 0)

    def kv_map(h, j, pages_ref):
        return (pages_ref[j], h, 0, 0)

    def smem_map(h, j, pages_ref):
        del h, j, pages_ref
        return (0,)

    in_specs = [
        pl.BlockSpec((1, gcp, hd), q_map, memory_space=_VMEM),
        pl.BlockSpec((1, 1, page, hdk), kv_map, memory_space=_VMEM),
        pl.BlockSpec((1, 1, page, hdk), kv_map, memory_space=_VMEM),
        pl.BlockSpec((1,), smem_map, memory_space=pltpu.SMEM),
    ]
    operands = [qf, k_pool, v_pool, pos0v]
    if quantized:
        # Kernel arg order is q, k, v, pos0, THEN the scale tiles (the
        # kernel pops them off *refs after the SMEM scalar); chunked
        # (P/128, 128) scale views as in _paged_impl.
        for s in (k_scales, v_scales):
            operands.append(
                s.reshape(s.shape[0], kvh, page // 128, 128)
            )
            in_specs.append(
                pl.BlockSpec(
                    (1, 1, page // 128, 128), kv_map, memory_space=_VMEM
                )
            )
    on_tpu = jax.default_backend() == "tpu"
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(kvh, n),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, gcp, hd), q_map, memory_space=_VMEM),
        scratch_shapes=[
            pltpu.VMEM((gcp, 1), jnp.float32),
            pltpu.VMEM((gcp, 1), jnp.float32),
            pltpu.VMEM((gcp, hd), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _chunk_kernel,
            block_k=page,
            num_kv=n,
            sm_scale=1.0 / (hd ** 0.5),
            chunk=chunk,
            window=window,
            quantized=quantized,
            packed=packed,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((kvh, gcp, hd), q.dtype),
        compiler_params=(
            pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary")
            )
            if on_tpu
            else None
        ),
        interpret=not on_tpu,
    )(jnp.asarray(pages, jnp.int32), *operands)
    return out.reshape(1, kvh, gcp, hd)[:, :, :gc, :]


def paged_chunk_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    pages: jax.Array,
    pos0,
    chunk: int,
    prefer: str | None = None,
    window: int | None = None,
) -> jax.Array:
    """Chunk-prefill attention over a paged window, in place — the
    incremental-prefill counterpart of :func:`paged_attention` (no
    gathered strip, no scatter-back; the caller writes the chunk's K/V
    pages first, this reads the window page by page).

    q (1, kv_h, g*chunk, hd) group-folded; ``pages`` (n,) covers the
    whole live window [0, pos0 + chunk) (pow2 padding to the trash page
    is fine — those positions are past every row's mask). Pools may be
    quantized ``(int8 values, f32 scales)`` pairs. Dispatch as
    :func:`paged_attention`: kernel on real TPUs with lane-multiple
    pages, oracle elsewhere."""
    quantized = isinstance(k_pool, tuple)
    check_head_parity(q.shape[1], pool_values(k_pool).shape[1])
    page = pool_values(k_pool).shape[2]
    supported = _kernel_supported(page, quantized)
    if prefer is None:
        prefer = (
            "pallas"
            if supported and jax.default_backend() == "tpu"
            else "xla"
        )
    elif prefer not in ("pallas", "xla"):
        raise ValueError(
            f"prefer={prefer!r}: expected None, 'pallas' or 'xla'"
        )
    if prefer == "pallas" and supported:
        record_kernel_dispatch("paged_chunk", "pallas")
        kv, vv, ks, vs = _split_pools(k_pool, v_pool)
        return _chunk_impl(q, kv, vv, ks, vs, pages, pos0, chunk, window)
    record_kernel_dispatch("paged_chunk", "xla")
    return paged_chunk_attention_reference(
        q, k_pool, v_pool, pages, pos0, chunk, window
    )


def paged_verify_attention_reference(q, k_pool, v_pool, page_table, index,
                                     chunk: int, window: int | None = None,
                                     tree_tail: int = 0):
    """jnp oracle for the batched paged VERIFY: gather each slot's pages
    into a contiguous window and run the contiguous verify oracle
    (``ops/decode_attention.verify_attention``, which owns the
    quantized scale application for ``(int8 values, f32 scales)``
    pools) — per-row diagonal ``col <= index[b] + row % chunk``. q
    (b, kv_h, g*chunk, hd) group-folded K-major; ``index`` (b,)
    per-slot base positions (negative = dead row, fully masked)."""
    from adapt_tpu.ops.decode_attention import verify_attention

    b = q.shape[0]

    def gather(pool):
        g_ = pool[page_table]  # (b, pages, kvh, P, hd)
        g_ = jnp.moveaxis(g_, 2, 1)
        return g_.reshape(b, pool.shape[1], -1, pool.shape[3])

    if isinstance(k_pool, tuple):
        cache_k = (gather(k_pool[0]), gather(k_pool[1]))
        cache_v = (gather(v_pool[0]), gather(v_pool[1]))
    else:
        cache_k, cache_v = gather(k_pool), gather(v_pool)
    return verify_attention(
        q, cache_k, cache_v, index, chunk, window=window,
        tree_tail=tree_tail,
    )


def _verify_kernel(table_ref, q_ref, k_ref, v_ref, idx_ref, *refs,
                   block_k, num_kv, sm_scale, chunk, window=None,
                   quantized=False, packed=False, tree_tail=0, bps=None):
    """Batched chunk-query paged attention: one (batch, kv_head) row of
    K-major verify rows streams ITS page-table row innermost (scalar
    prefetch, as ``_paged_kernel``) with ``_chunk_kernel``'s per-row
    diagonal mask anchored at this slot's OWN base position
    (``idx_ref`` SMEM) — the speculative verify over a paged cache.
    Dead rows (negative index) skip every block and emit zeros.
    Quantized pools add chunked (page/128, 128) f32 scale tiles applied to the
    score/probability columns in VMEM (the fused dequant; ``packed``
    int4 pools unpack their nibbles there). ``tree_tail`` = w marks the
    chunk's last w rows as TREE LEAVES: each attends the chain prefix
    (depth ``chunk - 1 - w``) plus its OWN physical slot only — the
    tree-draft verify mask (``ops.decode_attention.verify_attention``).
    ``bps`` non-None selects the FLASH-SPLIT grid (b * kv_h, split,
    bps): partial (acc, m, l) emission per split with the caller's
    rescale combine, the ``_decode_split_kernel`` discipline."""
    del table_ref  # consumed by the index_maps
    split_mode = bps is not None
    refs = list(refs)
    ksc_ref = refs.pop(0) if quantized else None
    vsc_ref = refs.pop(0) if quantized else None
    if split_mode:
        o_ref, m_ref, l_ref, m_scr, l_scr, acc_scr = refs
        j = pl.program_id(2)
        jg = pl.program_id(1) * bps + j  # global page index (clamped map)
        last_j = bps - 1
    else:
        o_ref, m_scr, l_scr, acc_scr = refs
        j = pl.program_id(1)
        jg = j
        last_j = num_kv - 1
    gc = q_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        _init_softmax_scratch(m_scr, l_scr, acc_scr)

    def _step():
        rows = jax.lax.broadcasted_iota(jnp.int32, (gc, block_k), 0) % chunk
        cols = jg * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (gc, block_k), 1
        )
        if tree_tail:
            depth = jnp.minimum(rows, chunk - 1 - tree_tail)
        else:
            depth = rows
        live = cols <= idx_ref[0] + depth
        if window is not None:
            live = jnp.logical_and(live, cols > idx_ref[0] + depth - window)
        if tree_tail:
            # A leaf row's own physical slot is live even though it sits
            # past the chain edge; siblings' slots stay masked.
            live = jnp.logical_or(live, cols == idx_ref[0] + rows)
        _attend_tile(
            q_ref[0], k_ref[0, 0], v_ref[0, 0],
            ksc_ref[0, 0].reshape(1, block_k) if quantized else None,
            vsc_ref[0, 0].reshape(1, block_k) if quantized else None,
            live, m_scr, l_scr, acc_scr, sm_scale, packed,
        )

    # Pages wholly past this slot's last chunk position are dead (every
    # page, for a negative dead-row index); under a sliding window so
    # are pages wholly below row 0's window. The ragged split tail's
    # clamped pages mask here too (jg >= num_kv).
    live_block = jg * block_k <= idx_ref[0] + chunk - 1
    if split_mode:
        live_block = jnp.logical_and(live_block, jg < num_kv)
    if window is not None:
        live_block = jnp.logical_and(
            live_block, (jg + 1) * block_k - 1 > idx_ref[0] - window
        )
    pl.when(live_block)(_step)

    @pl.when(j == last_j)
    def _emit():
        if split_mode:
            hd = o_ref.shape[-1]
            o_ref[0, 0] = acc_scr[...]
            m_ref[0, 0] = jnp.broadcast_to(m_scr[...], (gc, hd))
            l_ref[0, 0] = jnp.broadcast_to(l_scr[...], (gc, hd))
        else:
            o_ref[0] = (
                acc_scr[...] / jnp.maximum(l_scr[...], 1e-30)
            ).astype(o_ref.dtype)


@functools.partial(
    jax.jit, static_argnames=("chunk", "window", "tree_tail", "split")
)
def _verify_impl(q, k_pool, v_pool, k_scales, v_scales, page_table, index,
                 chunk, window=None, tree_tail=0, split=1):
    b, kvh, gc, hd = q.shape
    page = k_pool.shape[2]
    hdk = k_pool.shape[3]  # head_dim // 2 for packed int4 pools
    pages_per_slot = page_table.shape[1]
    quantized = k_scales is not None
    packed = quantized and hdk * 2 == hd
    pad_g = (-gc) % 8
    if pad_g:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_g), (0, 0)))
    gcp = gc + pad_g
    qf = q.reshape(b * kvh, gcp, hd)
    idx = jnp.repeat(
        jnp.broadcast_to(jnp.asarray(index, jnp.int32).reshape(-1), (b,)),
        kvh,
    )
    bps = -(-pages_per_slot // split)

    def blk(bh, *js):
        if split == 1:
            (j,) = js
            return j
        s_id, j = js
        return jnp.minimum(s_id * bps + j, pages_per_slot - 1)

    def q_map(bh, *js_table):
        return (bh, 0, 0)

    def kv_map(bh, *js_table):
        *js, table_ref = js_table
        return (table_ref[bh // kvh, blk(bh, *js)], bh % kvh, 0, 0)

    def smem_map(bh, *js_table):
        return (bh,)

    in_specs = [
        pl.BlockSpec((1, gcp, hd), q_map, memory_space=_VMEM),
        pl.BlockSpec((1, 1, page, hdk), kv_map, memory_space=_VMEM),
        pl.BlockSpec((1, 1, page, hdk), kv_map, memory_space=_VMEM),
        pl.BlockSpec((1,), smem_map, memory_space=pltpu.SMEM),
    ]
    operands = [qf, k_pool, v_pool, idx]
    if quantized:
        # Chunked (P/128, 128) scale views as in _paged_impl.
        for s in (k_scales, v_scales):
            operands.append(
                s.reshape(s.shape[0], kvh, page // 128, 128)
            )
            in_specs.append(
                pl.BlockSpec(
                    (1, 1, page // 128, 128), kv_map, memory_space=_VMEM
                )
            )
    on_tpu = jax.default_backend() == "tpu"
    scratch = [
        pltpu.VMEM((gcp, 1), jnp.float32),
        pltpu.VMEM((gcp, 1), jnp.float32),
        pltpu.VMEM((gcp, hd), jnp.float32),
    ]
    if split == 1:
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b * kvh, pages_per_slot),
            in_specs=in_specs,
            out_specs=pl.BlockSpec((1, gcp, hd), q_map, memory_space=_VMEM),
            scratch_shapes=scratch,
        )
        out = pl.pallas_call(
            functools.partial(
                _verify_kernel,
                block_k=page,
                num_kv=pages_per_slot,
                sm_scale=1.0 / (hd ** 0.5),
                chunk=chunk,
                window=window,
                quantized=quantized,
                packed=packed,
                tree_tail=tree_tail,
            ),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((b * kvh, gcp, hd), q.dtype),
            compiler_params=(
                pltpu.CompilerParams(
                    dimension_semantics=("parallel", "arbitrary")
                )
                if on_tpu
                else None
            ),
            interpret=not on_tpu,
        )(jnp.asarray(page_table, jnp.int32), *operands)
        return out.reshape(b, kvh, gcp, hd)[:, :, :gc, :]

    def part_map(bh, s_id, j, table_ref):
        del j, table_ref
        return (bh, s_id, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * kvh, split, bps),
        in_specs=in_specs,
        out_specs=(
            pl.BlockSpec((1, 1, gcp, hd), part_map, memory_space=_VMEM),
            pl.BlockSpec((1, 1, gcp, hd), part_map, memory_space=_VMEM),
            pl.BlockSpec((1, 1, gcp, hd), part_map, memory_space=_VMEM),
        ),
        scratch_shapes=scratch,
    )
    o_p, m_p, l_p = pl.pallas_call(
        functools.partial(
            _verify_kernel,
            block_k=page,
            num_kv=pages_per_slot,
            sm_scale=1.0 / (hd ** 0.5),
            chunk=chunk,
            window=window,
            quantized=quantized,
            packed=packed,
            tree_tail=tree_tail,
            bps=bps,
        ),
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((b * kvh, split, gcp, hd), jnp.float32),
            jax.ShapeDtypeStruct((b * kvh, split, gcp, hd), jnp.float32),
            jax.ShapeDtypeStruct((b * kvh, split, gcp, hd), jnp.float32),
        ),
        compiler_params=(
            pltpu.CompilerParams(
                dimension_semantics=("parallel", "parallel", "arbitrary")
            )
            if on_tpu
            else None
        ),
        interpret=not on_tpu,
    )(jnp.asarray(page_table, jnp.int32), *operands)
    out = _combine_splits(o_p, m_p, l_p, q.dtype)
    return out.reshape(b, kvh, gcp, hd)[:, :, :gc, :]


def paged_verify_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    page_table: jax.Array,
    index,
    chunk: int,
    prefer: str | None = None,
    window: int | None = None,
    tree_tail: int = 0,
    split: int | None = None,
) -> jax.Array:
    """Batched multi-token verify attention over a paged KV cache — the
    speculative-decode counterpart of :func:`paged_attention` (K chunk
    rows per slot, each masked to its own ``index[b] + t`` diagonal;
    the caller has already scattered the chunk's K/V into the pages).

    Pools are native arrays or quantized ``(int8 values, f32 scales)``
    pairs (the caller scattered the chunk's quantized K/V into BOTH
    members; int4-PACKED pairs carry ``head_dim // 2`` nibble lanes).
    ``tree_tail`` marks the chunk's last w rows as tree-draft leaves
    (``decode_attention.verify_attention``'s mask). ``split`` is the
    flash-decoding page-axis split (None = auto on TPU, 1 off-TPU).
    Dispatch as :func:`paged_attention`: the scalar-prefetch
    kernel on a real TPU with lane-multiple pages (the gather oracle
    materializes every slot's whole window — the traffic paging exists
    to avoid), the oracle everywhere else. Grids and the GQA fold
    derive from the shapes given — the per-shard head count under
    tensor parallelism — so q and pool must carry the same head count
    (``decode_attention.check_head_parity``)."""
    quantized = isinstance(k_pool, tuple)
    check_head_parity(q.shape[1], pool_values(k_pool).shape[1])
    page = pool_values(k_pool).shape[2]
    supported = _kernel_supported(page, quantized)
    if prefer is None:
        prefer = (
            "pallas"
            if supported and jax.default_backend() == "tpu"
            else "xla"
        )
    elif prefer not in ("pallas", "xla"):
        raise ValueError(
            f"prefer={prefer!r}: expected None, 'pallas' or 'xla'"
        )
    if prefer == "pallas" and supported:
        split = resolve_decode_split(page_table.shape[1], split)
        record_kernel_dispatch("paged_verify", "pallas")
        kv, vv, ks, vs = _split_pools(k_pool, v_pool)
        return _verify_impl(
            q, kv, vv, ks, vs, page_table, index, chunk, window,
            tree_tail, split,
        )
    record_kernel_dispatch("paged_verify", "xla")
    return paged_verify_attention_reference(
        q, k_pool, v_pool, page_table, index, chunk, window, tree_tail
    )


def paged_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    page_table: jax.Array,
    index,
    valid_from=None,
    prefer: str | None = None,
    split: int | None = None,
) -> jax.Array:
    """Decode attention over a paged KV cache.

    Pools are native arrays or ``(int8 values, f32 scales)`` pairs (one
    scale per cached vector — the module-docstring layout); both pools
    must agree on quantization.

    ``prefer``: None = auto — the kernel on a real TPU whenever the page
    size is a lane multiple (the gather oracle materializes the whole
    window, the exact traffic paging exists to avoid), the oracle
    everywhere else (off-TPU the kernel only has the Pallas INTERPRETER,
    orders of magnitude slower than XLA's gather — tests opt in with
    ``prefer="pallas"``). ``"pallas"`` / ``"xla"`` force. ``split`` is
    the flash-decoding split along the slot's page list (None = auto:
    ``decode_attention.default_decode_split`` of pages_per_slot on a
    real TPU, 1 off-TPU; 1 = the original single-stream kernel,
    bit-exact). Grids/folds
    derive from the given (per-shard, under TP) head count — q and pool
    must agree (``decode_attention.check_head_parity``)."""
    quantized = isinstance(k_pool, tuple)
    check_head_parity(q.shape[1], pool_values(k_pool).shape[1])
    page = pool_values(k_pool).shape[2]
    supported = _kernel_supported(page, quantized)
    if prefer is None:
        on_tpu = jax.default_backend() == "tpu"
        prefer = "pallas" if (supported and on_tpu) else "xla"
    elif prefer not in ("pallas", "xla"):
        raise ValueError(
            f"prefer={prefer!r}: expected None, 'pallas' or 'xla'"
        )
    if prefer == "pallas" and supported:
        split = resolve_decode_split(page_table.shape[1], split)
        record_kernel_dispatch("paged_decode", "pallas")
        kv, vv, ks, vs = _split_pools(k_pool, v_pool)
        return _paged_impl(
            q, kv, vv, ks, vs, page_table, index, valid_from, split
        )
    record_kernel_dispatch("paged_decode", "xla")
    return paged_attention_reference(
        q, k_pool, v_pool, page_table, index, valid_from
    )
