"""Paged decode attention: KV cache pages + a scalar-prefetched kernel.

Contiguous per-slot KV caches (``runtime/continuous.py``) reserve
``slots x max_len`` positions in HBM whatever the actual request mix —
a short request in a long-context server wastes almost its whole strip.
Paged KV (the vLLM idea, TPU-native here) carves the cache into
fixed-size PAGES in one shared pool; each slot owns just the pages its
live window touches, and a page table maps logical position blocks to
physical pages. Capacity then scales with actual resident tokens, not
with ``slots x max_len``.

The TPU part: attention over a paged cache must NOT gather pages into a
contiguous buffer first (that would write + re-read the whole window,
doubling HBM traffic — the exact cost paging exists to avoid). The
Pallas kernel here streams pages directly: the page table rides as a
SCALAR-PREFETCH operand (``pltpu.PrefetchScalarGridSpec``), and the K/V
``index_map`` consults it to pick each grid step's physical page — the
DMA engine fetches pool blocks in table order while the online-softmax
state carries across them. The kernel body is ``ops/decode_attention``'s
(same masks, same skip of dead blocks past ``index``); only the block
FETCH differs, which is the whole point: one attention discipline, two
memory layouts.

Layouts:
- pool: (num_pages, kv_heads, page_size, head_dim) in the native dtype
  (bf16/f32), OR an ``(int8 values, f32 scales)`` PAIR of pools —
  values (num_pages, kv_heads, page_size, head_dim) int8, scales
  (num_pages, kv_heads, page_size, 1) f32, one absmax scale per cached
  K/V vector (``ops/quantize.quantize_kv_vectors``, the same scheme as
  the dense int8 strips). Quantized pools compose paging's
  resident-token capacity with int8's ~2-4x byte shrink: the scale
  plane rides the SAME page table (page id addresses both pools), and
  the kernels stream it as one chunked (page/128, 128) f32 tile per
  page — 4/head_dim of the int8 payload's bytes (one f32 per vector)
  — applying scales to the score/probability
  COLUMNS so the big cache operand stays int8 end to end (dequant fused
  in VMEM, the ``_decode_kernel`` discipline). On REAL TPUs the
  quantized kernel path additionally requires
  ``page % DECODE_BLOCK_K == 0`` so the scale tile fills a full f32
  (8, 128) tile (``_kernel_supported`` — the dense int8 path's
  constraint); smaller quantized pages serve through the XLA oracle
  until a hardware A/B motivates a packed-scale layout. Off-TPU the
  interpreter has no tiling, so CI parity drives the quantized kernel
  bodies at ordinary page sizes.
- page table: (slots, pages_per_slot) int32 physical page ids; entries
  past a slot's live window may be ANY valid page id (their positions
  are masked, their blocks' compute skipped — point them at page 0).
- q: (slots, kv_heads, g, head_dim) group-folded, as in
  ``decode_attention``.

``page_size`` must be a lane multiple (128); the grid streams
``pages_per_slot`` blocks of ``page_size`` positions.

No reference analog (SURVEY.md §2.2: the reference is CNN-only) — this
is the framework's own serving-memory frontier.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from adapt_tpu.ops.decode_attention import (
    DECODE_BLOCK_K,
    _decode_kernel,
    check_head_parity,
)

try:
    from jax.experimental.pallas import tpu as pltpu

    _VMEM = pltpu.VMEM
except Exception:  # pragma: no cover — jax builds without pallas-tpu
    pltpu = None
    _VMEM = None

DEFAULT_PAGE_SIZE = 128


def pool_values(pool):
    """The VALUE array of a pool operand: the int8 member of a
    quantized ``(values, scales)`` pair, the pool itself otherwise —
    the one place shape/head/page derivation looks, so every entry
    point sees through the tuple identically."""
    return pool[0] if isinstance(pool, tuple) else pool


def _split_pools(k_pool, v_pool):
    """Split possibly-quantized pool operands into ``(k_vals, v_vals,
    k_scales, v_scales)`` — scales ``None`` for native pools. THE one
    unpack the three kernel dispatchers share, so a future change to
    the pair representation lands in one place."""
    if isinstance(k_pool, tuple):
        (kv, ks), (vv, vs) = k_pool, v_pool
        return kv, vv, ks, vs
    return k_pool, v_pool, None, None


def _kernel_supported(page: int, quantized: bool) -> bool:
    """Shared pallas-dispatch gate for the three paged kernels. Native
    pools need a lane-multiple page. Quantized pools ALSO need the
    scale tile to satisfy f32 (8, 128) tiling ON HARDWARE: a page
    carries page/128 rows of 128 scales, so real TPUs require
    ``page % DECODE_BLOCK_K == 0`` (the dense int8 path's documented
    constraint — small pages would hand Mosaic a 1-sublane f32 tile);
    smaller quantized pages fall back to the XLA oracle until a
    hardware A/B motivates a packed-scale layout. The INTERPRETER has
    no tiling, so off-TPU the CI parity tests still drive the quantized
    kernel bodies at ordinary page sizes."""
    if pltpu is None or page % 128:
        return False
    if quantized and jax.default_backend() == "tpu":
        return page % DECODE_BLOCK_K == 0
    return True


def paged_attention_reference(q, k_pool, v_pool, page_table, index,
                              valid_from=None):
    """jnp oracle: gather each slot's pages into a contiguous window,
    then run the contiguous decode-attention oracle (which owns the
    quantized score/probability-column scale application — one
    definition, so paged int8 decode matches the dense int8 slot path
    value-for-value). This is the semantics definition AND the
    materializing schedule the kernel exists to beat.

    q (b, kvh, g, hd); pools (num_pages, kvh, P, hd) or ``(int8 values,
    f32 scales)`` pairs; page_table (b, pages_per_slot) int32; index
    scalar or (b,)."""
    from adapt_tpu.ops.decode_attention import decode_attention_reference

    b = q.shape[0]
    # (b, pages, kvh, P, hd) -> (b, kvh, pages*P, hd)
    def gather(pool):
        g_ = pool[page_table]  # (b, pages, kvh, P, hd)
        g_ = jnp.moveaxis(g_, 2, 1)
        return g_.reshape(b, pool.shape[1], -1, pool.shape[3])

    if isinstance(k_pool, tuple):
        cache_k = (gather(k_pool[0]), gather(k_pool[1]))
        cache_v = (gather(v_pool[0]), gather(v_pool[1]))
    else:
        cache_k, cache_v = gather(k_pool), gather(v_pool)
    return decode_attention_reference(
        q, cache_k, cache_v, index, valid_from
    )


@functools.partial(jax.jit, static_argnames=())
def _paged_impl(q, k_pool, v_pool, k_scales, v_scales, page_table, index,
                valid_from):
    b, kvh, g, hd = q.shape
    page = k_pool.shape[2]
    pages_per_slot = page_table.shape[1]
    quantized = k_scales is not None
    has_vf = valid_from is not None
    pad_g = (-g) % 8
    if pad_g:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_g), (0, 0)))
    gq = g + pad_g
    qf = q.reshape(b * kvh, gq, hd)
    idx = jnp.repeat(
        jnp.broadcast_to(jnp.asarray(index, jnp.int32).reshape(-1), (b,)),
        kvh,
    )
    sm_scale = 1.0 / (hd ** 0.5)

    # Scalar-prefetch operand 0: the page table, flattened with the idx /
    # valid_from vectors appended is NOT needed — table stays 2-D; the
    # kernel's SMEM scalars (idx, vf) remain ordinary SMEM inputs.
    def q_map(bh, j, table_ref):
        del j, table_ref
        return (bh, 0, 0)

    def kv_map(bh, j, table_ref):
        return (table_ref[bh // kvh, j], bh % kvh, 0, 0)

    def smem_map(bh, j, table_ref):
        del j, table_ref
        return (bh,)

    def out_map(bh, j, table_ref):
        del j, table_ref
        return (bh, 0, 0)

    in_specs = [
        pl.BlockSpec((1, gq, hd), q_map, memory_space=_VMEM),
        pl.BlockSpec((1, 1, page, hd), kv_map, memory_space=_VMEM),
        pl.BlockSpec((1, 1, page, hd), kv_map, memory_space=_VMEM),
        pl.BlockSpec((1,), smem_map, memory_space=pltpu.SMEM),
    ]
    operands = [qf, k_pool, v_pool, idx]
    if quantized:
        # (pages, kvh, P, 1) f32 scale pools -> (pages, kvh, P/128,
        # 128) CHUNKED views (position = row*128 + lane — the dense
        # kernel's scale-tile trick, so a >=1024 page fills whole f32
        # (8, 128) tiles on hardware); table-addressed by the SAME
        # scalar-prefetch index_map as the int8 payload, 4/head_dim of
        # its bytes (one f32 per int8 vector).
        for s in (k_scales, v_scales):
            operands.append(
                s.reshape(s.shape[0], kvh, page // 128, 128)
            )
            in_specs.append(
                pl.BlockSpec(
                    (1, 1, page // 128, 128), kv_map, memory_space=_VMEM
                )
            )
    if has_vf:
        operands.append(jnp.repeat(jnp.asarray(valid_from, jnp.int32), kvh))
        in_specs.append(
            pl.BlockSpec((1,), smem_map, memory_space=pltpu.SMEM)
        )

    kernel = functools.partial(
        _paged_kernel,
        block_k=page,
        num_kv=pages_per_slot,
        sm_scale=sm_scale,
        quantized=quantized,
        has_vf=has_vf,
    )
    on_tpu = jax.default_backend() == "tpu"
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * kvh, pages_per_slot),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, gq, hd), out_map, memory_space=_VMEM),
        scratch_shapes=[
            pltpu.VMEM((gq, 1), jnp.float32),
            pltpu.VMEM((gq, 1), jnp.float32),
            pltpu.VMEM((gq, hd), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b * kvh, gq, hd), q.dtype),
        compiler_params=(
            pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary")
            )
            if on_tpu
            else None
        ),
        interpret=not on_tpu,
    )(jnp.asarray(page_table, jnp.int32), *operands)
    return out.reshape(b, kvh, gq, hd)[:, :, :g, :]


def _paged_kernel(table_ref, q_ref, k_ref, v_ref, idx_ref, *refs, block_k,
                  num_kv, sm_scale, quantized, has_vf):
    """Scalar-prefetch wrapper: the table ref arrives first (consumed by
    the index_maps, unused in the body) and the K/V tiles arrive as
    (1, 1, page, hd) — drop the head axis and delegate to the contiguous
    decode kernel body (one attention discipline, two layouts).
    Quantized pools add chunked (1, 1, page/128, 128) f32 scale tiles,
    table-addressed like the int8 payload; ``_decode_kernel``'s quantized branch applies
    them to the score/probability columns in VMEM — the fused dequant."""
    del table_ref
    _decode_kernel(
        q_ref,
        k_ref.at[:, 0],
        v_ref.at[:, 0],
        idx_ref,
        *refs,
        block_k=block_k,
        num_kv=num_kv,
        sm_scale=sm_scale,
        quantized=quantized,
        has_vf=has_vf,
    )


def _chunk_kernel(pages_ref, q_ref, k_ref, v_ref, pos0_ref, *refs,
                  block_k, num_kv, sm_scale, chunk, window=None,
                  quantized=False):
    """Chunk-query paged attention: q rows are a CHUNK of positions
    [pos0, pos0 + chunk) (GQA groups folded in, row = member*chunk + p)
    attending the paged window up to each row's own position — the
    per-row causal mask ``col <= pos0 + row % chunk``. One (kv_head)
    program streams the window's pages innermost with online-softmax
    scratch, exactly the decode kernel's discipline with a row-dependent
    diagonal instead of a shared index. Quantized pools add chunked
    (page/128, 128) f32 scale tiles applied to the score/probability
    columns in VMEM (``_decode_kernel``'s fused-dequant discipline)."""
    del pages_ref  # consumed by the index_maps
    refs = list(refs)
    ksc_ref = refs.pop(0) if quantized else None
    vsc_ref = refs.pop(0) if quantized else None
    o_ref, m_scr, l_scr, acc_scr = refs
    j = pl.program_id(1)
    gc = q_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, -1e30, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    def _step():
        q = q_ref[0].astype(jnp.float32)  # (gc, hd)
        k = k_ref[0, 0].astype(jnp.float32)  # (block_k, hd)
        v = v_ref[0, 0].astype(jnp.float32)
        s = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * sm_scale
        )  # (gc, block_k)
        if quantized:
            # One f32 scale per column of this page: factors out of the
            # per-vector dot, applied to the small score row.
            s = s * ksc_ref[0, 0].reshape(1, block_k)
        rows = jax.lax.broadcasted_iota(jnp.int32, (gc, block_k), 0) % chunk
        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (gc, block_k), 1
        )
        live = cols <= pos0_ref[0] + rows
        if window is not None:
            # Sliding window: row at absolute position p attends
            # (p - window, p].
            live = jnp.logical_and(
                live, cols > pos0_ref[0] + rows - window
            )
        s = jnp.where(live, s, -1e30)
        m = m_scr[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = p * vsc_ref[0, 0].reshape(1, block_k) if quantized else p
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            pv, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    # Pages entirely past the chunk's last position are dead (the pow2
    # padding's trash pages land here too); under a sliding window so
    # are pages entirely below EVERY row's window (row 0's is lowest).
    live_block = j * block_k <= pos0_ref[0] + chunk - 1
    if window is not None:
        live_block = jnp.logical_and(
            live_block, (j + 1) * block_k - 1 > pos0_ref[0] - window
        )
    pl.when(live_block)(_step)

    @pl.when(j == num_kv - 1)
    def _emit():
        o_ref[0] = (
            acc_scr[...] / jnp.maximum(l_scr[...], 1e-30)
        ).astype(o_ref.dtype)


def paged_chunk_attention_reference(q, k_pool, v_pool, pages, pos0,
                                    chunk: int, window: int | None = None):
    """jnp oracle for the chunk-query kernel: gather the window, mask
    ``col <= pos0 + row % chunk`` (banded by ``window`` when set),
    softmax, weight. q is (1, kv_h, g*C, hd) GROUP-FOLDED (row =
    member*C + position), pages (n,). Quantized ``(values, scales)``
    pool pairs apply scales to the score/probability columns, in
    ``decode_attention_reference``'s op order."""
    quantized = isinstance(k_pool, tuple)
    kv = pool_values(k_pool)
    kvh, hd = kv.shape[1], kv.shape[3]

    def gather(pool):
        return jnp.moveaxis(pool[pages], 1, 0).reshape(
            1, kvh, -1, pool.shape[3]
        )

    sm = 1.0 / jnp.sqrt(jnp.asarray(hd, jnp.float32))
    if quantized:
        k, ksc = gather(k_pool[0]), gather(k_pool[1])
        v, vsc = gather(v_pool[0]), gather(v_pool[1])
        s = jnp.einsum(
            "bhqd,bhkd->bhqk",
            q.astype(jnp.float32),
            k.astype(jnp.float32),
        ) * jnp.swapaxes(ksc, 2, 3) * sm
    else:
        k, v = gather(k_pool), gather(v_pool)
        s = jnp.einsum(
            "bhqd,bhkd->bhqk", q.astype(jnp.float32), k.astype(jnp.float32)
        ) * sm
    rows = jnp.arange(q.shape[2]) % chunk
    cols = jnp.arange(k.shape[2])
    live = cols[None, :] <= pos0 + rows[:, None]
    if window is not None:
        live = live & (cols[None, :] > pos0 + rows[:, None] - window)
    s = jnp.where(live[None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    if quantized:
        p = p * jnp.swapaxes(vsc, 2, 3)
    return jnp.einsum(
        "bhqk,bhkd->bhqd", p, v.astype(jnp.float32)
    ).astype(q.dtype)


@functools.partial(jax.jit, static_argnames=("chunk", "window"))
def _chunk_impl(q, k_pool, v_pool, k_scales, v_scales, pages, pos0, chunk,
                window=None):
    _, kvh, gc, hd = q.shape
    page = k_pool.shape[2]
    n = pages.shape[0]
    quantized = k_scales is not None
    pad_g = (-gc) % 8
    if pad_g:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_g), (0, 0)))
    gcp = gc + pad_g
    qf = q.reshape(kvh, gcp, hd)
    pos0v = jnp.reshape(jnp.asarray(pos0, jnp.int32), (1,))

    def q_map(h, j, pages_ref):
        del j, pages_ref
        return (h, 0, 0)

    def kv_map(h, j, pages_ref):
        return (pages_ref[j], h, 0, 0)

    def smem_map(h, j, pages_ref):
        del h, j, pages_ref
        return (0,)

    in_specs = [
        pl.BlockSpec((1, gcp, hd), q_map, memory_space=_VMEM),
        pl.BlockSpec((1, 1, page, hd), kv_map, memory_space=_VMEM),
        pl.BlockSpec((1, 1, page, hd), kv_map, memory_space=_VMEM),
        pl.BlockSpec((1,), smem_map, memory_space=pltpu.SMEM),
    ]
    operands = [qf, k_pool, v_pool, pos0v]
    if quantized:
        # Kernel arg order is q, k, v, pos0, THEN the scale tiles (the
        # kernel pops them off *refs after the SMEM scalar); chunked
        # (P/128, 128) scale views as in _paged_impl.
        for s in (k_scales, v_scales):
            operands.append(
                s.reshape(s.shape[0], kvh, page // 128, 128)
            )
            in_specs.append(
                pl.BlockSpec(
                    (1, 1, page // 128, 128), kv_map, memory_space=_VMEM
                )
            )
    on_tpu = jax.default_backend() == "tpu"
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(kvh, n),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, gcp, hd), q_map, memory_space=_VMEM),
        scratch_shapes=[
            pltpu.VMEM((gcp, 1), jnp.float32),
            pltpu.VMEM((gcp, 1), jnp.float32),
            pltpu.VMEM((gcp, hd), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _chunk_kernel,
            block_k=page,
            num_kv=n,
            sm_scale=1.0 / (hd ** 0.5),
            chunk=chunk,
            window=window,
            quantized=quantized,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((kvh, gcp, hd), q.dtype),
        compiler_params=(
            pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary")
            )
            if on_tpu
            else None
        ),
        interpret=not on_tpu,
    )(jnp.asarray(pages, jnp.int32), *operands)
    return out.reshape(1, kvh, gcp, hd)[:, :, :gc, :]


def paged_chunk_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    pages: jax.Array,
    pos0,
    chunk: int,
    prefer: str | None = None,
    window: int | None = None,
) -> jax.Array:
    """Chunk-prefill attention over a paged window, in place — the
    incremental-prefill counterpart of :func:`paged_attention` (no
    gathered strip, no scatter-back; the caller writes the chunk's K/V
    pages first, this reads the window page by page).

    q (1, kv_h, g*chunk, hd) group-folded; ``pages`` (n,) covers the
    whole live window [0, pos0 + chunk) (pow2 padding to the trash page
    is fine — those positions are past every row's mask). Pools may be
    quantized ``(int8 values, f32 scales)`` pairs. Dispatch as
    :func:`paged_attention`: kernel on real TPUs with lane-multiple
    pages, oracle elsewhere."""
    quantized = isinstance(k_pool, tuple)
    check_head_parity(q.shape[1], pool_values(k_pool).shape[1])
    page = pool_values(k_pool).shape[2]
    supported = _kernel_supported(page, quantized)
    if prefer is None:
        prefer = (
            "pallas"
            if supported and jax.default_backend() == "tpu"
            else "xla"
        )
    elif prefer not in ("pallas", "xla"):
        raise ValueError(
            f"prefer={prefer!r}: expected None, 'pallas' or 'xla'"
        )
    if prefer == "pallas" and supported:
        kv, vv, ks, vs = _split_pools(k_pool, v_pool)
        return _chunk_impl(q, kv, vv, ks, vs, pages, pos0, chunk, window)
    return paged_chunk_attention_reference(
        q, k_pool, v_pool, pages, pos0, chunk, window
    )


def paged_verify_attention_reference(q, k_pool, v_pool, page_table, index,
                                     chunk: int, window: int | None = None):
    """jnp oracle for the batched paged VERIFY: gather each slot's pages
    into a contiguous window and run the contiguous verify oracle
    (``ops/decode_attention.verify_attention``, which owns the
    quantized scale application for ``(int8 values, f32 scales)``
    pools) — per-row diagonal ``col <= index[b] + row % chunk``. q
    (b, kv_h, g*chunk, hd) group-folded K-major; ``index`` (b,)
    per-slot base positions (negative = dead row, fully masked)."""
    from adapt_tpu.ops.decode_attention import verify_attention

    b = q.shape[0]

    def gather(pool):
        g_ = pool[page_table]  # (b, pages, kvh, P, hd)
        g_ = jnp.moveaxis(g_, 2, 1)
        return g_.reshape(b, pool.shape[1], -1, pool.shape[3])

    if isinstance(k_pool, tuple):
        cache_k = (gather(k_pool[0]), gather(k_pool[1]))
        cache_v = (gather(v_pool[0]), gather(v_pool[1]))
    else:
        cache_k, cache_v = gather(k_pool), gather(v_pool)
    return verify_attention(
        q, cache_k, cache_v, index, chunk, window=window
    )


def _verify_kernel(table_ref, q_ref, k_ref, v_ref, idx_ref, *refs,
                   block_k, num_kv, sm_scale, chunk, window=None,
                   quantized=False):
    """Batched chunk-query paged attention: one (batch, kv_head) row of
    K-major verify rows streams ITS page-table row innermost (scalar
    prefetch, as ``_paged_kernel``) with ``_chunk_kernel``'s per-row
    diagonal mask anchored at this slot's OWN base position
    (``idx_ref`` SMEM) — the speculative verify over a paged cache.
    Dead rows (negative index) skip every block and emit zeros.
    Quantized pools add chunked (page/128, 128) f32 scale tiles applied to the
    score/probability columns in VMEM (the fused dequant)."""
    del table_ref  # consumed by the index_maps
    refs = list(refs)
    ksc_ref = refs.pop(0) if quantized else None
    vsc_ref = refs.pop(0) if quantized else None
    o_ref, m_scr, l_scr, acc_scr = refs
    j = pl.program_id(1)
    gc = q_ref.shape[1]

    @pl.when(j == 0)
    def _init():
        m_scr[...] = jnp.full(m_scr.shape, -1e30, jnp.float32)
        l_scr[...] = jnp.zeros(l_scr.shape, jnp.float32)
        acc_scr[...] = jnp.zeros(acc_scr.shape, jnp.float32)

    def _step():
        q = q_ref[0].astype(jnp.float32)  # (gc, hd)
        k = k_ref[0, 0].astype(jnp.float32)  # (block_k, hd)
        v = v_ref[0, 0].astype(jnp.float32)
        s = (
            jax.lax.dot_general(
                q, k, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32,
            )
            * sm_scale
        )  # (gc, block_k)
        if quantized:
            s = s * ksc_ref[0, 0].reshape(1, block_k)
        rows = jax.lax.broadcasted_iota(jnp.int32, (gc, block_k), 0) % chunk
        cols = j * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (gc, block_k), 1
        )
        live = cols <= idx_ref[0] + rows
        if window is not None:
            live = jnp.logical_and(live, cols > idx_ref[0] + rows - window)
        s = jnp.where(live, s, -1e30)
        m = m_scr[...]
        m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
        alpha = jnp.exp(m - m_new)
        p = jnp.exp(s - m_new)
        m_scr[...] = m_new
        l_scr[...] = l_scr[...] * alpha + jnp.sum(p, axis=-1, keepdims=True)
        pv = p * vsc_ref[0, 0].reshape(1, block_k) if quantized else p
        acc_scr[...] = acc_scr[...] * alpha + jax.lax.dot_general(
            pv, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )

    # Pages wholly past this slot's last chunk position are dead (every
    # page, for a negative dead-row index); under a sliding window so
    # are pages wholly below row 0's window.
    live_block = j * block_k <= idx_ref[0] + chunk - 1
    if window is not None:
        live_block = jnp.logical_and(
            live_block, (j + 1) * block_k - 1 > idx_ref[0] - window
        )
    pl.when(live_block)(_step)

    @pl.when(j == num_kv - 1)
    def _emit():
        o_ref[0] = (
            acc_scr[...] / jnp.maximum(l_scr[...], 1e-30)
        ).astype(o_ref.dtype)


@functools.partial(jax.jit, static_argnames=("chunk", "window"))
def _verify_impl(q, k_pool, v_pool, k_scales, v_scales, page_table, index,
                 chunk, window=None):
    b, kvh, gc, hd = q.shape
    page = k_pool.shape[2]
    pages_per_slot = page_table.shape[1]
    quantized = k_scales is not None
    pad_g = (-gc) % 8
    if pad_g:
        q = jnp.pad(q, ((0, 0), (0, 0), (0, pad_g), (0, 0)))
    gcp = gc + pad_g
    qf = q.reshape(b * kvh, gcp, hd)
    idx = jnp.repeat(
        jnp.broadcast_to(jnp.asarray(index, jnp.int32).reshape(-1), (b,)),
        kvh,
    )

    def q_map(bh, j, table_ref):
        del j, table_ref
        return (bh, 0, 0)

    def kv_map(bh, j, table_ref):
        return (table_ref[bh // kvh, j], bh % kvh, 0, 0)

    def smem_map(bh, j, table_ref):
        del j, table_ref
        return (bh,)

    in_specs = [
        pl.BlockSpec((1, gcp, hd), q_map, memory_space=_VMEM),
        pl.BlockSpec((1, 1, page, hd), kv_map, memory_space=_VMEM),
        pl.BlockSpec((1, 1, page, hd), kv_map, memory_space=_VMEM),
        pl.BlockSpec((1,), smem_map, memory_space=pltpu.SMEM),
    ]
    operands = [qf, k_pool, v_pool, idx]
    if quantized:
        # Chunked (P/128, 128) scale views as in _paged_impl.
        for s in (k_scales, v_scales):
            operands.append(
                s.reshape(s.shape[0], kvh, page // 128, 128)
            )
            in_specs.append(
                pl.BlockSpec(
                    (1, 1, page // 128, 128), kv_map, memory_space=_VMEM
                )
            )
    on_tpu = jax.default_backend() == "tpu"
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b * kvh, pages_per_slot),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, gcp, hd), q_map, memory_space=_VMEM),
        scratch_shapes=[
            pltpu.VMEM((gcp, 1), jnp.float32),
            pltpu.VMEM((gcp, 1), jnp.float32),
            pltpu.VMEM((gcp, hd), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _verify_kernel,
            block_k=page,
            num_kv=pages_per_slot,
            sm_scale=1.0 / (hd ** 0.5),
            chunk=chunk,
            window=window,
            quantized=quantized,
        ),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((b * kvh, gcp, hd), q.dtype),
        compiler_params=(
            pltpu.CompilerParams(
                dimension_semantics=("parallel", "arbitrary")
            )
            if on_tpu
            else None
        ),
        interpret=not on_tpu,
    )(jnp.asarray(page_table, jnp.int32), *operands)
    return out.reshape(b, kvh, gcp, hd)[:, :, :gc, :]


def paged_verify_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    page_table: jax.Array,
    index,
    chunk: int,
    prefer: str | None = None,
    window: int | None = None,
) -> jax.Array:
    """Batched multi-token verify attention over a paged KV cache — the
    speculative-decode counterpart of :func:`paged_attention` (K chunk
    rows per slot, each masked to its own ``index[b] + t`` diagonal;
    the caller has already scattered the chunk's K/V into the pages).

    Pools are native arrays or quantized ``(int8 values, f32 scales)``
    pairs (the caller scattered the chunk's quantized K/V into BOTH
    members). Dispatch as :func:`paged_attention`: the scalar-prefetch
    kernel on a real TPU with lane-multiple pages (the gather oracle
    materializes every slot's whole window — the traffic paging exists
    to avoid), the oracle everywhere else. Grids and the GQA fold
    derive from the shapes given — the per-shard head count under
    tensor parallelism — so q and pool must carry the same head count
    (``decode_attention.check_head_parity``)."""
    quantized = isinstance(k_pool, tuple)
    check_head_parity(q.shape[1], pool_values(k_pool).shape[1])
    page = pool_values(k_pool).shape[2]
    supported = _kernel_supported(page, quantized)
    if prefer is None:
        prefer = (
            "pallas"
            if supported and jax.default_backend() == "tpu"
            else "xla"
        )
    elif prefer not in ("pallas", "xla"):
        raise ValueError(
            f"prefer={prefer!r}: expected None, 'pallas' or 'xla'"
        )
    if prefer == "pallas" and supported:
        kv, vv, ks, vs = _split_pools(k_pool, v_pool)
        return _verify_impl(
            q, kv, vv, ks, vs, page_table, index, chunk, window
        )
    return paged_verify_attention_reference(
        q, k_pool, v_pool, page_table, index, chunk, window
    )


def paged_attention(
    q: jax.Array,
    k_pool: jax.Array,
    v_pool: jax.Array,
    page_table: jax.Array,
    index,
    valid_from=None,
    prefer: str | None = None,
) -> jax.Array:
    """Decode attention over a paged KV cache.

    Pools are native arrays or ``(int8 values, f32 scales)`` pairs (one
    scale per cached vector — the module-docstring layout); both pools
    must agree on quantization.

    ``prefer``: None = auto — the kernel on a real TPU whenever the page
    size is a lane multiple (the gather oracle materializes the whole
    window, the exact traffic paging exists to avoid), the oracle
    everywhere else (off-TPU the kernel only has the Pallas INTERPRETER,
    orders of magnitude slower than XLA's gather — tests opt in with
    ``prefer="pallas"``). ``"pallas"`` / ``"xla"`` force. Grids/folds
    derive from the given (per-shard, under TP) head count — q and pool
    must agree (``decode_attention.check_head_parity``)."""
    quantized = isinstance(k_pool, tuple)
    check_head_parity(q.shape[1], pool_values(k_pool).shape[1])
    page = pool_values(k_pool).shape[2]
    supported = _kernel_supported(page, quantized)
    if prefer is None:
        on_tpu = jax.default_backend() == "tpu"
        prefer = "pallas" if (supported and on_tpu) else "xla"
    elif prefer not in ("pallas", "xla"):
        raise ValueError(
            f"prefer={prefer!r}: expected None, 'pallas' or 'xla'"
        )
    if prefer == "pallas" and supported:
        kv, vv, ks, vs = _split_pools(k_pool, v_pool)
        return _paged_impl(
            q, kv, vv, ks, vs, page_table, index, valid_from
        )
    return paged_attention_reference(
        q, k_pool, v_pool, page_table, index, valid_from
    )
