from adapt_tpu.core.mesh import MeshSpec, build_mesh, stage_devices
from adapt_tpu.core.stage import CompiledStage, compile_stages

__all__ = [
    "MeshSpec",
    "build_mesh",
    "stage_devices",
    "CompiledStage",
    "compile_stages",
]
