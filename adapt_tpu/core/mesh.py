"""Device discovery and mesh construction.

The reference's "cluster" is a hand-edited IP list plus etcd discovery
(``/root/reference/test/test.py:10``, ``src/node_state.py:16-20``). The
TPU-native analog: the device pool is ``jax.devices()`` (chips over ICI),
and placement is a ``jax.sharding.Mesh``. Stage->device binding is late
(control plane decides at runtime which device hosts which stage), so the
mesh helpers here are deliberately small and stateless.
"""

from __future__ import annotations

import dataclasses
from collections.abc import Sequence

import jax
import numpy as np
from jax.sharding import Mesh


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """A named logical mesh shape, e.g. (dp=2, pp=4) over 8 chips."""

    axes: tuple[tuple[str, int], ...]

    @property
    def axis_names(self) -> tuple[str, ...]:
        return tuple(name for name, _ in self.axes)

    @property
    def shape(self) -> tuple[int, ...]:
        return tuple(size for _, size in self.axes)

    @property
    def num_devices(self) -> int:
        return int(np.prod(self.shape)) if self.axes else 1


def build_mesh(
    spec: MeshSpec, devices: Sequence[jax.Device] | None = None
) -> Mesh:
    """Build a Mesh from the first ``spec.num_devices`` devices.

    On real hardware, ``jax.devices()`` order follows the ICI torus
    enumeration, so contiguous slices keep collectives on ICI rather than
    DCN (the scaling-book recipe; the reference's analog — TCP peer lists —
    has no locality notion at all)."""
    devices = list(devices if devices is not None else jax.devices())
    n = spec.num_devices
    if len(devices) < n:
        raise ValueError(
            f"mesh {spec} needs {n} devices, only {len(devices)} available"
        )
    arr = np.array(devices[:n]).reshape(spec.shape)
    return Mesh(arr, spec.axis_names)


def stage_devices(
    num_stages: int, devices: Sequence[jax.Device] | None = None
) -> list[jax.Device]:
    """Round-robin device assignment for pipeline stages (the initial
    binding; the control plane may rebind later on failure)."""
    devices = list(devices if devices is not None else jax.devices())
    if not devices:
        raise RuntimeError("no JAX devices")
    return [devices[i % len(devices)] for i in range(num_stages)]
