"""Compiled pipeline stages: the TPU-native worker executor.

Replaces the reference's per-worker Keras slice executor
(``/root/reference/src/node.py:40-45`` builds `model_from_json`+
`set_weights`; ``:177`` runs `model.predict` per request). Here a stage is
an XLA program: the stage's sub-DAG jit-compiled with its variables resident
on a specific device. "Configuring a worker" (reference: re-send JSON+weights
over TCP, ``src/dispatcher.py:223-264``) becomes placing the variable pytree
on the target device and reusing the jit cache — the compiled executable is
shared across devices of the same kind, so re-binding a stage to a new
device is a weight transfer, not a recompile (the <2 s recovery budget,
SURVEY.md §7.4).
"""

from __future__ import annotations

import dataclasses
from collections.abc import Mapping, Sequence
from typing import Any

import jax

from adapt_tpu.graph.ir import Variables
from adapt_tpu.graph.partition import PartitionPlan, StageSpec


@dataclasses.dataclass
class CompiledStage:
    """A stage bound to a device: jitted apply + device-resident variables.

    ``host_variables`` stays on host (the dispatcher-side master copy the
    reference keeps to reconfigure workers on demand); ``variables`` is the
    device copy actually used by ``__call__``.
    """

    spec: StageSpec
    fn: Any  # jitted (variables, x) -> y
    device: jax.Device
    variables: Mapping[str, Variables]

    def __call__(self, x: jax.Array) -> jax.Array:
        x = jax.device_put(x, self.device)
        return self.fn(self.variables, x)

    def rebind(self, device: jax.Device, host_variables) -> "CompiledStage":
        """Re-materialize this stage on another device (failure recovery /
        late binding). jit reuses the compiled executable for the new
        device; only weights move."""
        return CompiledStage(
            spec=self.spec,
            fn=self.fn,
            device=device,
            variables=jax.device_put(host_variables, device),
        )


def compile_stages(
    plan: PartitionPlan,
    variables: Mapping[str, Variables],
    devices: Sequence[jax.Device],
    donate_activations: bool = False,
) -> list[CompiledStage]:
    """Build one CompiledStage per plan stage, round-robin over devices.

    ``donate_activations``: donate the input activation buffer to XLA,
    saving HBM on large activations. Only enable when callers never reuse
    the arrays they pass in: donation aliases the caller's buffer whenever
    it already lives on the stage device (device_put is then a no-op), so a
    reused input would be a use-after-donate error.
    """
    if not devices:
        raise ValueError("no devices")
    stage_vars = plan.extract_variables(variables)
    out = []
    for spec, svars in zip(plan.stages, stage_vars):
        device = devices[spec.index % len(devices)]
        fn = jax.jit(
            plan.stage_apply(spec),
            donate_argnums=(1,) if donate_activations else (),
        )
        out.append(
            CompiledStage(
                spec=spec,
                fn=fn,
                device=device,
                variables=jax.device_put(svars, device),
            )
        )
    return out
