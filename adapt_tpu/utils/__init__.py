from adapt_tpu.utils.logging import get_logger
from adapt_tpu.utils.metrics import MetricsRegistry, global_metrics
from adapt_tpu.utils.tracing import Tracer, global_tracer

__all__ = [
    "get_logger",
    "MetricsRegistry",
    "global_metrics",
    "Tracer",
    "global_tracer",
]
