from adapt_tpu.utils.exporter import prometheus_text, serve_metrics
from adapt_tpu.utils.logging import get_logger
from adapt_tpu.utils.metrics import MetricsRegistry, global_metrics
from adapt_tpu.utils.tracing import (
    FlightRecorder,
    Tracer,
    global_flight_recorder,
    global_tracer,
)

__all__ = [
    "get_logger",
    "MetricsRegistry",
    "global_metrics",
    "prometheus_text",
    "serve_metrics",
    "FlightRecorder",
    "global_flight_recorder",
    "Tracer",
    "global_tracer",
]
