from adapt_tpu.utils.exporter import prometheus_text, serve_metrics
from adapt_tpu.utils.logging import get_logger
from adapt_tpu.utils.metrics import MetricsRegistry, global_metrics
from adapt_tpu.utils.profiling import (
    CompileSentinel,
    EngineObs,
    engine_collector,
    global_compile_sentinel,
    global_engine_obs,
    register_memory_source,
    unregister_memory_source,
)
from adapt_tpu.utils.telemetry import (
    FederatedStore,
    TelemetryReporter,
    assemble_request,
    global_federated_store,
)
from adapt_tpu.utils.tracing import (
    FlightRecorder,
    Tracer,
    global_flight_recorder,
    global_tracer,
)

__all__ = [
    "get_logger",
    "MetricsRegistry",
    "global_metrics",
    "prometheus_text",
    "serve_metrics",
    "FlightRecorder",
    "global_flight_recorder",
    "Tracer",
    "global_tracer",
    "CompileSentinel",
    "EngineObs",
    "engine_collector",
    "global_compile_sentinel",
    "global_engine_obs",
    "register_memory_source",
    "unregister_memory_source",
    "FederatedStore",
    "TelemetryReporter",
    "assemble_request",
    "global_federated_store",
]
