"""Checkpoint / resume (orbax-backed).

The reference has NO checkpointing (SURVEY.md §5): its nearest analog is
the dispatcher holding the full model in RAM and re-sending slices on
demand (``/root/reference/src/dispatcher.py:223-264``) plus retained
in-flight payloads (``:190-194``). Framework-owned upgrade, two layers:

- ``save_variables`` / ``restore_variables``: one pytree snapshot on disk
  (orbax StandardCheckpointer) with a JSON sidecar for framework metadata
  (model name, partition cuts, step) — enough to re-materialize a serving
  pipeline: restore host-side, hand to ``ServingPipeline``/``Dispatcher``
  which device_put stage slices as workers are configured.
- ``TrainCheckpointer``: step-numbered train state (params + opt_state)
  with retention and latest-step resume, for the training path
  (``adapt_tpu.parallel.train`` — beyond reference parity).

Restores are host-first by design: placement is the dispatcher's job
(late binding, SURVEY.md §2.7), so checkpoints stay mesh-shape-agnostic —
a checkpoint taken on an 8-chip mesh restores onto any survivor count.
"""

from __future__ import annotations

import json
import os
from typing import Any

import jax
import numpy as np

_META_NAME = "adapt_meta.json"


def _ocp():
    import orbax.checkpoint as ocp

    return ocp


def _to_host(tree: Any) -> Any:
    return jax.tree.map(np.asarray, tree)


def save_variables(
    path: str | os.PathLike,
    variables: Any,
    metadata: dict | None = None,
) -> None:
    """Write one pytree checkpoint (+ JSON metadata sidecar) at ``path``."""
    path = os.path.abspath(os.fspath(path))
    ocp = _ocp()
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, _to_host(variables), force=True)
    if metadata is not None:
        with open(os.path.join(path, _META_NAME), "w") as f:
            json.dump(metadata, f)


def restore_variables(
    path: str | os.PathLike, example: Any | None = None
) -> tuple[Any, dict]:
    """Restore (variables, metadata). ``example`` (a matching pytree of
    arrays or ShapeDtypeStructs) pins structure/dtypes; without it orbax
    restores the saved layout as plain numpy arrays."""
    path = os.path.abspath(os.fspath(path))
    ocp = _ocp()
    with ocp.StandardCheckpointer() as ckptr:
        if example is not None:
            target = jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(np.shape(a), a.dtype), example
            )
            variables = ckptr.restore(path, target)
        else:
            variables = ckptr.restore(path)
    meta_path = os.path.join(path, _META_NAME)
    metadata: dict = {}
    if os.path.exists(meta_path):
        with open(meta_path) as f:
            metadata = json.load(f)
    return variables, metadata


class TrainCheckpointer:
    """Step-numbered train-state checkpoints with retention + resume."""

    def __init__(self, directory: str | os.PathLike, max_to_keep: int = 3):
        ocp = _ocp()
        self._dir = os.path.abspath(os.fspath(directory))
        self._mngr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True
            ),
        )

    def save(self, step: int, params: Any, opt_state: Any) -> None:
        ocp = _ocp()
        self._mngr.save(
            step,
            args=ocp.args.StandardSave(
                {"params": _to_host(params), "opt_state": _to_host(opt_state)}
            ),
        )
        self._mngr.wait_until_finished()

    def latest_step(self) -> int | None:
        return self._mngr.latest_step()

    def restore(
        self, params_example: Any, opt_state_example: Any, step: int | None = None
    ) -> tuple[Any, Any, int]:
        """Restore (params, opt_state, step); latest step if not given."""
        ocp = _ocp()
        step = self._mngr.latest_step() if step is None else step
        if step is None:
            raise FileNotFoundError(f"no checkpoints under {self._dir}")
        target = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(np.shape(a), a.dtype),
            {"params": params_example, "opt_state": opt_state_example},
        )
        restored = self._mngr.restore(
            step, args=ocp.args.StandardRestore(target)
        )
        return restored["params"], restored["opt_state"], step

    def close(self) -> None:
        self._mngr.close()

    def __enter__(self) -> "TrainCheckpointer":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
