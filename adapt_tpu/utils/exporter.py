"""HTTP observability exporter: metrics, traces, flight-recorder events.

The observability surface SURVEY.md §5 calls for, made scrapeable: a
stdlib ``ThreadingHTTPServer`` serving

- ``GET /metrics`` — Prometheus text exposition with ``# HELP`` /
  ``# TYPE`` lines (counters as ``adapt_<name>_total``, gauges as
  ``adapt_<name>``, histograms as a ``summary`` family of ``_count`` /
  ``_sum`` plus p50/p99 gauges; dots in metric names become
  underscores),
- ``GET /metrics.json`` — the raw :meth:`MetricsRegistry.snapshot`,
- ``GET /trace.json`` — the :class:`~adapt_tpu.utils.tracing.Tracer`
  ring as Chrome trace-event JSON: save it (or fetch it with curl) and
  open in https://ui.perfetto.dev or ``chrome://tracing`` to see the
  serving timeline — per-stage spans, hop/compute overlap, and remote
  workers' stitched spans on their own process rows,
- ``GET /debug/events`` — the flight recorder's structured event ring
  (admissions, re-dispatches, quarantines, probe misses, recoveries),
- ``GET /healthz`` — ``{"ok": true}`` liveness.

Serving-side components (dispatcher, continuous batcher, gateway) all
write the shared :func:`adapt_tpu.utils.metrics.global_metrics`
registry, so one exporter per process covers them. Start with
``serve_metrics(port)`` (daemon thread, returns the server; ``port=0``
picks a free port — tests use that) and stop with ``.shutdown()``.

No reference analog: the reference's only telemetry is ``print()``
(SURVEY.md §5, ``/root/reference/src/dispatcher.py:129,147-150``).
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from adapt_tpu.utils.logging import get_logger
from adapt_tpu.utils.metrics import MetricsRegistry, global_metrics
from adapt_tpu.utils.tracing import (
    FlightRecorder,
    Tracer,
    global_flight_recorder,
    global_tracer,
)

log = get_logger("exporter")

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name: str) -> str:
    return "adapt_" + _NAME_RE.sub("_", name)


def _family(lines: list[str], name: str, mtype: str, help_: str) -> None:
    lines.append(f"# HELP {name} {help_}")
    lines.append(f"# TYPE {name} {mtype}")


def prometheus_text(snapshot: dict) -> str:
    """Render a :meth:`MetricsRegistry.snapshot` in the Prometheus text
    exposition format. Every sample family gets ``# HELP``/``# TYPE``
    lines (scrapers and promtool-style parsers want them); histograms
    render as a ``summary`` family (count/sum) plus percentile gauges —
    enough for dashboards without native histogram buckets."""
    lines: list[str] = []
    for name, value in sorted(snapshot.get("counters", {}).items()):
        pname = _prom_name(name) + "_total"
        _family(lines, pname, "counter", f"cumulative count of {name}")
        lines.append(f"{pname} {value}")
    for name, value in sorted(snapshot.get("gauges", {}).items()):
        pname = _prom_name(name)
        _family(lines, pname, "gauge", f"current value of {name}")
        lines.append(f"{pname} {value}")
    for name, summ in sorted(snapshot.get("histograms", {}).items()):
        base = _prom_name(name)
        _family(lines, base, "summary", f"distribution of {name}")
        lines.append(f"{base}_count {summ.get('count', 0)}")
        if summ.get("count"):
            lines.append(f"{base}_sum {summ['sum']}")
            for p in ("p50", "p99"):
                pname = f"{base}_{p}"
                _family(
                    lines, pname, "gauge", f"{p} of {name} (reservoir)"
                )
                lines.append(f"{pname} {summ[p]}")
    return "\n".join(lines) + "\n"


def serve_metrics(
    port: int = 9100,
    host: str = "127.0.0.1",
    registry: MetricsRegistry | None = None,
    tracer: Tracer | None = None,
    recorder: FlightRecorder | None = None,
) -> ThreadingHTTPServer:
    """Start the exporter on a daemon thread; returns the server
    (``.server_address[1]`` is the bound port). Stop with
    ``.shutdown()`` then ``.server_close()`` — shutdown alone stops the
    loop but leaks the listening socket. ``registry``/``tracer``/
    ``recorder`` default to the process-global ones."""
    reg = registry if registry is not None else global_metrics()
    tr = tracer if tracer is not None else global_tracer()
    rec = recorder if recorder is not None else global_flight_recorder()
    # Pull-side bridges: codec registers its copy-stats collector on the
    # GLOBAL registry at import; re-register it on the registry actually
    # being served, so custom-registry exporters (tests, multi-tenant
    # processes) get codec.copy_{bytes,calls} too. register_collector is
    # idempotent per function. Function-scoped import: utils must not
    # depend on comm at module level.
    from adapt_tpu.comm.codec import _copy_stats_collector

    reg.register_collector(_copy_stats_collector)
    # Engine-tier bridge (utils.profiling): memory gauges (KV strips,
    # draft caches, paged pool occupancy, backend HBM) + a compile-
    # sentinel sample per scrape, on the registry actually served.
    from adapt_tpu.utils.profiling import engine_collector

    reg.register_collector(engine_collector)

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — http.server API
            path = self.path.split("?", 1)[0]
            if path == "/metrics":
                body = prometheus_text(reg.snapshot()).encode()
                ctype = "text/plain; version=0.0.4"
            elif path == "/metrics.json":
                body = json.dumps(reg.snapshot()).encode()
                ctype = "application/json"
            elif path == "/trace.json":
                # default=str: one non-JSON span attr / event value
                # (numpy scalar, exception object) must degrade to its
                # repr, not turn every scrape into a 500.
                body = json.dumps(
                    tr.to_chrome_trace(), default=str
                ).encode()
                ctype = "application/json"
            elif path == "/debug/events":
                body = json.dumps(rec.snapshot(), default=str).encode()
                ctype = "application/json"
            elif path == "/healthz":
                body = b'{"ok": true}'
                ctype = "application/json"
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # scrapes are not log events
            pass

    server = ThreadingHTTPServer((host, port), Handler)
    thread = threading.Thread(
        target=server.serve_forever, name="metrics-exporter", daemon=True
    )
    thread.start()
    log.info("metrics exporter on %s:%d", host, server.server_address[1])
    return server
