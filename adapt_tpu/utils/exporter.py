"""HTTP metrics exporter: scrape the process's MetricsRegistry.

The observability surface SURVEY.md §5 calls for, made scrapeable: a
stdlib ``ThreadingHTTPServer`` serving

- ``GET /metrics`` — Prometheus text exposition (counters as
  ``adapt_<name>_total``, gauges as ``adapt_<name>``, histograms as
  ``_count`` / ``_sum`` plus p50/p99 gauges; dots in metric names become
  underscores),
- ``GET /metrics.json`` — the raw :meth:`MetricsRegistry.snapshot`,
- ``GET /healthz`` — ``{"ok": true}`` liveness.

Serving-side components (dispatcher, continuous batcher, gateway) all
write the shared :func:`adapt_tpu.utils.metrics.global_metrics`
registry, so one exporter per process covers them. Start with
``serve_metrics(port)`` (daemon thread, returns the server; ``port=0``
picks a free port — tests use that) and stop with ``.shutdown()``.

No reference analog: the reference's only telemetry is ``print()``
(SURVEY.md §5, ``/root/reference/src/dispatcher.py:129,147-150``).
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from adapt_tpu.utils.logging import get_logger
from adapt_tpu.utils.metrics import MetricsRegistry, global_metrics

log = get_logger("exporter")

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")


def _prom_name(name: str) -> str:
    return "adapt_" + _NAME_RE.sub("_", name)


def prometheus_text(snapshot: dict) -> str:
    """Render a :meth:`MetricsRegistry.snapshot` in the Prometheus text
    exposition format (one line per sample; histograms as count/sum +
    percentile gauges — enough for dashboards without native histogram
    buckets)."""
    lines: list[str] = []
    for name, value in sorted(snapshot.get("counters", {}).items()):
        lines.append(f"{_prom_name(name)}_total {value}")
    for name, value in sorted(snapshot.get("gauges", {}).items()):
        lines.append(f"{_prom_name(name)} {value}")
    for name, summ in sorted(snapshot.get("histograms", {}).items()):
        base = _prom_name(name)
        lines.append(f"{base}_count {summ.get('count', 0)}")
        if summ.get("count"):
            lines.append(f"{base}_sum {summ['sum']}")
            for p in ("p50", "p99"):
                lines.append(f"{base}_{p} {summ[p]}")
    return "\n".join(lines) + "\n"


def serve_metrics(
    port: int = 9100,
    host: str = "127.0.0.1",
    registry: MetricsRegistry | None = None,
) -> ThreadingHTTPServer:
    """Start the exporter on a daemon thread; returns the server
    (``.server_address[1]`` is the bound port). Stop with
    ``.shutdown()`` then ``.server_close()`` — shutdown alone stops the
    loop but leaks the listening socket. ``registry`` defaults to the
    process-global one."""
    reg = registry if registry is not None else global_metrics()

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — http.server API
            if self.path == "/metrics":
                body = prometheus_text(reg.snapshot()).encode()
                ctype = "text/plain; version=0.0.4"
            elif self.path == "/metrics.json":
                body = json.dumps(reg.snapshot()).encode()
                ctype = "application/json"
            elif self.path == "/healthz":
                body = b'{"ok": true}'
                ctype = "application/json"
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # scrapes are not log events
            pass

    server = ThreadingHTTPServer((host, port), Handler)
    thread = threading.Thread(
        target=server.serve_forever, name="metrics-exporter", daemon=True
    )
    thread.start()
    log.info("metrics exporter on %s:%d", host, server.server_address[1])
    return server
