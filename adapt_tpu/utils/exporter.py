"""HTTP observability exporter: metrics, traces, flight-recorder
events, fleet federation, per-request forensics.

The observability surface SURVEY.md §5 calls for, made scrapeable: a
stdlib ``ThreadingHTTPServer`` serving

- ``GET /metrics`` — Prometheus text exposition with ``# HELP`` /
  ``# TYPE`` lines (counters as ``adapt_<name>_total``, gauges as
  ``adapt_<name>``, histograms as a ``summary`` family of ``_count`` /
  ``_sum`` plus p50/p99 gauges; dots in metric names become
  underscores). Known dynamic name suffixes — per-tenant
  ``scheduler.queue_depth.<tenant>`` / ``slo.*_total.<tenant>``,
  per-source ``fleet.report_age_s.<source>`` — render as Prometheus
  LABELS (``adapt_scheduler_queue_depth{tenant="gold"}``), not baked
  into the metric name,
- ``GET /metrics.json`` — the raw :meth:`MetricsRegistry.snapshot`
  (non-finite floats sanitized to ``null`` — a NaN roofline gauge must
  not make the endpoint emit invalid JSON),
- ``GET /trace.json`` — the :class:`~adapt_tpu.utils.tracing.Tracer`
  ring as Chrome trace-event JSON (Perfetto / ``chrome://tracing``),
- ``GET /debug/events`` — the flight recorder's structured event ring,
- ``GET /debug/request/<id>`` — per-request FORENSICS: one bundle
  assembling the request's complete story across every federated
  source (``utils.telemetry.assemble_request``),
- ``GET /fleet/metrics`` / ``/fleet/metrics.json`` — the
  :class:`~adapt_tpu.utils.telemetry.FederatedStore` merged across
  every reporting process, Prometheus samples labeled
  ``role``/``worker``, fleet histogram percentiles merged from the
  sources' shipped reservoirs,
- ``GET /fleet/events`` — the merged, wall-clock-ordered flight
  stream across sources,
- ``GET /fleet/capacity`` — the merged capacity plane
  (``FederatedStore.capacity_snapshot``): per-replica headroom /
  TTFT-forecast / prefix-affinity-sketch / health books, labeled
  role/worker/pid with first-class staleness,
- ``GET /telemetry.json`` — this process's own
  ``TelemetryReporter.collect()`` body: the HTTP-PULL federation
  fallback for processes the dispatcher has no comm link to (advertise
  the URL in the worker's registry lease ``meta["telemetry"]``; one
  puller per endpoint — each GET returns the delta since the last),
- ``GET /healthz`` — ``{"ok": true, "pid": ..., "role": ...,
  "uptime_s": ...}`` liveness (the fields fleet liveness checks key
  on).

Serving-side components (dispatcher, continuous batcher, gateway) all
write the shared :func:`adapt_tpu.utils.metrics.global_metrics`
registry, so one exporter per process covers them. Start with
``serve_metrics(port)`` (daemon thread, returns the server; ``port=0``
picks a free port — tests use that) and stop with ``.shutdown()``.

No reference analog: the reference's only telemetry is ``print()``
(SURVEY.md §5, ``/root/reference/src/dispatcher.py:129,147-150``).
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from adapt_tpu.utils.logging import get_logger
from adapt_tpu.utils.metrics import MetricsRegistry, global_metrics
from adapt_tpu.utils.telemetry import (
    FederatedStore,
    TelemetryReporter,
    assemble_request,
    global_federated_store,
)
from adapt_tpu.utils.tracing import (
    FlightRecorder,
    Tracer,
    global_flight_recorder,
    global_tracer,
)

log = get_logger("exporter")

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")

#: Dotted-name families whose LAST component is a dynamic value
#: (tenant label, federation source key), not part of the metric's
#: identity. Baking the value into the Prometheus name
#: (``adapt_scheduler_queue_depth_gold``) makes every tenant a new
#: metric no dashboard can aggregate; these render as labels instead.
_LABEL_RULES: tuple[tuple[str, str], ...] = (
    ("scheduler.queue_depth.", "tenant"),
    ("slo.met_total.", "tenant"),
    ("slo.missed_total.", "tenant"),
    ("fleet.report_age_s.", "source"),
    ("fleet.events_lost.", "source"),
    ("fleet.reports_lost.", "source"),
)


def _prom_name(name: str) -> str:
    return "adapt_" + _NAME_RE.sub("_", name)


def _counter_name(base: str) -> str:
    """Counter family name: ``_total`` appended per convention, but
    never doubled for dotted names that already end in ``.total`` /
    ``_total`` (``slo.met_total`` must render ``adapt_slo_met_total``,
    not ``..._total_total``)."""
    pname = _prom_name(base)
    return pname if pname.endswith("_total") else pname + "_total"


def _split_labels(name: str) -> tuple[str, dict[str, str]]:
    """``scheduler.queue_depth.gold`` ->
    ``("scheduler.queue_depth", {"tenant": "gold"})``; unknown names
    pass through with no labels."""
    for prefix, label in _LABEL_RULES:
        if name.startswith(prefix) and len(name) > len(prefix):
            return name[: len(prefix) - 1], {label: name[len(prefix):]}
    return name, {}


def _esc_label(value: str) -> str:
    return (
        str(value)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _fmt_labels(labels: dict[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{k}="{_esc_label(v)}"' for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


class _PromDoc:
    """Accumulates samples grouped by family so ``# HELP``/``# TYPE``
    emit exactly once per family however many label combinations
    sample it (the exposition-format contract scrapers parse by)."""

    def __init__(self):
        #: family name -> (mtype, help, [sample lines])
        self._fams: dict[str, tuple[str, str, list[str]]] = {}

    def add(
        self,
        fname: str,
        mtype: str,
        help_: str,
        value,
        labels: dict[str, str] | None = None,
        sample: str | None = None,
    ) -> None:
        """One sample under family ``fname``. ``sample`` overrides the
        sample line's metric name (summary families emit
        ``<family>_count`` / ``<family>_sum`` under the family's own
        HELP/TYPE, per the exposition format)."""
        fam = self._fams.get(fname)
        if fam is None:
            fam = self._fams[fname] = (mtype, help_, [])
        if isinstance(value, float) and not math.isfinite(value):
            value = "NaN" if math.isnan(value) else (
                "+Inf" if value > 0 else "-Inf"
            )  # the text format HAS a spelling for these; JSON doesn't
        fam[2].append(
            f"{sample or fname}{_fmt_labels(labels or {})} {value}"
        )

    def render(self) -> str:
        lines: list[str] = []
        for fname in sorted(self._fams):
            mtype, help_, samples = self._fams[fname]
            lines.append(f"# HELP {fname} {help_}")
            lines.append(f"# TYPE {fname} {mtype}")
            lines.extend(samples)
        return "\n".join(lines) + "\n"


def prometheus_text(
    snapshot: dict, const_labels: dict[str, str] | None = None
) -> str:
    """Render a :meth:`MetricsRegistry.snapshot` in the Prometheus text
    exposition format. Every sample family gets ``# HELP``/``# TYPE``
    lines exactly once; histograms render as a ``summary`` family
    (count/sum) plus percentile gauges. Known dynamic suffixes become
    labels (see ``_LABEL_RULES``); ``const_labels`` (the fleet view's
    ``role``/``worker``) attach to every sample."""
    doc = _PromDoc()
    base_labels = dict(const_labels or {})
    for name, value in sorted(snapshot.get("counters", {}).items()):
        base, labels = _split_labels(name)
        doc.add(
            _counter_name(base),
            "counter",
            f"cumulative count of {base}",
            value,
            {**base_labels, **labels},
        )
    for name, value in sorted(snapshot.get("gauges", {}).items()):
        base, labels = _split_labels(name)
        doc.add(
            _prom_name(base),
            "gauge",
            f"current value of {base}",
            value,
            {**base_labels, **labels},
        )
    for name, summ in sorted(snapshot.get("histograms", {}).items()):
        base, labels = _split_labels(name)
        pname = _prom_name(base)
        lab = {**base_labels, **labels}
        help_ = f"distribution of {base}"
        doc.add(
            pname, "summary", help_, summ.get("count", 0), lab,
            sample=pname + "_count",
        )
        if summ.get("count"):
            doc.add(
                pname, "summary", help_, summ["sum"], lab,
                sample=pname + "_sum",
            )
            for p in ("p50", "p99"):
                if p in summ:
                    doc.add(
                        f"{pname}_{p}",
                        "gauge",
                        f"{p} of {base} (reservoir)",
                        summ[p],
                        lab,
                    )
    return doc.render()


def fleet_prometheus_text(fleet: dict) -> str:
    """Render a :meth:`FederatedStore.fleet_snapshot` as ONE
    Prometheus document: every source's counters/gauges/histogram
    count+sum labeled ``role``/``worker``/``pid``, per-source
    percentile gauges labeled the same, MERGED fleet percentiles (the
    union-of-reservoirs numbers) as the unlabeled series, and the
    staleness block as ``adapt_fleet_report_age_s{source=...}``."""
    doc = _PromDoc()
    for key, src in sorted(fleet.get("sources", {}).items()):
        lab = {
            "role": src["role"],
            "worker": src["worker"],
            "pid": str(src["pid"]),
        }
        for name, value in sorted(src.get("counters", {}).items()):
            base, extra = _split_labels(name)
            doc.add(
                _counter_name(base), "counter",
                f"cumulative count of {base} (federated)",
                value, {**lab, **extra},
            )
        for name, value in sorted(src.get("gauges", {}).items()):
            base, extra = _split_labels(name)
            doc.add(
                _prom_name(base), "gauge",
                f"current value of {base} (federated)",
                value, {**lab, **extra},
            )
        for name, summ in sorted(src.get("histograms", {}).items()):
            base, extra = _split_labels(name)
            pname = _prom_name(base)
            hl = {**lab, **extra}
            help_ = f"distribution of {base} (federated)"
            doc.add(
                pname, "summary", help_, summ.get("count", 0), hl,
                sample=pname + "_count",
            )
            doc.add(
                pname, "summary", help_, summ.get("sum", 0.0), hl,
                sample=pname + "_sum",
            )
            for p in ("p50", "p99"):
                if p in summ:
                    doc.add(
                        f"{pname}_{p}", "gauge",
                        f"{p} of {base} (per-source reservoir)",
                        summ[p], hl,
                    )
    merged = fleet.get("merged", {})
    for name, summ in sorted(merged.get("histograms", {}).items()):
        base, _ = _split_labels(name)
        pname = _prom_name(base)
        for p in ("p50", "p99"):
            if p in summ:
                doc.add(
                    f"{pname}_{p}", "gauge",
                    f"{p} of {base} (fleet-merged reservoirs)",
                    summ[p], None,
                )
    for key, age in sorted(fleet.get("staleness", {}).items()):
        doc.add(
            "adapt_fleet_report_age_s", "gauge",
            "seconds since each source's last telemetry report "
            "(a growing age = a wedged or dead source)",
            age, {"source": key},
        )
    doc.add(
        "adapt_fleet_sources", "gauge",
        "telemetry sources currently known to the federated store",
        len(fleet.get("sources", {})), None,
    )
    return doc.render()


def _sanitize(obj):
    """Recursively replace non-finite floats with None: ``json.dumps``
    spells them ``NaN``/``Infinity``, which is NOT JSON — one bad
    roofline gauge on an odd backend must not make every
    ``/metrics.json`` consumer's parser throw."""
    if isinstance(obj, float):
        return obj if math.isfinite(obj) else None
    if isinstance(obj, dict):
        return {k: _sanitize(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return [_sanitize(v) for v in obj]
    return obj


def _json_bytes(obj) -> bytes:
    # default=str: one non-JSON value (numpy scalar, exception object)
    # must degrade to its repr, not turn the scrape into a 500.
    return json.dumps(_sanitize(obj), default=str).encode()


def serve_metrics(
    port: int = 9100,
    host: str = "127.0.0.1",
    registry: MetricsRegistry | None = None,
    tracer: Tracer | None = None,
    recorder: FlightRecorder | None = None,
    store: FederatedStore | None = None,
    role: str = "server",
    worker: str | None = None,
    journal=None,
    capacity_provider=None,
    placements_provider=None,
) -> ThreadingHTTPServer:
    """Start the exporter on a daemon thread; returns the server
    (``.server_address[1]`` is the bound port). Stop with
    ``.shutdown()`` then ``.server_close()`` — shutdown alone stops the
    loop but leaks the listening socket. ``registry``/``tracer``/
    ``recorder``/``store`` default to the process-global ones.

    ``role``/``worker`` name this process in the fleet views (and
    ``/healthz``); the process registers itself as a LOCAL federation
    source, so ``/fleet/*`` always includes the serving process's own
    telemetry next to its workers'. ``journal`` (a
    ``control.journal.DispatcherJournal``) enriches
    ``/debug/request/<id>`` with submit metadata. ``capacity_provider``
    (zero-arg -> capacity book dict, e.g. a batcher's
    ``capacity_book``) makes this process a ``/fleet/capacity`` source
    and stamps the book onto ``/telemetry.json`` pulls.
    ``placements_provider`` (zero-arg -> dict, e.g. a
    ``runtime/router.FleetRouter``'s ``placements`` method) turns on
    ``GET /fleet/placements`` — the router's bounded decision ring:
    why each recent request landed on the replica it did."""
    reg = registry if registry is not None else global_metrics()
    tr = tracer if tracer is not None else global_tracer()
    rec = recorder if recorder is not None else global_flight_recorder()
    fed = store if store is not None else global_federated_store()
    # Pull-side bridges: codec registers its copy-stats collector on the
    # GLOBAL registry at import; re-register it on the registry actually
    # being served, so custom-registry exporters (tests, multi-tenant
    # processes) get codec.copy_{bytes,calls} too. register_collector is
    # idempotent per function. Function-scoped import: utils must not
    # depend on comm at module level.
    from adapt_tpu.comm.codec import _copy_stats_collector

    reg.register_collector(_copy_stats_collector)
    # Engine-tier bridge (utils.profiling): memory gauges (KV strips,
    # draft caches, paged pool occupancy, backend HBM) + a compile-
    # sentinel sample per scrape, on the registry actually served.
    from adapt_tpu.utils.profiling import engine_collector

    reg.register_collector(engine_collector)
    # Federation bridges: this process is itself a fleet source, and
    # the staleness gauges (fleet.report_age_s.<source>) land on the
    # served registry so a plain /metrics scrape sees a wedged worker.
    fed.attach_local(
        role, worker, registry=reg, recorder=rec, tracer=tr,
        capacity_provider=capacity_provider,
    )
    reg.register_collector(fed.collector)
    if journal is not None:
        fed.attach_journal(journal)
    #: One pull-fallback reporter for /telemetry.json — independent of
    #: the local-source reporter above (each keeps its own window and
    #: cursors, so the two consumers don't split each other's deltas).
    pull_reporter = TelemetryReporter(
        role,
        worker if worker is not None else f"pid{os.getpid()}",
        registry=reg,
        recorder=rec,
        tracer=tr,
    )
    pull_reporter.capacity_provider = capacity_provider
    t_start = time.monotonic()

    class Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — http.server API
            try:
                self._serve()
            except (BrokenPipeError, ConnectionResetError):
                # A scraper hanging up mid-body is the CLIENT's
                # problem; tracebacks per disconnect would spam the
                # serving process's stderr under flaky collectors.
                pass

        def _serve(self):
            path = self.path.split("?", 1)[0]
            if path == "/metrics":
                body = prometheus_text(reg.snapshot()).encode()
                ctype = "text/plain; version=0.0.4"
            elif path == "/metrics.json":
                body = _json_bytes(reg.snapshot())
                ctype = "application/json"
            elif path == "/trace.json":
                body = _json_bytes(tr.to_chrome_trace())
                ctype = "application/json"
            elif path == "/debug/events":
                body = _json_bytes(rec.snapshot())
                ctype = "application/json"
            elif path.startswith("/debug/request/"):
                try:
                    rid = int(path.rsplit("/", 1)[1])
                except ValueError:
                    self.send_response(404)
                    self.end_headers()
                    return
                body = _json_bytes(
                    assemble_request(
                        rid, store=fed, tracer=tr, journal=journal
                    )
                )
                ctype = "application/json"
            elif path == "/fleet/metrics":
                body = fleet_prometheus_text(
                    fed.fleet_snapshot()
                ).encode()
                ctype = "text/plain; version=0.0.4"
            elif path == "/fleet/metrics.json":
                body = _json_bytes(fed.fleet_snapshot())
                ctype = "application/json"
            elif path == "/fleet/events":
                fed.refresh()
                body = _json_bytes({"events": fed.events()})
                ctype = "application/json"
            elif path == "/fleet/capacity":
                # The capacity plane: per-replica books (headroom,
                # TTFT forecast, affinity sketch, health) labeled
                # role/worker/pid with first-class age_s staleness —
                # the router/autoscaler placement view.
                body = _json_bytes(fed.capacity_snapshot())
                ctype = "application/json"
            elif path == "/fleet/placements":
                # The router's decision ring: which replica each
                # recent request landed on and WHY (affinity tokens,
                # forecast, queue, health, the losing alternatives).
                # 404 when no router runs in this process — a fleet
                # endpoint must not fabricate an empty router.
                if placements_provider is None:
                    self.send_response(404)
                    self.end_headers()
                    return
                body = _json_bytes(placements_provider())
                ctype = "application/json"
            elif path == "/telemetry.json":
                body = _json_bytes(pull_reporter.collect())
                ctype = "application/json"
            elif path == "/healthz":
                body = _json_bytes(
                    {
                        "ok": True,
                        "pid": os.getpid(),
                        "role": role,
                        "uptime_s": round(
                            time.monotonic() - t_start, 3
                        ),
                    }
                )
                ctype = "application/json"
            else:
                self.send_response(404)
                self.end_headers()
                return
            self.send_response(200)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, fmt, *args):  # scrapes are not log events
            pass

    server = ThreadingHTTPServer((host, port), Handler)
    # server_close() also retires the pull reporter: its chained
    # snapshot window must not outlive the endpoint that drives it
    # (an orphaned window taxes every later observe() and can evict a
    # live load-harness phase window at _MAX_WINDOWS). The store's
    # local-source reporter is shared store state — the store's own
    # close() owns that one.
    orig_close = server.server_close

    def _close():
        orig_close()
        pull_reporter.close()

    server.server_close = _close
    thread = threading.Thread(
        target=server.serve_forever, name="metrics-exporter", daemon=True
    )
    thread.start()
    log.info("metrics exporter on %s:%d", host, server.server_address[1])
    return server
