"""Request/stage tracing + the serving flight recorder.

Absent from the reference (SURVEY.md §5: only per-task ``start_time``
stamps, ``src/dispatcher.py:193``). Three layers:

- :class:`Tracer` — a bounded pid/tid-aware span RING (oldest spans are
  overwritten, never silently dropped: ``spans_dropped`` counts them,
  mirrored into the metrics registry as ``tracer.spans_dropped``).
  Disabled tracing costs one branch per ``span()`` call. Spans convert
  to the Chrome trace-event JSON format (:meth:`Tracer.to_chrome_trace`)
  that Perfetto / ``chrome://tracing`` open directly — served by the
  exporter as ``GET /trace.json``.

- **Cross-process stitching** — spans recorded in a remote worker
  process are serialized against the WALL clock (:func:`export_spans`),
  ride back to the dispatcher as a flags-byte annex on the result frame
  (``comm.framing``), and :meth:`Tracer.ingest` merges them into the
  local ring keeping the remote pid/tid — so one ``/trace.json`` shows
  the whole request across processes, rows per process, correlated by
  the ``request``/``attempt`` span attrs (the same ids the framing
  header already carries).

- :class:`FlightRecorder` — a bounded structured-event ring for the
  fault-tolerance control plane (admissions, evictions, re-dispatches,
  quarantines, probe misses, recoveries). Always on (events are
  per-lifecycle, not per-token), dumped by the exporter as
  ``GET /debug/events`` and snapshotted to the journal directory on
  :meth:`Dispatcher.recover` — post-mortems stop depending on log
  scraping. Knobs: ``config.ObservabilityConfig``.

``ADAPT_TPU_TRACE=1`` in the environment enables the global tracer at
import — the switch a remote worker process (``python -m
adapt_tpu.comm.remote``) is enabled with, since no dispatcher-side
config reaches its constructor.

An optional bridge to ``jax.profiler`` (:meth:`Tracer.device_trace`)
covers XLA-level profiling on TPU; this module's spans are the
host/serving-path complement.
"""

from __future__ import annotations

import collections
import contextlib
import json
import os
import threading
import time
from dataclasses import dataclass, field

from adapt_tpu.utils.metrics import global_metrics

#: Wall-clock anchor: ``perf_counter() + _EPOCH_OFFSET ~= time.time()``.
#: Spans are recorded on the high-resolution perf clock and shifted onto
#: the epoch clock only at export/ingest — which is what lets spans from
#: two processes on one machine land on a shared timeline.
_EPOCH_OFFSET = time.time() - time.perf_counter()


@dataclass
class Span:
    name: str
    start: float
    end: float = 0.0
    attrs: dict = field(default_factory=dict)
    #: Origin thread (Chrome trace row). 0 is never a real ident.
    tid: int = 0
    #: Origin process; None = the owning tracer's process.
    pid: int | None = None
    #: Per-tracer monotonic record number (assigned at ``_record``):
    #: the cursor :meth:`Tracer.spans_since` pages the ring with, so
    #: the telemetry reporter ships each span exactly once even while
    #: the ring keeps evicting.
    seq: int = 0

    @property
    def duration(self) -> float:
        return self.end - self.start


class Tracer:
    """Bounded span ring. ``enabled`` is the one-branch hot-path guard;
    everything else (export, ingest, resize) is off-path."""

    def __init__(self, capacity: int = 65536):
        self._lock = threading.Lock()
        self._spans: collections.deque[Span] = collections.deque(
            maxlen=capacity
        )
        self._capacity = capacity
        self.enabled = False
        self.spans_dropped = 0
        self.pid = os.getpid()
        self._seq = 0  # monotonic record counter (spans_since cursor)

    def set_capacity(self, capacity: int) -> None:
        """Resize the ring, keeping the newest spans. No-op when the
        capacity is unchanged (so re-applying a config is free)."""
        if capacity == self._capacity:
            return
        with self._lock:
            self._spans = collections.deque(self._spans, maxlen=capacity)
            self._capacity = capacity

    def _record(self, s: Span) -> None:
        with self._lock:
            self._seq += 1
            s.seq = self._seq
            if len(self._spans) == self._capacity:
                # deque(maxlen) evicts the oldest on append — a RING, not
                # the old fill-once-then-drop-everything list. Count the
                # evictions so a saturated ring is visible on /metrics.
                self.spans_dropped += 1
                dropped = True
            else:
                dropped = False
            self._spans.append(s)
        if dropped:
            global_metrics().inc("tracer.spans_dropped")

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        if not self.enabled:
            yield None
            return
        s = Span(
            name=name,
            start=time.perf_counter(),
            attrs=attrs,
            tid=threading.get_ident(),
        )
        try:
            yield s
        finally:
            s.end = time.perf_counter()
            self._record(s)

    def add_span(
        self, name: str, start: float, end: float, **attrs
    ) -> None:
        """Record an interval timed by the caller (``time.perf_counter``
        values) — for spans whose begin and end live on different
        threads (e.g. dispatch -> result), where a context manager can't
        wrap the region."""
        if not self.enabled:
            return
        self._record(
            Span(
                name=name,
                start=start,
                end=end,
                attrs=attrs,
                tid=threading.get_ident(),
            )
        )

    def now(self) -> float:
        """The clock spans are recorded on (``time.perf_counter``)."""
        return time.perf_counter()

    def instant(self, name: str, **attrs) -> None:
        """Record a zero-duration marker event (e.g. the compile
        sentinel's ``engine.recompile``) — it renders in Perfetto as a
        point on the timeline next to the tick that paid for it."""
        if not self.enabled:
            return
        t = time.perf_counter()
        self._record(
            Span(
                name=name,
                start=t,
                end=t,
                attrs=attrs,
                tid=threading.get_ident(),
            )
        )

    def ingest(self, exported: list[dict]) -> None:
        """Merge spans exported by ANOTHER process (:func:`export_spans`
        dicts: wall-clock times + origin pid/tid) into this ring. Times
        shift back onto the local perf clock so one
        :meth:`to_chrome_trace` exports both processes on a shared
        timeline. Tolerant of garbage (a corrupt annex from a
        version-skewed peer must never take down the caller's read
        loop): non-list input and malformed entries are counted as
        ``tracer.ingest_rejected``, nothing raises."""
        if not isinstance(exported, list):
            global_metrics().inc("tracer.ingest_rejected")
            return
        for d in exported:
            try:
                self._record(
                    Span(
                        name=str(d["name"]),
                        start=float(d["t0"]) - _EPOCH_OFFSET,
                        end=float(d["t1"]) - _EPOCH_OFFSET,
                        attrs=dict(d.get("attrs", {})),
                        tid=int(d.get("tid", 0)),
                        pid=d.get("pid"),
                    )
                )
            except (AttributeError, KeyError, TypeError, ValueError):
                global_metrics().inc("tracer.ingest_rejected")

    def spans(self, name: str | None = None) -> list[Span]:
        with self._lock:
            return [
                s for s in self._spans if name is None or s.name == name
            ]

    def spans_since(self, seq: int) -> tuple[list[Span], int]:
        """Spans recorded after cursor ``seq`` (oldest first) plus the
        new cursor — the telemetry reporter's incremental read.
        Ring-eviction-safe: a span that fell out of the ring before a
        read is simply gone (``spans_dropped`` counts it); the cursor
        never re-delivers or skips survivors. Locally-recorded spans
        only — remote-ingested spans (``pid`` set) are the OTHER
        process's to report, and forwarding them would duplicate every
        span once per federation hop."""
        with self._lock:
            out = [
                s
                for s in self._spans
                if s.seq > seq and s.pid is None
            ]
            return out, self._seq

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def to_chrome_trace(self) -> dict:
        """The ring as a Chrome trace-event JSON object (the format
        Perfetto and ``chrome://tracing`` load): complete ``"X"`` events
        in microseconds on the wall clock, one ``pid`` per origin
        process (remote-ingested spans keep theirs), span attrs under
        ``args`` — so every event of one request shares
        ``args.request``."""
        with self._lock:
            spans = list(self._spans)
        events: list[dict] = []
        pids: set[int] = set()
        for s in spans:
            pid = s.pid if s.pid is not None else self.pid
            pids.add(pid)
            events.append(
                {
                    "name": s.name,
                    "ph": "X",
                    "cat": "serving",
                    "ts": (s.start + _EPOCH_OFFSET) * 1e6,
                    "dur": max(s.end - s.start, 0.0) * 1e6,
                    "pid": pid,
                    "tid": s.tid,
                    "args": dict(s.attrs),
                }
            )
        meta = [
            {
                "name": "process_name",
                "ph": "M",
                "ts": 0,
                "pid": pid,
                "tid": 0,
                "args": {
                    "name": (
                        f"adapt_tpu (pid {pid})"
                        if pid == self.pid
                        else f"adapt_tpu remote (pid {pid})"
                    )
                },
            }
            for pid in sorted(pids)
        ]
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}

    @contextlib.contextmanager
    def device_trace(self, logdir: str):
        """XLA-level profiling (TensorBoard-viewable) around a region."""
        import jax

        jax.profiler.start_trace(logdir)
        try:
            yield
        finally:
            jax.profiler.stop_trace()


def export_spans(spans: list[Span | None]) -> list[dict]:
    """Serialize spans for another process to :meth:`Tracer.ingest`:
    wall-clock times (comparable across processes on one machine) plus
    origin pid/tid. ``None`` entries (disabled-tracer spans) are
    skipped, so callers can pass ``[s]`` straight from a ``span()``
    block."""
    out = []
    for s in spans:
        if s is None:
            continue
        out.append(
            {
                "name": s.name,
                "t0": s.start + _EPOCH_OFFSET,
                "t1": s.end + _EPOCH_OFFSET,
                "pid": s.pid if s.pid is not None else os.getpid(),
                "tid": s.tid,
                "attrs": s.attrs,
            }
        )
    return out


class FlightRecorder:
    """Bounded ring of structured control-plane events.

    One ``record()`` is a timestamped dict append under a lock —
    cheap enough to leave ALWAYS on (writers are per-request/-fault
    lifecycle paths, never per-token). The ring holds the last
    ``capacity`` events; evictions are counted, not silent."""

    def __init__(self, capacity: int = 2048):
        self._lock = threading.Lock()
        self._events: collections.deque[dict] = collections.deque(
            maxlen=capacity
        )
        self._capacity = capacity
        self.events_dropped = 0
        self.enabled = True
        #: Lifetime count per event kind — survives ring eviction, so
        #: lifecycle-edge accounting (every admit has a finish/cancel)
        #: stays checkable after a storm overflows the ring.
        self._kind_counts: collections.Counter = collections.Counter()
        #: Per-process monotonic event number, stamped into every
        #: event as ``"seq"``: the :meth:`events_since` cursor, and —
        #: once events federate across processes (utils.telemetry) —
        #: what lets the merged stream detect per-source loss (a seq
        #: gap = events evicted before they shipped) instead of
        #: silently presenting a holey timeline as complete.
        self._seq = 0

    def set_capacity(self, capacity: int) -> None:
        if capacity == self._capacity:
            return
        with self._lock:
            self._events = collections.deque(
                self._events, maxlen=capacity
            )
            self._capacity = capacity

    def record(self, kind: str, **data) -> None:
        if not self.enabled:
            return
        ev = {"ts": time.time(), "kind": kind, "data": data}
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            if len(self._events) == self._capacity:
                self.events_dropped += 1
            self._events.append(ev)
            self._kind_counts[kind] += 1

    def events(self, kind: str | None = None) -> list[dict]:
        with self._lock:
            return [
                e for e in self._events if kind is None or e["kind"] == kind
            ]

    def events_since(self, seq: int) -> tuple[list[dict], int]:
        """Events recorded after cursor ``seq`` (oldest first) plus
        the new cursor — the telemetry reporter's incremental read.
        Events evicted from the ring before a read are lost to the
        stream (the receiver sees the seq gap); the cursor never
        re-delivers a survivor."""
        with self._lock:
            return (
                [e for e in self._events if e["seq"] > seq],
                self._seq,
            )

    def kind_counts(self) -> dict[str, int]:
        """Lifetime event count per kind, INDEPENDENT of ring eviction:
        a cancel storm that overflows the ring still balances its books
        here (admits == finishes when drained — the lifecycle-edge
        invariant the storm tests pin)."""
        with self._lock:
            return dict(self._kind_counts)

    def snapshot(self) -> dict:
        """JSON-ready dump (the ``GET /debug/events`` body)."""
        with self._lock:
            return {
                "capacity": self._capacity,
                "dropped": self.events_dropped,
                "kind_counts": dict(self._kind_counts),
                "events": list(self._events),
            }

    def snapshot_to(self, path: str) -> str:
        """Write :meth:`snapshot` to ``path`` (post-mortem artifact —
        ``Dispatcher.recover`` drops one beside the journal).
        ``default=str``: a writer that recorded a non-JSON value (numpy
        scalar, exception object) degrades that field to its repr — a
        post-mortem dump must never itself raise."""
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.snapshot(), f, indent=1, default=str)
        return path

    def clear(self) -> None:
        with self._lock:
            self._events.clear()
            self._kind_counts.clear()


_GLOBAL = Tracer()
#: Truthy-only spellings enable: "ADAPT_TPU_TRACE=off"/"=no" must NOT
#: silently turn span recording on in every worker process.
_GLOBAL.enabled = os.environ.get("ADAPT_TPU_TRACE", "").lower() in (
    "1",
    "true",
    "yes",
    "on",
)

_FLIGHT = FlightRecorder()


def global_tracer() -> Tracer:
    return _GLOBAL


def global_flight_recorder() -> FlightRecorder:
    return _FLIGHT
