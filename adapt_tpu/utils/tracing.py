"""Request/stage tracing.

Absent from the reference (SURVEY.md §5: only per-task ``start_time``
stamps, ``src/dispatcher.py:193``). Provides span recording for the serving
path plus an optional bridge to ``jax.profiler`` traces for XLA-level
profiling on TPU.
"""

from __future__ import annotations

import contextlib
import threading
import time
from dataclasses import dataclass, field


@dataclass
class Span:
    name: str
    start: float
    end: float = 0.0
    attrs: dict = field(default_factory=dict)

    @property
    def duration(self) -> float:
        return self.end - self.start


class Tracer:
    def __init__(self, capacity: int = 65536):
        self._lock = threading.Lock()
        self._spans: list[Span] = []
        self._capacity = capacity
        self.enabled = False

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        if not self.enabled:
            yield None
            return
        s = Span(name=name, start=time.perf_counter(), attrs=attrs)
        try:
            yield s
        finally:
            s.end = time.perf_counter()
            with self._lock:
                if len(self._spans) < self._capacity:
                    self._spans.append(s)

    def spans(self, name: str | None = None) -> list[Span]:
        with self._lock:
            return [
                s for s in self._spans if name is None or s.name == name
            ]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    @contextlib.contextmanager
    def device_trace(self, logdir: str):
        """XLA-level profiling (TensorBoard-viewable) around a region."""
        import jax

        jax.profiler.start_trace(logdir)
        try:
            yield
        finally:
            jax.profiler.stop_trace()


_GLOBAL = Tracer()


def global_tracer() -> Tracer:
    return _GLOBAL
