"""Structured logging.

The reference's observability is bare ``print()`` (e.g.
``/root/reference/src/dispatcher.py:129,147-150,198``). Framework-owned
replacement: stdlib logging with a compact single-line formatter carrying
component + key=value fields, quiet by default (WARNING) so the serving hot
path never blocks on stdout; ``ADAPT_TPU_LOG=debug`` to turn up.
"""

from __future__ import annotations

import logging
import os
import sys

_CONFIGURED = False


def _configure_root() -> None:
    global _CONFIGURED
    if _CONFIGURED:
        return
    level = os.environ.get("ADAPT_TPU_LOG", "warning").upper()
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        logging.Formatter(
            "%(asctime)s.%(msecs)03d %(levelname).1s %(name)s: %(message)s",
            datefmt="%H:%M:%S",
        )
    )
    root = logging.getLogger("adapt_tpu")
    root.addHandler(handler)
    root.setLevel(getattr(logging, level, logging.WARNING))
    root.propagate = False
    _CONFIGURED = True


def get_logger(component: str) -> logging.Logger:
    _configure_root()
    return logging.getLogger(f"adapt_tpu.{component}")


def _kv_value(v) -> str:
    """One field value, quoted when unquoted rendering would be
    unparseable: spaces or ``=`` inside a bare value make ``a=x y=1``
    ambiguous to any key=value splitter, so such values (and ones
    carrying quotes/newlines, or the empty string) render as a
    double-quoted, backslash-escaped token."""
    s = str(v)
    if s and not any(
        c in s for c in (" ", "=", '"', "\\", "\n", "\r", "\t")
    ):
        return s
    s = s.replace("\\", "\\\\").replace('"', '\\"')
    s = s.replace("\n", "\\n").replace("\r", "\\r").replace("\t", "\\t")
    return f'"{s}"'


def kv(**fields) -> str:
    """Render key=value fields for structured log lines. Values that
    would break the line's key=value grammar are quoted
    (:func:`_kv_value`), so ``kv(msg="send failed", peer="a=b")`` stays
    machine-splittable on unquoted whitespace."""
    return " ".join(f"{k}={_kv_value(v)}" for k, v in fields.items())
