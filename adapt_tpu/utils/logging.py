"""Structured logging.

The reference's observability is bare ``print()`` (e.g.
``/root/reference/src/dispatcher.py:129,147-150,198``). Framework-owned
replacement: stdlib logging with a compact single-line formatter carrying
component + key=value fields, quiet by default (WARNING) so the serving hot
path never blocks on stdout; ``ADAPT_TPU_LOG=debug`` to turn up.
"""

from __future__ import annotations

import logging
import os
import sys

_CONFIGURED = False


def _configure_root() -> None:
    global _CONFIGURED
    if _CONFIGURED:
        return
    level = os.environ.get("ADAPT_TPU_LOG", "warning").upper()
    handler = logging.StreamHandler(sys.stderr)
    handler.setFormatter(
        logging.Formatter(
            "%(asctime)s.%(msecs)03d %(levelname).1s %(name)s: %(message)s",
            datefmt="%H:%M:%S",
        )
    )
    root = logging.getLogger("adapt_tpu")
    root.addHandler(handler)
    root.setLevel(getattr(logging, level, logging.WARNING))
    root.propagate = False
    _CONFIGURED = True


def get_logger(component: str) -> logging.Logger:
    _configure_root()
    return logging.getLogger(f"adapt_tpu.{component}")


def kv(**fields) -> str:
    """Render key=value fields for structured log lines."""
    return " ".join(f"{k}={v}" for k, v in fields.items())
