"""Engine-tier observability: compile sentinel, memory accounting,
roofline (MBU/MFU) accounting, tick-phase timing.

PR 2 made the *request* tier visible (timelines, stitched spans, flight
recorder); this module watches the *engine* underneath — the things that
silently destroy TPU serving performance without ever failing a test:

- :class:`CompileSentinel` — a registry of the serving hot-path jit
  entry points (the continuous tick's decode/verify programs, the
  admission setters, ``draft_chunk``, pipeline stage fns, the pipelined
  decoder's per-stage programs). Each :meth:`~CompileSentinel.sample`
  reads every registered program's jit cache size, exports it as an
  ``engine.compiles.<program>`` gauge, and — after a configurable
  warmup — treats ANY growth as an unintended recompile: it bumps the
  ``engine.compile_events`` counter, records a ``recompile`` flight-
  recorder event, logs a WARNING, and drops a zero-duration tracer
  event so the recompile lands in the Perfetto timeline next to the
  tick that paid for it. Static-shape serving (the Mesh-TensorFlow
  discipline) makes "the cache grew" a precise proxy for "a tick just
  stalled on XLA"; re-registering a program (every batcher constructor
  does) re-arms its warmup, because jit caches key on ``self`` and a
  new instance legitimately compiles its own first variants.

- **Memory accounting** — pull-style: components register themselves as
  weakly-held sources (:func:`register_memory_source`) exposing a
  ``_memory_stats() -> {metric: value}`` dict, and
  :func:`engine_collector` (hooked into ``MetricsRegistry.snapshot`` /
  the exporter, like the codec copy-stats bridge) sums them at scrape
  time into ``memory.*`` gauges: dense KV strip bytes, draft-cache
  bytes, paged pool occupancy (``memory.pages_{used,free,cached}`` +
  ``memory.pool_pages``/``pool_bytes``), the pager's prefix-cache
  effectiveness counters (``paged.prefix_{hits,misses}``), and — when
  a hierarchical cache tier is configured — the host-DRAM tier's
  occupancy (``memory.host_bytes`` encoded-resident bytes,
  ``memory.pages_spilled`` host-resident pages; the per-event
  ``cache_tier.*_total`` counters land at their event sites in
  ``runtime/continuous``, not here). Sharded
  components report BOTH logical and per-device bytes
  (``memory.kv_bytes_per_device`` / ``memory.pool_bytes_per_device``
  via :func:`device_local_nbytes`) — under tensor parallelism the
  logical size alone would read as if the whole cache lived on one
  chip. When the
  backend provides ``device.memory_stats()`` (TPU/GPU; CPU does not),
  ``memory.hbm_bytes_in_use`` / ``memory.hbm_bytes_limit`` ride along.
  Sources are weakrefs: a retired batcher drops out of the gauges with
  its arrays, never pinned by telemetry.

- **Roofline accounting** — how close the engine runs to the hardware
  ceiling, from numbers the system already has: components register as
  weakly-held roofline sources (:func:`register_roofline_source`)
  exposing ``_roofline_stats() -> {program: {flops, bytes, wall_s}}``
  — flops/bytes come from XLA's own ``cost_analysis()`` of the watched
  executables (lowered once, lazily; no recompile, no jit-cache
  growth), wall seconds from the :class:`EngineObs` phase timing the
  tick loop already records. :func:`engine_collector` turns them into
  ``engine.flops.<program>`` / ``engine.bytes_accessed.<program>``
  gauges always, and — when the platform's peak numbers are known
  (:func:`roofline_peaks`: TPU table mirroring
  ``benchmarks/tpu_models.py``, or the ``ADAPT_TPU_PEAK_FLOPS`` /
  ``ADAPT_TPU_PEAK_BYTES_S`` env overrides) — ``engine.mfu.<program>``
  / ``engine.mbu.<program>`` plus headline ``engine.mfu`` /
  ``engine.mbu`` taken from the byte-heaviest program (the one whose
  stream defines the decode roofline). The CPU backend exports
  bytes/flops WITHOUT utilization claims — there is no honest CPU
  "peak" to divide by.

- :class:`EngineObs` — the one-branch gate for per-phase tick timing
  (``config.ObservabilityConfig.obs_engine``). Enabled, each serving
  phase (admit / prefill / draft / verify / decode / commit / update in
  ``ContinuousBatcher.tick``; stage / hop in ``LocalPipeline.stream``)
  records an ``engine.phase.<name>_s`` histogram sample and, when the
  tracer is on, a span — ``benchmarks/micro/obs_overhead.py`` measures
  the enabled cost against the <5% tick budget. Disabled (default),
  every phase site costs exactly one attribute check.

Catalog + semantics: ``docs/OBSERVABILITY.md`` "Engine telemetry".
"""

from __future__ import annotations

import math
import os
import threading
import time
import weakref
from collections.abc import Callable

from adapt_tpu.utils.logging import get_logger, kv
from adapt_tpu.utils.metrics import MetricsRegistry, global_metrics
from adapt_tpu.utils.tracing import global_flight_recorder, global_tracer

log = get_logger("profiling")


# -- compile sentinel -------------------------------------------------------


class _Watch:
    __slots__ = ("size_fn", "last", "samples", "expected")

    def __init__(self, size_fn: Callable[[], int]):
        self.size_fn = size_fn
        self.last: int | None = None
        self.samples = 0
        #: Outstanding EXPECTED-compile allowance (:meth:`rearm`):
        #: post-warmup growth is absorbed against it, one executable
        #: per unit, before anything is flagged as unexpected.
        self.expected = 0


class CompileSentinel:
    """Watches registered jit entry points for unexpected recompiles.

    ``register(name, fn)`` takes any jit-wrapped callable (jax exposes
    the executable-cache size as ``fn._cache_size()``) or an explicit
    0-arg ``size_fn`` (which may return ``None`` to say "my owner is
    gone" — the watch is then pruned). :meth:`sample` is called once
    per serving tick (and at every exporter scrape via
    :func:`engine_collector`): cheap — one cache-size read per program
    under one lock, plus one gauge write per program on the sampled
    registry (every registry that samples gets the full
    ``engine.compiles.*`` family, not just the one that happened to see
    a change).

    Warmup counts ACTIVE samples only — samples where the program has
    compiled at least once (size > 0). A program registered at startup
    and then scraped for an hour while the serve loop sits idle keeps
    its full grace window: its first real compiles are expected, not
    flagged. After ``warmup_samples`` active samples, any growth is an
    unintended recompile (counter + flight event + WARNING + tracer
    instant event). Growth during warmup still moves the gauge, so the
    expected variant count is visible too.

    One watch per name; re-registering re-arms the warmup and replaces
    the size_fn (latest instance wins — right for class-level shared
    jit caches, where a fresh ``self`` legitimately compiles new
    entries; per-instance program families should register ONE
    aggregate size_fn over their live instances —
    :func:`aggregate_size_fn` builds one). Event DETECTION happens
    once, against the sentinel's own cumulative state; every sampling
    registry's ``engine.compile_events`` counter is then synced up to
    that cumulative count, so a custom registry served by the exporter
    reports the same events as the process registry the ticks drive."""

    def __init__(self, warmup_samples: int = 8):
        if warmup_samples < 0:
            raise ValueError(
                f"warmup_samples must be >= 0, got {warmup_samples}"
            )
        self._lock = threading.Lock()
        self._watches: dict[str, _Watch] = {}
        self.warmup_samples = warmup_samples
        self._events = 0
        #: Per-registry high-water mark of events already inc'd there
        #: (weak keys: the sentinel must not pin test registries).
        self._synced: "weakref.WeakKeyDictionary[MetricsRegistry, int]" = (
            weakref.WeakKeyDictionary()
        )
        #: Tombstones of pruned watches: every sample clears their
        #: stale ``engine.compiles.*`` gauge from the sampled registry
        #: (a retired program must not scrape as still-compiled).
        #: Bounded by the set of program names ever watched.
        self._pruned: set[str] = set()

    def register(
        self,
        name: str,
        fn=None,
        *,
        size_fn: Callable[[], int] | None = None,
    ) -> None:
        """Watch ``name``. Re-registering (same or different fn) re-arms
        the warmup window — constructors re-register their class-level
        jits precisely because a fresh ``self`` legitimately compiles
        fresh cache entries."""
        if size_fn is None:
            if fn is None or not hasattr(fn, "_cache_size"):
                raise TypeError(
                    f"{name}: need a jit-wrapped fn (with _cache_size) "
                    "or an explicit size_fn"
                )
            size_fn = fn._cache_size
        with self._lock:
            w = _Watch(size_fn)
            prev = self._watches.get(name)
            if prev is not None:
                # An outstanding expected-compile allowance (rearm)
                # survives re-registration: a second instance's
                # construction must not erase the first one's pending
                # planned re-lowering and turn it into a false alarm.
                w.expected = prev.expected
            self._watches[name] = w
            self._pruned.discard(name)

    def unregister(self, name: str) -> None:
        with self._lock:
            if self._watches.pop(name, None) is not None:
                self._pruned.add(name)

    def rearm(self, name: str, expect: int = 1) -> None:
        """Grant ``name`` an allowance of ``expect`` EXPECTED compiles
        — for planned re-lowering events. Elastic mesh recovery
        re-lowers every program family against the shrunk mesh, but
        lazily (stage_slot on the next admission, a prefill bucket on
        its next use — possibly long after any warmup window would
        have re-closed), so the allowance is consumed whenever the
        growth actually lands: the next ``expect`` new executables are
        absorbed without an event, and anything beyond them is the
        phantom-variant alarm the sentinel exists for. Unknown names
        are a no-op (a spec-less batcher re-arms no draft watch).

        Caveat, same as re-registration's warmup re-arm: watches on
        class-level shared jits see every live instance, so an
        allowance granted for one batcher's recovery can absorb
        another's growth until consumed — grant only compiles the
        caller is confident will land (the batcher scopes its grants
        to the program families it actually dispatches)."""
        with self._lock:
            w = self._watches.get(name)
            if w is not None:
                w.expected += expect

    def disarm(self, name: str, expect: int = 1) -> None:
        """Revoke up to ``expect`` units of ``name``'s outstanding
        allowance (clamped at zero; unknown names are a no-op). A
        granter that retires before its planned re-lowering lands MUST
        call this with its full grant — consumed units are already
        subtracted, so the clamp removes exactly the leftover — or the
        slack survives on the shared class-level watch and silently
        absorbs another instance's REAL phantom variant. With
        concurrent granters the clamp can bite into another's pending
        allowance (same shared-watch caveat as :meth:`rearm`): the
        failure direction is a spurious alarm, never a masked one."""
        with self._lock:
            w = self._watches.get(name)
            if w is not None:
                w.expected = max(0, w.expected - expect)

    def watched(self) -> list[str]:
        with self._lock:
            return list(self._watches)

    def compiles(self, name: str) -> int:
        """Current executable-cache size of one watched program — the
        public replacement for poking ``fn._cache_size()`` in tests."""
        with self._lock:
            size = self._watches[name].size_fn()
        if size is None:
            raise KeyError(f"{name}: watched program's owner is gone")
        return int(size)

    def counts(self) -> dict[str, int]:
        """Current cache size of every watched program (one consistent
        read pass; programs whose size_fn raises — or whose owner is
        gone — are skipped)."""
        out = {}
        with self._lock:
            for name, w in self._watches.items():
                try:
                    size = w.size_fn()
                except Exception:  # noqa: BLE001 — a probe must not raise
                    continue
                if size is not None:
                    out[name] = int(size)
        return out

    @property
    def events(self) -> int:
        """Lifetime count of unexpected post-warmup compiles (summed
        new executables across all programs) — the cumulative value
        every sampling registry's ``engine.compile_events`` counter
        converges to."""
        with self._lock:
            return self._events

    def sample(
        self,
        registry: MetricsRegistry | None = None,
        *,
        write_gauges: bool = True,
    ) -> int:
        """One sentinel pass over every watched program. Returns the
        number of unexpected-recompile events fired. ``registry``
        defaults to the process-global one (the exporter passes the
        registry actually being scraped). ``write_gauges=False`` is the
        hot tick path's detection-only mode: it skips the per-program
        gauge writes and tombstone cleanup (one registry-lock acquire
        each), which every scrape refreshes anyway via
        :func:`engine_collector` — detection, the event counter sync
        and the flight/log/tracer side effects still run."""
        reg = registry if registry is not None else global_metrics()
        fired: list[tuple[str, int, int]] = []  # (name, size, delta)
        sizes: list[tuple[str, int]] = []
        dead: list[str] = []
        with self._lock:
            for name, w in self._watches.items():
                try:
                    raw = w.size_fn()
                except Exception:  # noqa: BLE001 — a sick probe is skipped
                    continue
                if raw is None:  # owner retired: prune the watch
                    dead.append(name)
                    continue
                size = int(raw)
                sizes.append((name, size))
                # Warmup advances only while the program is ACTIVE
                # (compiled at least once): idle-process scrapes must
                # not burn the grace window before the first request.
                warmed = w.samples >= self.warmup_samples
                if size > 0:
                    w.samples += 1
                if w.last is None or size == w.last:
                    w.last = size
                    continue
                delta = size - w.last
                w.last = size
                if delta > 0 and warmed and w.expected > 0:
                    # Planned re-lowering (rearm): absorb the expected
                    # executables; only the excess can fire. Warmup-
                    # covered growth is already silent and must NOT
                    # spend the allowance — the planned compile it was
                    # banked for may land later, post-warmup.
                    absorbed = min(delta, w.expected)
                    w.expected -= absorbed
                    delta -= absorbed
                if delta > 0 and warmed:
                    fired.append((name, size, delta))
                    self._events += delta
            for name in dead:
                del self._watches[name]
            self._pruned.update(dead)
            tombstones = list(self._pruned)
            # Sync this registry's counter to the cumulative event
            # count: detection is sentinel-global, so a registry that
            # was not the one sampling when an event fired still
            # converges to the same engine.compile_events total.
            behind = self._events - self._synced.get(reg, 0)
            if behind > 0:
                self._synced[reg] = self._events
        # Registry / recorder / tracer writes happen outside the
        # sentinel lock (each has its own locking; no nesting). Gauges
        # are written unconditionally: a registry that samples less
        # often than the ticking one must still serve current values.
        if behind > 0:
            reg.inc("engine.compile_events", float(behind))
        if write_gauges:
            for name, size in sizes:
                reg.set_gauge(f"engine.compiles.{name}", float(size))
            for name in tombstones:
                # A retired program must not scrape as still-compiled.
                reg.remove_gauge(f"engine.compiles.{name}")
        tracer = global_tracer()
        for name, size, delta in fired:
            global_flight_recorder().record(
                "recompile", program=name, compiles=size, new=delta
            )
            log.warning(
                "unexpected recompile %s",
                kv(program=name, compiles=size, new=delta),
            )
            if tracer.enabled:
                tracer.instant("engine.recompile", program=name, new=delta)
        return len(fired)


def snapshot_weak(owners) -> list:
    """Snapshot a WeakSet that another thread may be ``add()``-ing to:
    WeakSet iteration is Python-level, so even ``list(owners)`` can
    raise ``RuntimeError: Set changed size during iteration`` when a
    constructor registers concurrently with an exporter scrape.
    Bounded retries; a PERSISTENT race re-raises — callers in sentinel
    size_fns deliberately let it escape, because the sentinel skips a
    watch whose probe raises (sample untouched, retried next pass),
    whereas returning an empty/zero snapshot would be misread as "no
    owners" (pruning a live watch) or "cache size 0" (arming a false
    recompile event on recovery)."""
    last_err = None
    for _ in range(4):
        try:
            return list(owners)
        except RuntimeError as e:
            last_err = e
    raise last_err


def aggregate_size_fn(owners, extract: Callable) -> Callable:
    """Build a sentinel ``size_fn`` that SUMS a per-owner cache size
    over a weakly-held owner collection (one shared watch per program
    name — a second live instance aggregates instead of silently
    replacing the first's watch, and a collected owner drops out).

    ``extract(owner) -> int | None`` returns the owner's cache size for
    the watched program, or None when the owner does not carry it
    (e.g. a pipeline with fewer stages). When NO live owner matches,
    the size_fn returns None and the sentinel prunes the watch."""

    def size_fn():
        sizes = [
            s
            for s in (extract(o) for o in snapshot_weak(owners))
            if s is not None
        ]
        if not sizes:
            return None
        return sum(sizes)

    return size_fn


_SENTINEL = CompileSentinel()


def global_compile_sentinel() -> CompileSentinel:
    return _SENTINEL


# -- memory accounting ------------------------------------------------------


def device_local_nbytes(x) -> int:
    """PER-DEVICE bytes of one (possibly sharded) array: the shard
    shape's bytes, i.e. global nbytes divided by the mesh factors on
    every sharded axis. This is the number that matters for HBM
    capacity planning under tensor parallelism — a tp-sharded KV cache's
    ``nbytes`` is the LOGICAL size, which would read as if the whole
    cache lived on one chip. Plain numpy / unsharded arrays just return
    ``nbytes``."""
    sharding = getattr(x, "sharding", None)
    if sharding is None:
        return int(x.nbytes)
    try:
        shard = sharding.shard_shape(x.shape)
    except Exception:  # noqa: BLE001 — exotic shardings: logical bytes
        return int(x.nbytes)
    return int(math.prod(shard)) * x.dtype.itemsize

#: Weakly-held memory sources: (label, id) -> object exposing
#: ``_memory_stats() -> {metric_name: value}``. Weak values: a retired
#: batcher (and its device arrays) must never be pinned by telemetry.
_MEMORY_SOURCES: "weakref.WeakValueDictionary[tuple[str, int], object]" = (
    weakref.WeakValueDictionary()
)
_MEMORY_LOCK = threading.Lock()
#: Per-registry set of memory gauge names the collector wrote on its
#: previous pass: names that stop being produced (their sources
#: retired — e.g. a closed paged batcher's pool gauges) are REMOVED
#: from that registry instead of serving their last value forever.
_MEMORY_WRITTEN: "weakref.WeakKeyDictionary[MetricsRegistry, set]" = (
    weakref.WeakKeyDictionary()
)


def register_memory_source(label: str, obj) -> None:
    """Register ``obj`` (anything with ``_memory_stats() -> dict``) as a
    pull-style memory source. Held by weakref; keyed by ``(label,
    id(obj))`` so several batchers coexist and gauges SUM across the
    live ones. NOTE: a source whose own jit caches pin it (a batcher —
    ``static_argnums=(0,)`` holds ``self`` strongly) is never collected
    by GC, so retiring such a component must call
    :func:`unregister_memory_source` (``ContinuousBatcher.close``
    does), or the replaced instance keeps summing into the gauges."""
    if not hasattr(obj, "_memory_stats"):
        raise TypeError(f"{label}: source must expose _memory_stats()")
    with _MEMORY_LOCK:
        _MEMORY_SOURCES[(label, id(obj))] = obj


def unregister_memory_source(label: str, obj) -> None:
    """Drop ``obj`` from the gauge sums (idempotent). For components
    whose jit caches pin them alive — explicit retirement is the only
    way their bytes leave the gauges."""
    with _MEMORY_LOCK:
        _MEMORY_SOURCES.pop((label, id(obj)), None)


def _device_memory_stats() -> dict[str, float]:
    """``memory.hbm_*`` from the backend, when it reports them (TPU/GPU
    backends do; CPU returns None/raises — then nothing is exported,
    rather than a lying zero)."""
    try:
        import jax

        stats = jax.local_devices()[0].memory_stats()
    except Exception:  # noqa: BLE001 — no backend / no stats: no gauges
        return {}
    if not stats:
        return {}
    out = {}
    if "bytes_in_use" in stats:
        out["memory.hbm_bytes_in_use"] = float(stats["bytes_in_use"])
    if "bytes_limit" in stats:
        out["memory.hbm_bytes_limit"] = float(stats["bytes_limit"])
    return out


def engine_collector(reg: MetricsRegistry) -> None:
    """The engine-tier pull hook (``register_collector`` style, like the
    codec copy-stats bridge): runs at every snapshot/scrape. Sums each
    registered memory source's ``_memory_stats()`` into gauges, adds
    backend HBM stats when available, and runs one compile-sentinel
    sample so a scrape sees fresh ``engine.compiles.*`` gauges even
    between ticks."""
    totals: dict[str, float] = {}
    with _MEMORY_LOCK:
        sources = list(_MEMORY_SOURCES.values())
    for obj in sources:
        try:
            stats = obj._memory_stats()
        except Exception:  # noqa: BLE001 — one sick source must not kill scrape
            continue
        for k, v in stats.items():
            totals[k] = totals.get(k, 0.0) + float(v)
    totals.update(_device_memory_stats())
    # Roofline gauges ride the same write/stale-cleanup pass: a
    # retired batcher's engine.flops.*/mbu/mfu entries disappear with
    # its memory gauges instead of scraping stale forever.
    totals.update(_roofline_gauges())
    # Kernel-vs-oracle dispatch gauges (ops/decode_attention records
    # every dispatcher resolution at trace time): the
    # ``_kernel_supported`` fallback to the XLA oracle used to be
    # SILENT — a perf cliff invisible in metrics. 1.0 = the op's most
    # recent lowering took the Pallas kernel, 0.0 = the oracle; the
    # per-path lifetime counts ride along so a mixed history (some
    # programs on each path) is visible too.
    try:
        from adapt_tpu.ops.decode_attention import kernel_dispatch_stats

        for op, d in kernel_dispatch_stats().items():
            totals[f"engine.kernel_dispatch.{op}"] = d["last"]
            totals[f"engine.kernel_dispatch.{op}.pallas_total"] = (
                d["pallas"]
            )
            totals[f"engine.kernel_dispatch.{op}.xla_total"] = d["xla"]
    except Exception:  # noqa: BLE001 — never break a scrape
        pass
    for k, v in totals.items():
        reg.set_gauge(k, v)
    # Gauges whose every source retired since the last pass (a closed
    # paged batcher's pool gauges, a vanished draft cache) are removed,
    # not served stale forever.
    with _MEMORY_LOCK:
        stale = _MEMORY_WRITTEN.get(reg, set()) - set(totals)
        _MEMORY_WRITTEN[reg] = set(totals)
    for k in stale:
        reg.remove_gauge(k)
    _SENTINEL.sample(reg)


# Pull-side default: the process registry scrapes engine state without
# any component having to push (the exporter re-registers this on
# whichever registry it actually serves; register_collector is
# idempotent per function object).
global_metrics().register_collector(engine_collector)


# -- roofline accounting ----------------------------------------------------

#: Peak (FLOP/s, HBM bytes/s) per device KIND (``device.device_kind``,
#: lowercased) with a bare-platform fallback row — the denominators of
#: MFU/MBU. Generation rows are the published bf16 peak FLOP/s and HBM
#: bandwidth: v4 275 TF / 1.23 TB/s, v5e 197 TF / 819 GB/s (mirroring
#: ``benchmarks/tpu_models.py`` TPU_V5E_PEAK_FLOPS and the
#: ``benchmarks/README.md`` decode-MBU model), v5p 459 TF / 2.77 TB/s,
#: v6e (Trillium) 918 TF / 1.64 TB/s. The bare ``"tpu"`` row keeps the
#: historical v5e default for kinds not listed (override via the env
#: knobs below). Platforms absent here (CPU!) get NO mfu/mbu gauges —
#: flops and bytes export alone, because dividing by a made-up peak
#: would manufacture a utilization number.
ROOFLINE_PEAKS: dict[str, tuple[float, float]] = {
    "tpu": (197e12, 8.19e11),
    "tpu v4": (275e12, 1.2288e12),
    "tpu v5e": (197e12, 8.19e11),
    "tpu v5 lite": (197e12, 8.19e11),
    "tpu v5p": (459e12, 2.765e12),
    "tpu v5": (459e12, 2.765e12),
    "tpu v6e": (918e12, 1.64e12),
    "tpu v6 lite": (918e12, 1.64e12),
}


def roofline_peaks() -> tuple[float, float] | None:
    """(peak FLOP/s, peak bytes/s) for the current backend, or None
    when no honest peak is known. Resolution order: the
    ``ADAPT_TPU_PEAK_FLOPS`` / ``ADAPT_TPU_PEAK_BYTES_S`` env vars
    override everything (set BOTH — the knob for unlisted hardware,
    and what lets tests exercise the mfu/mbu math on the CPU backend
    with explicit, visible peaks); otherwise the device KIND row
    (``jax.local_devices()[0].device_kind``, lowercased — v4/v5e/v5p/
    v6e each have their own peaks), falling back to the bare platform
    row. Catalog: ``docs/OBSERVABILITY.md`` "Roofline gauges"."""
    env_f = os.environ.get("ADAPT_TPU_PEAK_FLOPS")
    env_b = os.environ.get("ADAPT_TPU_PEAK_BYTES_S")
    if env_f and env_b:
        try:
            return (float(env_f), float(env_b))
        except ValueError:
            return None
    try:
        import jax

        dev = jax.local_devices()[0]
        platform = dev.platform
        kind = str(getattr(dev, "device_kind", "") or "").lower()
    except Exception:  # noqa: BLE001 — no backend: no claims
        return None
    if kind in ROOFLINE_PEAKS:
        return ROOFLINE_PEAKS[kind]
    return ROOFLINE_PEAKS.get(platform)


#: Weakly-held roofline sources: (label, id) -> object exposing
#: ``_roofline_stats() -> {program: {"flops": F, "bytes": B,
#: "wall_s": seconds-per-execution | None}}``. Same lifetime rules as
#: the memory sources (a batcher's jit caches pin it — retire via
#: :func:`unregister_roofline_source`, ``ContinuousBatcher.close``
#: does).
_ROOFLINE_SOURCES: "weakref.WeakValueDictionary[tuple[str, int], object]" = (
    weakref.WeakValueDictionary()
)


def register_roofline_source(label: str, obj) -> None:
    """Register ``obj`` (anything with ``_roofline_stats() -> dict``)
    as a pull-style roofline source (weakref; several sources coexist,
    later-registered same-program entries win)."""
    if not hasattr(obj, "_roofline_stats"):
        raise TypeError(f"{label}: source must expose _roofline_stats()")
    with _MEMORY_LOCK:
        _ROOFLINE_SOURCES[(label, id(obj))] = obj


def unregister_roofline_source(label: str, obj) -> None:
    """Drop ``obj`` from the roofline gauges (idempotent)."""
    with _MEMORY_LOCK:
        _ROOFLINE_SOURCES.pop((label, id(obj)), None)


def program_cost_analysis(jit_fn, *args, **kwargs) -> dict[str, float]:
    """``{"flops": F, "bytes": B}`` for ONE execution of ``jit_fn`` at
    the given arguments, from XLA's own ``cost_analysis()`` on the
    LOWERED module — no compile, no execution, and crucially no growth
    of the jit's executable cache (sentinel-checked in tests: pulling
    roofline numbers must never itself read as a recompile). Arguments
    may be real arrays or ``jax.ShapeDtypeStruct``s — only shapes and
    dtypes matter. Raises on backends whose lowering or analysis is
    unavailable; callers cache and degrade."""
    ca = jit_fn.lower(*args, **kwargs).cost_analysis()
    if isinstance(ca, list):  # some backends return one dict per device
        ca = ca[0] if ca else {}
    return {
        "flops": float(ca.get("flops", 0.0)),
        "bytes": float(ca.get("bytes accessed", 0.0)),
    }


def _roofline_gauges() -> dict[str, float]:
    """Compute the roofline gauge family from the registered sources:
    per-program flops/bytes always; per-program + headline MFU/MBU only
    when the platform peak is known AND the program has a measured wall
    time (``EngineObs`` phase timing — enable ``obs_engine`` to get
    utilization numbers)."""
    with _MEMORY_LOCK:
        sources = list(_ROOFLINE_SOURCES.values())
    out: dict[str, float] = {}
    peaks = roofline_peaks()
    best_bytes = -1.0
    for obj in sources:
        try:
            stats = obj._roofline_stats()
        except Exception:  # noqa: BLE001 — a sick source must not kill scrape
            continue
        for prog, st in stats.items():
            flops = float(st.get("flops", 0.0))
            nbytes = float(st.get("bytes", 0.0))
            out[f"engine.flops.{prog}"] = flops
            out[f"engine.bytes_accessed.{prog}"] = nbytes
            wall = st.get("wall_s")
            if peaks is None or not wall:
                continue
            peak_f, peak_b = peaks
            mfu = flops / wall / peak_f
            mbu = nbytes / wall / peak_b
            out[f"engine.mfu.{prog}"] = mfu
            out[f"engine.mbu.{prog}"] = mbu
            if nbytes > best_bytes:
                # Headline = the byte-heaviest program: its stream is
                # what the decode roofline is made of.
                best_bytes = nbytes
                out["engine.mfu"] = mfu
                out["engine.mbu"] = mbu
    return out


# -- tick-phase timing ------------------------------------------------------


class EngineObs:
    """Process-global gate for per-phase engine timing.

    ``enabled`` is the one branch every phase site pays when off (the
    ``obs_timeline`` pattern). On, :meth:`phase` records one
    ``engine.phase.<name>_s`` histogram sample (one registry-lock hold)
    and, when the global tracer is enabled, an ``engine.<name>`` span —
    so tick phases land in the same Perfetto timeline as the request
    spans. Enable via ``ObservabilityConfig(obs_engine=True)`` (applied
    when a Dispatcher is constructed) or directly:
    ``global_engine_obs().enabled = True``."""

    __slots__ = ("enabled", "last_s")

    def __init__(self):
        self.enabled = False
        #: Most recent wall seconds per phase name — the per-execution
        #: denominator the roofline gauges divide flops/bytes by (a
        #: dict write per phase sample; no lock: single writer per
        #: phase, readers tolerate one-sample staleness).
        self.last_s: dict[str, float] = {}

    @staticmethod
    def now() -> float:
        return time.perf_counter()

    def phase(
        self, name: str, t0: float, *, span: bool = True, **attrs
    ) -> float:
        """Close phase ``name`` opened at ``t0``; returns the close time
        (the next phase's open). ``span=False`` for sites that already
        record their own tracer span (``LocalPipeline``'s stage/hop)."""
        t1 = time.perf_counter()
        self.last_s[name] = t1 - t0
        global_metrics().observe(f"engine.phase.{name}_s", t1 - t0)
        if span:
            tracer = global_tracer()
            if tracer.enabled:
                tracer.add_span(
                    f"engine.{name}", start=t0, end=t1, **attrs
                )
        return t1


_ENGINE_OBS = EngineObs()


def global_engine_obs() -> EngineObs:
    return _ENGINE_OBS
