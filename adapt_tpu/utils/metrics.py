"""Counters, gauges, latency histograms.

The reference measures exactly one thing: wall-clock req/s in the driver
(``/root/reference/test/test.py:25,34-37``). The framework exports the
metrics SURVEY.md §5 calls for: req/s, per-stage latency, recovery time,
re-dispatch counts — cheap, lock-guarded, snapshot-able.
"""

from __future__ import annotations

import threading
from collections import defaultdict


class _Histogram:
    __slots__ = ("count", "total", "min", "max", "_samples")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self._samples: list[float] = []  # reservoir, capped

    def observe(self, v: float) -> None:
        self.count += 1
        self.total += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        if len(self._samples) < 4096:
            self._samples.append(v)

    def percentile(self, p: float) -> float:
        if not self._samples:
            return 0.0
        s = sorted(self._samples)
        idx = min(len(s) - 1, int(p / 100.0 * len(s)))
        return s[idx]

    def summary(self) -> dict:
        if self.count == 0:
            return {"count": 0}
        # ONE sort for every percentile: summary() runs under the
        # registry lock (snapshot()), and a scrape must not stall
        # serving-path observe() calls on repeated reservoir sorts.
        s = sorted(self._samples)

        def pct(p):
            return s[min(len(s) - 1, int(p / 100.0 * len(s)))]

        return {
            "count": self.count,
            "sum": self.total,
            "mean": self.total / self.count,
            "min": self.min,
            "max": self.max,
            "p50": pct(50),
            "p99": pct(99),
        }


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, float] = defaultdict(float)
        self._gauges: dict[str, float] = {}
        self._histograms: dict[str, _Histogram] = defaultdict(_Histogram)

    def inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            self._histograms[name].observe(value)

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {
                    k: h.summary() for k, h in self._histograms.items()
                },
            }

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


_GLOBAL = MetricsRegistry()


def global_metrics() -> MetricsRegistry:
    return _GLOBAL
