"""Counters, gauges, latency histograms.

The reference measures exactly one thing: wall-clock req/s in the driver
(``/root/reference/test/test.py:25,34-37``). The framework exports the
metrics SURVEY.md §5 calls for: req/s, per-stage latency, recovery time,
re-dispatch counts — cheap, lock-guarded, snapshot-able.

Percentiles come from a DETERMINISTIC DECIMATING reservoir: the sample
buffer is bounded, and when it fills, every other retained sample is
dropped and the sampling stride doubles — so the reservoir always spans
the histogram's whole history (early and late observations alike) in
bounded memory. A keep-the-first-N reservoir freezes p50/p99 at the
warm-up distribution forever; this one shifts as traffic shifts
(``tests/test_observability.py`` pins that).

``register_collector`` hooks pull-style sources (module counters like
``comm.codec.copy_stats``) into :meth:`snapshot`: collectors run at
scrape time, right before the snapshot is taken, so ``/metrics`` shows
their current values without a push on every hot-path mutation.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from collections.abc import Callable, Iterable


class _Histogram:
    #: Reservoir cap: when full, every other sample is discarded and the
    #: sampling stride doubles (memory stays O(cap), coverage stays the
    #: whole stream).
    _CAP = 4096

    __slots__ = ("count", "total", "min", "max", "_samples", "_stride",
                 "_skip")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self._samples: list[float] = []  # decimating reservoir, capped
        self._stride = 1  # keep every _stride-th observation
        self._skip = 0  # observations left to skip before the next keep

    def observe(self, v: float) -> None:
        self.count += 1
        self.total += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        # Deterministic decimation: unlike keep-first-N (which freezes
        # percentiles at the warm-up distribution), every epoch of the
        # stream stays represented at equal stride.
        if self._skip:
            self._skip -= 1
            return
        self._samples.append(v)
        if len(self._samples) >= self._CAP:
            del self._samples[::2]  # halve, oldest-first interleaved
            self._stride *= 2
        self._skip = self._stride - 1

    def percentile(self, p: float) -> float:
        if not self._samples:
            return 0.0
        s = sorted(self._samples)
        idx = min(len(s) - 1, int(p / 100.0 * len(s)))
        return s[idx]

    def summary(self) -> dict:
        if self.count == 0:
            return {"count": 0}
        # ONE sort for every percentile: summary() runs under the
        # registry lock (snapshot()), and a scrape must not stall
        # serving-path observe() calls on repeated reservoir sorts.
        s = sorted(self._samples)

        def pct(p):
            return s[min(len(s) - 1, int(p / 100.0 * len(s)))]

        return {
            "count": self.count,
            "sum": self.total,
            "mean": self.total / self.count,
            "min": self.min,
            "max": self.max,
            "p50": pct(50),
            "p99": pct(99),
        }


class MetricsRegistry:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, float] = defaultdict(float)
        self._gauges: dict[str, float] = {}
        self._histograms: dict[str, _Histogram] = defaultdict(_Histogram)
        self._collectors: list[Callable[["MetricsRegistry"], None]] = []

    def inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def remove_gauge(self, name: str) -> None:
        """Drop one gauge so snapshots stop serving its last value —
        for sources that disappear (e.g. a retired program's
        ``engine.compiles.*`` entry). No-op when absent."""
        with self._lock:
            self._gauges.pop(name, None)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            self._histograms[name].observe(value)

    def observe_many(self, name: str, values: Iterable[float]) -> None:
        """Batch observe under ONE lock acquisition — the serving paths
        (per-token inter-token latencies) flush a tick's samples in one
        call instead of contending per token."""
        values = list(values)
        if not values:
            return
        with self._lock:
            h = self._histograms[name]
            for v in values:
                h.observe(v)

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def register_collector(
        self, fn: Callable[["MetricsRegistry"], None]
    ) -> None:
        """Register a pull hook run at the top of every :meth:`snapshot`
        (outside the lock — collectors call ``set_gauge``/``inc``
        themselves). Idempotent per function object."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def snapshot(self) -> dict:
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn(self)
            except Exception:  # noqa: BLE001 — a scrape must not fail
                pass
        with self._lock:
            return {
                "counters": dict(self._counters),
                "gauges": dict(self._gauges),
                "histograms": {
                    k: h.summary() for k, h in self._histograms.items()
                },
            }

    def reset(self) -> None:
        """Clear all recorded values (collectors stay registered)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()


_GLOBAL = MetricsRegistry()


def global_metrics() -> MetricsRegistry:
    return _GLOBAL
