"""Counters, gauges, latency histograms.

The reference measures exactly one thing: wall-clock req/s in the driver
(``/root/reference/test/test.py:25,34-37``). The framework exports the
metrics SURVEY.md §5 calls for: req/s, per-stage latency, recovery time,
re-dispatch counts — cheap, lock-guarded, snapshot-able.

Percentiles come from a DETERMINISTIC DECIMATING reservoir: the sample
buffer is bounded, and when it fills, every other retained sample is
dropped and the sampling stride doubles — so the reservoir always spans
the histogram's whole history (early and late observations alike) in
bounded memory. A keep-the-first-N reservoir freezes p50/p99 at the
warm-up distribution forever; this one shifts as traffic shifts
(``tests/test_observability.py`` pins that).

``register_collector`` hooks pull-style sources (module counters like
``comm.codec.copy_stats``) into :meth:`snapshot`: collectors run at
scrape time, right before the snapshot is taken, so ``/metrics`` shows
their current values without a push on every hot-path mutation.

**Windowed snapshots** (``docs/OBSERVABILITY.md`` "Workload
telemetry"): cumulative-since-boot percentiles are useless for "what
was p99 TTFT during *this* load phase" — the warm-up phase's samples
never leave the reservoir. ``snapshot(window=True)`` opens a WINDOW: a
per-histogram decimating-reservoir FORK that receives every subsequent
observation in parallel with the cumulative reservoir.
``snapshot(since=prev)`` then closes ``prev``'s window and returns the
window's view — counter DELTAS against ``prev`` and histogram
summaries computed from the fork alone (percentile isolation: a
window's p99 contains only the window's samples). Phase-by-phase
chaining passes ``window=True`` with every read that has a next phase
(``s = reg.snapshot(window=True); ...;
s = reg.snapshot(since=s, window=True)``); the final read omits it,
so a finished sweep leaves NO open window behind. Hot-path cost:
zero when no window is open (one truthiness
check under the already-held lock); one extra reservoir append per
open window otherwise. Open windows are bounded (``_MAX_WINDOWS``,
oldest evicted) so an abandoned window can never leak observations
forever.
"""

from __future__ import annotations

import threading
import time
from collections import defaultdict
from collections.abc import Callable, Iterable


class _Histogram:
    #: Reservoir cap: when full, every other sample is discarded and the
    #: sampling stride doubles (memory stays O(cap), coverage stays the
    #: whole stream).
    _CAP = 4096

    __slots__ = ("count", "total", "min", "max", "_samples", "_stride",
                 "_skip")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self._samples: list[float] = []  # decimating reservoir, capped
        self._stride = 1  # keep every _stride-th observation
        self._skip = 0  # observations left to skip before the next keep

    def observe(self, v: float) -> None:
        self.count += 1
        self.total += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        # Deterministic decimation: unlike keep-first-N (which freezes
        # percentiles at the warm-up distribution), every epoch of the
        # stream stays represented at equal stride.
        if self._skip:
            self._skip -= 1
            return
        self._samples.append(v)
        if len(self._samples) >= self._CAP:
            del self._samples[::2]  # halve, oldest-first interleaved
            self._stride *= 2
        self._skip = self._stride - 1

    def percentile(self, p: float) -> float:
        if not self._samples:
            return 0.0
        s = sorted(self._samples)
        idx = min(len(s) - 1, int(p / 100.0 * len(s)))
        return s[idx]

    def summary(self, reservoir: bool = False) -> dict:
        if self.count == 0:
            return {"count": 0}
        # ONE sort for every percentile: summary() runs under the
        # registry lock (snapshot()), and a scrape must not stall
        # serving-path observe() calls on repeated reservoir sorts.
        s = sorted(self._samples)

        def pct(p):
            return s[min(len(s) - 1, int(p / 100.0 * len(s)))]

        out = {
            "count": self.count,
            "sum": self.total,
            "mean": self.total / self.count,
            "min": self.min,
            "max": self.max,
            "p50": pct(50),
            "p99": pct(99),
        }
        if reservoir:
            # The raw decimating reservoir (every sample stands for
            # ``stride`` observations) — what lets ANOTHER process
            # merge this histogram's percentiles with its own honestly
            # (utils.telemetry federation) instead of averaging
            # pre-computed p99s, which has no meaning.
            out["reservoir"] = {
                "samples": list(self._samples),
                "stride": self._stride,
            }
        return out


class MetricsRegistry:
    #: Max concurrently open snapshot windows; opening past it evicts
    #: the OLDEST window (its ``snapshot(since=...)`` read then falls
    #: back to cumulative summaries, flagged ``window_evicted``) so an
    #: abandoned window cannot make every observe() pay forever.
    _MAX_WINDOWS = 8

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[str, float] = defaultdict(float)
        self._gauges: dict[str, float] = {}
        self._histograms: dict[str, _Histogram] = defaultdict(_Histogram)
        self._collectors: list[Callable[["MetricsRegistry"], None]] = []
        #: Open snapshot windows: id -> {histogram name -> fork}.
        #: Forks are ordinary decimating reservoirs created lazily at
        #: the first in-window observation of each histogram.
        self._windows: dict[int, dict[str, _Histogram]] = {}
        self._next_window = 0

    def inc(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value

    def set_gauge(self, name: str, value: float) -> None:
        with self._lock:
            self._gauges[name] = value

    def remove_gauge(self, name: str) -> None:
        """Drop one gauge so snapshots stop serving its last value —
        for sources that disappear (e.g. a retired program's
        ``engine.compiles.*`` entry). No-op when absent."""
        with self._lock:
            self._gauges.pop(name, None)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            self._histograms[name].observe(value)
            if self._windows:
                for forks in self._windows.values():
                    f = forks.get(name)
                    if f is None:
                        f = forks[name] = _Histogram()
                    f.observe(value)

    def observe_many(self, name: str, values: Iterable[float]) -> None:
        """Batch observe under ONE lock acquisition — the serving paths
        (per-token inter-token latencies) flush a tick's samples in one
        call instead of contending per token."""
        values = list(values)
        if not values:
            return
        with self._lock:
            h = self._histograms[name]
            for v in values:
                h.observe(v)
            if self._windows:
                for forks in self._windows.values():
                    f = forks.get(name)
                    if f is None:
                        f = forks[name] = _Histogram()
                    for v in values:
                        f.observe(v)

    def counter(self, name: str) -> float:
        with self._lock:
            return self._counters.get(name, 0.0)

    def register_collector(
        self, fn: Callable[["MetricsRegistry"], None]
    ) -> None:
        """Register a pull hook run at the top of every :meth:`snapshot`
        (outside the lock — collectors call ``set_gauge``/``inc``
        themselves). Idempotent per function object."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def snapshot(
        self,
        *,
        window: bool = False,
        since: dict | None = None,
        reservoirs: bool = False,
    ) -> dict:
        """Point-in-time view of every metric.

        Plain ``snapshot()`` (the exporter's scrape) is unchanged:
        cumulative counters, current gauges, whole-history histogram
        summaries — and costs nothing on the observe() hot path.

        ``window=True`` additionally OPENS a window: the returned dict
        carries a ``"window"`` id and every later observation also
        lands in that window's per-histogram reservoir forks.

        ``since=prev`` (``prev`` a ``window=True`` snapshot) returns
        the WINDOW view instead: ``counters`` are deltas vs ``prev``,
        ``histograms`` summarize only the samples observed since
        ``prev`` (fork reservoirs — percentile isolation between
        phases), ``gauges`` stay current values (a gauge has no
        meaningful delta), and ``window_s`` is the wall-clock span.
        The read CLOSES ``prev``'s window; pass ``window=True``
        alongside ``since=`` to open the next phase's window in the
        same call (phase chaining) — a plain ``since=`` read opens
        nothing, so one-shot callers cannot leak open windows that
        every later observe() would pay for. Reading a window that was
        evicted (``_MAX_WINDOWS`` exceeded) or never opened raises
        ``ValueError`` for the latter and degrades to cumulative
        summaries flagged ``"window_evicted": True`` for the former —
        a load sweep must notice, not silently report boot-cumulative
        percentiles as a phase's.

        ``reservoirs=True`` adds each histogram summary's raw
        decimating reservoir (``{"samples", "stride"}``) — the
        serialized form the telemetry federation layer ships so fleet
        percentiles merge from real samples, not from other
        processes' pre-computed percentiles."""
        with self._lock:
            collectors = list(self._collectors)
        for fn in collectors:
            try:
                fn(self)
            except Exception:  # noqa: BLE001 — a scrape must not fail
                pass
        if since is not None and "window" not in since:
            raise ValueError(
                "snapshot(since=...) needs a snapshot taken with "
                "window=True (or a previous since= snapshot)"
            )
        with self._lock:
            out: dict = {"gauges": dict(self._gauges)}
            if since is None:
                out["counters"] = dict(self._counters)
                out["histograms"] = {
                    k: h.summary(reservoir=reservoirs)
                    for k, h in self._histograms.items()
                }
            else:
                prev_counters = since.get("counters", {})
                base = since.get("_abs_counters", prev_counters)
                out["counters"] = {
                    k: v - base.get(k, 0.0)
                    for k, v in self._counters.items()
                }
                forks = self._windows.pop(since["window"], None)
                if forks is None:
                    out["histograms"] = {
                        k: h.summary(reservoir=reservoirs)
                        for k, h in self._histograms.items()
                    }
                    out["window_evicted"] = True
                else:
                    out["histograms"] = {
                        k: f.summary(reservoir=reservoirs)
                        for k, f in forks.items()
                    }
                out["window_s"] = time.monotonic() - since["_t"]
            if window:
                wid = self._next_window
                self._next_window += 1
                self._windows[wid] = {}
                while len(self._windows) > self._MAX_WINDOWS:
                    self._windows.pop(next(iter(self._windows)))
                out["window"] = wid
                out["_t"] = time.monotonic()
                #: Absolute counter values at window open — the delta
                #: base for the NEXT since= read (out["counters"] may
                #: itself already be a delta).
                out["_abs_counters"] = dict(self._counters)
            return out

    def reset(self) -> None:
        """Clear all recorded values (collectors stay registered; open
        windows are discarded)."""
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._histograms.clear()
            self._windows.clear()


_GLOBAL = MetricsRegistry()


def global_metrics() -> MetricsRegistry:
    return _GLOBAL
