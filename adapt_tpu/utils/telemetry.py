"""Fleet telemetry federation + per-request forensics.

The stack is multi-process — remote stage workers (``comm/remote``),
the disaggregated prefill tier (``runtime/disagg``), and next a whole
replica fleet — but until this module every telemetry surface except
trace spans was per-process: each exporter served only its own
``MetricsRegistry``, flight recorders were private rings, and "what
happened to request X" meant hand-joining ``/debug/events`` across N
processes. The paper's own architecture makes the dispatcher the
star-topology control point with an etcd membership registry
(PAPER.md §0); that is the natural aggregation point, and this module
is the aggregation:

- :class:`TelemetryReporter` — one per process: each
  :meth:`~TelemetryReporter.collect` produces a JSON-serializable
  **report** holding the registry's windowed snapshot delta since the
  previous report (the PR-7 window API — counters as deltas,
  histograms as *this window's* decimating reservoir, so nothing is
  double-counted downstream), the flight events recorded since the
  last report (each carrying the recorder's per-process monotonic
  ``seq``, so loss is a visible gap, never a silent hole), and the
  tracer spans recorded since (``export_spans`` wall-clock form).
- **The wire** — a report rides as one ``comm.framing`` frame
  (``MSG_TELEMETRY``, JSON payload): ``RemoteStageServer`` pushes one
  every ``telemetry_s`` on its dispatcher link's ping thread, and
  ``RemoteWorkerProxy`` ingests it into the process-global
  :class:`FederatedStore`. Processes the dispatcher does NOT own
  (e.g. a future cross-host prefill tier) advertise an HTTP **pull**
  fallback instead: their exporter serves ``GET /telemetry.json``
  (the same ``collect()`` body) and their registry lease carries
  ``meta["telemetry"] = url`` — :meth:`FederatedStore.poll_registry`
  walks live leases and pulls.
- :class:`FederatedStore` — sources keyed by ``(role, worker, pid)``:
  counters accumulate from deltas, gauges keep last-written, histogram
  percentiles merge from the shipped reservoirs via
  :class:`WeightedReservoir` (every sample weighted by its decimation
  stride — fleet p99 is computed over real samples from every source,
  never an average of per-source p99s, which has no meaning), and
  flight events merge into one wall-clock-ordered stream, each tagged
  with its source. Per-source **staleness** is first-class:
  ``fleet.report_age_s.<source>`` gauges (see
  :meth:`FederatedStore.collector`) make a wedged worker visible as
  MISSING data instead of silently-flat gauges.
- :func:`assemble_request` — the forensics assembler behind
  ``GET /debug/request/<id>``: one bundle holding every federated
  flight edge that names the request (submit/admit/preempt/reject/
  replay/handoff/finish, across all sources), its SLO verdicts and
  per-life TTFT/ITL stamps, recovery lives, the spans tagged with the
  request id from every process, and the journal's submit metadata.

The exporter serves the merged views: ``GET /fleet/metrics`` (merged
Prometheus with ``role``/``worker`` labels), ``/fleet/metrics.json``,
``/fleet/events``, ``/debug/request/<id>``. See
``docs/OBSERVABILITY.md`` "Fleet federation".

Cost stance: reports are periodic control-plane JSON (reservoirs are
decimated to ``max_hist_samples`` per histogram for the wire), never
per-token; the report path is measured inside the <5% observability
budget by ``benchmarks/micro/obs_overhead.py``'s federation config.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time
import urllib.request

from adapt_tpu.utils.logging import get_logger
from adapt_tpu.utils.metrics import MetricsRegistry, global_metrics
from adapt_tpu.utils.tracing import (
    FlightRecorder,
    Tracer,
    export_spans,
    global_flight_recorder,
    global_tracer,
)

log = get_logger("telemetry")

#: Report schema version (reports from a newer peer with an unknown
#: version are rejected loudly, not half-parsed).
REPORT_V = 1


def source_key(role: str, worker: str, pid: int) -> str:
    """The store's source identity — also the ``<source>`` suffix of
    the ``fleet.report_age_s.<source>`` staleness gauge (rendered as a
    Prometheus ``source`` label)."""
    return f"{role}:{worker}:{int(pid)}"


class WeightedReservoir:
    """Deterministic weighted sample reservoir — the fleet-merge form
    of the registry's decimating reservoir.

    Each entry is ``(value, weight)`` where weight is the decimation
    stride the sample arrived with (one reservoir sample stands for
    ``stride`` real observations). When the buffer fills, every other
    entry is dropped and the survivors' weights double — the same
    deterministic decimation as ``metrics._Histogram``, so merging is
    order-deterministic and memory stays bounded however many reports
    a long-lived source ships."""

    _CAP = 4096

    __slots__ = ("samples",)

    def __init__(self):
        self.samples: list[tuple[float, float]] = []

    def add(self, values, weight: float) -> None:
        w = float(weight) if weight > 0 else 1.0
        self.samples.extend((float(v), w) for v in values)
        while len(self.samples) > self._CAP:
            self.samples = [(v, w * 2.0) for v, w in self.samples[::2]]

    @staticmethod
    def percentiles(
        reservoirs: "list[WeightedReservoir]", ps=(50, 99)
    ) -> dict[str, float]:
        """Weighted percentiles over the UNION of several sources'
        reservoirs — the honest fleet percentile (a mean of per-source
        p99s is not a p99 of anything)."""
        merged: list[tuple[float, float]] = []
        for r in reservoirs:
            merged.extend(r.samples)
        if not merged:
            return {}
        merged.sort(key=lambda vw: vw[0])
        total = sum(w for _, w in merged)
        out: dict[str, float] = {}
        for p in ps:
            target = p / 100.0 * total
            acc = 0.0
            val = merged[-1][0]
            for v, w in merged:
                acc += w
                if acc >= target:
                    val = v
                    break
            out[f"p{int(p)}"] = val
        return out


class TelemetryReporter:
    """One per process (or per registry): produces the incremental
    report dicts the federation layer ships.

    Every :meth:`collect` chains the registry's snapshot window
    (``snapshot(since=prev, window=True)``), so consecutive reports
    carry disjoint counter deltas and disjoint histogram samples — the
    store can simply accumulate. Exactly ONE consumer may drive a
    reporter (a second would split the deltas); a process that both
    pushes over the comm link and serves the HTTP pull endpoint uses
    two independent reporters, which is safe — the cursors and windows
    are per-reporter."""

    def __init__(
        self,
        role: str,
        worker: str,
        registry: MetricsRegistry | None = None,
        recorder: FlightRecorder | None = None,
        tracer: Tracer | None = None,
        max_hist_samples: int = 512,
        max_events: int = 2048,
        max_spans: int = 512,
    ):
        self.role = str(role)
        self.worker = str(worker)
        self.pid = os.getpid()
        self._reg = registry if registry is not None else global_metrics()
        self._rec = (
            recorder if recorder is not None else global_flight_recorder()
        )
        self._tracer = tracer if tracer is not None else global_tracer()
        self._max_hist = max(8, int(max_hist_samples))
        self._max_events = max(1, int(max_events))
        self._max_spans = max(1, int(max_spans))
        self._win: dict | None = None
        self._ev_seq = 0
        self._span_seq = 0
        self._seq = 0
        self._lock = threading.Lock()
        #: Optional zero-arg callable returning this process's capacity
        #: book (``runtime/capacity``): when set, every report carries
        #: a ``"capacity"`` section — an OPTIONAL key, so stores and
        #: wires that predate it ignore it instead of breaking (no
        #: REPORT_V bump needed).
        self.capacity_provider = None

    def collect(self) -> dict:
        """The next report. First call: cumulative-since-boot counters
        and reservoirs (so a parent that attaches late still sees the
        source's full totals); every later call: the delta since the
        previous collect."""
        with self._lock:
            # Reopen after close(): the previous window is gone, so a
            # plain snapshot would re-ship CUMULATIVE counters and
            # reservoirs that look like a delta — double-counting in
            # any store that accumulated the earlier reports. Ship one
            # empty, flagged round instead (the window just opened
            # makes the NEXT collect's deltas correct again).
            reopened = self._win is None and self._seq > 0
            if self._win is None:
                snap = self._reg.snapshot(window=True, reservoirs=True)
            else:
                snap = self._reg.snapshot(
                    since=self._win, window=True, reservoirs=True
                )
            self._win = snap
            first = self._seq == 0
            degraded = bool(snap.get("window_evicted")) or reopened
            hists: dict[str, dict] = {}
            if first or not degraded:
                # A window evicted under this reporter (registry reset,
                # or > _MAX_WINDOWS concurrent readers) degrades the
                # read to CUMULATIVE summaries — shipping those as a
                # delta would double-count every histogram into the
                # fleet view, so the degraded round ships none and
                # flags itself.
                for name, s in snap["histograms"].items():
                    if not s.get("count"):
                        continue
                    res = s.get("reservoir", {})
                    samples = list(res.get("samples", ()))
                    stride = max(1, int(res.get("stride", 1)))
                    while len(samples) > self._max_hist:
                        samples = samples[::2]
                        stride *= 2
                    hists[name] = {
                        "count": s["count"],
                        "sum": s["sum"],
                        "min": s["min"],
                        "max": s["max"],
                        "samples": samples,
                        "stride": stride,
                    }
            events, self._ev_seq = self._rec.events_since(self._ev_seq)
            if len(events) > self._max_events:
                events = events[-self._max_events:]
            spans, self._span_seq = self._tracer.spans_since(
                self._span_seq
            )
            self._seq += 1
            capacity = None
            if self.capacity_provider is not None:
                try:
                    capacity = self.capacity_provider()
                except Exception:  # noqa: BLE001 — a broken book must
                    # not take the whole report (counters, events) down.
                    log.exception("capacity provider failed")
            report = {
                "v": REPORT_V,
                "source": {
                    "role": self.role,
                    "worker": self.worker,
                    "pid": self.pid,
                },
                "seq": self._seq,
                "wall": time.time(),
                "counters": (
                    {}
                    if reopened
                    else {
                        k: v
                        for k, v in snap["counters"].items()
                        if v
                    }
                ),
                "gauges": dict(snap["gauges"]),
                "histograms": hists,
                "events": events,
                "spans": export_spans(spans)[-self._max_spans:],
                "degraded": degraded and not first,
            }
            if isinstance(capacity, dict):
                report["capacity"] = capacity
            return report

    def close(self) -> None:
        """Close the chained snapshot window (a retired reporter must
        not leave a fork every later ``observe()`` pays for)."""
        with self._lock:
            if self._win is not None:
                try:
                    self._reg.snapshot(since=self._win)
                except ValueError:
                    pass
                self._win = None


class _FleetHist:
    __slots__ = ("count", "total", "min", "max", "reservoir")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.min = float("inf")
        self.max = float("-inf")
        self.reservoir = WeightedReservoir()

    def add(self, h: dict) -> None:
        self.count += int(h.get("count", 0))
        self.total += float(h.get("sum", 0.0))
        self.min = min(self.min, float(h.get("min", float("inf"))))
        self.max = max(self.max, float(h.get("max", float("-inf"))))
        self.reservoir.add(
            h.get("samples", ()), float(h.get("stride", 1))
        )

    def summary(self) -> dict:
        out = {
            "count": self.count,
            "sum": self.total,
            "min": self.min if self.count else 0.0,
            "max": self.max if self.count else 0.0,
        }
        out.update(WeightedReservoir.percentiles([self.reservoir]))
        return out


class _Source:
    """Accumulated state for one (role, worker, pid)."""

    def __init__(self, role: str, worker: str, pid: int):
        self.role = role
        self.worker = worker
        self.pid = pid
        self.counters: dict[str, float] = collections.defaultdict(float)
        self.gauges: dict[str, float] = {}
        self.hists: dict[str, _FleetHist] = {}
        self.seq = 0
        self.reports = 0
        self.lost_events = 0
        self.lost_reports = 0
        self.duplicate_reports = 0
        self.last_event_seq = 0
        self.last_mono = time.monotonic()
        self.last_wall = 0.0
        self.degraded = 0
        #: Last capacity book this source shipped (reports carry it as
        #: an optional section) + its arrival stamp — a killed source's
        #: book reads as GROWING age, never as fresh headroom.
        self.capacity: dict | None = None
        self.capacity_mono = 0.0


class FederatedStore:
    """The parent-side aggregation point: ingests reports from any
    number of sources and serves merged, labeled views.

    Sources arrive three ways — pushed over the comm link
    (``RemoteWorkerProxy`` calls :meth:`ingest`), pulled over HTTP
    from lease-advertised endpoints (:meth:`poll_registry`), or LOCAL
    (:meth:`attach_local` registers an in-process reporter that
    :meth:`refresh` drains at read time, so the serving process's own
    metrics appear in ``/fleet/*`` with no push loop)."""

    def __init__(self, event_capacity: int = 8192, span_capacity: int = 4096):
        self._lock = threading.Lock()
        #: Serializes whole refresh passes (collect -> ingest must be
        #: atomic per local reporter: two concurrent refreshes could
        #: otherwise ingest windows n and n+1 out of order, and the
        #: duplicate-seq guard would drop window n's deltas).
        self._refresh_lock = threading.Lock()
        self._sources: dict[str, _Source] = {}
        #: Merged flight stream: each entry is the source event plus a
        #: ``"source"`` tag. Bounded; kept in arrival order, sorted by
        #: wall clock at read time (clocks across processes on one
        #: machine share time.time()).
        self._events: collections.deque[dict] = collections.deque(
            maxlen=event_capacity
        )
        #: Remote spans retained for forensics (local spans live in
        #: the local tracer ring; retaining them twice would force
        #: dedupe at assemble time).
        self._spans: collections.deque[dict] = collections.deque(
            maxlen=span_capacity
        )
        self._locals: dict[str, TelemetryReporter] = {}
        self._registries: list = []  # WorkerRegistry refs for polling
        self._poll_last: dict[str, float] = {}
        #: Lease-advertised capacity books (``meta["capacity"]`` on a
        #: WorkerRegistry lease — the disagg prefill tier's path):
        #: ``worker_id -> (book, first-seen-mono-at-this-wall)``. The
        #: mono stamp only advances when the book's ``wall`` does, and
        #: entries OUTLIVE their lease — an expired or frozen source
        #: reads as growing age, never as a fresh book — up to
        #: ``capacity_max_age_s``, where they evict for good.
        self._lease_caps: dict[str, tuple[dict, float]] = {}
        #: Staleness evict for :meth:`capacity_snapshot`: a book older
        #: than this (lease-sourced or telemetry-sourced) leaves the
        #: placement view entirely. The GROWING-age window below the
        #: bound is the operator's "it's wedged" signal; past it, a
        #: replica dead for minutes must stop being a placement
        #: candidate — the capacity plane owns staleness policy so no
        #: router has to re-implement it. None = keep forever (the
        #: pre-evict behavior).
        self.capacity_max_age_s: float | None = 60.0
        self._journal = None
        self.poll_interval_s = 1.0
        self.poll_timeout_s = 1.0

    # -- wiring ------------------------------------------------------------

    def attach_local(
        self,
        role: str,
        worker: str | None = None,
        registry: MetricsRegistry | None = None,
        recorder: FlightRecorder | None = None,
        tracer: Tracer | None = None,
        capacity_provider=None,
    ) -> str:
        """Register this process itself as a source; its reporter is
        drained lazily at every :meth:`refresh` (scrape-time pull, no
        thread). Idempotent per (role, worker): re-attaching with the
        same identity keeps the existing reporter and its cursors.
        ``capacity_provider`` (zero-arg -> book dict) makes the local
        source self-describing in ``/fleet/capacity``; passing one to
        a re-attach updates the existing reporter's provider."""
        worker = worker if worker is not None else f"pid{os.getpid()}"
        key = source_key(role, worker, os.getpid())
        stale: TelemetryReporter | None = None
        with self._lock:
            existing = self._locals.get(key)
            if existing is not None and existing._reg is (
                registry if registry is not None else global_metrics()
            ):
                if capacity_provider is not None:
                    existing.capacity_provider = capacity_provider
                return key
            stale = existing
            rep = TelemetryReporter(
                role, worker, registry=registry, recorder=recorder,
                tracer=tracer,
            )
            rep.capacity_provider = capacity_provider
            self._locals[key] = rep
        if stale is not None:
            # OUTSIDE the lock: close() snapshots the old registry,
            # which runs its collectors — and this store's own
            # staleness collector re-enters self._lock (same
            # discipline as FederatedStore.close()).
            stale.close()
        return key

    def attach_registry(self, registry) -> None:
        """Register a ``control.registry.WorkerRegistry`` whose live
        leases :meth:`refresh` scans for ``meta["telemetry"]`` pull
        URLs — the fallback for processes the dispatcher doesn't own a
        comm link to."""
        with self._lock:
            if registry not in self._registries:
                self._registries.append(registry)

    def attach_journal(self, journal) -> None:
        """Give :func:`assemble_request` (and ``/debug/request/<id>``)
        access to submit metadata / pending state."""
        self._journal = journal

    @property
    def journal(self):
        return self._journal

    # -- ingest ------------------------------------------------------------

    def ingest(self, report: dict, worker: str | None = None) -> str:
        """Fold one report in; returns the source key. ``worker``
        overrides the report's self-declared worker id (the dispatcher
        knows the worker by ITS name — a dial-out stage server only
        knows its port). Malformed reports raise ``ValueError`` (the
        comm ingest site guards and counts); a well-formed report can
        never half-apply."""
        if not isinstance(report, dict) or int(report.get("v", -1)) != (
            REPORT_V
        ):
            raise ValueError(f"unknown telemetry report: {report!r:.80}")
        src = report["source"]
        role = str(src["role"])
        wid = str(worker if worker is not None else src["worker"])
        pid = int(src["pid"])
        key = source_key(role, wid, pid)
        events = report.get("events", ())
        spans = report.get("spans", ())
        with self._lock:
            s = self._sources.get(key)
            seq = int(report.get("seq", 0))
            if s is not None and seq and seq <= s.seq:
                # Duplicate: the push path RETRANSMITS frames whose
                # send erred after TCP may already have buffered them
                # (comm.remote's telemetry backlog) — folding a
                # duplicate in would double-count every counter delta,
                # reservoir sample, and flight event. Drop it; the
                # source key carries the pid, so a restarted worker is
                # a fresh source, never mistaken for a replay.
                s.duplicate_reports += 1
                s.last_mono = time.monotonic()
                return key
            if s is None:
                s = self._sources[key] = _Source(role, wid, pid)
            s.reports += 1
            if s.seq and seq > s.seq + 1:
                # Report-seq gap: windows collected but never
                # delivered (backlog overflow during an outage). The
                # gap is the fleet-counters under-report signal —
                # counter deltas, unlike events, carry no per-item seq
                # of their own.
                s.lost_reports += seq - s.seq - 1
            s.seq = max(s.seq, seq)
            s.last_mono = time.monotonic()
            s.last_wall = float(report.get("wall", 0.0))
            if report.get("degraded"):
                s.degraded += 1
            for name, v in report.get("counters", {}).items():
                if v > 0:
                    s.counters[name] += float(v)
                # A negative delta means the source's registry was
                # reset mid-flight; dropping it keeps totals monotone
                # (the alternative — subtracting — would present a
                # counter that went backwards to every scraper).
            s.gauges.update(report.get("gauges", {}))
            cap = report.get("capacity")
            if isinstance(cap, dict):
                s.capacity = cap
                s.capacity_mono = time.monotonic()
            for name, h in report.get("histograms", {}).items():
                fh = s.hists.get(name)
                if fh is None:
                    fh = s.hists[name] = _FleetHist()
                fh.add(h)
            for ev in events:
                eseq = int(ev.get("seq", 0))
                if s.last_event_seq and eseq > s.last_event_seq + 1:
                    s.lost_events += eseq - s.last_event_seq - 1
                s.last_event_seq = max(s.last_event_seq, eseq)
                self._events.append({**ev, "source": key})
            if key not in self._locals:
                # LOCAL sources' spans already live in the local tracer
                # ring (assemble_request reads them from there);
                # retaining them here too would force dedupe. Keyed on
                # attach_local membership, NOT pid equality — two
                # containers can both be pid 1.
                self._spans.extend(spans)
        return key

    # -- refresh (read-time pulls) ----------------------------------------

    def refresh(self) -> None:
        """Drain local reporters and poll lease-advertised HTTP
        sources. Runs at read time (every ``/fleet/*`` scrape and
        forensics assemble); HTTP polls are rate-limited by
        ``poll_interval_s`` and bounded by ``poll_timeout_s``."""
        with self._refresh_lock:
            with self._lock:
                locals_ = list(self._locals.values())
                registries = list(self._registries)
            for rep in locals_:
                try:
                    self.ingest(rep.collect())
                except Exception:  # noqa: BLE001 — a scrape must not
                    log.exception("local telemetry collect failed")
            for registry in registries:
                try:
                    self.poll_registry(registry)
                except Exception:  # noqa: BLE001
                    log.exception("telemetry registry poll failed")

    def poll_registry(self, registry) -> int:
        """Pull ``/telemetry.json`` from every live lease advertising
        ``meta["telemetry"]``; returns the number of reports ingested.
        Failures count as ``fleet.poll_failed_total`` — a dead
        advertised endpoint is a staleness signal, never a scrape
        error."""
        n = 0
        now = time.monotonic()
        for wid, meta in registry.alive_meta().items():
            url = meta.get("telemetry")
            if not url:
                continue
            last = self._poll_last.get(url, 0.0)
            if now - last < self.poll_interval_s:
                continue
            self._poll_last[url] = now
            try:
                with urllib.request.urlopen(
                    url, timeout=self.poll_timeout_s
                ) as r:
                    self.ingest(
                        json.loads(r.read().decode()), worker=wid
                    )
                n += 1
            except Exception:  # noqa: BLE001 — counted, not raised
                global_metrics().inc("fleet.poll_failed_total")
        return n

    # -- read side ---------------------------------------------------------

    def sources(self) -> dict[str, dict]:
        """Per-source status (the staleness view): last report age,
        seq, loss accounting."""
        now = time.monotonic()
        with self._lock:
            return {
                key: {
                    "role": s.role,
                    "worker": s.worker,
                    "pid": s.pid,
                    "age_s": round(now - s.last_mono, 3),
                    "seq": s.seq,
                    "reports": s.reports,
                    "lost_events": s.lost_events,
                    "lost_reports": s.lost_reports,
                    "duplicate_reports": s.duplicate_reports,
                    "degraded_reports": s.degraded,
                }
                for key, s in self._sources.items()
            }

    def fleet_snapshot(self, refresh: bool = True) -> dict:
        """The merged view ``/fleet/metrics.json`` serves: per-source
        counters/gauges/histograms (histograms with per-source
        percentiles), plus ``merged`` totals whose percentiles come
        from the UNION of every source's reservoir, plus the
        staleness block."""
        if refresh:
            self.refresh()
        now = time.monotonic()
        with self._lock:
            per_source: dict[str, dict] = {}
            merged_counters: dict[str, float] = collections.defaultdict(
                float
            )
            merged_hists: dict[str, list] = collections.defaultdict(list)
            for key, s in self._sources.items():
                per_source[key] = {
                    "role": s.role,
                    "worker": s.worker,
                    "pid": s.pid,
                    "age_s": round(now - s.last_mono, 3),
                    "seq": s.seq,
                    "lost_events": s.lost_events,
                    "counters": dict(s.counters),
                    "gauges": dict(s.gauges),
                    "histograms": {
                        n: h.summary() for n, h in s.hists.items()
                    },
                }
                for n, v in s.counters.items():
                    merged_counters[n] += v
                for n, h in s.hists.items():
                    merged_hists[n].append(h)
            merged = {
                "counters": dict(merged_counters),
                "histograms": {},
            }
            for n, hs in merged_hists.items():
                total = _FleetHist()
                for h in hs:
                    total.count += h.count
                    total.total += h.total
                    total.min = min(total.min, h.min)
                    total.max = max(total.max, h.max)
                merged["histograms"][n] = {
                    "count": total.count,
                    "sum": total.total,
                    "min": total.min if total.count else 0.0,
                    "max": total.max if total.count else 0.0,
                    **WeightedReservoir.percentiles(
                        [h.reservoir for h in hs]
                    ),
                }
        out = {"sources": per_source, "merged": merged}
        out["staleness"] = {
            k: v["age_s"] for k, v in self.sources().items()
        }
        return out

    def capacity_snapshot(self, refresh: bool = True) -> dict:
        """The merged capacity plane ``GET /fleet/capacity`` serves:
        one entry per replica that has shipped a book — telemetry-wire
        sources (reports' optional ``capacity`` section) plus
        lease-meta books (``meta["capacity"]`` on live registry
        leases) — each labeled role/worker/pid with first-class
        ``age_s`` staleness. A killed source's last book stays in the
        view with GROWING age (placement must see "stale", not
        "gone"); a router treats age above its own bound as no
        capacity at all, and past ``capacity_max_age_s`` the book
        EVICTS — a replica dead for minutes is not a placement
        candidate and must not scroll a fleet view forever."""
        if refresh:
            self.refresh()
        now = time.monotonic()
        with self._lock:
            registries = list(self._registries)
        # Registry scan OUTSIDE self._lock (alive_meta takes the
        # registry's own lock; same discipline as poll_registry).
        lease_books: dict[str, dict] = {}
        for registry in registries:
            try:
                for wid, meta in registry.alive_meta().items():
                    book = meta.get("capacity")
                    if isinstance(book, dict):
                        lease_books[str(wid)] = book
            except Exception:  # noqa: BLE001 — a wedged registry must
                log.exception("capacity lease scan failed")
        replicas: dict[str, dict] = {}
        max_age = self.capacity_max_age_s
        with self._lock:
            for wid, book in lease_books.items():
                prev = self._lease_caps.get(wid)
                if prev is None or prev[0].get("wall") != book.get(
                    "wall"
                ):
                    self._lease_caps[wid] = (book, now)
            if max_age is not None:
                # The evict: books stale past the bound leave the view
                # (lease-sourced entries drop from the retention map
                # itself; telemetry-sourced ones just stop listing —
                # their _Source may still carry live counters).
                for wid in [
                    w
                    for w, (_, mono) in self._lease_caps.items()
                    if now - mono > max_age
                ]:
                    del self._lease_caps[wid]
            for key, s in self._sources.items():
                if s.capacity is None:
                    continue
                if max_age is not None and (
                    now - s.capacity_mono > max_age
                ):
                    continue
                replicas[key] = {
                    "role": s.role,
                    "worker": s.worker,
                    "pid": s.pid,
                    "via": "telemetry",
                    "age_s": round(now - s.capacity_mono, 3),
                    "book": s.capacity,
                }
            for wid, (book, mono) in self._lease_caps.items():
                replicas[f"lease:{wid}"] = {
                    "role": str(book.get("kind", "worker")),
                    "worker": wid,
                    "pid": 0,
                    "via": "lease",
                    "age_s": round(now - mono, 3),
                    "book": book,
                }
        return {"v": REPORT_V, "replicas": replicas}

    def events(
        self,
        request: int | None = None,
        kind: str | None = None,
        limit: int | None = None,
    ) -> list[dict]:
        """The merged flight stream, WALL-CLOCK ordered across sources
        (every event is ``{ts, kind, data, seq, source}``). With
        ``request``, only events naming that request (``data.request``
        or ``data.for_request``)."""
        with self._lock:
            evs = list(self._events)
        if request is not None:
            evs = [
                e
                for e in evs
                if e.get("data", {}).get("request") == request
                or e.get("data", {}).get("for_request") == request
            ]
        if kind is not None:
            evs = [e for e in evs if e.get("kind") == kind]
        evs.sort(key=lambda e: (e.get("ts", 0.0), e.get("seq", 0)))
        if limit is not None:
            evs = evs[-limit:]
        return evs

    def spans(self, request: int | None = None) -> list[dict]:
        """Remote-ingested span exports (wall-clock ``t0``/``t1``
        dicts); local spans live in the local tracer ring."""
        with self._lock:
            spans = list(self._spans)
        if request is not None:
            spans = [
                s
                for s in spans
                if s.get("attrs", {}).get("request") == request
            ]
        return spans

    def collector(self, reg: MetricsRegistry) -> None:
        """``MetricsRegistry.register_collector`` hook: surfaces the
        staleness signal on the PARENT's own ``/metrics`` —
        ``fleet.report_age_s.<source>`` per source (a wedged worker is
        visible as a growing age, not frozen gauges), plus
        ``fleet.sources`` and per-source loss counters. Registered by
        ``serve_metrics`` on whatever registry it serves."""
        infos = self.sources()
        reg.set_gauge("fleet.sources", float(len(infos)))
        for key, info in infos.items():
            reg.set_gauge(
                f"fleet.report_age_s.{key}", round(info["age_s"], 3)
            )
            if info["lost_events"]:
                reg.set_gauge(
                    f"fleet.events_lost.{key}",
                    float(info["lost_events"]),
                )
            if info["lost_reports"]:
                # Whole report windows lost (backlog overflow during
                # an outage): the fleet counters under-report by those
                # windows' deltas, and THIS gauge is the only signal —
                # counter deltas carry no per-item seq of their own.
                reg.set_gauge(
                    f"fleet.reports_lost.{key}",
                    float(info["lost_reports"]),
                )

    def close(self) -> None:
        with self._lock:
            locals_ = list(self._locals.values())
            self._locals.clear()
        for rep in locals_:
            rep.close()


def assemble_request(
    req_id: int,
    store: "FederatedStore | None" = None,
    tracer: Tracer | None = None,
    journal=None,
    refresh: bool = True,
) -> dict:
    """One JSON bundle telling request ``req_id``'s complete story
    across every federated source — the body of
    ``GET /debug/request/<id>``.

    Sections:

    - ``events`` — every flight edge naming the request (admit /
      finish / cancel / preempted / replayed_from_journal /
      kv_migrated / kv_handoff / request_rejected / slo_missed / ...),
      wall-clock ordered, each tagged with its source process;
    - ``lives`` — one entry per admission (a preempted or
      recovery-replayed request has several), with each life's queue
      wait and slot;
    - ``delivery`` — exactly-once accounting: final token count, the
      tokens each replay discarded, per-life TTFT/ITL stamps off the
      finish edge;
    - ``slo`` — violation edges and the terminal verdict;
    - ``spans`` — tracer spans tagged ``request=req_id`` from EVERY
      process (the local ring plus remote exports the store ingested);
    - ``journal`` — submit metadata and whether the request is still
      pending replay.
    """
    store = store if store is not None else global_federated_store()
    tracer = tracer if tracer is not None else global_tracer()
    if refresh:
        store.refresh()
    evs = store.events(request=req_id)
    by_kind: dict[str, list] = collections.defaultdict(list)
    for e in evs:
        by_kind[e["kind"]].append(e)
    lives = [
        {
            "slot": e["data"].get("slot"),
            "queue_wait_s": e["data"].get("queue_wait_s"),
            "ts": e.get("ts"),
            "source": e.get("source"),
        }
        for e in by_kind.get("admit", [])
    ]
    finishes = by_kind.get("finish", [])
    fin = finishes[-1]["data"] if finishes else {}
    replays = by_kind.get("replayed_from_journal", []) + by_kind.get(
        "preempted", []
    )
    # Per-life stamps: each interrupted life's TTFT/ITL ride its
    # replay/preemption edge, the last life's ride the finish edge —
    # chronological, one entry per life that emitted anything.
    life_stamps = [
        {
            k: e["data"][k]
            for k in ("ttft_s", "life_itl_mean_s", "tokens_discarded")
            if k in e["data"]
        }
        for e in sorted(replays, key=lambda e: e.get("ts", 0.0))
    ] + (
        [
            {
                k: fin[k]
                for k in ("ttft_s", "life_itl_mean_s", "tokens")
                if k in fin
            }
        ]
        if finishes
        else []
    )
    ttft = fin.get("ttft_s")
    if ttft is None:
        ttft = next(
            (s["ttft_s"] for s in life_stamps if "ttft_s" in s), None
        )
    delivery = {
        "finished": bool(finishes),
        "reason": fin.get("reason"),
        "tokens": fin.get("tokens"),
        "ttft_s": ttft,
        "life_stamps": life_stamps,
        "lives": len(lives),
        "tokens_discarded": [
            e["data"].get("tokens_discarded", 0) for e in replays
        ],
    }
    slo_evs = by_kind.get("slo_missed", [])
    slo = {
        "violated": bool(slo_evs),
        "violations": [e["data"] for e in slo_evs],
    }
    # Spans: the local ring (both locally-recorded and annex-ingested
    # remote spans live there) plus whatever remote reports shipped —
    # everything exported onto the WALL clock (export_spans), the same
    # clock report-shipped spans arrive on, so cross-source ordering
    # and the dedupe key below actually compare like with like.
    spans = export_spans(
        [
            s
            for s in tracer.spans()
            if s.attrs.get("request") == req_id
        ]
    )
    seen = {(s["pid"], s["tid"], s["name"], round(s["t0"], 6))
            for s in spans}
    for s in store.spans(request=req_id):
        key = (
            s.get("pid"), s.get("tid"), s.get("name"),
            round(float(s.get("t0", 0.0)), 6),
        )
        if key not in seen:
            seen.add(key)
            spans.append(s)
    journal = journal if journal is not None else store.journal
    jinfo = None
    if journal is not None:
        try:
            jinfo = {
                "pending": req_id in journal.pending_ids(),
                "meta": journal.submit_meta(req_id),
            }
        except Exception:  # noqa: BLE001 — forensics never raise
            jinfo = {"error": "journal read failed"}
    return {
        "request": req_id,
        "events": evs,
        "lives": lives,
        "delivery": delivery,
        "slo": slo,
        "preemptions": [e["data"] for e in by_kind.get("preempted", [])],
        "replays": [
            e["data"] for e in by_kind.get("replayed_from_journal", [])
        ],
        "kv_handoffs": [e["data"] for e in by_kind.get("kv_handoff", [])],
        "rejections": [
            e["data"] for e in by_kind.get("request_rejected", [])
        ],
        "spans": sorted(spans, key=lambda s: s["t0"]),
        "journal": jinfo,
    }


_GLOBAL = FederatedStore()


def global_federated_store() -> FederatedStore:
    """The process-global store: the comm-layer ingest site
    (``RemoteWorkerProxy``) and the exporter's ``/fleet/*`` endpoints
    default to it, so one serving process needs zero wiring to see its
    whole worker fleet."""
    return _GLOBAL
