"""Ulysses-style sequence parallelism: all-to-all head/sequence swap.

The second first-class long-context strategy next to
:mod:`adapt_tpu.parallel.ring_attention` (neither exists in the reference —
SURVEY.md §2.2: no attention at all). Where ring attention rotates K/V
blocks around the ``sp`` ring (P-1 neighbor hops, O(S/P) memory, best when
S is huge), Ulysses does two ``lax.all_to_all`` collectives: re-shard the
[B, H, S/P, D] sequence shards into [B, H/P, S, D] head shards, run FULL
(unsharded-sequence) attention on the local heads, and all-to-all back.
Two collectives total instead of P-1 hops — the better trade when heads
are plentiful and S fits per chip; both strategies expose the same
sharded-in/sharded-out contract, so callers pick per workload.

Constraint: num_heads % axis_size == 0 (heads shard across the axis).
The local attention defaults to :func:`adapt_tpu.ops.attention.
flash_attention`, whose measured dispatch (``scores_over_budget`` — the
SAME predicate the kernel's own forward/backward and ring attention's
``"auto"`` consult, so the three can't drift) sees the post-all-to-all
local shape [B, H/P, S, D]: sub-budget scores run XLA's fused path,
super-budget runs the streaming Pallas kernel. Any custom
``attn_fn(q, k, v, causal=...)`` overrides.
"""

from __future__ import annotations

import functools
from typing import Callable

import jax
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from adapt_tpu.parallel.compat import shard_map


def ulysses_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = False,
    attn_fn: Callable | None = None,
) -> jax.Array:
    """Sequence-parallel attention via head/sequence all-to-all.

    q, k, v: [B, H, S, D] with S divisible by the axis size and H divisible
    by the axis size; sharded on S over ``axis`` in and out.
    """
    if attn_fn is None:
        # The measured dispatch IS the default: flash_attention routes by
        # scores_over_budget on the exact local block it will compute
        # ([B, H/P, S, D] after the head/sequence swap).
        from adapt_tpu.ops.attention import flash_attention

        attn_fn = flash_attention

    num_ranks = mesh.shape[axis]
    _, h, s, _ = q.shape
    if s % num_ranks:
        raise ValueError(f"sequence {s} not divisible by axis size {num_ranks}")
    if h % num_ranks:
        raise ValueError(f"heads {h} not divisible by axis size {num_ranks}")

    spec = P(None, None, axis, None)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    def swapped(q_l, k_l, v_l):
        # [B, H, S/P, D] -> [B, H/P, S, D]: every rank trades sequence
        # shards for head shards (one all-to-all per tensor, on ICI).
        def to_heads(x):
            return lax.all_to_all(
                x, axis, split_axis=1, concat_axis=2, tiled=True
            )

        o = attn_fn(
            to_heads(q_l), to_heads(k_l), to_heads(v_l), causal=causal
        )
        # [B, H/P, S, D] -> [B, H, S/P, D]: swap back.
        return lax.all_to_all(o, axis, split_axis=2, concat_axis=1, tiled=True)

    return swapped(q, k, v)
