"""SPMD pipeline parallelism: one XLA program, activations on ICI.

The reference's pipeline is MPMD over TCP — one process per stage, framed
sockets between them (SURVEY.md §2.3). On TPU the idiomatic equivalent for
*homogeneous* stages (transformer blocks) is a single SPMD program: stack
the L identical blocks' params with leading dim L, shard that dim over the
``pp`` mesh axis (each device holds L/P consecutive blocks), and run the
GPipe-style schedule as a ``lax.scan`` whose per-step activation hand-off
is a ``lax.ppermute`` — compiled by XLA onto ICI with no host round-trips,
no framing, no codec (the design SURVEY §2.3 calls for).

Heterogeneous-stage models (ResNet/EfficientNet) use the MPMD path
(``runtime.LocalPipeline`` / the adaptive dispatcher); this module is the
throughput path for block-structured transformers, and it composes with
``dp`` (batch axis) in the same mesh — and it is differentiable, so the
same schedule backs pipelined training steps.

Schedule (M microbatches, P pipeline ranks, T = M+P-1 ticks): at tick t,
rank p runs microbatch ``t-p`` through its block slice; rank 0 injects
``xs[t]``, rank P-1 writes finished microbatches into the output buffer.
Invalid (bubble) ticks compute on garbage and are masked out of the output.
Utilization is M/(M+P-1) — choose M >= 2P.
"""

from __future__ import annotations

import functools
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


def stack_stage_params(per_block_variables: list[Any]) -> Any:
    """Stack identical-structure per-block param pytrees along a new leading
    axis (the pipeline-shardable layout)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *per_block_variables)


def spmd_pipeline(
    block_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    xs: jax.Array,
    mesh: Mesh,
    axis: str = "pp",
    batch_axis: str | None = None,
) -> jax.Array:
    """Run ``xs`` (shape [M, mb, ...]) through L stacked blocks pipelined
    over the ``axis`` dimension of ``mesh``.

    ``block_fn(params_i, x) -> y`` applies ONE block (y.shape == x.shape).
    ``stacked_params`` leaves have leading dim L with L % P == 0.
    If ``batch_axis`` is given, the microbatch batch dim (dim 1 of xs) is
    additionally sharded over it (dp x pp in one program).
    """
    num_ranks = mesh.shape[axis]
    num_micro = xs.shape[0]
    lead = jax.tree.leaves(stacked_params)[0].shape[0]
    if lead % num_ranks:
        raise ValueError(
            f"stacked block count {lead} not divisible by pipeline ranks "
            f"{num_ranks}"
        )

    def local_stack(params_local, h):
        def body(carry, p):
            return block_fn(p, carry), None

        h, _ = lax.scan(body, h, params_local)
        return h

    param_specs = jax.tree.map(lambda _: P(axis), stacked_params)
    x_spec = (
        P(None, batch_axis) if batch_axis is not None else P()
    )

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(param_specs, x_spec),
        out_specs=x_spec,
        # check_vma=False so arbitrary stage bodies compose — the stage fn
        # may contain a pallas_call (ViT blocks run the fused flash
        # kernel), whose out_shape carries no vma annotation.
        check_vma=False,
    )
    def pipelined(params_local, xs_local):
        rank = lax.axis_index(axis)
        ticks = num_micro + num_ranks - 1
        mb_shape = xs_local.shape[1:]
        shift = [(i, i + 1) for i in range(num_ranks - 1)]

        def step(carry, t):
            prev_y, outputs = carry
            # Hand the previous tick's output to the next rank (ICI hop).
            recv = lax.ppermute(prev_y, axis, shift)
            inject = lax.dynamic_index_in_dim(
                xs_local, jnp.clip(t, 0, num_micro - 1), 0, keepdims=False
            )
            h = jnp.where(rank == 0, inject, recv)
            y = local_stack(params_local, h)
            m = t - rank
            is_last = rank == num_ranks - 1
            valid = jnp.logical_and(m >= 0, m < num_micro)
            write = jnp.logical_and(is_last, valid)
            updated = lax.dynamic_update_index_in_dim(
                outputs,
                y.astype(outputs.dtype),
                jnp.clip(m, 0, num_micro - 1),
                0,
            )
            outputs = jnp.where(write, updated, outputs)
            return (y, outputs), None

        vary_axes = (axis,) + ((batch_axis,) if batch_axis else ())
        init = lax.pcast(
            (
                jnp.zeros(mb_shape, xs_local.dtype),
                jnp.zeros((num_micro, *mb_shape), xs_local.dtype),
            ),
            vary_axes,
            to="varying",
        )
        (_, outputs), _ = lax.scan(step, init, jnp.arange(ticks))
        # Only the last rank holds real outputs; replicate over the pipeline
        # axis (zeros elsewhere make psum a broadcast of rank P-1's buffer).
        return lax.psum(outputs, axis)

    return pipelined(stacked_params, xs)


def pipeline_microbatch(
    x: jax.Array, num_micro: int
) -> jax.Array:
    """[B, ...] -> [M, B/M, ...] microbatch split."""
    if x.shape[0] % num_micro:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by {num_micro} microbatches"
        )
    return x.reshape(num_micro, x.shape[0] // num_micro, *x.shape[1:])


def pipeline_unmicrobatch(y: jax.Array) -> jax.Array:
    """[M, mb, ...] -> [B, ...]."""
    return y.reshape(y.shape[0] * y.shape[1], *y.shape[2:])
