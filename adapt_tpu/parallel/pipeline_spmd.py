"""SPMD pipeline parallelism: one XLA program, activations on ICI.

The reference's pipeline is MPMD over TCP — one process per stage, framed
sockets between them (SURVEY.md §2.3). On TPU the idiomatic equivalent for
*homogeneous* stages (transformer blocks) is a single SPMD program: stack
the L identical blocks' params with leading dim L, shard that dim over the
``pp`` mesh axis (each device holds L/P consecutive blocks), and run the
GPipe-style schedule as a ``lax.scan`` whose per-step activation hand-off
is a ``lax.ppermute`` — compiled by XLA onto ICI with no host round-trips,
no framing, no codec (the design SURVEY §2.3 calls for).

Heterogeneous-stage models (ResNet/EfficientNet) use the MPMD path
(``runtime.LocalPipeline`` / the adaptive dispatcher); this module is the
throughput path for block-structured transformers, and it composes with
``dp`` (batch axis) in the same mesh — and it is differentiable, so the
same schedule backs pipelined training steps.

Two schedules, one body:

- ``schedule="serial"`` (GPipe): at tick t, rank p computes microbatch
  ``t - p``; the ppermute hop for a microbatch's activation is CONSUMED
  by the next rank's compute in the very next tick, so the hop sits on
  the critical path — each tick costs compute + hop. T = M + P - 1
  ticks.
- ``schedule="overlap"`` (double-buffered): each rank holds a circular
  buffer of its last ``hop_buffers - 1`` outputs and, inside one scan
  step, ISSUES the ppermute for the activation computed ``d =
  hop_buffers - 1`` ticks ago while computing the current microbatch —
  the two have no data dependency, so XLA schedules the
  collective-permute concurrently with compute (async CP start/done on
  TPU) and hop latency hides under compute: each tick costs
  max(compute, hop). The price is schedule depth — a hop takes d + 1
  ticks to land, T = M + (P - 1)(d + 1) — so for M >> P the wall-clock
  ratio approaches (compute + hop) / max(compute, hop): up to 2x when
  hops rival compute ("On Optimizing the Communication of Model
  Parallelism", PAPERS.md). Outputs are BIT-IDENTICAL to the serial
  schedule: every microbatch runs the same blocks in the same order —
  only the tick a hop occupies moves (tested for 2-4 stages).

Knob plumbing: ``config.PipelineConfig`` carries (schedule,
microbatches, hop_buffers) for drivers; ``benchmarks/micro/
hop_overlap.py`` measures the schedules against each other on CPU.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P


from adapt_tpu.parallel.compat import shard_map as _shard_map_compat
from adapt_tpu.parallel.compat import to_varying as _to_varying


def stack_stage_params(per_block_variables: list[Any]) -> Any:
    """Stack identical-structure per-block param pytrees along a new leading
    axis (the pipeline-shardable layout)."""
    return jax.tree.map(lambda *xs: jnp.stack(xs, axis=0), *per_block_variables)


def spmd_pipeline(
    block_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    xs: jax.Array,
    mesh: Mesh,
    axis: str = "pp",
    batch_axis: str | None = None,
    schedule: str = "serial",
    hop_buffers: int = 2,
) -> jax.Array:
    """Run ``xs`` (shape [M, mb, ...]) through L stacked blocks pipelined
    over the ``axis`` dimension of ``mesh``.

    ``block_fn(params_i, x) -> y`` applies ONE block (y.shape == x.shape).
    ``stacked_params`` leaves have leading dim L with L % P == 0.
    If ``batch_axis`` is given, the microbatch batch dim (dim 1 of xs) is
    additionally sharded over it (dp x pp in one program).

    ``schedule="overlap"`` runs the double-buffered schedule (module
    docstring): ``hop_buffers`` >= 2 sets the circular activation-buffer
    depth (send delay = hop_buffers - 1 ticks; 2 = classic double
    buffering, more hides longer hop latency at more ticks). Both
    schedules produce bit-identical outputs.
    """
    if schedule not in ("serial", "overlap"):
        raise ValueError(
            f"schedule={schedule!r}: expected 'serial' or 'overlap'"
        )
    if schedule == "overlap" and hop_buffers < 2:
        raise ValueError(
            f"hop_buffers must be >= 2 for the overlap schedule, got "
            f"{hop_buffers}"
        )
    num_ranks = mesh.shape[axis]
    num_micro = xs.shape[0]
    lead = jax.tree.leaves(stacked_params)[0].shape[0]
    if lead % num_ranks:
        raise ValueError(
            f"stacked block count {lead} not divisible by pipeline ranks "
            f"{num_ranks}"
        )

    def local_stack(params_local, h):
        def body(carry, p):
            return block_fn(p, carry), None

        h, _ = lax.scan(body, h, params_local)
        return h

    param_specs = jax.tree.map(lambda _: P(axis), stacked_params)
    x_spec = (
        P(None, batch_axis) if batch_axis is not None else P()
    )
    vary_axes = (axis,) + ((batch_axis,) if batch_axis else ())
    shift = [(i, i + 1) for i in range(num_ranks - 1)]

    def pipelined_serial(params_local, xs_local):
        rank = lax.axis_index(axis)
        ticks = num_micro + num_ranks - 1
        mb_shape = xs_local.shape[1:]

        def step(carry, t):
            prev_y, outputs = carry
            # Hand the previous tick's output to the next rank (ICI hop).
            # The next compute CONSUMES recv immediately, so the hop is
            # on the critical path — the serial schedule's defining cost.
            recv = lax.ppermute(prev_y, axis, shift)
            inject = lax.dynamic_index_in_dim(
                xs_local, jnp.clip(t, 0, num_micro - 1), 0, keepdims=False
            )
            h = jnp.where(rank == 0, inject, recv)
            y = local_stack(params_local, h)
            m = t - rank
            is_last = rank == num_ranks - 1
            valid = jnp.logical_and(m >= 0, m < num_micro)
            write = jnp.logical_and(is_last, valid)
            updated = lax.dynamic_update_index_in_dim(
                outputs,
                y.astype(outputs.dtype),
                jnp.clip(m, 0, num_micro - 1),
                0,
            )
            outputs = jnp.where(write, updated, outputs)
            return (y, outputs), None

        init = _to_varying(
            (
                jnp.zeros(mb_shape, xs_local.dtype),
                jnp.zeros((num_micro, *mb_shape), xs_local.dtype),
            ),
            vary_axes,
        )
        (_, outputs), _ = lax.scan(step, init, jnp.arange(ticks))
        # Only the last rank holds real outputs; replicate over the pipeline
        # axis (zeros elsewhere make psum a broadcast of rank P-1's buffer).
        return lax.psum(outputs, axis)

    def pipelined_overlap(params_local, xs_local):
        rank = lax.axis_index(axis)
        d = hop_buffers - 1  # send delay (ticks a hop has to hide in)
        ticks = num_micro + (num_ranks - 1) * (d + 1)
        mb_shape = xs_local.shape[1:]

        def step(carry, t):
            cur, sendbuf, outputs = carry
            # Issue the hop for the activation computed d ticks ago
            # (circular buffer slot t % d). It has NO data dependency on
            # this tick's compute below — XLA is free to run the
            # collective-permute concurrently with it, which is the
            # whole point of the schedule.
            send = lax.dynamic_index_in_dim(
                sendbuf, jnp.mod(t, d), 0, keepdims=False
            )
            recv = lax.ppermute(send, axis, shift)
            y = local_stack(params_local, cur)
            m = t - (num_ranks - 1) * (d + 1)
            write = jnp.logical_and(
                rank == num_ranks - 1,
                jnp.logical_and(m >= 0, m < num_micro),
            )
            updated = lax.dynamic_update_index_in_dim(
                outputs,
                y.astype(outputs.dtype),
                jnp.clip(m, 0, num_micro - 1),
                0,
            )
            outputs = jnp.where(write, updated, outputs)
            sendbuf = lax.dynamic_update_index_in_dim(
                sendbuf, y, jnp.mod(t, d), 0
            )
            # Rank 0 injects next tick's microbatch; everyone else
            # consumes what just arrived (computed d+1 ticks ago
            # upstream — bubble ticks carry garbage the output mask
            # drops).
            inject = lax.dynamic_index_in_dim(
                xs_local, jnp.clip(t + 1, 0, num_micro - 1), 0,
                keepdims=False,
            )
            cur = jnp.where(rank == 0, inject, recv)
            return (cur, sendbuf, outputs), None

        first = lax.dynamic_index_in_dim(xs_local, 0, 0, keepdims=False)
        init = _to_varying(
            (
                jnp.where(
                    rank == 0, first, jnp.zeros(mb_shape, xs_local.dtype)
                ),
                jnp.zeros((d, *mb_shape), xs_local.dtype),
                jnp.zeros((num_micro, *mb_shape), xs_local.dtype),
            ),
            vary_axes,
        )
        (_, _, outputs), _ = lax.scan(step, init, jnp.arange(ticks))
        return lax.psum(outputs, axis)

    body = (
        pipelined_serial if schedule == "serial" else pipelined_overlap
    )
    pipelined = _shard_map_compat(
        body, mesh=mesh, in_specs=(param_specs, x_spec), out_specs=x_spec
    )
    return pipelined(stacked_params, xs)


def spmd_pipeline_from_config(
    block_fn: Callable[[Any, jax.Array], jax.Array],
    stacked_params: Any,
    x: jax.Array,
    mesh: Mesh,
    config,
    axis: str = "pp",
    batch_axis: str | None = None,
) -> jax.Array:
    """``spmd_pipeline`` driven by a :class:`adapt_tpu.config.
    PipelineConfig`: splits the [B, ...] batch into
    ``config.microbatches`` and runs its schedule/hop_buffers knobs —
    the one-stop entry for drivers and benchmarks."""
    xs = pipeline_microbatch(x, config.microbatches)
    y = spmd_pipeline(
        block_fn,
        stacked_params,
        xs,
        mesh,
        axis=axis,
        batch_axis=batch_axis,
        schedule=config.schedule,
        hop_buffers=config.hop_buffers,
    )
    return pipeline_unmicrobatch(y)


def pipeline_microbatch(
    x: jax.Array, num_micro: int
) -> jax.Array:
    """[B, ...] -> [M, B/M, ...] microbatch split."""
    if x.shape[0] % num_micro:
        raise ValueError(
            f"batch {x.shape[0]} not divisible by {num_micro} microbatches"
        )
    return x.reshape(num_micro, x.shape[0] // num_micro, *x.shape[1:])


def pipeline_unmicrobatch(y: jax.Array) -> jax.Array:
    """[M, mb, ...] -> [B, ...]."""
    return y.reshape(y.shape[0] * y.shape[1], *y.shape[2:])
