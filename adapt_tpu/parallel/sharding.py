"""Sharding helpers: NamedShardings and param-placement rules.

The reference has no intra-model parallelism at all (SURVEY.md §2.2: PP
only). TPU-native, DP/TP are nearly free via GSPMD: annotate batch and
weight shardings over a mesh and let XLA insert the collectives (the
scaling-book recipe). These helpers centralize the annotations — the
Mesh-TensorFlow discipline of expressing the layout ONCE: path-pattern
rules map a param tree to PartitionSpecs (``vit_tp_rules`` for the ViT
encoder, ``lm_tp_rules`` for the transformer-LM serving tier), and
``merge_specs`` composes orthogonal placements (EP x TP) for one param.
"""

from __future__ import annotations

import re
from collections.abc import Mapping

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def batch_sharding(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    """Shard the leading (batch) dimension over ``axis``."""
    return NamedSharding(mesh, P(axis))


def shard_batch(x: jax.Array, mesh: Mesh, axis: str = "dp") -> jax.Array:
    return jax.device_put(x, batch_sharding(mesh, axis))


def replicate(tree, mesh: Mesh):
    """Fully replicate a pytree over the mesh."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(tree, sharding)


def kv_head_sharding(mesh: Mesh, axis: str = "tp") -> NamedSharding:
    """THE KV-cache placement under tensor parallelism: shard dim 1 —
    the head axis — over ``axis``, leave everything else whole. One
    spec serves every KV leaf the serving tier allocates, because they
    all put heads on dim 1 by convention: dense slot strips
    ``(slots, kv_heads, L, hd)``, paged pools
    ``(pages, kv_heads, P, hd)``, and the int8 SCALE PLANES of
    quantized caches/pools ``(..., kv_heads, ..., 1)`` — a quantized
    cache is a ``(values, scales)`` pytree whose members must pin to
    the SAME sharding or GSPMD reshards one of them mid-decode
    (``runtime/continuous._shard_kv`` applies this spec per leaf)."""
    return NamedSharding(mesh, P(None, axis))


#: Tensor-parallel placement rules for the ViT encoder blocks
#: (``models/vit.py``): megatron-style — qkv/mlp-in column-split over 'tp',
#: attn-out/mlp-out row-split, so each block needs exactly one psum pair
#: (inserted automatically by GSPMD). Param paths follow
#: ``MultiHeadSelfAttention`` (fused ``attn/qkv`` DenseGeneral with a
#: (d, 3, heads, head_dim) kernel — heads axis is the column split — and a
#: 2-D ``attn/out`` row-split on the contracted d = heads*head_dim).
_VIT_TP_PATTERNS: list[tuple[str, tuple]] = [
    (r"encoder_block.*attn/qkv/kernel", (None, None, "tp", None)),
    (r"encoder_block.*attn/qkv/bias", (None, "tp", None)),
    (r"encoder_block.*attn/out/kernel", ("tp", None)),
    (r"encoder_block.*Dense_0.*kernel", (None, "tp")),  # mlp in
    (r"encoder_block.*Dense_0.*bias", ("tp",)),
    (r"encoder_block.*Dense_1.*kernel", ("tp", None)),  # mlp out
]


def vit_tp_rules(path: str, value_ndim: int) -> P:
    """Map a flattened param path to its TP PartitionSpec (default:
    replicated)."""
    for pattern, spec in _VIT_TP_PATTERNS:
        if re.fullmatch(pattern, path):
            if len(spec) == value_ndim:
                return P(*spec)
    return P()


#: Tensor-parallel placement rules for the transformer-LM decoder blocks
#: (``models/transformer_lm.py``) — the serving-tier counterpart of
#: ``_VIT_TP_PATTERNS``, megatron-style so each block costs exactly ONE
#: psum pair per token (attn-out + mlp-out row splits; everything before
#: them column-splits and needs no collective):
#:
#: - fused MHA ``attn/qkv`` ((d, 3, heads, hd) DenseGeneral): the heads
#:   axis is the column split — each shard projects heads/tp query AND
#:   KV heads, so the KV cache head axis shards with it;
#: - GQA ``attn/q`` ((d, heads, hd)) / ``attn/kv`` ((d, 2, kv_heads,
#:   hd)): both head axes split over tp — kv_heads % tp == 0 keeps every
#:   shard's query-head groups aligned with its own KV heads (adjacent
#:   groups, the ``_group_q`` fold), so GQA attention stays collective-
#:   free;
#: - ``attn/out`` ((heads*hd, d)): row split on the contracted axis —
#:   the block's first psum;
#: - dense MLP ``mlp_in`` column / ``mlp_out`` row — the second psum;
#: - MoE experts ``moe/w1`` ((E, d, hidden)) / ``moe/w2`` ((E, hidden,
#:   d)): the HIDDEN axis splits over tp, the leading expert axis is
#:   deliberately left unsharded so these specs compose with
#:   ``parallel/expert.py``'s ``ep`` placement (``merge_specs``); the
#:   router ``gate`` replicates;
#: - ``head/logits`` ((d, vocab)): row split on the contracted model dim
#:   (one final psum; logits come out replicated, so sampling/argmax is
#:   sharding-blind). Embeddings, LayerNorms and out/mlp_out biases
#:   replicate (biases add after the psum).
_LM_TP_PATTERNS: list[tuple[str, tuple]] = [
    (r"decoder_block.*attn/qkv/kernel", (None, None, "tp", None)),
    (r"decoder_block.*attn/qkv/bias", (None, "tp", None)),
    (r"decoder_block.*attn/q/kernel", (None, "tp", None)),
    (r"decoder_block.*attn/q/bias", ("tp", None)),
    (r"decoder_block.*attn/kv/kernel", (None, None, "tp", None)),
    (r"decoder_block.*attn/kv/bias", (None, "tp", None)),
    (r"decoder_block.*attn/out/kernel", ("tp", None)),
    (r"decoder_block.*mlp_in/kernel", (None, "tp")),
    (r"decoder_block.*mlp_in/bias", ("tp",)),
    (r"decoder_block.*mlp_out/kernel", ("tp", None)),
    (r"decoder_block.*moe/w1", (None, None, "tp")),
    (r"decoder_block.*moe/b1", (None, "tp")),
    (r"decoder_block.*moe/w2", (None, "tp", None)),
    (r"head.*logits/kernel", ("tp", None)),
]


def lm_tp_rules(path: str, value_ndim: int, axis: str = "tp") -> P:
    """Map a flattened transformer-LM param path to its TP PartitionSpec
    (default: replicated). ``axis`` renames the mesh axis the splits
    land on (``config.ParallelConfig.axis``)."""
    for pattern, spec in _LM_TP_PATTERNS:
        if re.fullmatch(pattern, path):
            if len(spec) == value_ndim:
                return P(*(axis if s == "tp" else s for s in spec))
    return P()


def merge_specs(a: P, b: P) -> P:
    """Compose two PartitionSpecs for ONE param — e.g. an MoE expert
    weight's ``ep`` placement (``parallel/expert.py``: leading expert
    axis) with its ``tp`` placement (``lm_tp_rules``: hidden axis) into
    ``P('ep', None, 'tp')``. Each dim takes whichever spec shards it;
    both sharding the same dim onto different axes is a conflict and
    raises."""
    n = max(len(a), len(b))
    out = []
    for i in range(n):
        ax_a = a[i] if i < len(a) else None
        ax_b = b[i] if i < len(b) else None
        if ax_a is not None and ax_b is not None and ax_a != ax_b:
            raise ValueError(
                f"specs conflict on dim {i}: {a} vs {b} "
                f"({ax_a!r} != {ax_b!r})"
            )
        out.append(ax_a if ax_a is not None else ax_b)
    return P(*out)


def tree_shardings(
    variables: Mapping, mesh: Mesh, rules=vit_tp_rules
) -> Mapping:
    """Build a NamedSharding pytree from path-based rules."""

    def assign(path, leaf):
        path_str = "/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in path
        )
        return NamedSharding(mesh, rules(path_str, leaf.ndim))

    return jax.tree_util.tree_map_with_path(assign, variables)
