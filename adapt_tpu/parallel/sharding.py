"""Sharding helpers: NamedShardings and param-placement rules.

The reference has no intra-model parallelism at all (SURVEY.md §2.2: PP
only). TPU-native, DP/TP are nearly free via GSPMD: annotate batch and
weight shardings over a mesh and let XLA insert the collectives (the
scaling-book recipe). These helpers centralize the annotations — the
Mesh-TensorFlow discipline of expressing the layout ONCE: path-pattern
rules map a param tree to PartitionSpecs (``vit_tp_rules`` for the ViT
encoder, ``lm_tp_rules`` for the transformer-LM serving tier), and
``merge_specs`` composes orthogonal placements (EP x TP) for one param.
"""

from __future__ import annotations

import dataclasses
import re
from collections.abc import Mapping

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def batch_sharding(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    """Shard the leading (batch) dimension over ``axis``."""
    return NamedSharding(mesh, P(axis))


def shard_batch(x: jax.Array, mesh: Mesh, axis: str = "dp") -> jax.Array:
    return jax.device_put(x, batch_sharding(mesh, axis))


def replicate(tree, mesh: Mesh):
    """Fully replicate a pytree over the mesh."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(tree, sharding)


def kv_head_sharding(mesh: Mesh, axis: str = "tp") -> NamedSharding:
    """THE KV-cache placement under tensor parallelism: shard dim 1 —
    the head axis — over ``axis``, leave everything else whole. One
    spec serves every KV leaf the serving tier allocates, because they
    all put heads on dim 1 by convention: dense slot strips
    ``(slots, kv_heads, L, hd)``, paged pools
    ``(pages, kv_heads, P, hd)``, and the int8 SCALE PLANES of
    quantized caches/pools ``(..., kv_heads, ..., 1)`` — a quantized
    cache is a ``(values, scales)`` pytree whose members must pin to
    the SAME sharding or GSPMD reshards one of them mid-decode
    (``runtime/continuous._shard_kv`` applies this spec per leaf)."""
    return NamedSharding(mesh, P(None, axis))


#: Tensor-parallel placement rules for the ViT encoder blocks
#: (``models/vit.py``): megatron-style — qkv/mlp-in column-split over 'tp',
#: attn-out/mlp-out row-split, so each block needs exactly one psum pair
#: (inserted automatically by GSPMD). Param paths follow
#: ``MultiHeadSelfAttention`` (fused ``attn/qkv`` DenseGeneral with a
#: (d, 3, heads, head_dim) kernel — heads axis is the column split — and a
#: 2-D ``attn/out`` row-split on the contracted d = heads*head_dim).
_VIT_TP_PATTERNS: list[tuple[str, tuple]] = [
    (r"encoder_block.*attn/qkv/kernel", (None, None, "tp", None)),
    (r"encoder_block.*attn/qkv/bias", (None, "tp", None)),
    (r"encoder_block.*attn/out/kernel", ("tp", None)),
    (r"encoder_block.*Dense_0.*kernel", (None, "tp")),  # mlp in
    (r"encoder_block.*Dense_0.*bias", ("tp",)),
    (r"encoder_block.*Dense_1.*kernel", ("tp", None)),  # mlp out
]


def vit_tp_rules(path: str, value_ndim: int) -> P:
    """Map a flattened param path to its TP PartitionSpec (default:
    replicated)."""
    for pattern, spec in _VIT_TP_PATTERNS:
        if re.fullmatch(pattern, path):
            if len(spec) == value_ndim:
                return P(*spec)
    return P()


#: Tensor-parallel placement rules for the transformer-LM decoder blocks
#: (``models/transformer_lm.py``) — the serving-tier counterpart of
#: ``_VIT_TP_PATTERNS``, megatron-style so each block costs exactly ONE
#: psum pair per token (attn-out + mlp-out row splits; everything before
#: them column-splits and needs no collective):
#:
#: - fused MHA ``attn/qkv`` ((d, 3, heads, hd) DenseGeneral): the heads
#:   axis is the column split — each shard projects heads/tp query AND
#:   KV heads, so the KV cache head axis shards with it;
#: - GQA ``attn/q`` ((d, heads, hd)) / ``attn/kv`` ((d, 2, kv_heads,
#:   hd)): both head axes split over tp — kv_heads % tp == 0 keeps every
#:   shard's query-head groups aligned with its own KV heads (adjacent
#:   groups, the ``_group_q`` fold), so GQA attention stays collective-
#:   free;
#: - ``attn/out`` ((heads*hd, d)): row split on the contracted axis —
#:   the block's first psum;
#: - dense MLP ``mlp_in`` column / ``mlp_out`` row — the second psum;
#: - MoE experts ``moe/w1`` ((E, d, hidden)) / ``moe/w2`` ((E, hidden,
#:   d)): the HIDDEN axis splits over tp, the leading expert axis is
#:   deliberately left unsharded so these specs compose with
#:   ``parallel/expert.py``'s ``ep`` placement (``merge_specs``); the
#:   router ``gate`` replicates;
#: - ``head/logits`` ((d, vocab)): row split on the contracted model dim
#:   (one final psum; logits come out replicated, so sampling/argmax is
#:   sharding-blind). Embeddings, LayerNorms and out/mlp_out biases
#:   replicate (biases add after the psum).
_LM_TP_PATTERNS: list[tuple[str, tuple]] = [
    (r"decoder_block.*attn/qkv/kernel", (None, None, "tp", None)),
    (r"decoder_block.*attn/qkv/bias", (None, "tp", None)),
    (r"decoder_block.*attn/q/kernel", (None, "tp", None)),
    (r"decoder_block.*attn/q/bias", ("tp", None)),
    (r"decoder_block.*attn/kv/kernel", (None, None, "tp", None)),
    (r"decoder_block.*attn/kv/bias", (None, "tp", None)),
    (r"decoder_block.*attn/out/kernel", ("tp", None)),
    (r"decoder_block.*mlp_in/kernel", (None, "tp")),
    (r"decoder_block.*mlp_in/bias", ("tp",)),
    (r"decoder_block.*mlp_out/kernel", ("tp", None)),
    (r"decoder_block.*moe/w1", (None, None, "tp")),
    (r"decoder_block.*moe/b1", (None, "tp")),
    (r"decoder_block.*moe/w2", (None, "tp", None)),
    (r"head.*logits/kernel", ("tp", None)),
]


def lm_tp_rules(path: str, value_ndim: int, axis: str = "tp") -> P:
    """Map a flattened transformer-LM param path to its TP PartitionSpec
    (default: replicated). ``axis`` renames the mesh axis the splits
    land on (``config.ParallelConfig.axis``)."""
    for pattern, spec in _LM_TP_PATTERNS:
        if re.fullmatch(pattern, path):
            if len(spec) == value_ndim:
                return P(*(axis if s == "tp" else s for s in spec))
    return P()


def merge_specs(a: P, b: P) -> P:
    """Compose two PartitionSpecs for ONE param — e.g. an MoE expert
    weight's ``ep`` placement (``parallel/expert.py``: leading expert
    axis) with its ``tp`` placement (``lm_tp_rules``: hidden axis) into
    ``P('ep', None, 'tp')``. Each dim takes whichever spec shards it;
    both sharding the same dim onto different axes is a conflict and
    raises."""
    n = max(len(a), len(b))
    out = []
    for i in range(n):
        ax_a = a[i] if i < len(a) else None
        ax_b = b[i] if i < len(b) else None
        if ax_a is not None and ax_b is not None and ax_a != ax_b:
            raise ValueError(
                f"specs conflict on dim {i}: {a} vs {b} "
                f"({ax_a!r} != {ax_b!r})"
            )
        out.append(ax_a if ax_a is not None else ax_b)
    return P(*out)


@dataclasses.dataclass
class KVReshardPlan:
    """Explicit redistribution plan for live head-sharded KV state
    across a mesh SHRINK (elastic recovery: tp=4 -> tp=2 after a chip
    loss) — the ``runtime/continuous`` migration executor.

    The plan is per-SHARD, never a global gather (the
    memory-efficient-redistribution discipline of arXiv:2112.01075):
    each new shard's head range is an aligned union of old shard
    ranges (``new_tp`` divides ``old_tp``, and both divide the head
    count, so ranges tile exactly), and every old range moves by the
    cheapest route its source allows —

    - **surviving shard** -> device-to-device re-place onto the new
      owner (counted in :attr:`moved_bytes`); the peer-to-peer
      transfer shape of arXiv:2211.05322's cross-mesh resharding;
    - **lost shard** -> staged through the HOST
      (:attr:`host_staged_bytes`). Under the simulated-kill fault
      model this reads the killed device's still-resident buffer; in a
      real deployment this is the seam where the host-tier recovery
      source (host-RAM KV mirror, disaggregated KV store, or
      recompute-from-journal) plugs in — requests unwilling to pay it
      replay from the journal instead
      (``config.RecoveryConfig.policy``).

    Replicated state (page tables, the device-resident sampling state,
    the draft model) moves via :meth:`migrate_replicated`: one
    surviving replica is the source, so a dead device never serves a
    read on the fast path."""

    #: Old tp-axis device order (mesh axis order — shard i held heads
    #: ``[i * H/old_tp, (i+1) * H/old_tp)``).
    old_devices: tuple
    #: New tp-axis device order (the shrunk mesh's axis; a 1-tuple for
    #: the single-device fallback).
    new_devices: tuple
    #: Device ids whose shards are lost (host-staged sources).
    lost_ids: frozenset
    #: Mesh axis the head splits live on (accounting/debug only — the
    #: shard geometry is read off each migrated array's sharding).
    axis: str = "tp"
    #: Bytes moved device-to-device (surviving shards).
    moved_bytes: int = 0
    #: Bytes staged through the host (the lost shard's head ranges).
    host_staged_bytes: int = 0

    def __post_init__(self):
        old_n, new_n = len(self.old_devices), len(self.new_devices)
        if new_n < 1:
            raise ValueError("plan needs at least one new device")
        if old_n % new_n:
            raise ValueError(
                f"new tp {new_n} must divide old tp {old_n} — head "
                "ranges only tile exactly for divisor shrinks"
            )
        survivors = {
            int(d.id) for d in self.old_devices
        } - set(self.lost_ids)
        for d in self.new_devices:
            if int(d.id) not in survivors:
                raise ValueError(
                    f"new device {d} is not a surviving old-mesh device"
                )

    def _shard_data(self, x) -> dict:
        """device id -> resident single-device shard of ``x``."""
        return {int(s.device.id): s.data for s in x.addressable_shards}

    def migrate(self, x, new_sharding, head_dim: int = 1):
        """Move ONE head-sharded leaf (heads on ``head_dim`` — the
        repo-wide KV convention, dense strips / pools / int8 scale
        planes alike) from its current layout onto ``new_sharding``.
        Bit-exact: the output holds the same bytes re-placed, so a
        migrated request's stream cannot diverge."""
        shape = x.shape
        old_map = x.sharding.devices_indices_map(shape)
        old_data = self._shard_data(x)
        # Old head ranges in ascending order: (lo, hi, device_id).
        spans = sorted(
            (
                idx[head_dim].indices(shape[head_dim])[:2] + (int(d.id),)
                for d, idx in old_map.items()
            ),
        )
        new_map = new_sharding.devices_indices_map(shape)
        bufs = []
        for ndev, nidx in new_map.items():
            lo, hi = nidx[head_dim].indices(shape[head_dim])[:2]
            pieces, cover = [], lo
            for slo, shi, did in spans:
                if slo < lo or shi > hi:
                    continue  # outside this new shard's range
                if slo != cover:
                    raise ValueError(
                        f"head ranges misaligned: need [{lo},{hi}), "
                        f"next source starts at {slo}, covered to {cover}"
                    )
                src = old_data[did]
                if did in self.lost_ids:
                    # Host staging: the ONLY read path touching the
                    # lost shard (see class docstring for what stands
                    # behind it on real hardware).
                    src = np.asarray(src)
                    self.host_staged_bytes += int(src.nbytes)
                elif did != int(ndev.id):
                    # A shard whose new owner is the device it already
                    # lives on does not move (device_put is a no-op) —
                    # moved_bytes reports real inter-device traffic,
                    # the number ICI/capacity planning needs.
                    self.moved_bytes += int(src.nbytes)
                pieces.append(jax.device_put(src, ndev))
                cover = shi
            if cover != hi:
                raise ValueError(
                    f"head range [{lo},{hi}) not covered (reached "
                    f"{cover}) — old/new shardings do not tile"
                )
            bufs.append(
                pieces[0]
                if len(pieces) == 1
                else jnp.concatenate(pieces, axis=head_dim)
            )
        return jax.make_array_from_single_device_arrays(
            shape, new_sharding, bufs
        )

    def migrate_tree(self, tree, new_sharding, head_dim: int = 1):
        """:meth:`migrate` over every leaf of a KV pytree — the
        ``(values, scales)`` members of quantized caches move under the
        SAME plan, so a page's scales always travel with its int8
        payload."""
        return jax.tree.map(
            lambda x: self.migrate(x, new_sharding, head_dim), tree
        )

    def migrate_replicated(self, tree, new_sharding):
        """Re-place fully-replicated state (sampling state, draft
        weights/caches, staged tables) onto the new layout, reading
        from a SURVIVING replica — never the lost device."""

        def one(x):
            src = src_id = None
            for s in x.addressable_shards:
                if int(s.device.id) not in self.lost_ids:
                    src, src_id = s.data, int(s.device.id)
                    break
            if src is None:  # every replica lost: host fallback
                src = np.asarray(x)
                self.host_staged_bytes += int(src.nbytes)
            else:
                # One copy per destination the replica does not already
                # live on — a same-device re-place is a no-op
                # device_put (migrate()'s only-real-traffic rule).
                self.moved_bytes += int(src.nbytes) * sum(
                    1
                    for d in new_sharding.device_set
                    if int(d.id) != src_id
                )
            return jax.device_put(src, new_sharding)

        return jax.tree.map(one, tree)

    def summary(self) -> dict:
        """Accounting for flight events / logs."""
        return {
            "old_tp": len(self.old_devices),
            "new_tp": len(self.new_devices),
            "lost": sorted(self.lost_ids),
            "moved_bytes": self.moved_bytes,
            "host_staged_bytes": self.host_staged_bytes,
        }


@dataclasses.dataclass
class KVHandoffPlan:
    """Placement plan for KV pages STREAMED INTO a serving pool from
    outside the mesh — the disaggregated prefill/decode handoff
    (``runtime/disagg``), sibling of :class:`KVReshardPlan` (that one
    moves live state ACROSS a mesh shrink; this one lands host-staged
    pages on whatever layout the destination pool runs).

    The pages arrive host-side as page-major ``(n_pages, kv_heads,
    page, w)`` arrays holding the FULL head range (the prefill tier is
    layout-agnostic by design — it need not know the decode mesh). The
    plan maps them onto the pool's sharding by ALIGNED UNION, never a
    global gather (the 2211.05322 cross-mesh point-to-point
    discipline): under a head-sharded decode pool each shard's head
    range is a contiguous slice of the incoming array, so every device
    receives ONLY its own heads — one host->device transfer of
    ``logical_bytes / tp`` per shard, no replicated staging, no
    all-gather for GSPMD to untangle. Single-device and no-mesh pools
    degrade to one ordinary placement. Both members of a quantized
    ``(values, scales)`` pool place under the same plan
    (:meth:`place_tree`), so a page's scales land with its int8
    payload."""

    #: The destination pool's sharding: a head-axis ``NamedSharding``
    #: (``kv_head_sharding``), a ``SingleDeviceSharding`` (tp=1
    #: remnant), or None (no-mesh pool — default placement). The shard
    #: slices are read straight off ``devices_indices_map``, so any
    #: axis layout the sharding expresses is honored as-is.
    sharding: object | None
    #: Bytes staged host->device by this plan (sums to the logical
    #: bytes once per placed tree — each shard stages only its slice).
    staged_bytes: int = 0

    def place(self, kv_host):
        """Place ONE page-major host array onto the pool's layout.
        Returns a jax array whose sharding matches the pool's, built
        shard-by-shard — the scatter into the pool is then fully
        shard-local (no collective in the adoption program)."""
        kv_host = np.asarray(kv_host)
        self.staged_bytes += int(kv_host.nbytes)
        if self.sharding is None:
            return jnp.asarray(kv_host)
        if not isinstance(self.sharding, NamedSharding):
            # SingleDeviceSharding (and duck-typed equivalents): one
            # committed placement, same discipline as the tp=1 remnant.
            return jax.device_put(kv_host, self.sharding)
        shape = kv_host.shape
        bufs = [
            # Basic slicing: each shard's slice is a VIEW of the host
            # array; the only copy is the transfer itself.
            jax.device_put(kv_host[idx], d)
            for d, idx in self.sharding.devices_indices_map(shape).items()
        ]
        return jax.make_array_from_single_device_arrays(
            shape, self.sharding, bufs
        )

    def place_tree(self, tree):
        """:meth:`place` over every leaf — the ``(values, scales)``
        members of quantized page chunks land under ONE plan."""
        return jax.tree.map(self.place, tree)


def fetch_head_shards(x, index: int, head_dim: int = 1):
    """Host copy of ``x[index]`` assembled PER SHARD along the head
    axis — the D2H counterpart of :meth:`KVHandoffPlan.place`, used by
    the host-tier SPILL path (``runtime/continuous``): each device
    ships only its resident heads (one single-device slice fetch per
    shard), and the full logical head range is concatenated on the
    HOST — never a device-side gather for GSPMD to materialize (the
    same 2112.01075 discipline the reshard/handoff plans keep).

    ``x`` is a leading-axis-indexed pool leaf ``(pages, kv_heads, P,
    w)``; head ranges must tile the head axis exactly (any sharding a
    ``kv_head_sharding`` pool can carry does) or this raises — a
    layout the spill path cannot reassemble must fail by name, never
    spill interleaved garbage."""
    sharding = getattr(x, "sharding", None)
    if sharding is None or len(getattr(sharding, "device_set", ())) <= 1:
        return np.asarray(x[index])
    h = x.shape[head_dim]
    spans = []
    for s in x.addressable_shards:
        lo, hi = s.index[head_dim].indices(h)[:2]
        spans.append((lo, hi, s))
    spans.sort(key=lambda t: (t[0], t[1]))
    pieces, cover = [], 0
    for lo, hi, s in spans:
        if lo < cover:
            if hi <= cover:
                continue  # replicated duplicate of a covered range
            raise ValueError(
                f"head ranges overlap without nesting: [{lo},{hi}) vs "
                f"covered [0,{cover})"
            )
        if lo != cover:
            raise ValueError(
                f"head ranges misaligned: next shard starts at {lo}, "
                f"covered to {cover}"
            )
        # One tiny slice dispatch on the shard's own device, then the
        # per-shard D2H — the only transfers this fetch issues.
        pieces.append(np.asarray(s.data[index]))
        cover = hi
    if cover != h:
        raise ValueError(
            f"head axis not covered: reached {cover} of {h}"
        )
    return (
        pieces[0] if len(pieces) == 1 else np.concatenate(pieces, axis=0)
    )


def head_tiles(kv_heads: int, parts: int) -> list[tuple[int, int]]:
    """Contiguous even ``(lo, hi)`` head-axis tiles — the slices a
    ``kv_head_sharding`` destination of ``parts`` shards reads off
    ``devices_indices_map``, computable WITHOUT the destination's mesh
    in hand (the cross-replica sender knows only the peer's tp). A
    sender framing KV pages per tile ships exactly the bytes each
    destination shard will ``device_put`` — the aligned-union wire
    counterpart of :meth:`KVHandoffPlan.place` (2211.05322: point to
    point, never a global gather)."""
    kv_heads, parts = int(kv_heads), int(parts)
    if parts < 1:
        raise ValueError(f"parts must be >= 1, got {parts}")
    if kv_heads < 1 or kv_heads % parts:
        raise ValueError(
            f"{parts} tiles must evenly cover {kv_heads} kv heads"
        )
    w = kv_heads // parts
    return [(i * w, (i + 1) * w) for i in range(parts)]


def plan_kv_handoff(sharding) -> KVHandoffPlan:
    """Build the :class:`KVHandoffPlan` for a destination pool's
    sharding (None for a no-mesh pool)."""
    return KVHandoffPlan(sharding=sharding)


def plan_kv_reshard(
    old_devices, new_devices, lost_ids, axis: str = "tp"
) -> KVReshardPlan:
    """Build the :class:`KVReshardPlan` for a mesh shrink: ``old_devices``
    in old tp-axis order, ``new_devices`` the surviving devices chosen
    for the shrunk axis, ``lost_ids`` the dead device ids."""
    return KVReshardPlan(
        old_devices=tuple(old_devices),
        new_devices=tuple(new_devices),
        lost_ids=frozenset(int(i) for i in lost_ids),
        axis=axis,
    )


def tree_shardings(
    variables: Mapping, mesh: Mesh, rules=vit_tp_rules
) -> Mapping:
    """Build a NamedSharding pytree from path-based rules."""

    def assign(path, leaf):
        path_str = "/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in path
        )
        return NamedSharding(mesh, rules(path_str, leaf.ndim))

    return jax.tree_util.tree_map_with_path(assign, variables)
