"""Sharding helpers: NamedShardings and param-placement rules.

The reference has no intra-model parallelism at all (SURVEY.md §2.2: PP
only). TPU-native, DP/TP are nearly free via GSPMD: annotate batch and
weight shardings over a mesh and let XLA insert the collectives (the
scaling-book recipe). These helpers centralize the annotations.
"""

from __future__ import annotations

import re
from collections.abc import Mapping

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def batch_sharding(mesh: Mesh, axis: str = "dp") -> NamedSharding:
    """Shard the leading (batch) dimension over ``axis``."""
    return NamedSharding(mesh, P(axis))


def shard_batch(x: jax.Array, mesh: Mesh, axis: str = "dp") -> jax.Array:
    return jax.device_put(x, batch_sharding(mesh, axis))


def replicate(tree, mesh: Mesh):
    """Fully replicate a pytree over the mesh."""
    sharding = NamedSharding(mesh, P())
    return jax.device_put(tree, sharding)


#: Tensor-parallel placement rules for the ViT encoder blocks
#: (``models/vit.py``): megatron-style — qkv/mlp-in column-split over 'tp',
#: attn-out/mlp-out row-split, so each block needs exactly one psum pair
#: (inserted automatically by GSPMD). Param paths follow
#: ``MultiHeadSelfAttention`` (fused ``attn/qkv`` DenseGeneral with a
#: (d, 3, heads, head_dim) kernel — heads axis is the column split — and a
#: 2-D ``attn/out`` row-split on the contracted d = heads*head_dim).
_VIT_TP_PATTERNS: list[tuple[str, tuple]] = [
    (r"encoder_block.*attn/qkv/kernel", (None, None, "tp", None)),
    (r"encoder_block.*attn/qkv/bias", (None, "tp", None)),
    (r"encoder_block.*attn/out/kernel", ("tp", None)),
    (r"encoder_block.*Dense_0.*kernel", (None, "tp")),  # mlp in
    (r"encoder_block.*Dense_0.*bias", ("tp",)),
    (r"encoder_block.*Dense_1.*kernel", ("tp", None)),  # mlp out
]


def vit_tp_rules(path: str, value_ndim: int) -> P:
    """Map a flattened param path to its TP PartitionSpec (default:
    replicated)."""
    for pattern, spec in _VIT_TP_PATTERNS:
        if re.fullmatch(pattern, path):
            if len(spec) == value_ndim:
                return P(*spec)
    return P()


def tree_shardings(
    variables: Mapping, mesh: Mesh, rules=vit_tp_rules
) -> Mapping:
    """Build a NamedSharding pytree from path-based rules."""

    def assign(path, leaf):
        path_str = "/".join(
            str(getattr(k, "key", getattr(k, "idx", k))) for k in path
        )
        return NamedSharding(mesh, rules(path_str, leaf.ndim))

    return jax.tree_util.tree_map_with_path(assign, variables)
