"""Ring attention: sequence parallelism for long contexts.

Not in the reference (SURVEY.md §2.2: no attention at all), but first-class
here: sequences too long for one chip's HBM are sharded over an ``sp`` mesh
axis; each device holds a [S/P] slice of Q, K, V. K/V blocks rotate around
the ring via ``lax.ppermute`` (ICI neighbor hops) while each device
accumulates its Q-block's attention with the streaming (online-softmax)
update, so the full S x S score matrix never materializes — compute stays
flash-style blockwise and memory per chip is O(S/P).

The accumulator update is the standard two-pass-free softmax: carrying
running max ``m``, normalizer ``l``, and unnormalized output ``o``; each
incoming K/V block rescales the accumulators by ``exp(m - m_new)``.
Causal masking uses *global* positions recovered from ring step and rank,
so the result matches single-device causal attention exactly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

_NEG_INF = -1e30


def _attention_block(q, k, v, mask, m, l, o):
    """One online-softmax accumulation step.

    q: [B, H, Sq, D]; k, v: [B, H, Skv, D]; mask: [Sq, Skv] additive.
    m, l: [B, H, Sq, 1]; o: [B, H, Sq, D].
    """
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale + mask
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    o_new = o * corr + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return m_new, l_new, o_new


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = False,
) -> jax.Array:
    """Sequence-parallel attention over ``axis``.

    q, k, v: [B, H, S, D] with S divisible by the axis size; inputs/outputs
    are sharded on the S dimension over ``axis`` (pass global arrays under
    jit; GSPMD splits them per the shard_map specs).
    """
    num_ranks = mesh.shape[axis]
    seq = q.shape[2]
    if seq % num_ranks:
        raise ValueError(f"sequence {seq} not divisible by ring size {num_ranks}")
    s_local = seq // num_ranks
    ring = [(i, (i + 1) % num_ranks) for i in range(num_ranks)]

    spec = P(None, None, axis, None)

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    def ringed(q_l, k_l, v_l):
        rank = lax.axis_index(axis)
        b, h, sq, d = q_l.shape
        q_pos = rank * s_local + jnp.arange(s_local)

        def step(carry, i):
            m, l, o, k_cur, v_cur = carry
            # After i hops of forward rotation, this rank holds the K/V
            # block that originated at rank - i (mod P).
            src = jnp.mod(rank - i, num_ranks)
            kv_pos = src * s_local + jnp.arange(s_local)
            if causal:
                mask = jnp.where(
                    q_pos[:, None] >= kv_pos[None, :], 0.0, _NEG_INF
                ).astype(q_l.dtype)
            else:
                mask = jnp.zeros((s_local, s_local), q_l.dtype)
            m, l, o = _attention_block(q_l, k_cur, v_cur, mask, m, l, o)
            k_nxt = lax.ppermute(k_cur, axis, ring)
            v_nxt = lax.ppermute(v_cur, axis, ring)
            return (m, l, o, k_nxt, v_nxt), None

        init = (
            *lax.pcast(
                (
                    jnp.full((b, h, sq, 1), _NEG_INF, q_l.dtype),
                    jnp.zeros((b, h, sq, 1), q_l.dtype),
                    jnp.zeros((b, h, sq, d), q_l.dtype),
                ),
                (axis,),
                to="varying",
            ),
            k_l,
            v_l,
        )
        (m, l, o, _, _), _ = lax.scan(step, init, jnp.arange(num_ranks))
        return o / jnp.maximum(l, 1e-20)

    return ringed(q, k, v)


def full_attention(q, k, v, causal: bool = False) -> jax.Array:
    """Single-device oracle — delegates to the one canonical reference in
    :mod:`adapt_tpu.ops.attention` (same causal convention: absolute
    position i attends j <= i)."""
    from adapt_tpu.ops.attention import attention_reference

    return attention_reference(q, k, v, causal=causal)
