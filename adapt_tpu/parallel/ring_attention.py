"""Ring attention: sequence parallelism for long contexts.

Not in the reference (SURVEY.md §2.2: no attention at all), but first-class
here: sequences too long for one chip's HBM are sharded over an ``sp`` mesh
axis; each device holds a [S/P] slice of Q, K, V. K/V blocks rotate around
the ring via ``lax.ppermute`` (ICI neighbor hops) while each device
accumulates its Q-block's attention with the streaming (online-softmax)
update, so the full S x S score matrix never materializes — compute stays
flash-style blockwise and memory per chip is O(S/P).

The accumulator update is the standard two-pass-free softmax: carrying
running max ``m``, normalizer ``l``, and unnormalized output ``o``; each
incoming K/V block rescales the accumulators by ``exp(m - m_new)``.
Causal masking uses *global* positions recovered from ring step and rank,
so the result matches single-device causal attention exactly.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from adapt_tpu.parallel.compat import shard_map, to_varying

_NEG_INF = -1e30


def _attention_block(q, k, v, mask, m, l, o):
    """One online-softmax accumulation step.

    q: [B, H, Sq, D]; k, v: [B, H, Skv, D]; mask: [Sq, Skv] additive.
    m, l: [B, H, Sq, 1]; o: [B, H, Sq, D].
    """
    scale = 1.0 / jnp.sqrt(q.shape[-1]).astype(q.dtype)
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale + mask
    m_new = jnp.maximum(m, jnp.max(s, axis=-1, keepdims=True))
    p = jnp.exp(s - m_new)
    corr = jnp.exp(m - m_new)
    l_new = l * corr + jnp.sum(p, axis=-1, keepdims=True)
    o_new = o * corr + jnp.einsum("bhqk,bhkd->bhqd", p, v)
    return m_new, l_new, o_new


def stripe_sequence(x: jax.Array, num_ranks: int, axis: int = 2) -> jax.Array:
    """Permute a sequence axis into the STRIPED ring layout: rank r's
    shard holds tokens {r, r + P, r + 2P, ...} instead of a contiguous
    block. ``stripe(x)[..., r*s_local + i, ...] = x[..., i*P + r, ...]``.
    Apply to q/k/v before ``ring_attention(..., layout="striped")`` and
    :func:`unstripe_sequence` to the output (a reshape-transpose; under
    GSPMD it lowers to one all-to-all–class relayout at the boundary,
    paid once per sequence, not per ring step)."""
    s = x.shape[axis]
    if s % num_ranks:
        raise ValueError(f"sequence {s} not divisible by {num_ranks}")
    parts = jnp.moveaxis(x, axis, 0).reshape(
        s // num_ranks, num_ranks, *x.shape[:axis], *x.shape[axis + 1:]
    )
    return jnp.moveaxis(
        jnp.swapaxes(parts, 0, 1).reshape(s, *x.shape[:axis],
                                          *x.shape[axis + 1:]),
        0, axis,
    )


def unstripe_sequence(x: jax.Array, num_ranks: int, axis: int = 2) -> jax.Array:
    """Inverse of :func:`stripe_sequence` — which is striping by the
    complementary factor (out[i*P + r] = x[r*(S/P) + i] both ways), so
    one permutation body serves both and cannot desynchronize."""
    s = x.shape[axis]
    if s % num_ranks:
        raise ValueError(f"sequence {s} not divisible by {num_ranks}")
    return stripe_sequence(x, s // num_ranks, axis)


def ring_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = "sp",
    causal: bool = False,
    block_impl: str = "jnp",
    layout: str = "contiguous",
) -> jax.Array:
    """Sequence-parallel attention over ``axis``.

    q, k, v: [B, H, S, D] with S divisible by the axis size; inputs/outputs
    are sharded on the S dimension over ``axis`` (pass global arrays under
    jit; GSPMD splits them per the shard_map specs).

    ``block_impl`` picks the per-device block compute:

    - ``"jnp"`` (default) — the fused-by-XLA online-softmax update
      below. Fully differentiable (training and serving); materializes
      one (S/P, S/P) score block per ring step, which is fine until
      shards are themselves long.
    - ``"flash"`` — the streaming Pallas kernel via
      :func:`adapt_tpu.ops.attention.flash_attention_with_lse`; per-step
      results merge by logsumexp, so per-device memory stays O(S/P * D)
      even at 32k-token *shards* (the regime where a materialized score
      block is itself gigabytes — same wall as
      ``benchmarks/results/r03/attn_longseq.json``). FORWARD-ONLY: the
      lse entry point has no VJP; ``jax.grad`` through it raises a
      ``NotImplementedError`` naming ``block_impl`` at this function's
      boundary — an explicit serving-path opt-in, which is why it is
      not the default.
    - ``"auto"`` — ``"flash"`` exactly when a single score block busts
      ``FLASH_SCORE_BYTES_BUDGET`` (the same measured predicate the
      kernel dispatch uses), ``"jnp"`` otherwise. For inference
      pipelines that want the memory ceiling lifted without thinking;
      carries the same forward-only caveat whenever it picks flash.

    ``layout`` is how global token positions map to shards:

    - ``"contiguous"`` (default) — rank r holds tokens [r*S/P, (r+1)*S/P).
      Under ``causal`` the ring is LOAD-IMBALANCED: rank 0's queries see
      only their own block while rank P-1's see everything, and because
      the ``ppermute`` rotation must run the same trip count on every
      rank, the idle lower-triangle steps are latency floor, not saved
      work (the flash path's ``lax.cond`` computes both branches under
      SPMD).
    - ``"striped"`` — rank r holds tokens {r, r+P, ...} (pre-permute
      q/k/v with :func:`stripe_sequence`, un-permute the output with
      :func:`unstripe_sequence`; the output of this function is in
      striped order). Every causal ring step becomes a triangular block
      with diagonal shift 0 (src <= rank) or 1 (src > rank) — uniformly
      HALF the work on every rank at every step, with no cond at all:
      the flash path passes the traced shift to the kernel's
      ``causal_shift`` and rides its block-skip, the jnp path's mask
      just uses striped positions. This is the classic striped-attention
      balance fix; ~2x over contiguous causal at long S.
    """
    num_ranks = mesh.shape[axis]
    seq = q.shape[2]
    if seq % num_ranks:
        raise ValueError(f"sequence {seq} not divisible by ring size {num_ranks}")
    s_local = seq // num_ranks
    ring = [(i, (i + 1) % num_ranks) for i in range(num_ranks)]

    if block_impl not in ("auto", "jnp", "flash"):
        raise ValueError(
            f"block_impl={block_impl!r}: expected 'auto', 'jnp' or 'flash'"
        )
    if layout not in ("contiguous", "striped"):
        raise ValueError(
            f"layout={layout!r}: expected 'contiguous' or 'striped'"
        )
    if block_impl == "auto":
        from adapt_tpu.ops.attention import scores_over_budget

        local_shape = (q.shape[0], q.shape[1], s_local, q.shape[3])
        block_impl = (
            "flash" if scores_over_budget(local_shape, local_shape) else "jnp"
        )
    if block_impl == "flash":
        # custom_vjp wrapper so differentiating (e.g. a training run whose
        # sequence length grew past the budget while "auto" silently
        # switched to flash) fails at THIS boundary with a message naming
        # block_impl — not deep inside pallas_call internals.
        kw = dict(
            mesh=mesh,
            axis=axis,
            causal=causal,
            num_ranks=num_ranks,
            s_local=s_local,
            ring=ring,
            striped=layout == "striped",
        )

        @jax.custom_vjp
        def run(q, k, v):
            return _ring_attention_flash(q, k, v, **kw)

        def fwd(q, k, v):
            return _ring_attention_flash(q, k, v, **kw), None

        def bwd(_, g):
            raise NotImplementedError(
                "ring_attention block_impl='flash' (including 'auto' "
                "resolving to flash at this shard shape) is forward-only: "
                "the streaming-kernel lse entry point has no VJP. Use "
                "block_impl='jnp' for training, or shrink the per-shard "
                "score block under FLASH_SCORE_BYTES_BUDGET."
            )

        run.defvjp(fwd, bwd)
        return run(q, k, v)

    spec = P(None, None, axis, None)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    def ringed(q_l, k_l, v_l):
        rank = lax.axis_index(axis)
        b, h, sq, d = q_l.shape
        local = jnp.arange(s_local)
        q_pos = (
            local * num_ranks + rank
            if layout == "striped"
            else rank * s_local + local
        )

        def step(carry, i):
            m, l, o, k_cur, v_cur = carry
            # After i hops of forward rotation, this rank holds the K/V
            # block that originated at rank - i (mod P).
            src = jnp.mod(rank - i, num_ranks)
            kv_pos = (
                local * num_ranks + src
                if layout == "striped"
                else src * s_local + local
            )
            if causal:
                mask = jnp.where(
                    q_pos[:, None] >= kv_pos[None, :], 0.0, _NEG_INF
                ).astype(q_l.dtype)
            else:
                mask = jnp.zeros((s_local, s_local), q_l.dtype)
            m, l, o = _attention_block(q_l, k_cur, v_cur, mask, m, l, o)
            k_nxt = lax.ppermute(k_cur, axis, ring)
            v_nxt = lax.ppermute(v_cur, axis, ring)
            return (m, l, o, k_nxt, v_nxt), None

        init = (
            *to_varying(
                (
                    jnp.full((b, h, sq, 1), _NEG_INF, q_l.dtype),
                    jnp.zeros((b, h, sq, 1), q_l.dtype),
                    jnp.zeros((b, h, sq, d), q_l.dtype),
                ),
                (axis,),
            ),
            k_l,
            v_l,
        )
        (m, l, o, _, _), _ = lax.scan(step, init, jnp.arange(num_ranks))
        return o / jnp.maximum(l, 1e-20)

    return ringed(q, k, v)


def _ring_attention_flash(
    q, k, v, mesh, axis, causal, num_ranks, s_local, ring, striped=False
):
    """Ring attention whose per-device block compute is the streaming
    Pallas kernel; per-step normalized results combine exactly via the
    logsumexp merge (see ``flash_attention_with_lse``'s contract).

    Under causal masking every (rank, step) block is all-or-nothing
    except the diagonal: the K/V block that originated at ``src`` is
    fully visible when ``src < rank``, fully masked when ``src > rank``,
    and plain causal when ``src == rank`` (step 0) — so no positional
    mask tensor is ever built; the diagonal runs the kernel's own causal
    path and masked steps contribute ``lse = -inf`` to the merge.

    The CONTIGUOUS layout's ``lax.cond`` on ``src < rank`` is
    *correctness* masking, not a compute skip: under SPMD the predicate
    is device-varying, so XLA lowers the cond to running both branches
    and selecting — every rank pays the full kernel on its dead steps
    too. Shortening the loop per-rank cannot fix this: the ``ppermute``
    rotation must run the same number of times on every rank or the
    collective deadlocks, so the contiguous causal ring's lower triangle
    is latency floor, not saved work.

    ``striped=True`` IS the classic layout fix: with tokens striped
    round-robin (see :func:`stripe_sequence`), every (rank, step) causal
    block is a triangle with diagonal shift ``src > rank`` — no cond, no
    dead blocks; each step passes the traced shift to the kernel's
    ``causal_shift`` and its block-level skip does ~half the work,
    uniformly on every rank."""
    from adapt_tpu.ops.attention import flash_attention_with_lse

    spec = P(None, None, axis, None)

    @functools.partial(
        shard_map,
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
    )
    def ringed(q_l, k_l, v_l):
        rank = lax.axis_index(axis)
        # Step 0: the diagonal block (q and K/V positions coincide).
        o0, lse = flash_attention_with_lse(q_l, k_l, v_l, causal=causal)
        o = o0.astype(jnp.float32)
        k_cur = lax.ppermute(k_l, axis, ring)
        v_cur = lax.ppermute(v_l, axis, ring)

        def step(carry, i):
            o, lse, k_cur, v_cur = carry
            src = jnp.mod(rank - i, num_ranks)

            def live(_):
                o_j, lse_j = flash_attention_with_lse(
                    q_l, k_cur, v_cur, causal=False
                )
                return o_j.astype(jnp.float32), lse_j

            def dead(_):
                return (
                    jnp.zeros(o.shape, jnp.float32),
                    jnp.full(lse.shape, _NEG_INF, jnp.float32),
                )

            if causal and striped:
                # Balanced path: every step is a shift-0/1 triangle —
                # the kernel's own causal block-skip does ~half the
                # work on every rank, no cond, no dead blocks.
                o_j, lse_j = flash_attention_with_lse(
                    q_l, k_cur, v_cur, causal=True,
                    causal_shift=(src > rank).astype(jnp.int32),
                )
                o_j = o_j.astype(jnp.float32)
            elif causal:
                o_j, lse_j = lax.cond(src < rank, live, dead, None)
            else:
                o_j, lse_j = live(None)
            m = jnp.maximum(lse, lse_j)
            w_a = jnp.exp(lse - m)
            w_b = jnp.exp(lse_j - m)
            denom = w_a + w_b
            o_new = (
                o * w_a[..., None] + o_j * w_b[..., None]
            ) / denom[..., None]
            lse_new = m + jnp.log(denom)
            # Collectives stay unconditional (outside the cond).
            k_nxt = lax.ppermute(k_cur, axis, ring)
            v_nxt = lax.ppermute(v_cur, axis, ring)
            return (o_new, lse_new, k_nxt, v_nxt), None

        (o, lse, _, _), _ = lax.scan(
            step, (o, lse, k_cur, v_cur), jnp.arange(1, num_ranks)
        )
        return o.astype(q_l.dtype)

    return ringed(q, k, v)


def full_attention(q, k, v, causal: bool = False) -> jax.Array:
    """Single-device oracle — delegates to the one canonical reference in
    :mod:`adapt_tpu.ops.attention` (same causal convention: absolute
    position i attends j <= i)."""
    from adapt_tpu.ops.attention import attention_reference

    return attention_reference(q, k, v, causal=causal)
