from adapt_tpu.parallel.pipeline_decode import (
    pipelined_generate,
    shard_for_pipeline,
)
from adapt_tpu.parallel.pipeline_spmd import spmd_pipeline, stack_stage_params
from adapt_tpu.parallel.ring_attention import (
    ring_attention,
    stripe_sequence,
    unstripe_sequence,
)
from adapt_tpu.parallel.ulysses import ulysses_attention
from adapt_tpu.parallel.sharding import (
    batch_sharding,
    lm_tp_rules,
    merge_specs,
    replicate,
    shard_batch,
    tree_shardings,
    vit_tp_rules,
)

__all__ = [
    "pipelined_generate",
    "shard_for_pipeline",
    "spmd_pipeline",
    "stack_stage_params",
    "ring_attention",
    "stripe_sequence",
    "unstripe_sequence",
    "ulysses_attention",
    "batch_sharding",
    "lm_tp_rules",
    "merge_specs",
    "replicate",
    "shard_batch",
    "tree_shardings",
    "vit_tp_rules",
]
