"""Distributed training step over a device mesh (dp x pp [+ tp/sp]).

Parity scope is inference-only (SURVEY.md §2.8: the reference has no
training path), but the SPMD machinery (``spmd_pipeline`` is
differentiable; GSPMD handles dp/tp) makes a mesh-sharded training step
nearly free, and it is the canonical proof that the multi-chip sharding
design is real: batch over ``dp``, stacked transformer blocks over ``pp``,
grads reduced by XLA-inserted collectives, optax update applied under the
same shardings.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from adapt_tpu.parallel.pipeline_spmd import (
    pipeline_microbatch,
    pipeline_unmicrobatch,
    spmd_pipeline,
)


class PipelinedViT(NamedTuple):
    """ViT params split into pipeline-stacked blocks + replicated ends."""

    embed: Any  # patch_embed variables (replicated)
    blocks: Any  # stacked encoder block variables, leading dim L (pp-sharded)
    head: Any  # classifier variables (replicated)


def split_vit_variables(graph, variables, depth: int) -> PipelinedViT:
    """Reshape a ``models.vit`` LayerGraph's variables into the pipelined
    layout (stack the homogeneous encoder blocks)."""
    from adapt_tpu.parallel.pipeline_spmd import stack_stage_params

    blocks = stack_stage_params(
        [variables[f"encoder_block_{i}"] for i in range(depth)]
    )
    return PipelinedViT(
        embed=variables["patch_embed"],
        blocks=blocks,
        head=variables["head"],
    )


def vit_shardings(params: PipelinedViT, mesh: Mesh) -> PipelinedViT:
    """NamedShardings for the pipelined layout: blocks pp-sharded on the
    stack dim, ends replicated."""
    return PipelinedViT(
        embed=jax.tree.map(
            lambda _: NamedSharding(mesh, P()), params.embed
        ),
        blocks=jax.tree.map(
            lambda _: NamedSharding(mesh, P("pp")), params.blocks
        ),
        head=jax.tree.map(lambda _: NamedSharding(mesh, P()), params.head),
    )


def make_pipelined_vit_apply(graph, mesh: Mesh, num_micro: int):
    """Forward: embed -> pp-pipelined blocks -> head, one XLA program."""
    embed_mod = graph.node("patch_embed").module
    block_mod = graph.node("encoder_block_0").module
    head_mod = graph.node("head").module

    def apply_fn(params: PipelinedViT, x: jax.Array) -> jax.Array:
        h = embed_mod.apply(params.embed, x)
        xs = pipeline_microbatch(h, num_micro)
        ys = spmd_pipeline(
            lambda p, a: block_mod.apply(p, a),
            params.blocks,
            xs,
            mesh,
            axis="pp",
            batch_axis="dp" if "dp" in mesh.axis_names else None,
        )
        h = pipeline_unmicrobatch(ys)
        return head_mod.apply(params.head, h)

    return apply_fn


def make_train_step(apply_fn, optimizer: optax.GradientTransformation):
    """(params, opt_state, x, y) -> (params, opt_state, loss), jittable
    over the mesh; XLA inserts the dp grad reduction from the shardings."""

    def loss_fn(params, x, y):
        logits = apply_fn(params, x)
        return jnp.mean(
            optax.softmax_cross_entropy_with_integer_labels(logits, y)
        )

    def train_step(params, opt_state, x, y):
        loss, grads = jax.value_and_grad(loss_fn)(params, x, y)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    return train_step
