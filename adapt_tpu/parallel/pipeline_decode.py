"""SPMD pipelined KV-cache generation: decode an LM bigger than one chip.

``models.transformer_lm.generate`` is a single-program loop — weights AND
the KV cache for every block must fit one chip. This module partitions the
decoder by block over a ``pp`` mesh axis (the same cut contract as the
scoring path, ``graph_partition(lm.graph, [...])``) and keeps each rank's
block params *and KV caches* device-resident, so an LM whose weights+cache
exceed one chip's HBM generates across P chips. The placement that makes
that true is :func:`shard_for_pipeline`: block weights are staged through
host RAM and each rank receives only its own L/P blocks — the full set is
never materialized on any single device. No reference analog (the
reference is CNN-only, SURVEY.md §2.2); this is SURVEY §2.3 pipeline
parallelism applied to the repo's flagship serving workload the TPU way:
one XLA program, activations on ICI, no host round-trips.

Schedule — a token ring, not GPipe:

- The batch is split into M = P microbatches. At any tick each rank holds
  exactly one microbatch's single-token activation (b/P, 1, d); a
  ``lax.ppermute`` ROTATION (P-1 wraps to 0) hands them all one hop each
  tick.
- Rank p at tick T works on microbatch ``(T-p) mod P`` at decode pass
  ``(T-p) div P``: runs its L/P blocks' cached ``decode_step``.
- The LAST rank additionally runs the LM head, samples the next token
  (per-row keys — ``sample_next_tokens`` — so a microbatch slice draws
  exactly what the full batch would), and puts the *embedding of the
  sampled token* into the rotation; one hop later rank 0 consumes it as
  the next pass's input. Steady state: every rank busy every tick, and
  each microbatch decodes one token per P ticks — aggregate one token per
  tick, the single-chip rate, at P x the memory.
- Prefill runs first with the same schedule over (b/P, s0, d) prompt
  activations (a plain shift, no wrap), building every rank's caches and
  sampling each microbatch's first token.

Fill/drain bubble ticks compute on garbage; instead of guarding every
cache write with a full-slice select, caches carry ONE trash position
(``max_len + 1`` slots) and invalid ticks write there — O(1) writes on the
hot path, and the decode attention's ``positions <= index`` mask never
admits the trash slot for a valid pass.

Parity contract (tested): output is token-for-token identical to
single-program ``generate`` for greedy AND sampled paths, with ragged
prompts and int8 KV caches — same math, same per-row sampling keys, just
a different schedule over the same weights.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from adapt_tpu.models.transformer_lm import (
    TransformerLM,
    _left_align,
    sample_next_tokens,
    validate_generate_args,
)
from adapt_tpu.parallel.compat import shard_map


@dataclasses.dataclass(frozen=True)
class PipelinedVariables:
    """Weights placed for pipelined decode: block params stacked with the
    leading (depth) dim sharded over the pipeline axis, embed/head
    replicated. Build once with :func:`shard_for_pipeline`, reuse across
    calls."""

    stacked: Any
    embed: Any
    head: Any


def shard_for_pipeline(
    lm: TransformerLM, variables, mesh: Mesh, axis: str = "pp"
) -> PipelinedVariables:
    """Place ``variables`` for pipelined decode — the capacity-critical
    step. Block leaves are staged through HOST memory and ``device_put``
    with a ``P(axis)`` leading-dim sharding, so each rank's devices ever
    receive only their own L/P blocks: total weights may exceed one
    chip's HBM as long as each rank's slice (plus embed + head, which
    are replicated) fits. Never stacks the full block set on one device.
    """
    block_sharding = NamedSharding(mesh, P(axis))
    replicated = NamedSharding(mesh, P())

    def place(*leaves):
        host = np.stack([np.asarray(x) for x in leaves], axis=0)
        return jax.device_put(host, block_sharding)

    stacked = jax.tree.map(
        place, *[variables[name] for name in lm.block_names]
    )
    put_rep = lambda t: jax.tree.map(  # noqa: E731
        lambda x: jax.device_put(x, replicated), t
    )
    return PipelinedVariables(
        stacked=stacked,
        embed=put_rep(variables["embed"]),
        head=put_rep(variables["head"]),
    )


def pipelined_generate(
    lm: TransformerLM,
    variables,
    prompt: jax.Array,
    steps: int,
    mesh: Mesh,
    axis: str = "pp",
    dp_axis: str | None = None,
    temperature: float = 0.0,
    top_k: int | None = None,
    top_p: float | None = None,
    eos_id: int | None = None,
    rng: jax.Array | None = None,
    prompt_lengths: jax.Array | None = None,
    kv_cache_dtype: str = "native",
) -> jax.Array:
    """``generate`` semantics, pipelined over ``mesh.shape[axis]`` ranks.

    prompt: (b, s0) int32 ids with b divisible by the pipeline size (the
    microbatch split) and ``lm.depth`` divisible by it (the block split);
    returns (b, steps) ids identical to single-program ``generate`` with
    the same arguments. All sampling knobs, ragged prompts
    (``prompt_lengths``) and ``kv_cache_dtype="int8"`` carry over.

    ``variables`` may be the raw per-node dict (convenience: each call
    re-stages weights through host memory) or a
    :class:`PipelinedVariables` from :func:`shard_for_pipeline` —
    serving, and any model too big for one chip, should pre-place once
    and reuse.

    ``dp_axis`` composes data parallelism with the pipeline on a 2-D
    mesh: every microbatch's rows shard over ``dp_axis`` (batch must
    divide by pipeline_size * dp_size) while blocks + caches shard over
    ``axis`` — sampling stays per-GLOBAL-row, so output is still
    token-identical to single-program ``generate``.
    """
    num_ranks = mesh.shape[axis]
    dp = mesh.shape[dp_axis] if dp_axis is not None else 1
    b, _ = prompt.shape
    lengths, rng, do_sample = validate_generate_args(
        lm, prompt, steps, temperature, top_k, rng, prompt_lengths,
        kv_cache_dtype, top_p=top_p,
    )
    if lm.depth % num_ranks:
        raise ValueError(
            f"depth {lm.depth} not divisible by pipeline size {num_ranks}"
        )
    if b % num_ranks:
        raise ValueError(
            f"batch {b} not divisible by pipeline size {num_ranks} "
            "(the microbatch split); pad the batch"
        )
    if (b // num_ranks) % dp:
        raise ValueError(
            f"per-microbatch rows {b // num_ranks} not divisible by "
            f"dp size {dp}"
        )
    if not isinstance(variables, PipelinedVariables):
        variables = shard_for_pipeline(lm, variables, mesh, axis)
    return _pipelined_impl(
        lm,
        variables.stacked,
        variables.embed,
        variables.head,
        prompt,
        lengths,
        jnp.asarray(temperature, jnp.float32),
        jnp.asarray(1.0 if top_p is None else top_p, jnp.float32),
        jnp.asarray(-1 if eos_id is None else eos_id, prompt.dtype),
        rng,
        steps=steps,
        do_sample=do_sample,
        top_k=top_k,
        use_top_p=top_p is not None,
        use_eos=eos_id is not None,
        ragged=prompt_lengths is not None,
        kv_quant=kv_cache_dtype == "int8",
        mesh=mesh,
        axis=axis,
        dp_axis=dp_axis,
    )


@partial(
    jax.jit,
    static_argnames=(
        "lm",
        "steps",
        "do_sample",
        "top_k",
        "use_top_p",
        "use_eos",
        "ragged",
        "kv_quant",
        "mesh",
        "axis",
        "dp_axis",
    ),
)
def _pipelined_impl(
    lm: TransformerLM,
    stacked,
    embed_vars,
    head_vars,
    prompt: jax.Array,
    lengths: jax.Array,
    temperature: jax.Array,
    top_p: jax.Array,
    eos_id: jax.Array,
    rng: jax.Array,
    *,
    steps: int,
    do_sample: bool,
    top_k: int | None,
    use_top_p: bool,
    use_eos: bool,
    ragged: bool,
    kv_quant: bool,
    mesh: Mesh,
    axis: str,
    dp_axis: str | None,
) -> jax.Array:
    g = lm.graph
    num_ranks = mesh.shape[axis]
    b, s0 = prompt.shape
    num_micro = num_ranks  # M == P: tight rotation, no idle ticks
    mb = b // num_micro  # global rows per microbatch
    dp = mesh.shape[dp_axis] if dp_axis is not None else 1
    mb_loc = mb // dp  # rows this dp shard holds per microbatch
    local_blocks = lm.depth // num_ranks
    embed = g.node("embed").module
    head = g.node("head").module
    block = g.node(lm.block_names[0]).module  # identical block structure

    # Cache buffers hold KV heads — fewer than query heads under GQA.
    heads, head_dim = block.cache_heads, block.head_dim
    # One extra slot: bubble ticks write their garbage K/V here instead of
    # forcing a full-slice select per tick. `positions <= index` masking
    # keeps it out of every valid pass's attention window.
    cache_len = lm.max_len + 1
    trash_index = lm.max_len

    if ragged:
        prompt_aligned, pos_ids, valid_from = _left_align(prompt, lengths)
        pos_all = pos_ids.reshape(num_micro, mb, s0)
        vf_all = valid_from.reshape(num_micro, mb)
    else:
        prompt_aligned = prompt
        pos_all = jnp.zeros((num_micro, mb, s0), jnp.int32)  # unused
        vf_all = jnp.zeros((num_micro, mb), jnp.int32)  # unused
    prompts_m = prompt_aligned.reshape(num_micro, mb, s0)

    # Exactly generate()'s key schedule: step_keys[0] samples the prefill
    # token, step_keys[s] samples produced token s.
    rng_next, key0 = jax.random.split(rng)
    if steps > 1:
        step_keys = jnp.concatenate(
            [key0[None], jax.random.split(rng_next, steps - 1)]
        )
    else:
        step_keys = key0[None]

    def cache_buf(last_dim, dtype):
        return jnp.zeros(
            (local_blocks, num_micro, mb_loc, heads, cache_len, last_dim),
            dtype,
        )

    if kv_quant:
        init_k = (cache_buf(head_dim, jnp.int8), cache_buf(1, jnp.float32))
        init_v = (cache_buf(head_dim, jnp.int8), cache_buf(1, jnp.float32))
    else:
        init_k = cache_buf(head_dim, block.dtype)
        init_v = cache_buf(head_dim, block.dtype)

    param_specs = jax.tree.map(lambda _: P(axis), stacked)
    rep = P()
    rep_tree = lambda t: jax.tree.map(lambda _: P(), t)  # noqa: E731
    # Row-carrying operands shard their mb dim over dp (replicated when
    # no dp axis).
    rows3 = P(None, dp_axis, None) if dp_axis else rep
    rows2 = P(None, dp_axis) if dp_axis else rep

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(
            param_specs,
            rep_tree(embed_vars),
            rep_tree(head_vars),
            rows3,  # prompts_m
            rows3,  # pos_all
            rows2,  # vf_all
            rep,  # step_keys
            rep,  # temperature
            rep,  # top_p
            rep,  # eos_id
        ),
        out_specs=rows3,
    )
    def run(
        params_loc,
        embed_vars,
        head_vars,
        prompts_m,
        pos_all,
        vf_all,
        step_keys,
        temperature,
        top_p,
        eos_id,
    ):
        rank = lax.axis_index(axis)
        dp_off = (
            lax.axis_index(dp_axis) * mb_loc if dp_axis is not None else 0
        )
        is_last = rank == num_ranks - 1
        shift = [(i, i + 1) for i in range(num_ranks - 1)]
        ring = [(i, (i + 1) % num_ranks) for i in range(num_ranks)]

        def masked_row_update(buf, row, m, on):
            """buf[m] = row where `on` (scalar), else unchanged."""
            old = lax.dynamic_index_in_dim(buf, m, 0, keepdims=False)
            return lax.dynamic_update_index_in_dim(
                buf, jnp.where(on, row, old), m, 0
            )

        def sample(logits, key, m, done_m):
            toks = sample_next_tokens(
                logits,
                key,
                temperature,
                do_sample=do_sample,
                top_k=top_k,
                top_p=top_p if use_top_p else None,
                # GLOBAL row index: microbatch base + this dp shard's
                # offset — a slice samples what the full batch would.
                row_offset=m * mb + dp_off,
            ).astype(prompts_m.dtype)
            if use_eos:
                toks = jnp.where(done_m, eos_id, toks)
                done_m = done_m | (toks == eos_id)
            return toks, done_m

        # ---- prefill: prompt activations shift down the chain ----------
        def prefill_tick(carry, t):
            h, ck, cv, first, toks, done = carry
            recv = lax.ppermute(h, axis, shift)
            m_in = jnp.clip(t, 0, num_micro - 1)
            ids_in = lax.dynamic_index_in_dim(
                prompts_m, m_in, 0, keepdims=False
            )
            if ragged:
                pos_in = lax.dynamic_index_in_dim(
                    pos_all, m_in, 0, keepdims=False
                )
                emb = embed.apply(
                    embed_vars, ids_in, pos_in, method="embed_positions"
                )
            else:
                emb = embed.apply(embed_vars, ids_in)
            h_in = jnp.where(rank == 0, emb, recv)

            j = t - rank
            m = jnp.clip(j, 0, num_micro - 1)
            valid = (j >= 0) & (j < num_micro)
            vf = (
                lax.dynamic_index_in_dim(vf_all, m, 0, keepdims=False)
                if ragged
                else None
            )

            def blk(x, p_i):
                x2, k_new, v_new = block.apply(
                    p_i, x, cache_len, vf, kv_quant, method="prefill"
                )
                return x2, (k_new, v_new)

            h_out, (k_news, v_news) = lax.scan(blk, h_in, params_loc)

            def write_cache(c, new):
                old = lax.dynamic_index_in_dim(c, m, 1, keepdims=False)
                return lax.dynamic_update_index_in_dim(
                    c, jnp.where(valid, new, old), m, 1
                )

            ck = jax.tree.map(write_cache, ck, k_news)
            cv = jax.tree.map(write_cache, cv, v_news)

            logits = head.apply(head_vars, h_out[:, -1:, :])[:, 0]
            done_m = lax.dynamic_index_in_dim(done, m, 0, keepdims=False)
            t0, done_m = sample(logits, step_keys[0], m, done_m)
            on = valid & is_last
            first = masked_row_update(first, t0, m, on)
            toks_m = lax.dynamic_index_in_dim(toks, m, 0, keepdims=False)
            toks = masked_row_update(toks, toks_m.at[:, 0].set(t0), m, on)
            done = masked_row_update(done, done_m, m, on)
            return (h_out, ck, cv, first, toks, done), None

        init = (
            jnp.zeros((mb_loc, s0, block.dim), block.dtype),
            init_k,
            init_v,
            jnp.zeros((num_micro, mb_loc), prompts_m.dtype),  # first toks
            jnp.zeros((num_micro, mb_loc, steps), prompts_m.dtype),
            jnp.zeros((num_micro, mb_loc), bool),
        )
        (_, ck, cv, first, toks, done), _ = lax.scan(
            prefill_tick, init, jnp.arange(num_micro + num_ranks - 1)
        )
        # Only the last rank sampled; broadcast so rank 0 can inject the
        # first decode pass's tokens.
        first = lax.psum(first, axis)

        if steps == 1:
            return lax.psum(toks, axis)

        # ---- decode: single-token ring rotation ------------------------
        def decode_tick(carry, t):
            h, ck, cv, toks, done = carry
            recv = lax.ppermute(h, axis, ring)
            j = t - rank
            m = jnp.mod(j, num_micro)
            sp = jnp.floor_divide(j, num_micro)  # pass: consumes token sp
            sp_c = jnp.clip(sp, 0, steps - 2)
            valid = (j >= 0) & (j < (steps - 1) * num_micro)
            index = jnp.where(valid, s0 + sp_c, trash_index)
            vf = (
                lax.dynamic_index_in_dim(vf_all, m, 0, keepdims=False)
                if ragged
                else None
            )

            # Rank 0, pass 0 consumes the prefill-sampled token; later
            # passes consume the embedding the last rank put on the ring.
            t_first = lax.dynamic_index_in_dim(first, m, 0, keepdims=False)
            if ragged:
                inj = embed.apply(
                    embed_vars,
                    t_first[:, None],
                    (index - vf)[:, None],
                    method="embed_positions",
                )
            else:
                inj = embed.apply(
                    embed_vars, t_first[:, None], index, method="embed_at"
                )
            h_in = jnp.where((rank == 0) & (sp == 0), inj, recv)

            ck_m = jax.tree.map(
                lambda c: lax.dynamic_index_in_dim(c, m, 1, keepdims=False),
                ck,
            )
            cv_m = jax.tree.map(
                lambda c: lax.dynamic_index_in_dim(c, m, 1, keepdims=False),
                cv,
            )

            def blk(x, xs_i):
                p_i, ck_i, cv_i = xs_i
                x2, ck_i, cv_i = block.apply(
                    p_i, x, ck_i, cv_i, index, vf, kv_quant,
                    method="decode_step",
                )
                return x2, (ck_i, cv_i)

            x_out, (ck_m, cv_m) = lax.scan(
                blk, h_in, (params_loc, ck_m, cv_m)
            )
            # Invalid ticks only touched the trash slot — write back
            # unguarded.
            ck = jax.tree.map(
                lambda c, n: lax.dynamic_update_index_in_dim(c, n, m, 1),
                ck,
                ck_m,
            )
            cv = jax.tree.map(
                lambda c, n: lax.dynamic_update_index_in_dim(c, n, m, 1),
                cv,
                cv_m,
            )

            logits = head.apply(head_vars, x_out)[:, 0]
            done_m = lax.dynamic_index_in_dim(done, m, 0, keepdims=False)
            nxt, done_m = sample(logits, step_keys[sp_c + 1], m, done_m)
            on = valid & is_last
            toks_m = lax.dynamic_index_in_dim(toks, m, 0, keepdims=False)
            toks = masked_row_update(
                toks, toks_m.at[:, sp_c + 1].set(nxt), m, on
            )
            done = masked_row_update(done, done_m, m, on)

            # The sampled token's embedding rides the ring back to rank 0
            # (position index+1 = the pass that consumes it).
            if ragged:
                emb_n = embed.apply(
                    embed_vars,
                    nxt[:, None],
                    (index + 1 - vf)[:, None],
                    method="embed_positions",
                )
            else:
                emb_n = embed.apply(
                    embed_vars, nxt[:, None], index + 1, method="embed_at"
                )
            h_next = jnp.where(is_last, emb_n, x_out)
            return (h_next, ck, cv, toks, done), None

        init_h = jnp.zeros((mb_loc, 1, block.dim), block.dtype)
        (_, _, _, toks, _), _ = lax.scan(
            decode_tick,
            (init_h, ck, cv, toks, done),
            jnp.arange(steps * num_ranks - 1),
        )
        return lax.psum(toks, axis)

    toks = run(
        stacked,
        embed_vars,
        head_vars,
        prompts_m,
        pos_all,
        vf_all,
        step_keys,
        temperature,
        top_p,
        eos_id,
    )
    return toks.reshape(b, steps)
