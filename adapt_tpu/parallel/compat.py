"""shard_map across jax generations — ONE shim for every SPMD module.

The repo targets the current API (``jax.shard_map`` with ``check_vma``,
``lax.pcast(..., to="varying")``); older jax (< 0.6) ships
``jax.experimental.shard_map`` with ``check_rep`` and no ``pcast``.
Every shard_map-based module (``ring_attention``, ``ulysses``,
``pipeline_spmd``, ``pipeline_decode``) routes through this shim so the
version probe lives in exactly one place.

Replication/vma checking stays OFF in both generations: the stage bodies
may contain a ``pallas_call`` (flash kernels), whose ``out_shape``
carries no mesh-varying annotation — the check would reject correct
programs.
"""

from __future__ import annotations

import jax
from jax import lax


def shard_map(f, *, mesh, in_specs, out_specs):
    """``jax.shard_map`` (new API) or ``jax.experimental.shard_map``
    (old), with vma/rep checking disabled (module docstring). Usable as
    ``functools.partial(shard_map, mesh=..., in_specs=..., out_specs=...)``
    decorator, mirroring the new API's shape."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=False,
    )


def to_varying(tree, axes):
    """``lax.pcast(tree, axes, to="varying")`` where the running jax has
    it; the old shard_map (``check_rep=False``) needs no
    replicated->varying cast, so this is the identity there."""
    pcast = getattr(lax, "pcast", None)
    if pcast is None:
        return tree
    return pcast(tree, axes, to="varying")
