"""Expert parallelism: shard the expert dimension over an ``ep`` mesh axis.

Beyond reference parity (SURVEY.md §2.2: no EP). The GSPMD route: MoE
params are expert-stacked (leading ``E`` dim — see
:class:`adapt_tpu.models.moe.MoEMlp`); shard that dim over ``ep``,
replicate everything else, and XLA lowers the dispatch/combine einsums
([N,E,C] x [N,D] -> [E,C,D] and back) into all-to-alls over ICI. No
hand-rolled collectives — annotate and let the compiler schedule
(the scaling-book recipe).
"""

from __future__ import annotations

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def _is_expert_stacked(
    path: tuple, leaf, num_experts: int, exclude: tuple[str, ...]
) -> bool:
    keystr = jax.tree_util.keystr(path)
    if any(name in keystr for name in exclude):
        return False
    return (
        hasattr(leaf, "ndim")
        and leaf.ndim >= 1
        and leaf.shape[0] == num_experts
    )


def expert_shardings(
    params,
    mesh: Mesh,
    num_experts: int,
    axis: str = "ep",
    exclude: tuple[str, ...] = ("gate",),
):
    """NamedShardings for a MoE param tree: leaves whose leading dim is the
    expert count get P(axis, ...); everything else is replicated.
    ``exclude`` lists path substrings that are never expert-stacked — the
    router's ``gate`` [D, E] by default, which would otherwise be
    mis-sharded whenever D happens to equal the expert count."""

    def shard_one(path, leaf):
        if _is_expert_stacked(path, leaf, num_experts, exclude):
            return NamedSharding(
                mesh, P(axis, *([None] * (leaf.ndim - 1)))
            )
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(shard_one, params)


def place_experts(
    params,
    mesh: Mesh,
    num_experts: int,
    axis: str = "ep",
    exclude: tuple[str, ...] = ("gate",),
):
    """device_put the param tree per :func:`expert_shardings`."""
    return jax.device_put(
        params, expert_shardings(params, mesh, num_experts, axis, exclude)
    )


def expert_utilization(gates: jax.Array) -> np.ndarray:
    """Fraction of top-1 routed tokens per expert — the EP load-balance
    observability hook (pairs with MoEMlp's sown aux_loss)."""
    idx = np.asarray(gates.argmax(axis=-1)).reshape(-1)
    counts = np.bincount(idx, minlength=gates.shape[-1])
    return counts / max(1, idx.size)
