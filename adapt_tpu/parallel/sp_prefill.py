"""Sequence-parallel long-context prefill: one prompt, many chips.

A 32k-token prompt monopolizes the prefill path however it is chunked —
chunking bounds the per-tick stall (Sarathi-style), disaggregation moves
the wall off the decode tier (PR 9), but the WALL itself is O(S^2)
attention on one chip. This module splits it: the prompt's token axis
shards over an ``sp`` mesh axis, every chip computes its own chunk's
projections / rope / quantization / MLP sequence-locally (token-local
math needs no communication — the Mesh-TensorFlow named-axis split,
PAPERS.md 1811.02084), and the K/V window circulates the ring via
``lax.ppermute`` neighbor hops (the ring-attention communication
pattern of ``parallel/ring_attention``) while each chip computes only
its own chunk's attention-score rows — so the prefill wall drops
~linearly with the ring size.

**The byte-equality contract.** Serving demands more than numerical
closeness: the sp-prefilled pages must be BYTE-EQUAL to what the
single-device chunked prefill would have written, so a request landed
through the prefix cache decodes bit-identically to the collocated
path. The online-softmax accumulation of classic ring attention
(``ring_attention.ring_attention``) re-orders the softmax reduction
per ring step and cannot satisfy that pin. This module keeps the ring
TRANSPORT but not the online-softmax arithmetic: each rank ACCUMULATES
the rotating pool-representation K/V blocks into its full window
(:func:`ring_collect` — P-1 neighbor hops, no global gather primitive)
and then computes its rows' attention with exactly the chunk oracle's
op order (``models.transformer_lm.CausalSelfAttention.prefill_sp``
mirrors ``paged_chunk_attention_reference``). Byte-equality holds at
MATCHED decode-tier tp (an sp x tp prefill compares against the tp-
sharded chunked prefill — tp math was never bitwise-equal across tp
widths, only stream-identical, the PR-5 pin) and is PINNED at the
repo's test shapes for native/int8/int4 pools and sp in {2, 4},
sp x tp — the same scale every existing bit-identity pin runs at. At
larger shapes the sp pass joins chunked prefill's documented
equivalence class: XLA's matmul strategy varies with the row-block
shape, so pages can differ at ulp across SCHEDULES (exactly as
chunk-size choice already does, module docstring of
``runtime/continuous``), and the serving-level pin is greedy-stream
bit-identity — an argmax flip needs an exact fp tie. Per-chip window
memory is O(S) — the explicit trade against the online-softmax
ring's O(S/P), bought for the exact-oracle arithmetic; the O(S^2/P)
score-block COMPUTE split (the actual prefill wall) is pinned via
compiled-module cost analysis (per-device flops halve per sp
doubling).

**The sp -> tp layout transition.** The program's outputs are
seq-sharded pool-representation K/V; :meth:`SPPrefiller.prefill`
assembles them page-major on the host (per-shard D2H — each device
ships only its own chunk) and the caller lands them on the decode
pool's head-sharded layout through the SAME
``parallel.sharding.KVHandoffPlan`` / ``Pager.adopt_cached`` /
``_adopt_pages`` path as a disaggregated handoff — resharding on the
sender side of the boundary (PAPERS.md 2211.05322), never a gather
inside the decode mesh. Decode stays tp-sharded and untouched; the
request simply admits as a prefix-cache hit.

Composes with tensor parallelism as an ``(sp, tp)`` mesh: weights
place by ``lm_tp_rules`` over the tp axis (replicated over sp), the
kv-head axis of every window block rides the same tp split through
the ring, and the per-block psum pair stays tp-only — bitwise the
single-mesh tp math (the PR-5 pin).
"""

from __future__ import annotations

import threading
import weakref
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from adapt_tpu.models.transformer_lm import TransformerLM, validate_tp
from adapt_tpu.parallel.compat import shard_map
from adapt_tpu.parallel.sharding import lm_tp_rules, replicate, tree_shardings
from adapt_tpu.utils.logging import get_logger
from adapt_tpu.utils.profiling import aggregate_size_fn, global_compile_sentinel

log = get_logger("sp_prefill")

#: Live prefillers (weak): the ONE "sp.prefill" sentinel watch sums the
#: per-instance program families over this set, so a second prefiller
#: (or a post-recovery rebuild) aggregates instead of silently
#: replacing the first one's watch.
_LIVE_PREFILLERS: "weakref.WeakSet[SPPrefiller]" = weakref.WeakSet()


def _prefiller_family_size(pf: "SPPrefiller") -> int:
    return sum(f._cache_size() for f in list(pf._fn_cache.values()))


def ring_collect(x, mesh: Mesh, axis: str, seq_dim: int = 2,
                 in_spec: P | None = None, out_spec: P | None = None):
    """Collect a seq-sharded array's full window on every rank of the
    ``axis`` ring: P-1 ``lax.ppermute`` neighbor hops rotate the local
    blocks around the ring (exactly :mod:`ring_attention`'s transport —
    after ``i`` hops rank ``r`` holds the block that originated at
    ``r - i`` mod P) while each rank writes the arriving block at its
    global offset. No all-gather primitive, no host staging; the
    result is the full window, bit-identically the concatenation of
    the shards in sequence order.

    ``in_spec``/``out_spec`` default to the KV-leaf convention
    ``(1, kv_h, S, w)`` with ``seq_dim`` sharded over ``axis`` (name
    any other mesh axes — e.g. the tp split of the kv-head dim — in
    both specs; they pass through untouched)."""
    n = int(mesh.shape[axis])
    if n == 1:
        return x
    if in_spec is None:
        in_spec = P(*(axis if i == seq_dim else None
                      for i in range(x.ndim)))
    if out_spec is None:
        out_spec = P(*(None for _ in range(x.ndim)))
    full = x.shape[seq_dim]
    if full % n:
        raise ValueError(
            f"sequence axis {full} not divisible by ring size {n}"
        )
    ring = [(i, (i + 1) % n) for i in range(n)]

    @partial(shard_map, mesh=mesh, in_specs=(in_spec,),
             out_specs=out_spec)
    def run(xl):
        rank = lax.axis_index(axis)
        s_local = xl.shape[seq_dim]
        shape = list(xl.shape)
        shape[seq_dim] = full
        buf = jnp.zeros(tuple(shape), xl.dtype)
        cur = xl
        for i in range(n):
            src = jnp.mod(rank - i, n)
            buf = lax.dynamic_update_slice_in_dim(
                buf, cur, src * s_local, seq_dim
            )
            if i < n - 1:
                cur = lax.ppermute(cur, axis, ring)
        return buf

    return run(x)


class SPPrefiller:
    """The sequence-parallel prefill program family: one jitted,
    sp-sharded whole-span pass per power-of-two page bucket, producing
    page-major host K/V blocks in the decode pool's representation —
    the payload of a :class:`runtime.disagg.KVHandoff`, byte-equal to
    what the single-device chunked prefill would have written.

    Owns its OWN mesh (axes ``(sp,)`` or ``(sp, tp)``) and weight
    placement (tp rules over ``tp_axis``, replicated over the ring) —
    the decode tier's mesh stays tp-only and its pool layout is
    reached only through the handoff landing path (the sp -> tp
    transition happens sender-side, module docstring). Both serving
    entry points drive one of these: ``ContinuousBatcher`` collocated
    admission and ``runtime.disagg.PrefillWorker.step``."""

    def __init__(
        self,
        lm: TransformerLM,
        variables,
        mesh: Mesh,
        page_size: int,
        kv_cache_dtype: str = "native",
        sp_axis: str = "sp",
        tp_axis: str | None = None,
        name: str = "sp0",
    ):
        if sp_axis not in mesh.shape:
            raise ValueError(
                f"mesh has no {sp_axis!r} axis (axes: "
                f"{tuple(mesh.axis_names)})"
            )
        self.sp = int(mesh.shape[sp_axis])
        if self.sp < 2:
            raise ValueError(
                f"sp axis {sp_axis!r} has size {self.sp}; a ring needs "
                "at least 2 ranks (sp=1 is the ordinary prefill path)"
            )
        if tp_axis is not None:
            if tp_axis not in mesh.shape:
                raise ValueError(
                    f"mesh has no {tp_axis!r} axis (axes: "
                    f"{tuple(mesh.axis_names)})"
                )
            self.tp = int(mesh.shape[tp_axis])
            validate_tp(lm, self.tp)
        else:
            self.tp = 1
        if kv_cache_dtype not in ("native", "int8", "int4"):
            raise ValueError(
                f"kv_cache_dtype={kv_cache_dtype!r}: expected 'native', "
                "'int8' or 'int4'"
            )
        self.lm = lm
        self.name = name
        self.page_size = page_size
        self.kv_cache_dtype = kv_cache_dtype
        self.quantized = kv_cache_dtype != "native"
        self._mesh = mesh
        self._sp_axis = sp_axis
        self._tp_axis = tp_axis
        g = lm.graph
        self._embed = g.node("embed").module
        self._blocks = [g.node(n).module for n in lm.block_names]
        block0 = self._blocks[0]
        self._heads = block0.cache_heads
        self._head_dim = block0.head_dim
        if kv_cache_dtype == "int4" and self._head_dim % 2:
            raise ValueError(
                f"kv_cache_dtype='int4' needs an even head_dim, got "
                f"{self._head_dim}"
            )
        self._kv_width = (
            self._head_dim // 2 if kv_cache_dtype == "int4" else
            self._head_dim
        )
        #: The ORIGINAL variables as given — a post-recovery rebuild
        #: re-places from here, not from a possibly-dead placement.
        self._src_variables = variables
        if self.tp > 1:
            self._variables = jax.device_put(
                variables,
                tree_shardings(
                    variables, mesh,
                    rules=partial(lm_tp_rules, axis=tp_axis),
                ),
            )
        else:
            self._variables = replicate(variables, mesh)
        self._repl = NamedSharding(mesh, P())
        self._fn_cache: dict[int, object] = {}
        self._lock = threading.Lock()
        self.prefill_tokens = 0
        self.prefills = 0
        _LIVE_PREFILLERS.add(self)
        global_compile_sentinel().register(
            "sp.prefill",
            size_fn=aggregate_size_fn(
                _LIVE_PREFILLERS, _prefiller_family_size
            ),
        )

    # -- compiled pieces ---------------------------------------------------

    @property
    def variants(self) -> set[int]:
        """Page buckets whose program variant exists — the recovery
        allowance accounting (``recover()``'s nvar rule)."""
        return set(self._fn_cache)

    def _kv_spec(self) -> P:
        """Pool-representation K/V leaves ``(1, kv_h, S, w)``: kv-head
        axis over tp (when composed), sequence axis over the ring.
        One spec serves value planes and scale planes alike (the last
        axis stays whole)."""
        return P(None, self._tp_axis, self._sp_axis, None)

    def _sp_fn(self, nb: int):
        """The jitted sp-sharded whole-span prefill for one pow2 page
        bucket: embed -> per block (seq-local QKV/rope/quantize, ring
        window collect, chunk-oracle attention, seq-local MLP) ->
        pool-representation K/V per block, seq-sharded. Specializes
        per page bucket (log2 variants, the chunked-prefill
        discipline)."""
        if nb in self._fn_cache:
            return self._fn_cache[nb]
        S = nb * self.page_size
        if S % self.sp:
            raise ValueError(
                f"window of {S} tokens not divisible by sp={self.sp}"
            )
        mesh = self._mesh
        h_sh = NamedSharding(mesh, P(None, self._sp_axis, None))
        kv_sh = NamedSharding(mesh, self._kv_spec())
        #: Attention-intermediate row sharding (folded q, score block,
        #: attention output): without this pin GSPMD's propagation may
        #: replicate the O(S^2) score block over the ring — every rank
        #: computing every row — which forfeits the compute split
        #: (verified via compiled-module cost_analysis in the micro
        #: driver).
        rows_sh = NamedSharding(mesh, self._kv_spec())
        in_spec = self._kv_spec()
        out_spec = P(None, self._tp_axis, None, None)

        def gather(tree):
            # The ring transport: every pool-representation leaf (int8
            # values AND f32 scales of a quantized pair) rotates the
            # same ring; the tp split of the kv-head axis passes
            # through untouched.
            return jax.tree.map(
                lambda t: ring_collect(
                    t, mesh, self._sp_axis, seq_dim=2,
                    in_spec=in_spec, out_spec=out_spec,
                ),
                tree,
            )

        qflag = self.kv_cache_dtype if self.quantized else False

        def constrain(t):
            return lax.with_sharding_constraint(t, rows_sh)

        @jax.jit
        def prog(variables, ids):
            pos_ids = jnp.arange(S)[None]
            h = self._embed.apply(
                variables["embed"], ids, pos_ids,
                method="embed_positions",
            )
            h = lax.with_sharding_constraint(h, h_sh)
            outs = []
            for name, block in zip(self.lm.block_names, self._blocks):
                h, ck, cv = block.apply(
                    variables[name], h, gather, qflag, constrain,
                    method="prefill_sp",
                )
                h = lax.with_sharding_constraint(h, h_sh)
                outs.append(
                    jax.tree.map(
                        lambda t: lax.with_sharding_constraint(t, kv_sh),
                        (ck, cv),
                    )
                )
            return outs

        self._fn_cache[nb] = prog
        return prog

    # -- request surface ---------------------------------------------------

    def covers(self, prompt_len: int) -> int:
        """Full pages an sp prefill of this prompt would produce (0 =
        nothing to do; the partial last page always re-prefills as the
        decode-side suffix pass, exactly like a disagg handoff)."""
        return max(0, (prompt_len - 1) // self.page_size)

    def prefill(self, prompt) -> tuple[int, list]:
        """Run the sp-sharded prefill of ``prompt``'s full pages.
        Returns ``(n_pages, blocks)`` — one page-major ``(K, V)`` pair
        per decoder block, each member an ``(n_pages, kv_h, page, w)``
        host array (or a ``(values, scales)`` tuple of them for
        quantized pools): exactly the payload
        ``ContinuousBatcher.adopt_prefill_pages`` /
        :class:`runtime.disagg.KVHandoff` expect, byte-equal to the
        single-device chunked prefill's pages."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        s0 = prompt.shape[0]
        Pg = self.page_size
        m = (s0 - 1) // Pg
        if m < 1:
            raise ValueError(
                f"prompt of {s0} tokens has no full {Pg}-token page to "
                "sp-prefill"
            )
        nb = 1
        while nb < m:
            nb *= 2
        S = nb * Pg
        ids = np.zeros((1, S), np.int32)
        ids[0, : m * Pg] = prompt[: m * Pg]
        with self._lock:
            fn = self._sp_fn(nb)
        outs = fn(
            self._variables, jax.device_put(ids, self._repl)
        )
        kvh = self._heads

        def page_major(t):
            # (1, kv_h, S, w) seq-order -> (m, kv_h, page, w)
            # page-major; the host assembly is the per-shard D2H (each
            # ring rank ships only its own chunk's rows).
            a = np.asarray(t)[0]
            a = a.reshape(kvh, nb, Pg, a.shape[-1])
            return np.ascontiguousarray(np.swapaxes(a, 0, 1)[:m])

        blocks = [jax.tree.map(page_major, pair) for pair in outs]
        self.prefill_tokens += m * Pg
        self.prefills += 1
        return m, blocks

    def close(self) -> None:
        """Retire this prefiller: its programs leave the aggregate
        sentinel watch (the WeakSet holds it weakly; dropping the
        caches makes a lingering strong ref harmless)."""
        _LIVE_PREFILLERS.discard(self)
        self._fn_cache.clear()


def build_sp_mesh(
    sp_width: int,
    tp: int = 1,
    sp_axis: str = "sp",
    tp_axis: str = "tp",
    devices=None,
) -> Mesh:
    """An ``(sp,)`` or ``(sp, tp)`` mesh over the first
    ``sp_width * tp`` available devices — the default mesh the serving
    entry points build when handed a ``PrefillConfig`` without an
    explicit mesh. Raises when the platform has too few devices (the
    caller degrades to the ordinary prefill path and says so)."""
    need = sp_width * tp
    pool = list(devices) if devices is not None else jax.devices()
    if len(pool) < need:
        raise ValueError(
            f"sp_width={sp_width} x tp={tp} needs {need} devices; "
            f"have {len(pool)}"
        )
    arr = np.asarray(pool[:need])
    if tp > 1:
        return Mesh(arr.reshape(sp_width, tp), (sp_axis, tp_axis))
    return Mesh(arr.reshape(sp_width), (sp_axis,))
