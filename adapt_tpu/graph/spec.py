"""Architecture-by-value: (de)serialize a LayerGraph's STRUCTURE to JSON.

The reference ships the model architecture itself to bare workers —
``model.to_json()`` on the dispatcher (``/root/reference/src/dispatcher.py:
235``), ``model_from_json`` worker-side (``src/node.py:40-45``) — so a
worker needs no model code beyond the framework. The TPU-native analog:
a :class:`~adapt_tpu.graph.ir.LayerGraph` is already a declared DAG of
named flax-module nodes, and flax modules are dataclasses whose fields ARE
the hyperparameters. The spec is therefore {node name, module import path,
hyperparams, input edges} per node — everything needed to rebuild the
graph on a worker whose model REGISTRY is empty (custom cuts, hand-built
DAGs, and hyperparam variants all transfer by value).

What does NOT transfer: code. Module classes import from the installed
``adapt_tpu`` (or ``flax``) package on the worker — the same trust model
as the reference, where Keras classes come from the worker's TF install.
Imports are restricted to :data:`ALLOWED_MODULE_ROOTS` so a malicious
spec cannot import arbitrary modules, and field values are data only
(no pickles): callables/dtypes ride as registry names.
"""

from __future__ import annotations

import dataclasses
import importlib
from typing import Any

import jax
import numpy as np

from adapt_tpu.graph.ir import INPUT, Lambda, LayerGraph

#: Merge-op vocabulary for :class:`Lambda` nodes (the reference's Keras
#: ``Add``/``Concatenate`` analogs). A Lambda whose name is not here is
#: not architecture-by-value serializable — callers get a loud error at
#: SERIALIZE time, on the dispatcher, not at rebuild time on a worker.
LAMBDA_REGISTRY: dict[str, Any] = {
    "add": lambda a, b: a + b,
    "add_relu": lambda shortcut, branch: jax.nn.relu(shortcut + branch),
    "concat": lambda *xs: jax.numpy.concatenate(xs, axis=-1),
    "identity": lambda x: x,
}

#: Activation-function vocabulary for ``Callable`` module fields
#: (e.g. ``ConvBN.act``).
ACT_REGISTRY: dict[str, Any] = {
    "relu": jax.nn.relu,
    "silu": jax.nn.silu,
    "gelu": jax.nn.gelu,
    "sigmoid": jax.nn.sigmoid,
    "tanh": jax.numpy.tanh,
}

#: Only these package roots may be imported while rebuilding a spec: the
#: spec names classes, and an unrestricted dotted-path import would let a
#: spec execute arbitrary module-level code. Matching is dot-terminated
#: or exact (``_under_allowed_roots``): ``flax.linen.attention.X`` and
#: ``flax.linen.X`` qualify, a sibling package named ``flax.linenx``
#: does not (ADVICE r5 — a bare prefix check would admit it).
ALLOWED_MODULE_ROOTS = ("adapt_tpu.", "flax.linen")


def _under_allowed_roots(path: str) -> bool:
    """True when ``path`` (a dotted module.Class path) is exactly an
    allowed root or lives under one at a ``.`` boundary."""
    for root in ALLOWED_MODULE_ROOTS:
        if root.endswith("."):
            if path.startswith(root):
                return True
        elif path == root or path.startswith(root + "."):
            return True
    return False

#: flax dataclass plumbing fields that are NOT hyperparameters.
_FLAX_INTERNAL_FIELDS = frozenset({"parent", "name"})


def registered_lambda(name: str) -> Lambda:
    """The canonical way to build a serializable merge op: the Lambda
    carries the REGISTRY's function object, which serialization verifies
    by identity (a fresh ``lambda a, b: a + b`` named ``"add"`` would be
    indistinguishable on the wire from a custom op wearing that name)."""
    try:
        return Lambda(LAMBDA_REGISTRY[name], name)
    except KeyError:
        raise KeyError(
            f"no registered Lambda {name!r}; known: "
            f"{sorted(LAMBDA_REGISTRY)}"
        ) from None


def _encode_value(v: Any) -> Any:
    if v is None or isinstance(v, (bool, int, float, str)):
        return v
    if isinstance(v, (tuple, list)):
        return {"__seq__": [_encode_value(x) for x in v],
                "tuple": isinstance(v, tuple)}
    if isinstance(v, dict):
        return {"__map__": {k: _encode_value(x) for k, x in v.items()}}
    for name, fn in ACT_REGISTRY.items():
        if v is fn:
            return {"__act__": name}
    try:
        return {"__dtype__": np.dtype(v).name}
    except TypeError:
        pass
    raise TypeError(
        f"cannot serialize module field value {v!r} "
        "(architecture-by-value carries data, not code; register "
        "callables in spec.ACT_REGISTRY)"
    )


def _decode_value(v: Any) -> Any:
    if isinstance(v, dict):
        if "__seq__" in v:
            seq = [_decode_value(x) for x in v["__seq__"]]
            return tuple(seq) if v.get("tuple") else seq
        if "__map__" in v:
            return {k: _decode_value(x) for k, x in v["__map__"].items()}
        if "__act__" in v:
            try:
                return ACT_REGISTRY[v["__act__"]]
            except KeyError:
                raise ValueError(
                    f"unknown activation {v['__act__']!r} in graph spec"
                ) from None
        if "__dtype__" in v:
            return jax.numpy.dtype(v["__dtype__"])
    return v


def _module_to_spec(module: Any) -> dict:
    if isinstance(module, Lambda):
        if LAMBDA_REGISTRY.get(module.name) is not module._fn:
            # Name-only matching would let a custom op wearing a registry
            # name be silently REPLACED by the registry op worker-side —
            # a numerically wrong rebuild with no error anywhere. The
            # function object itself must come from the registry.
            raise TypeError(
                f"Lambda {module.name!r} does not carry the "
                "spec.LAMBDA_REGISTRY function of that name; build merge "
                "ops from the registry (spec.registered_lambda) to ship "
                "by value"
            )
        return {"kind": "lambda", "name": module.name}
    if dataclasses.is_dataclass(module):
        cls = type(module)
        path = f"{cls.__module__}.{cls.__qualname__}"
        # Fail at SERIALIZE time (on the dispatcher) for anything the
        # worker could never rebuild: classes outside the allowed roots
        # (user scripts, __main__) and nested classes (the import path
        # 'pkg.Outer.Inner' does not name a module attribute reachable
        # from import_module('pkg.Outer')).
        if not _under_allowed_roots(path):
            raise TypeError(
                f"cannot ship {path!r} by value: module classes must "
                f"live under {ALLOWED_MODULE_ROOTS} on the worker image"
            )
        if "." in cls.__qualname__:
            raise TypeError(
                f"cannot ship nested class {path!r} by value: "
                "define shipped modules at module top level"
            )
        config = {
            f.name: _encode_value(getattr(module, f.name))
            for f in dataclasses.fields(module)
            if f.name not in _FLAX_INTERNAL_FIELDS
        }
        return {"kind": "flax", "type": path, "config": config}
    raise TypeError(
        f"cannot serialize module {module!r} (need a flax dataclass "
        "module or a registered Lambda)"
    )


def _module_from_spec(spec: dict) -> Any:
    kind = spec.get("kind")
    if kind == "lambda":
        name = spec["name"]
        try:
            return Lambda(LAMBDA_REGISTRY[name], name)
        except KeyError:
            raise ValueError(f"unknown Lambda {name!r} in graph spec") from None
    if kind != "flax":
        raise ValueError(f"unknown module kind {kind!r} in graph spec")
    path = spec["type"]
    if not _under_allowed_roots(path):
        raise ValueError(
            f"refusing to import {path!r}: graph specs may only name "
            f"classes under {ALLOWED_MODULE_ROOTS}"
        )
    mod_path, _, clsname = path.rpartition(".")
    obj: Any = getattr(importlib.import_module(mod_path), clsname)
    # The resolved object must be a flax module CLASS: without this, any
    # callable under an allowed root is a gadget a spec could invoke with
    # chosen kwargs (e.g. a CLI main that SystemExits the serve thread).
    import flax.linen as nn

    if not (isinstance(obj, type) and issubclass(obj, nn.Module)):
        raise ValueError(
            f"{path!r} is not a flax module class; refusing to call it"
        )
    config = {k: _decode_value(v) for k, v in spec["config"].items()}
    return obj(**config)


def graph_to_spec(graph: LayerGraph) -> dict:
    """JSON-serializable structure of ``graph`` (names, hyperparams,
    edges — no weights; those stream separately per array, as always)."""
    return {
        "name": graph.name,
        "output": graph.output,
        "nodes": [
            {
                "name": node.name,
                "inputs": list(node.inputs),
                "module": _module_to_spec(node.module),
            }
            for node in graph.nodes.values()
        ],
    }


def graph_from_spec(spec: dict) -> LayerGraph:
    """Rebuild the LayerGraph a spec describes — the worker-side half
    (reference ``model_from_json``, ``src/node.py:40-45``). Topological
    node order is the list order, as :meth:`LayerGraph.add` requires."""
    g = LayerGraph(spec["name"])
    for node in spec["nodes"]:
        g.add(
            node["name"],
            _module_from_spec(node["module"]),
            tuple(node["inputs"]),
        )
    g.set_output(spec["output"])
    return g
