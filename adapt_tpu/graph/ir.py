"""Layer-graph IR: models as a DAG of named flax modules.

The reference slices Keras models by introspecting the framework's runtime
graph (``/root/reference/src/dag_util.py:3-62`` walks ``inbound_nodes``
backward from a named layer, memoizing rebuilt tensors so DAG joins are
rebuilt once). JAX has no such runtime graph, so here the graph is *declared*:
a model is a DAG of named nodes, each wrapping a flax module (or any pure
``apply(variables, *inputs)`` pair). Named nodes give the partitioner stable
cut points — the same capability the reference gets from Keras layer names —
without depending on tracer internals, and each stage lowers to one XLA
program (the Python topo-order loop unrolls at trace time).

Design notes (TPU-first):
- Node granularity is "block-ish" (a residual branch, a merge, a transformer
  block), keeping graphs small (tens of nodes) so per-stage jit traces fast
  and XLA sees large fusable regions.
- Multi-input nodes (residual ``add``, ``concat``) are first-class: a node's
  ``inputs`` tuple names its predecessors, exactly the DAG-join case the
  reference handles at ``src/dag_util.py:28-33``.
"""

from __future__ import annotations

import dataclasses
from collections.abc import Callable, Mapping, Sequence
from typing import Any

import jax

#: Sentinel name for the graph's input tensor (the reference's analog is the
#: output tensor of the ``start`` layer fed to ``tf.keras.Input`` at
#: ``src/dag_util.py:52``).
INPUT = "__input__"

Variables = Mapping[str, Any]


@dataclasses.dataclass(frozen=True)
class LayerNode:
    """One named node of the model DAG.

    ``module`` is any object with flax's ``init(rng, *inputs)`` /
    ``apply(variables, *inputs)`` protocol. ``inputs`` names predecessor
    nodes (or :data:`INPUT`).
    """

    name: str
    module: Any
    inputs: tuple[str, ...]

    def apply(self, variables: Variables, *args: jax.Array) -> jax.Array:
        return self.module.apply(variables, *args)


class LayerGraph:
    """A DAG of named layers with a single input and a single output node.

    Nodes must be added in topological order (every input must already
    exist), which makes insertion order a valid execution order — the same
    invariant Keras maintains for its layer list, relied on by the
    reference's partitioner (``src/dispatcher.py:39-53``).
    """

    def __init__(self, name: str):
        self.name = name
        self._nodes: dict[str, LayerNode] = {}
        self._output: str | None = None

    # -- construction -------------------------------------------------------

    def add(
        self,
        name: str,
        module: Any,
        inputs: str | Sequence[str] = INPUT,
    ) -> str:
        """Add a named node; returns the name so calls can be chained."""
        if isinstance(inputs, str):
            inputs = (inputs,)
        inputs = tuple(inputs)
        if name in self._nodes or name == INPUT:
            raise ValueError(f"duplicate layer name: {name!r}")
        for dep in inputs:
            if dep != INPUT and dep not in self._nodes:
                raise ValueError(
                    f"layer {name!r} depends on unknown layer {dep!r} "
                    "(nodes must be added in topological order)"
                )
        self._nodes[name] = LayerNode(name=name, module=module, inputs=inputs)
        self._output = name
        return name

    def set_output(self, name: str) -> None:
        if name not in self._nodes:
            raise ValueError(f"unknown layer: {name!r}")
        self._output = name

    # -- introspection ------------------------------------------------------

    @property
    def output(self) -> str:
        if self._output is None:
            raise ValueError("empty graph")
        return self._output

    @property
    def nodes(self) -> Mapping[str, LayerNode]:
        return self._nodes

    def node(self, name: str) -> LayerNode:
        try:
            return self._nodes[name]
        except KeyError:
            raise KeyError(
                f"no layer {name!r} in graph {self.name!r}; "
                f"known layers: {list(self._nodes)[:8]}..."
            ) from None

    def topo_order(self) -> list[str]:
        return list(self._nodes)

    def consumers(self, name: str) -> list[str]:
        return [n.name for n in self._nodes.values() if name in n.inputs]

    # -- execution ----------------------------------------------------------

    def init(self, rng: jax.Array, x: jax.Array) -> dict[str, Variables]:
        """Initialize every node by running a forward pass in topo order.

        Returns ``{node_name: flax variables}``. BatchNorm-style collections
        (``batch_stats``) are kept inside each node's variables; inference
        runs them in eval mode so ``apply`` stays pure.
        """
        variables: dict[str, Variables] = {}
        cache: dict[str, jax.Array] = {INPUT: x}
        for node in self._nodes.values():
            rng, sub = jax.random.split(rng)
            args = [cache[dep] for dep in node.inputs]
            variables[node.name] = node.module.init(sub, *args)
            cache[node.name] = node.module.apply(variables[node.name], *args)
        return variables

    def apply(
        self, variables: Mapping[str, Variables], x: jax.Array
    ) -> jax.Array:
        """Run the full graph (un-partitioned); the single-device path."""
        return self.apply_subset(
            variables, self.topo_order(), {INPUT: x}, output=self.output
        )

    def apply_subset(
        self,
        variables: Mapping[str, Variables],
        node_names: Sequence[str],
        boundary: Mapping[str, jax.Array],
        output: str | None = None,
    ) -> jax.Array:
        """Execute ``node_names`` (a topo-ordered subset) given boundary
        tensors; the primitive that stage ``apply`` functions build on."""
        cache: dict[str, jax.Array] = dict(boundary)
        for name in node_names:
            node = self._nodes[name]
            args = [cache[dep] for dep in node.inputs]
            cache[name] = node.apply(variables[name], *args)
        return cache[output if output is not None else node_names[-1]]

    def eval_shapes(
        self, variables: Mapping[str, Variables], x: jax.ShapeDtypeStruct
    ) -> dict[str, jax.ShapeDtypeStruct]:
        """Shape-propagate the graph without running it: per-node output
        shapes, used by the planner to size activation buffers/codecs."""
        shapes: dict[str, jax.ShapeDtypeStruct] = {INPUT: x}
        for node in self._nodes.values():
            args = [shapes[dep] for dep in node.inputs]
            fn = lambda *a, _n=node: _n.apply(variables[_n.name], *a)
            shapes[node.name] = jax.eval_shape(fn, *args)
        del shapes[INPUT]
        return shapes

    def __repr__(self) -> str:
        return (
            f"LayerGraph({self.name!r}, nodes={len(self._nodes)}, "
            f"output={self._output!r})"
        )


class Lambda:
    """Wrap a pure parameterless function as a node module (merge ops like
    residual add, concat — the reference's Keras ``Add``/``Concatenate``)."""

    def __init__(self, fn: Callable[..., jax.Array], name: str = "lambda"):
        self._fn = fn
        self.name = name

    def init(self, rng: jax.Array, *args: jax.Array) -> Variables:
        del rng, args
        return {}

    def apply(self, variables: Variables, *args: jax.Array) -> jax.Array:
        del variables
        return self._fn(*args)

    def __repr__(self) -> str:
        return f"Lambda({self.name})"
