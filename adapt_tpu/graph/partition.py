"""DAG partitioner: slice a :class:`LayerGraph` into sequential pipeline stages.

Capability parity with the reference's partitioner
(``/root/reference/src/dag_util.py:50-62`` + ``src/dispatcher.py:39-53``):
K named cut points produce K+1 stages; "cut at layer L" means L's *output*
is the stage boundary, so stage *p* spans (output of its start layer) through
its end layer inclusive. Slicing is a backward, memoized traversal from the
stage's end layer that terminates at the boundary — the algorithm that makes
multi-branch DAGs (residual adds, concats) slice correctly
(``src/dag_util.py:10-46``).

Beyond the reference, cuts are *validated*: a cut layer must dominate the
downstream graph (every backward path from a later node must pass through
it), otherwise a skip connection would cross the stage boundary and the
single-tensor activation hop would be wrong. The reference only surfaces
this as a runtime Keras error hint (``src/dag_util.py:41-43``); we reject
the plan up front and offer :func:`valid_cut_points`.
"""

from __future__ import annotations

import dataclasses
from collections.abc import Mapping, Sequence
from typing import Any

import jax

from adapt_tpu.graph.ir import INPUT, LayerGraph, Variables


class InvalidCutError(ValueError):
    """A requested cut does not dominate its downstream stage (a skip
    connection crosses the boundary) or names an unknown layer."""


@dataclasses.dataclass(frozen=True)
class StageSpec:
    """One pipeline stage: the sub-DAG spanning ``(output of start) -> end``.

    ``start`` is :data:`INPUT` for stage 0 (the graph input feeds it);
    otherwise it names the cut layer whose output is this stage's input.
    ``node_names`` is topo-ordered and excludes ``start``.
    """

    index: int
    name: str
    start: str
    end: str
    node_names: tuple[str, ...]

    @property
    def num_nodes(self) -> int:
        return len(self.node_names)


@dataclasses.dataclass(frozen=True)
class PartitionPlan:
    """An ordered list of stages covering the whole graph exactly once."""

    graph: LayerGraph
    stages: tuple[StageSpec, ...]

    @property
    def num_stages(self) -> int:
        return len(self.stages)

    @property
    def cuts(self) -> tuple[str, ...]:
        return tuple(s.start for s in self.stages[1:])

    def stage_apply(self, stage: StageSpec):
        """A pure ``(stage_variables, x) -> y`` function for one stage —
        the unit that gets jit-compiled and placed on a device (the
        TPU-native analog of the reference's per-worker Keras sub-model,
        ``src/node.py:40-45``)."""
        graph = self.graph

        def apply_fn(variables: Mapping[str, Variables], x: jax.Array):
            return graph.apply_subset(
                variables, stage.node_names, {stage.start: x}, output=stage.end
            )

        apply_fn.__name__ = f"{graph.name}_stage{stage.index}"
        return apply_fn

    def extract_variables(
        self, variables: Mapping[str, Variables]
    ) -> list[dict[str, Variables]]:
        """Split full-model variables into per-stage dicts (what the
        reference ships per worker as JSON+weights, ``src/dispatcher.py:
        223-264`` — here it is a host-side pytree slice, no serialization)."""
        return [
            {name: variables[name] for name in stage.node_names}
            for stage in self.stages
        ]

    def compose(
        self,
        stage_variables: Sequence[Mapping[str, Variables]],
        x: jax.Array,
    ) -> jax.Array:
        """Run all stages sequentially on the host device — the correctness
        oracle: ``compose(extract_variables(v), x) == graph.apply(v, x)``."""
        if len(stage_variables) != len(self.stages):
            raise ValueError(
                f"plan has {len(self.stages)} stages but got "
                f"{len(stage_variables)} variable sets (stale plan?)"
            )
        for stage, svars in zip(self.stages, stage_variables):
            x = self.stage_apply(stage)(svars, x)
        return x

    def describe(self) -> str:
        lines = [f"PartitionPlan({self.graph.name}, {self.num_stages} stages)"]
        for s in self.stages:
            lines.append(
                f"  stage {s.index}: [{s.start} -> {s.end}] "
                f"({s.num_nodes} nodes)"
            )
        return "\n".join(lines)


def _backward_slice(
    graph: LayerGraph, end: str, boundary: str
) -> tuple[str, ...]:
    """All nodes needed to compute ``end`` from ``boundary``'s output,
    topo-ordered. Memoized backward traversal (the reference's
    ``traverse_improved`` with ``tensor_cache``, ``src/dag_util.py:10-46``);
    raises :class:`InvalidCutError` if any backward path escapes the
    boundary (reaches :data:`INPUT` or dips below the cut)."""
    needed: set[str] = set()
    # Iterative DFS (graphs are small, but avoid recursion limits for
    # ResNet-152-scale graphs).
    stack = [end]
    while stack:
        name = stack.pop()
        if name in needed or name == boundary:
            continue
        if name == INPUT:
            raise InvalidCutError(
                f"cut at {boundary!r} does not dominate {end!r}: a path "
                "reaches the graph input without passing through the cut "
                "(a skip connection crosses the stage boundary)"
            )
        needed.add(name)
        stack.extend(graph.node(name).inputs)
    # Stage nodes in global topo order == valid stage execution order.
    return tuple(n for n in graph.topo_order() if n in needed)


def partition(graph: LayerGraph, cuts: Sequence[str]) -> PartitionPlan:
    """Split ``graph`` at named layers into ``len(cuts)+1`` stages.

    Mirrors the reference's ``_partition`` (``src/dispatcher.py:39-53``):
    stage 0 runs from the graph input to ``cuts[0]``; stage i runs from the
    output of ``cuts[i-1]`` to ``cuts[i]``; the last stage ends at the graph
    output. Additionally validates coverage: every stage's node set must be
    disjoint and their union must be the whole graph, so no weight is
    computed twice and none is dropped.
    """
    for c in cuts:
        if c not in graph.nodes:
            raise InvalidCutError(
                f"unknown cut layer {c!r} in graph {graph.name!r}"
            )
        if c == graph.output:
            raise InvalidCutError(
                f"cut at {c!r} is the graph output; it would create an "
                "empty final stage"
            )
    if len(set(cuts)) != len(cuts):
        raise InvalidCutError(f"duplicate cut layers: {list(cuts)}")

    bounds = [INPUT, *cuts, graph.output]
    stages: list[StageSpec] = []
    seen: set[str] = set()
    for i in range(len(bounds) - 1):
        start, end = bounds[i], bounds[i + 1]
        node_names = _backward_slice(graph, end, start)
        overlap = seen.intersection(node_names)
        if overlap:
            raise InvalidCutError(
                f"cuts {list(cuts)} are not in topological order: stage "
                f"{i} recomputes {sorted(overlap)[:4]}"
            )
        seen.update(node_names)
        stages.append(
            StageSpec(
                index=i,
                name=f"{graph.name}_stage{i}",
                start=start,
                end=end,
                node_names=node_names,
            )
        )
    uncovered = set(graph.topo_order()) - seen
    if uncovered:
        raise InvalidCutError(
            f"cuts {list(cuts)} leave layers unreached from the output "
            f"boundaries: {sorted(uncovered)[:4]} (dead branches are not "
            "supported)"
        )
    return PartitionPlan(graph=graph, stages=tuple(stages))


def valid_cut_points(graph: LayerGraph) -> list[str]:
    """Layers whose output is a legal single-tensor stage boundary — the
    articulation points of the DAG (excluding the output layer itself).

    Linear scan: a layer L is a valid cut iff, at the moment all of L's
    topological predecessors and L have been 'executed', L is the *only*
    live tensor (no earlier output is still awaited by a later node).
    """
    order = graph.topo_order()
    position = {name: i for i, name in enumerate(order)}
    last_use: dict[str, int] = {}
    for name in order:
        for dep in graph.node(name).inputs:
            last_use[dep] = position[name]  # includes INPUT
    valid = []
    # running = latest consumer position among INPUT and nodes[0..i-1]; node
    # i is a valid cut iff nothing before it is still live after i (its own
    # output being live is exactly the boundary tensor).
    running = last_use.get(INPUT, -1)
    for i, name in enumerate(order[:-1]):
        if running <= i:
            valid.append(name)
        running = max(running, last_use.get(name, -1))
    return valid


def balanced_cuts(
    graph: LayerGraph,
    num_stages: int,
    costs: Mapping[str, float] | None = None,
) -> list[str]:
    """Choose ``num_stages - 1`` valid cut points that balance per-stage
    cost (uniform node count by default; pass per-layer FLOP estimates for
    better balance). The reference has no automatic splitter — cut lists are
    hand-edited source constants (``test/test.py:18``); this is the
    framework-owned upgrade.
    """
    if num_stages < 1:
        raise ValueError("num_stages must be >= 1")
    if num_stages == 1:
        return []
    candidates = valid_cut_points(graph)
    if len(candidates) < num_stages - 1:
        raise InvalidCutError(
            f"graph {graph.name!r} has only {len(candidates)} valid cut "
            f"points; cannot make {num_stages} stages"
        )
    order = graph.topo_order()
    position = {name: i for i, name in enumerate(order)}
    if costs is None:
        costs = {name: 1.0 for name in order}
    total = sum(costs.get(n, 0.0) for n in order)
    prefix: dict[str, float] = {}
    acc = 0.0
    for n in order:
        acc += costs.get(n, 0.0)
        prefix[n] = acc
    cuts: list[str] = []
    for k in range(1, num_stages):
        target = total * k / num_stages
        # Only candidates strictly after the previous cut, and with enough
        # candidates left after them to place the remaining cuts.
        floor = position[cuts[-1]] if cuts else -1
        remaining_after = num_stages - 1 - k
        avail = [
            c
            for j, c in enumerate(candidates)
            if position[c] > floor and len(candidates) - 1 - j >= remaining_after
        ]
        if not avail:
            raise InvalidCutError(
                f"cannot place {num_stages - 1} distinct balanced cuts in "
                f"graph {graph.name!r} ({len(candidates)} valid cut points)"
            )
        cuts.append(min(avail, key=lambda c: abs(prefix[c] - target)))
    return cuts
