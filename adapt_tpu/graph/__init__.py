from adapt_tpu.graph.ir import INPUT, LayerGraph, LayerNode
from adapt_tpu.graph.partition import (
    InvalidCutError,
    PartitionPlan,
    StageSpec,
    partition,
    valid_cut_points,
)
from adapt_tpu.graph.spec import graph_from_spec, graph_to_spec

__all__ = [
    "INPUT",
    "LayerGraph",
    "LayerNode",
    "InvalidCutError",
    "PartitionPlan",
    "StageSpec",
    "partition",
    "valid_cut_points",
    "graph_from_spec",
    "graph_to_spec",
]
