from adapt_tpu.graph.ir import INPUT, LayerGraph, LayerNode
from adapt_tpu.graph.partition import (
    InvalidCutError,
    PartitionPlan,
    StageSpec,
    partition,
    valid_cut_points,
)

__all__ = [
    "INPUT",
    "LayerGraph",
    "LayerNode",
    "InvalidCutError",
    "PartitionPlan",
    "StageSpec",
    "partition",
    "valid_cut_points",
]
