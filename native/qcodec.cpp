// qcodec: native byte-stream codec for activation/weight transport.
//
// TPU-native replacement for the reference's pip-native compression stack
// (lz4.frame + zfpy C bindings wrapped per tensor at every socket hop,
// /root/reference/src/dispatcher.py:92-98, src/node.py:122-125). On TPU,
// intra-pod hops ride ICI and need no codec; this library serves the
// host/DCN boundary: an LZ77 byte compressor (LZ4-block-style format of our
// own design) applied after optional quantization done in numpy/JAX.
//
// Format (per block):
//   [u32 raw_len][compressed bytes...]
// Compressed stream: sequences of
//   token: hi 4 bits = literal run len (15 => extended bytes), lo 4 bits =
//   match len - 4 (15 => extended bytes); literals; u16 LE match offset.
// A final sequence may have no match (offset omitted when the stream ends
// after literals).
//
// Exposed via ctypes (no pybind11 in this image): see adapt_tpu/comm/codec.py.

#include <cstdint>
#include <cstring>
#include <cstddef>

namespace {

constexpr int kMinMatch = 4;
constexpr int kHashBits = 16;
constexpr int kHashSize = 1 << kHashBits;

inline uint32_t hash4(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return (v * 2654435761u) >> (32 - kHashBits);
}

inline void write_u32(uint8_t* p, uint32_t v) { std::memcpy(p, &v, 4); }
inline uint32_t read_u32(const uint8_t* p) {
  uint32_t v;
  std::memcpy(&v, p, 4);
  return v;
}

// Write a length using the 4-bit base + 255-extension scheme.
inline size_t write_len_ext(uint8_t* dst, size_t pos, size_t len) {
  while (len >= 255) {
    dst[pos++] = 255;
    len -= 255;
  }
  dst[pos++] = static_cast<uint8_t>(len);
  return pos;
}

}  // namespace

extern "C" {

// Worst-case compressed size for n input bytes.
size_t qz_bound(size_t n) { return n + n / 255 + 64; }

// Compress src[0..n) into dst (capacity >= qz_bound(n)).
// Returns compressed size, or 0 on failure.
size_t qz_compress(const uint8_t* src, size_t n, uint8_t* dst,
                   size_t dst_cap) {
  if (dst_cap < qz_bound(n)) return 0;
  size_t out = 0;
  write_u32(dst + out, static_cast<uint32_t>(n));
  out += 4;
  if (n < 16) {  // tiny input: all literals
    size_t tok = out++;
    dst[tok] = 0;
    size_t lit = n;
    if (lit >= 15) {
      dst[tok] = 15 << 4;
      out = write_len_ext(dst, out, lit - 15);
    } else {
      dst[tok] = static_cast<uint8_t>(lit << 4);
    }
    std::memcpy(dst + out, src, lit);
    out += lit;
    return out;
  }

  uint32_t table[kHashSize];
  std::memset(table, 0xFF, sizeof(table));

  size_t anchor = 0;
  size_t ip = 0;
  const size_t mflimit = n - 12;  // stop matching near the end

  while (ip < mflimit) {
    uint32_t h = hash4(src + ip);
    uint32_t ref = table[h];
    table[h] = static_cast<uint32_t>(ip);
    bool match = ref != 0xFFFFFFFFu && ip - ref <= 0xFFFF &&
                 std::memcmp(src + ref, src + ip, kMinMatch) == 0;
    if (!match) {
      ++ip;
      continue;
    }
    // Extend the match forward.
    size_t mlen = kMinMatch;
    while (ip + mlen < n - 5 && src[ref + mlen] == src[ip + mlen]) ++mlen;

    size_t lit = ip - anchor;
    size_t tok = out++;
    uint8_t t = 0;
    if (lit >= 15) {
      t |= 15 << 4;
      out = write_len_ext(dst, out, lit - 15);
    } else {
      t |= static_cast<uint8_t>(lit << 4);
    }
    std::memcpy(dst + out, src + anchor, lit);
    out += lit;
    size_t mcode = mlen - kMinMatch;
    if (mcode >= 15) {
      t |= 15;
      dst[tok] = t;
      out = write_len_ext(dst, out, mcode - 15);
    } else {
      t |= static_cast<uint8_t>(mcode);
      dst[tok] = t;
    }
    uint16_t off = static_cast<uint16_t>(ip - ref);
    std::memcpy(dst + out, &off, 2);
    out += 2;
    ip += mlen;
    anchor = ip;
  }

  // Trailing literals.
  size_t lit = n - anchor;
  size_t tok = out++;
  if (lit >= 15) {
    dst[tok] = 15 << 4;
    out = write_len_ext(dst, out, lit - 15);
  } else {
    dst[tok] = static_cast<uint8_t>(lit << 4);
  }
  std::memcpy(dst + out, src + anchor, lit);
  out += lit;
  return out;
}

// Decompress src[0..n) into dst (capacity dst_cap). Returns decompressed
// size, or 0 on malformed input / capacity overflow.
size_t qz_decompress(const uint8_t* src, size_t n, uint8_t* dst,
                     size_t dst_cap) {
  if (n < 4) return 0;
  size_t raw = read_u32(src);
  if (raw > dst_cap) return 0;
  size_t ip = 4;
  size_t out = 0;
  while (ip < n) {
    uint8_t tok = src[ip++];
    size_t lit = tok >> 4;
    if (lit == 15) {
      while (ip < n && src[ip] == 255) {
        lit += 255;
        ++ip;
      }
      if (ip >= n) return 0;
      lit += src[ip++];
    }
    if (ip + lit > n || out + lit > dst_cap) return 0;
    std::memcpy(dst + out, src + ip, lit);
    ip += lit;
    out += lit;
    if (ip >= n) break;  // stream may end after literals
    size_t mcode = tok & 0x0F;
    if (mcode == 15) {
      while (ip < n && src[ip] == 255) {
        mcode += 255;
        ++ip;
      }
      if (ip >= n) return 0;
      mcode += src[ip++];
    }
    size_t mlen = mcode + kMinMatch;
    if (ip + 2 > n) return 0;
    uint16_t off;
    std::memcpy(&off, src + ip, 2);
    ip += 2;
    if (off == 0 || off > out || out + mlen > dst_cap) return 0;
    // Byte-by-byte copy: offsets < mlen overlap (run encoding).
    const uint8_t* from = dst + out - off;
    for (size_t i = 0; i < mlen; ++i) dst[out + i] = from[i];
    out += mlen;
  }
  return out == raw ? out : 0;
}

}  // extern "C"
