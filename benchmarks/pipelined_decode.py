"""Pipelined KV-cache generation on the virtual multi-chip mesh.

The single real chip cannot host a >1 pipeline, so this driver validates
the *schedule* the way the multichip dryrun validates sharding: an
``n``-device virtual CPU mesh (``--xla_force_host_platform_device_count``)
runs ``parallel.pipeline_decode.pipelined_generate`` end-to-end and times
it against single-program ``generate`` on the same host.

What the numbers mean — and don't: every virtual rank timeshares the same
host cores, so the pipeline can never beat single-program here (it adds
rotation collectives to the same arithmetic); the honest claims are (a)
the compiled schedule executes and matches token-for-token, and (b) its
overhead factor vs single-program on shared cores, reported as
``vs_baseline`` (pipelined/single tokens-per-sec, expect <= 1.0 on a
virtual mesh; on P real chips the schedule's steady state runs one token
per tick aggregate — the single-chip rate at P x the memory — which only
hardware can demonstrate). ``--dp`` composes data parallelism on a 2-D
(dp, pp) mesh (rows shard over dp, blocks+caches over pp).

Artifact: ``results/r04/pipelined_decode.json`` for the default config,
``results/r04/pipelined_decode_<tag>.json`` otherwise (tag = ppN[_dpM]).

Usage: ``python benchmarks/pipelined_decode.py [--pp 4] [--dp 1]
[--batch 8] [--steps 32]``
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.common import int_flag, out_path  # noqa: E402  (imports no JAX)

VOCAB, DIM, DEPTH, HEADS, MLP = 1024, 256, 8, 8, 1024
PROMPT_LEN, MAX_LEN = 16, 128
DEFAULT_PP, DEFAULT_DP = 4, 1


def _tag(pp: int, dp: int) -> str:
    """One tag shared by the child's metric and the parent's fallback
    record + filename — a single source so they cannot disagree."""
    return f"pp{pp}" + (f"_dp{dp}" if dp > 1 else "")


def _out_path(tag: str) -> str:
    # The default config keeps the legacy filename README cites.
    name = (
        "pipelined_decode.json"
        if tag == _tag(DEFAULT_PP, DEFAULT_DP)
        else f"pipelined_decode_{tag}.json"
    )
    return out_path(name)


def _child(pp: int, batch: int, steps: int, trials: int, dp: int) -> None:
    from benchmarks.common import force_cpu_mesh

    force_cpu_mesh(pp * dp)
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh

    from adapt_tpu.models.transformer_lm import generate, transformer_lm
    from adapt_tpu.parallel.pipeline_decode import (
        pipelined_generate,
        shard_for_pipeline,
    )

    lm = transformer_lm(VOCAB, DIM, DEPTH, HEADS, MLP, max_len=MAX_LEN)
    prompt = jax.random.randint(
        jax.random.PRNGKey(0), (batch, PROMPT_LEN), 0, VOCAB
    )
    variables = jax.jit(lm.graph.init)(jax.random.PRNGKey(1), prompt)
    if dp > 1:
        mesh = Mesh(
            np.array(jax.devices()[: pp * dp]).reshape(dp, pp),
            ("dp", "pp"),
        )
        dec = lambda v, p: pipelined_generate(  # noqa: E731
            lm, v, p, steps, mesh, axis="pp", dp_axis="dp"
        )
    else:
        mesh = Mesh(np.array(jax.devices()[:pp]), ("pp",))
        dec = lambda v, p: pipelined_generate(  # noqa: E731
            lm, v, p, steps, mesh
        )
    # Pre-place once (the serving pattern): per-rank block slices +
    # replicated embed/head; the timed region is pure decode.
    placed = shard_for_pipeline(lm, variables, mesh)

    def timed(fn):
        out0 = np.asarray(fn(prompt))  # compile + warm
        times = []
        for t in range(trials):
            p = (prompt + t + 1) % VOCAB
            t0 = time.perf_counter()
            np.asarray(fn(p))
            times.append(time.perf_counter() - t0)
        return out0, sorted(times)[len(times) // 2]

    single_out, single_s = timed(
        lambda p: generate(lm, variables, p, steps)
    )
    piped_out, piped_s = timed(lambda p: dec(placed, p))
    match = bool((single_out == piped_out).all())

    single_tok_s = batch * steps / single_s
    piped_tok_s = batch * steps / piped_s
    tag = _tag(pp, dp)
    print(
        json.dumps(
            {
                "metric": f"pipelined_decode_{tag}_tokens_per_sec",
                "value": round(piped_tok_s, 2),
                "unit": "tokens/sec",
                "vs_baseline": round(piped_tok_s / single_tok_s, 4),
                "baseline": "single-program generate() on the same host "
                f"({single_tok_s:.1f} tok/s); virtual ranks timeshare "
                "host cores, so <=1.0 is expected — the claim is the "
                "schedule, not virtual-mesh speedup",
                "platform": jax.devices()[0].platform,
                "tokens_match_single_program": match,
                "config": f"vocab{VOCAB} d{DIM} L{DEPTH} h{HEADS} "
                f"prompt{PROMPT_LEN} steps{steps} bs{batch} {tag}",
                "single_s": round(single_s, 4),
                "pipelined_s": round(piped_s, 4),
            }
        ),
        flush=True,
    )


def main() -> int:
    pp = int_flag(sys.argv, "--pp", DEFAULT_PP)
    dp = int_flag(sys.argv, "--dp", DEFAULT_DP)
    batch = int_flag(sys.argv, "--batch", 8)
    steps = int_flag(sys.argv, "--steps", 32)
    trials = int_flag(sys.argv, "--trials", 3)
    if "--child" in sys.argv:
        _child(pp, batch, steps, trials, dp)
        return 0

    env = dict(os.environ)
    env.pop("PYTHONPATH", None)  # never dial the TPU relay for a CPU mesh
    tag = _tag(pp, dp)
    metric = f"pipelined_decode_{tag}_tokens_per_sec"
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--child",
             "--pp", str(pp), "--dp", str(dp), "--batch", str(batch),
             "--steps", str(steps), "--trials", str(trials)],
            capture_output=True,
            text=True,
            timeout=1200,
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        record = None
        for ln in proc.stdout.splitlines():
            if ln.strip().startswith("{"):
                try:
                    record = json.loads(ln)
                    break
                except json.JSONDecodeError:
                    continue
        if proc.returncode != 0 or record is None:
            record = {
                "metric": metric, "value": 0.0, "unit": "tokens/sec",
                "vs_baseline": 0.0,
                "error": (proc.stderr or proc.stdout or "").strip()[-300:],
            }
    except subprocess.TimeoutExpired:
        record = {
            "metric": metric, "value": 0.0, "unit": "tokens/sec",
            "vs_baseline": 0.0, "error": "child timed out after 1200s",
        }
    out = _out_path(tag)
    os.makedirs(os.path.dirname(out), exist_ok=True)
    with open(out, "w") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    print(json.dumps(record), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
