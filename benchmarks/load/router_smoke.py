"""Fleet-router smoke: prefix-affinity placement vs random, A/B.

Two gated records for ``runtime/router.FleetRouter`` (the DECISION
half of the capacity plane — docs/SERVING.md "Fleet routing"):

- ``load_router_affinity_ttft_ratio`` — the SAME seeded tenant-skewed
  corpus schedule (recurring Zipf-weighted prefixes) runs twice over
  a 2-replica fleet: once placed by the affinity scorer (capacity
  books: prefix-affinity sketch folded into the TTFT forecast, queue
  cost, health) and once by the random control arm. The record is
  random TTFT p50 / affinity TTFT p50 — above 1.0 means affinity
  placement turned resident prefixes into prefill skipped, i.e. the
  scoring formula is WORTH its bookkeeping on the workload shape it
  exists for.
- ``load_router_prefix_hit_ratio`` — the structural half: fleet-summed
  paged prefix-cache hits, affinity arm over random arm. Affinity
  prefills each recurring prefix ONCE fleet-wide (every repeat lands
  on the replica that already holds it); random splits a prefix's
  occurrences across replicas and pays a second prefill per split.
  This gate fails even when TTFT noise on a loaded CI box would mask
  the win.

Both arms drive the identical schedule through the identical fleet
construction (same seeds, same engines, same warmup) — the placement
policy is the ONLY difference.

Usage: ``python benchmarks/load/router_smoke.py [--seed 0]``
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks.common import emit, int_flag  # noqa: E402
from benchmarks.load.workload import build_schedule, preset  # noqa: E402

#: 6 full pages per 96-token corpus prefix (capacity_smoke's choice).
PAGE = 16
#: Per-arm fleet width. Two replicas is the smallest fleet where
#: placement matters: affinity concentrates each prefix on one of
#: them, random splits it.
REPLICAS = 2
#: Per-replica HBM page pool, sized BELOW the full corpus working set
#: (12 prefixes x 6 pages = 72 prefix pages + live decode pages) so
#: residency stays a bounded resource. The measured effect is
#: co-location: affinity prefills each recurring prefix once
#: fleet-wide, random splits a prefix's occurrences across replicas
#: and pays one extra prefill (one extra residency) per split.
#: Under-capacity rate keeps TTFT a prefill measure, not a
#: queue-cliff measure.
POOL_PAGES = 64
RATE_RPS = 10.0
DURATION_S = 3.0

_METRICS = (
    ("load_router_affinity_ttft_ratio",
     "random-placement TTFT p50 over affinity-placement TTFT p50 on "
     "the same corpus schedule (>1 = affinity faster)"),
    ("load_router_prefix_hit_ratio",
     "fleet prefix-cache hits, affinity arm over random arm "
     "(>1 = affinity keeps prefixes resident)"),
)


def _emit_errors(err: str) -> None:
    for metric, unit in _METRICS:
        print(
            json.dumps(
                {"metric": metric, "value": 0.0, "unit": unit,
                 "vs_baseline": 0.0, "error": err}
            ),
            flush=True,
        )


def _run_arm(policy: str, seed: int, spec) -> dict:
    """One arm: a fresh 2-replica fleet, warmed per-engine, driving
    the seeded corpus schedule through the router TWICE and measuring
    the SECOND pass (capacity_smoke's train-then-measure honesty: the
    first pass pays every mid-phase compile variant — the prefix-hit
    suffix passes warmup cannot know — and trains the forecasters, so
    the measured pass is steady-state routing, not XLA). Returns the
    measured phase report plus the fleet's prefix-hit count for it."""
    import jax
    import jax.numpy as jnp

    from benchmarks.load.harness import drive_phase, warmup

    from adapt_tpu.config import CapacityConfig, RouterConfig
    from adapt_tpu.models.transformer_lm import lm_tiny
    from adapt_tpu.runtime.continuous import ContinuousBatcher
    from adapt_tpu.runtime.router import FleetRouter

    lm = lm_tiny(
        vocab=spec.vocab,
        max_len=spec.prompt_max + spec.steps_max + 8,
    )
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    engines = {
        f"r{i}": ContinuousBatcher(
            lm, variables, slots=2, chunk=4, kv_layout="paged",
            page_size=PAGE, pool_pages=POOL_PAGES,
            # Books refresh every tick (placement must read the
            # CURRENT sketch/queue, not a quarter-second-old one) and
            # the sketch is sized to cover a full pool — a sketch
            # smaller than residency under-reports affinity.
            capacity=CapacityConfig(refresh_s=0.0, sketch_k=POOL_PAGES),
        )
        for i in range(REPLICAS)
    }
    # Warm each ENGINE directly (not through the router): both arms
    # must pay identical compile cost on every replica, or the first
    # placements would measure XLA, not routing.
    for eng in engines.values():
        warmup(eng, spec.vocab, spec.steps_max, spec.prompt_max)
    router = FleetRouter(
        engines, config=RouterConfig(policy=policy), seed=seed
    )
    # Train pass: identical schedule, identical seed — every compile
    # variant (including the prefix-hit suffix passes warmup cannot
    # know) and the TTFT forecasters reach steady state.
    drive_phase(router, build_schedule(spec, seed), spec)
    # Cold corpus, warm XLA: drop all cached prefix pages so the
    # measured pass pays REAL prefill per miss, never a compile.
    # This is the regime the A/B exists for — affinity prefills each
    # prefix once fleet-wide and then hits; random re-prefills it on
    # every replica it sprays the prefix onto.
    for eng in engines.values():
        eng._pager.evict_cached()
    hits0 = router.stats().get("prefix_hits", 0)
    report = drive_phase(router, build_schedule(spec, seed), spec)
    report["prefix_hits"] = router.stats().get("prefix_hits", 0) - hits0
    report["policy"] = policy
    report["router"] = {
        k: router.stats()[k]
        for k in ("placed", "shed", "replaced", "replicas_live")
    }
    router.close(close_engines=True)
    return report


def main() -> int:
    seed = int_flag(sys.argv, "--seed", 0)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        spec = preset(
            "corpus", duration_s=DURATION_S, rate_rps=RATE_RPS
        )
        affinity = _run_arm("affinity", seed, spec)
        random_ = _run_arm("random", seed, spec)

        aff_p50 = affinity["ttft_s"].get("p50", 0.0)
        rnd_p50 = random_["ttft_s"].get("p50", 0.0)
        ttft_ratio = (rnd_p50 / aff_p50) if aff_p50 > 0 else 0.0
        emit(
            _METRICS[0][0],
            round(ttft_ratio, 4),
            _METRICS[0][1],
            round(ttft_ratio - 1.0, 4),
            seed=seed,
            affinity_ttft_s=affinity["ttft_s"],
            random_ttft_s=random_["ttft_s"],
            affinity_goodput_tokens_s=affinity["goodput_tokens_s"],
            random_goodput_tokens_s=random_["goodput_tokens_s"],
            requests=affinity["requests"],
            router_affinity=affinity["router"],
            router_random=random_["router"],
        )

        hit_ratio = (
            affinity["prefix_hits"] / random_["prefix_hits"]
            if random_["prefix_hits"]
            else (float(affinity["prefix_hits"]) or 0.0)
        )
        emit(
            _METRICS[1][0],
            round(hit_ratio, 4),
            _METRICS[1][1],
            round(hit_ratio - 1.0, 4),
            seed=seed,
            affinity_prefix_hits=affinity["prefix_hits"],
            random_prefix_hits=random_["prefix_hits"],
        )
    except Exception as e:  # noqa: BLE001 — always JSON lines, rc 0
        _emit_errors(str(e)[-300:])
    return 0


if __name__ == "__main__":
    sys.exit(main())
