"""Async-vs-sync saturation A/B: the pipelined runtime must not lose.

The same seeded saturating schedule (one rate, high enough that the
batcher is the bottleneck, not the arrival process) runs through a
synchronous batcher (``RuntimeConfig(pipeline_depth=1)``) and a
pipelined one (depth 2) — fresh batcher per arm, each warmed, so jit
caches and KV state never cross. The gated value is the throughput
ratio async/sync: the pipelined loop overlaps tick *t+1*'s host
scheduling with tick *t*'s device programs, so at saturation it must
deliver AT LEAST the synchronous loop's tokens/s (>= 1.0 minus CI
slack — the non-regression floor in benchmarks/baselines/seed.json,
checked at unchanged SLO attainment).

Determinism rides in extras: greedy streams are request-deterministic
whatever the tick runtime, so both arms must finish with IDENTICAL
per-request token counts (``token_counts_match``) — a mismatch means
the one-tick commit lag leaked into results, which is a correctness
bug, not a perf delta.

One JSON line: value = async_throughput / sync_throughput;
``vs_baseline`` = value − 1.0 (positive = pipelining ahead).

Usage: ``python benchmarks/load/async_ratio.py [--seed 0]``
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks.common import emit, int_flag  # noqa: E402
from benchmarks.load.workload import (  # noqa: E402
    WorkloadSpec,
    build_schedule,
)

#: Saturating offered rate, req/s: well past the tiny model's capacity,
#: so both arms measure the tick loop's delivery rate, not the arrival
#: process.
RATE = 32.0
UNIT = "async/sync throughput ratio at saturation"


def main() -> int:
    seed = int_flag(sys.argv, "--seed", 0)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import dataclasses

        from benchmarks.load.harness import (
            build_batcher,
            drive_phase,
            warmup,
        )

        from adapt_tpu.config import RuntimeConfig
        from adapt_tpu.utils.profiling import global_engine_obs

        spec = WorkloadSpec(
            duration_s=2.0,
            rate_rps=RATE,
            prompt_median=6,
            prompt_max=16,
            steps_median=16,
            steps_sigma=0.4,
            steps_max=48,
            ttft_budget_s=30.0,
            itl_budget_s=10.0,
        )
        schedule = build_schedule(spec, seed)
        global_engine_obs().enabled = True
        reports = {}
        for arm, depth in (("sync", 1), ("async", 2)):
            bat = build_batcher(
                spec.vocab, spec.prompt_max + spec.steps_max + 8,
                slots=4, chunk=8,
                runtime=RuntimeConfig(pipeline_depth=depth),
            )
            warmup(bat, spec.vocab, spec.steps_max, spec.prompt_max)
            reports[arm] = drive_phase(
                bat, schedule, dataclasses.replace(spec), registry=None
            )
            bat.close()
        sync, asyn = reports["sync"], reports["async"]
        if sync["throughput_tokens_s"] <= 0:
            raise RuntimeError("sync arm delivered zero throughput")
        ratio = asyn["throughput_tokens_s"] / sync["throughput_tokens_s"]
        counts_match = sync["token_counts"] == asyn["token_counts"]
        if not counts_match:
            # A count divergence is a CORRECTNESS failure of the
            # pipelined commit path, not a perf delta — fail loud.
            raise RuntimeError(
                "per-request token counts diverge between sync and "
                "async arms (greedy streams must be runtime-invariant)"
            )
        emit(
            "load_async_saturation_ratio",
            round(ratio, 4),
            UNIT,
            round(ratio - 1.0, 4),
            seed=seed,
            rate_rps=RATE,
            sync_throughput_tokens_s=sync["throughput_tokens_s"],
            async_throughput_tokens_s=asyn["throughput_tokens_s"],
            sync_slo_attainment=sync["slo_attainment"],
            async_slo_attainment=asyn["slo_attainment"],
            sync_ttft_p99_s=sync["ttft_s"].get("p99"),
            async_ttft_p99_s=asyn["ttft_s"].get("p99"),
            token_counts_match=counts_match,
            tokens_delivered=asyn["tokens_delivered"],
            sync_ticks=sync["ticks"],
            async_ticks=asyn["ticks"],
            schedule_digest=sync["schedule_digest"],
        )
    except Exception as e:  # noqa: BLE001 — always one JSON line, rc 0
        print(
            json.dumps(
                {"metric": "load_async_saturation_ratio", "value": 0.0,
                 "unit": UNIT, "vs_baseline": 0.0,
                 "error": str(e)[-300:]}
            ),
            flush=True,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
