"""Traffic-control A/B under 2x overload: quota-on vs quota-off, gated.

The ``overload`` workload preset (``benchmarks/load/workload.PRESETS``)
is a two-tenant priority mix — "free" floods (~89% of arrivals) at the
ordinary class, "gold" is the protected ~11% minority in a strictly
higher class with a 1 s TTFT budget. The offered rate is CALIBRATED
per run: a saturating burst (every request submitted up front, run to
drain) measures THIS box's capacity in tokens/s, and the schedule then
offers exactly 2x it — on an idle CI container that lands near the
preset's documented 960 rps (throughput plateaus ~9.5-10k tok/s), on
a gate-loaded box proportionally lower. Calibration is what makes the
gate portable: a fixed rate is 2x overload on the box it was measured
on and 5-10x on a contended one, where even the protected tenant's own
traffic exceeds total capacity and no scheduler could save it. Gold's
~11% share keeps its offered load at ~0.22x capacity at the 2x point —
protecting it is a SCHEDULING problem, never a capacity one.

This driver runs the SAME calibrated, seeded schedule through two arms
on identically-configured batchers and emits TWO gated records:

- ``load_overload_hi_ttft_attainment`` — the fraction of GOLD requests
  whose first token landed inside the TTFT budget under the
  traffic-control tier (bounded ``AdmissionQueue`` + tenant quotas +
  weighted fair queueing + decode-slot preemption). Rejected or
  never-finished gold requests count as missed. The acceptance pin is
  >= 0.9: the protected tenant stays inside budget while the system
  is offered twice what it can serve. Per-request TTFTs are measured
  DRIVER-side (submit wall -> first ``on_token``), so the per-tenant
  split costs no registry cardinality.
- ``load_overload_goodput_ratio`` — aggregate goodput (delivered
  tokens inside budget / s), quota-on / quota-off. "Graceful
  degradation" means protecting gold must not collapse the aggregate
  BELOW the uncontrolled FIFO arm (which drowns: measured attainment
  ~0.3-0.5, goodput well under the saturation plateau); shedding the
  flood synchronously (``QueueFullError``) typically RAISES goodput,
  because every admitted request is one the tier can still serve
  inside budget.

Structural checks become error records the gate always fails:
- the quota-OFF control arm ALSO holding gold TTFT attainment >= 0.9
  (the overload no longer overloads — the A/B discriminates nothing);
- a quota-on arm that sheds nothing (no rejections) while the control
  arm misses budgets — the bounded queue is not engaging.

Usage: ``python benchmarks/load/overload_smoke.py [--seed 0]``
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks.common import emit, int_flag  # noqa: E402
from benchmarks.load.workload import (  # noqa: E402
    build_schedule,
    offered_tokens,
    preset,
)

DURATION_S = 2.0
SLOTS = 4
CHUNK = 8
#: Calibration burst: this many requests (deterministic arrivals, the
#: preset's length distributions) submitted up front and run to drain
#: measure the box's capacity — ~6k tokens: <1s idle, a few seconds
#: on a gate-loaded box.
CALIBRATION_REQUESTS = 300
#: The overload factor the A/B claims.
OVERLOAD_X = 2.0

_METRICS = (
    ("load_overload_hi_ttft_attainment",
     "gold-tenant TTFT attainment under 2x overload with the "
     "traffic-control tier on"),
    ("load_overload_goodput_ratio",
     "aggregate goodput under 2x overload, quota-on / quota-off"),
)


def _emit_errors(err: str) -> None:
    for metric, unit in _METRICS:
        print(
            json.dumps(
                {"metric": metric, "value": 0.0, "unit": unit,
                 "vs_baseline": 0.0, "error": err}
            ),
            flush=True,
        )


def _tenant_ttft_stats(schedule, report, tenant: str, budget: float):
    """(attainment, p99_s, count) for one tenant from the driver-side
    per-request TTFTs. A rejected request — or one that never emitted
    (must not happen after drain, but counted defensively) — is a
    miss: the client asked and was not served inside budget."""
    ttfts = report["request_ttfts"]
    rejected = report["rejected_flags"]
    met = tot = 0
    vals = []
    for a, t, rej in zip(schedule, ttfts, rejected):
        if a.tenant != tenant:
            continue
        tot += 1
        if not rej and t is not None:
            vals.append(t)
            if t <= budget:
                met += 1
    att = met / tot if tot else 0.0
    p99 = (
        sorted(vals)[max(0, int(0.99 * len(vals)) - 1)] if vals else None
    )
    return att, p99, tot


def main() -> int:
    seed = int_flag(sys.argv, "--seed", 0)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import dataclasses
        import time

        import numpy as np

        from benchmarks.load.harness import (
            build_batcher,
            drive_phase,
            warmup,
        )

        from adapt_tpu.config import SchedulerConfig, TenantQuota

        spec = preset("overload", duration_s=DURATION_S)
        budget = spec.ttft_budget_s
        max_len = spec.prompt_max + spec.steps_max + 8

        # -- calibrate: measure THIS box's capacity, offer 2x it -----
        # The control-arm batcher doubles as the calibration vehicle
        # (same config, already warmed — no third compile set).
        bat_off = build_batcher(spec.vocab, max_len, SLOTS, CHUNK)
        warmup(bat_off, spec.vocab, spec.steps_max, spec.prompt_max)
        burst = build_schedule(
            dataclasses.replace(
                spec, arrival="deterministic",
                rate_rps=CALIBRATION_REQUESTS / DURATION_S,
            ),
            seed + 7,
        )
        t0 = time.perf_counter()
        for a in burst:
            bat_off.submit(np.asarray(a.prompt, np.int32), a.steps)
        bat_off.run()
        burst_wall = time.perf_counter() - t0
        capacity_tok_s = offered_tokens(burst) / burst_wall
        mean_steps = offered_tokens(burst) / len(burst)
        rate = max(
            50.0,
            min(2000.0, OVERLOAD_X * capacity_tok_s / mean_steps),
        )
        spec = dataclasses.replace(spec, rate_rps=rate)
        schedule = build_schedule(spec, seed)

        # Quota-OFF control arm first (it is the calibration batcher):
        # the pre-traffic-control FIFO — the default AdmissionQueue
        # bound is far above this phase's backlog, so nothing rejects;
        # admission is pure arrival order.
        rep_off = drive_phase(bat_off, schedule, spec)
        st_off = bat_off.stats()
        bat_off.close()
        # Quota-ON: the traffic-control tier. Gold in a strictly
        # higher class (preset priorities) with 4x the DRR weight;
        # the free flood is burst-capped so admitted free requests
        # are ones the tier can still serve soon; preemption covers
        # the window where every slot is held by a free decode.
        sched_cfg = SchedulerConfig(
            max_queue_depth=256,
            quotas={
                "gold": TenantQuota(weight=4.0),
                "free": TenantQuota(weight=1.0, burst=16),
            },
            preempt=True,
        )
        bat_on = build_batcher(
            spec.vocab, max_len, SLOTS, CHUNK, scheduler=sched_cfg
        )
        warmup(bat_on, spec.vocab, spec.steps_max, spec.prompt_max)
        rep_on = drive_phase(bat_on, schedule, spec)
        st_on = bat_on.stats()
        bat_on.close()

        att_on, p99_on, n_gold = _tenant_ttft_stats(
            schedule, rep_on, "gold", budget
        )
        att_off, p99_off, _ = _tenant_ttft_stats(
            schedule, rep_off, "gold", budget
        )
        goodput_on = rep_on["goodput_tokens_s"]
        goodput_off = rep_off["goodput_tokens_s"]
        ratio = goodput_on / goodput_off if goodput_off > 0 else 0.0

        if att_off >= 0.9:
            # The control arm also protected gold: the calibrated
            # rate no longer overloads this config, so a quota-on
            # pass proves nothing.
            _emit_errors(
                f"quota-off control arm also passes (gold attainment "
                f"{att_off:.3f} >= 0.9 at the calibrated "
                f"{rate:.0f} rps == {OVERLOAD_X}x measured capacity "
                f"{capacity_tok_s:.0f} tok/s) — the A/B discriminates "
                "nothing"
            )
            return 0
        if st_on["rejected"] == 0 and st_on["preempted"] == 0:
            _emit_errors(
                "quota-on arm neither rejected nor preempted anything "
                "under 2x overload while the control arm missed "
                "budgets — the traffic-control tier is not engaging"
            )
            return 0

        extras = {
            "seed": seed,
            "rate_rps": round(rate, 1),
            "calibrated_capacity_tokens_s": round(capacity_tok_s, 1),
            "overload_x": OVERLOAD_X,
            "requests": rep_on["requests"],
            "gold_requests": n_gold,
            "ttft_budget_s": budget,
            "gold_ttft_p99_s": p99_on,
            "control_gold_ttft_attainment": round(att_off, 4),
            "control_gold_ttft_p99_s": p99_off,
            "rejected": st_on["rejected"],
            "preempted": st_on["preempted"],
            "offered_tokens_s": rep_on["offered_tokens_s"],
            "goodput_on_tokens_s": goodput_on,
            "goodput_off_tokens_s": goodput_off,
            "slo_attainment_on": rep_on["slo_attainment"],
            "slo_attainment_off": rep_off["slo_attainment"],
            "per_tenant_on": rep_on["per_tenant"],
            "per_tenant_off": rep_off["per_tenant"],
            "schedule_digest": rep_on["schedule_digest"],
        }
        emit(
            "load_overload_hi_ttft_attainment",
            round(att_on, 4),
            _METRICS[0][1],
            round(att_on - 1.0, 4),
            **extras,
        )
        emit(
            "load_overload_goodput_ratio",
            round(ratio, 4),
            _METRICS[1][1],
            round(ratio - 1.0, 4),
            seed=seed,
            goodput_on_tokens_s=goodput_on,
            goodput_off_tokens_s=goodput_off,
            rejected=st_on["rejected"],
            preempted=st_on["preempted"],
        )
    except Exception as e:  # noqa: BLE001 — always JSON lines, rc 0
        _emit_errors(str(e)[-300:])
    return 0


if __name__ == "__main__":
    sys.exit(main())
