"""Long-context-preset A/B: the SAME seeded heavy-prefill schedule
through an sp-off and an sp-on batcher — the load-harness TTFT gate
for the sequence-parallel prefill path (ROADMAP item 5 / ISSUE 15).

The schedule is a scaled-down instance of the ``long_context`` preset
shape (lognormal prompts dominating the work, short outputs) sized for
CI: the REAL preset's 8k-64k prompts drive manual runs via
``harness.py --preset long_context --sp on|off``; this driver keeps
the same prompt/output shape class at a tiny LM so the gate runs in
seconds. Two gated records:

- ``load_sp_ttft_ratio`` — sp-off p50 TTFT / sp-on p50 TTFT on the
  same seeded schedule. On THIS one-core CI box the virtual ring
  ranks serialize, so the honest pin is NON-REGRESSION (the sp path's
  ring/landing overhead must not damage TTFT); the prefill-wall WIN
  is gated structurally by ``micro_sp_prefill_flops_ratio`` (the
  per-device work split — the number that becomes wall clock the
  moment the ring ranks are real chips). On parallel hardware this
  ratio tracks that split; the gate's floor only catches the sp path
  making TTFT materially worse.
- ``load_sp_prefills`` — STRUCTURAL: long-prompt admissions that
  actually took the sp program in the sp-on arm (must be > 0, exact
  count is schedule-deterministic). An sp arm that silently
  collocates everything measures nothing; the driver also fails
  (error records) when the two arms' per-request token counts
  diverge — the determinism half of the bit-identity contract, whose
  full byte/stream pins live in tests/test_sp_prefill.py and the
  micro driver.

Usage: ``python benchmarks/load/sp_smoke.py [--seed 0]``
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks.common import emit, force_cpu_mesh, int_flag  # noqa: E402
from benchmarks.load.harness import (  # noqa: E402
    build_batcher,
    drive_phase,
    warmup,
)
from benchmarks.load.workload import WorkloadSpec, build_schedule  # noqa: E402

DURATION_S = 2.0
SLOTS = 2
CHUNK = 4
PAGE = 16
SP_THRESHOLD = 64
SP_WIDTH = 2


def main() -> int:
    seed = int_flag(sys.argv, "--seed", 0)
    try:
        force_cpu_mesh(max(2, SP_WIDTH))
        from adapt_tpu.config import PrefillConfig

        # The long_context preset's SHAPE (prefill-dominated heavy
        # tail, short outputs) at CI scale: median 6 pages, tail to 20
        # pages, outputs a handful of tokens.
        spec = WorkloadSpec(
            rate_rps=4.0,
            duration_s=DURATION_S,
            prompt_median=96,
            prompt_sigma=0.7,
            prompt_max=320,
            steps_median=6,
            steps_sigma=0.4,
            steps_max=12,
            ttft_budget_s=10.0,
            itl_budget_s=5.0,
        )
        schedule = build_schedule(spec, seed)
        max_len = spec.prompt_max + spec.steps_max + 8
        arms: dict[str, dict] = {}
        for arm, cfg in (
            ("off", None),
            ("on", PrefillConfig(sp_threshold=SP_THRESHOLD,
                                 sp_width=SP_WIDTH)),
        ):
            bat = build_batcher(
                spec.vocab, max_len, SLOTS, CHUNK, layout="paged",
                page_size=PAGE, prefill=cfg, prefill_chunk=2 * PAGE,
            )
            warmup(bat, spec.vocab, spec.steps_max, spec.prompt_max)
            report = drive_phase(bat, schedule, spec)
            arms[arm] = {
                "ttft_p50": report["ttft_s"].get("p50"),
                "ttft_p99": report["ttft_s"].get("p99"),
                "sp_prefills": report["sp_prefills"],
                "sp_width": report["sp_width"],
                "token_counts": report["token_counts"],
                "prefill_tokens_s": report["prefill_tokens_s"],
                "wall_s": report["wall_s"],
                "schedule_digest": report["schedule_digest"],
            }
            bat.close()

        off, on = arms["off"], arms["on"]
        violations: list[str] = []
        if not on["sp_prefills"]:
            violations.append(
                "sp-on arm never dispatched the sp program (threshold "
                f"{SP_THRESHOLD}, widths {on['sp_width']})"
            )
        if off["sp_prefills"]:
            violations.append(
                f"sp-off arm reports {off['sp_prefills']} sp prefills"
            )
        if off["token_counts"] != on["token_counts"]:
            violations.append(
                "per-request token counts diverged between arms "
                "(determinism contract broken)"
            )
        if violations:
            for metric in ("load_sp_ttft_ratio", "load_sp_prefills"):
                emit(metric, 0.0, "structural", 0.0,
                     error="; ".join(violations)[:300])
            return 0
        ratio = (
            off["ttft_p50"] / on["ttft_p50"]
            if on["ttft_p50"] else 0.0
        )
        extras = dict(
            seed=seed,
            sp_width=SP_WIDTH,
            sp_threshold=SP_THRESHOLD,
            requests=len(schedule),
            off={k: v for k, v in off.items() if k != "token_counts"},
            on={k: v for k, v in on.items() if k != "token_counts"},
        )
        emit(
            "load_sp_ttft_ratio", ratio,
            "sp-off p50 TTFT / sp-on p50 TTFT (same seeded schedule)",
            0.0, **extras,
        )
        emit(
            "load_sp_prefills", float(on["sp_prefills"]),
            "sp-program admissions in the sp-on arm (structural)",
            0.0, seed=seed,
        )
    except Exception as e:  # noqa: BLE001 — always one JSON line, rc 0
        for metric in ("load_sp_ttft_ratio", "load_sp_prefills"):
            emit(metric, 0.0, "structural", 0.0, error=str(e)[-300:])
    return 0


if __name__ == "__main__":
    sys.exit(main())
