"""SLO-aware closed-loop load harness for the continuous batcher.

ROADMAP item 4's measurement half: before an adaptive scheduler can
claim "goodput degrades gracefully under overload", something must
generate realistic traffic, record whether each request met its latency
budget, and put load, SLO attainment and hardware utilization on one
timeline. This package is that instrument:

- ``workload.py`` — seeded OPEN-LOOP arrival schedules (Poisson or
  deterministic spacing), heavy-tailed prompt/output lengths, tenant
  skew and cancel storms. A ``(WorkloadSpec, seed)`` pair fully
  determines the schedule — two runs submit identical requests.
- ``harness.py`` — drives a REAL ``ContinuousBatcher`` tick loop under
  a schedule, reads per-phase SLO attainment + windowed TTFT/ITL
  percentiles through the ``MetricsRegistry`` snapshot-delta API, and
  sweeps arrival rates into a goodput-vs-offered-load curve (BENCH-style
  report JSON, roofline-annotated).
- ``smoke.py`` — the CI-sized run (tiny model, two arrival rates,
  fixed seed) gated by ``benchmarks/ci_gate.py`` via
  ``baselines/seed.json`` (``load_goodput_tokens_s``,
  ``load_slo_attainment``).

How-to: ``docs/OBSERVABILITY.md`` "Workload telemetry".
"""
