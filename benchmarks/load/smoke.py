"""CI-sized load-harness smoke: two arrival rates, fixed seed, gated.

The smallest run that exercises the whole workload-observability loop —
seeded open-loop arrivals, SLO evaluation, windowed snapshot deltas,
goodput accounting, roofline annotation — fast enough for the
perf-regression gate. Emits TWO JSON records (one per line, the
multi-record driver contract ``benchmarks/ci_gate.py`` understands),
both measured at the UNDER-CAPACITY rate where the numbers are
CI-stable:

- ``load_goodput_tokens_s`` — delivered-inside-budget tokens/s. Under
  capacity this tracks the offered token rate (the schedule is
  seed-deterministic, so the numerator is exact; only the drain tail
  moves with CI noise) — a collapse means the serving tier stopped
  keeping up with traffic it comfortably handled at baseline.
- ``load_slo_attainment`` — request-level SLO attainment (met / all).
  Budgets are sized ~100x above the tiny model's tick time, so a miss
  under baseline-grade load is a real regression (a stall, a compile
  on the hot path, a scheduler bug), not noise.

The OVERLOAD point rides along as extras (and the full curve lives in
``benchmarks/load/harness.py``): ``overload_*`` fields show goodput
plateauing and attainment degrading at ~an order of magnitude more
offered load — the graceful-degradation shape, not gated because its
exact values are contention-dependent.

Usage: ``python benchmarks/load/smoke.py [--seed 0]``
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks.common import emit, int_flag  # noqa: E402
from benchmarks.load.workload import WorkloadSpec  # noqa: E402

#: (under-capacity, overload) offered rates, req/s.
RATE_LOW = 6.0
RATE_HIGH = 48.0


def main() -> int:
    seed = int_flag(sys.argv, "--seed", 0)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        from benchmarks.load.harness import (
            build_batcher,
            run_sweep,
            warmup,
        )

        from adapt_tpu.utils.profiling import global_engine_obs

        # Budgets sit ~100x above the tiny model's tick wall time:
        # at the under-capacity rate a miss is a genuine stall (hot
        # compile, scheduler bug), not shared-CI jitter. The overload
        # point violates them through queueing, by design.
        spec = WorkloadSpec(
            duration_s=2.0,
            prompt_median=6,
            prompt_max=16,
            steps_median=16,
            steps_sigma=0.4,
            steps_max=48,
            ttft_budget_s=3.0,
            itl_budget_s=2.0,
        )
        bat = build_batcher(
            spec.vocab, spec.prompt_max + spec.steps_max + 8,
            slots=4, chunk=8,
        )
        global_engine_obs().enabled = True
        warmup(bat, spec.vocab, spec.steps_max, spec.prompt_max)
        low, high = run_sweep(
            bat, spec, [RATE_LOW, RATE_HIGH], seed
        )
        extras = {
            "seed": seed,
            "rate_rps": RATE_LOW,
            "offered_tokens_s": low["offered_tokens_s"],
            "throughput_tokens_s": low["throughput_tokens_s"],
            "prefill_tokens_s": low["prefill_tokens_s"],
            "decode_tokens_s": low["decode_tokens_s"],
            "ttft_p99_s": low["ttft_s"].get("p99"),
            "itl_p99_s": low["itl_s"].get("p99"),
            "schedule_digest": low["schedule_digest"],
            "tokens_delivered": low["tokens_delivered"],
            "roofline": low["roofline"],
            "overload_rate_rps": RATE_HIGH,
            "overload_offered_tokens_s": high["offered_tokens_s"],
            "overload_goodput_tokens_s": high["goodput_tokens_s"],
            "overload_slo_attainment": high["slo_attainment"],
            "overload_ttft_p99_s": high["ttft_s"].get("p99"),
        }
        emit(
            "load_goodput_tokens_s",
            low["goodput_tokens_s"],
            "tokens/s inside budget at the under-capacity rate",
            low["goodput_tokens_s"] - low["offered_tokens_s"],
            **extras,
        )
        att = low["slo_attainment"]
        emit(
            "load_slo_attainment",
            att if att is not None else 0.0,
            "fraction of requests meeting their SLO at the "
            "under-capacity rate",
            (att if att is not None else 0.0) - 1.0,
            seed=seed,
            rate_rps=RATE_LOW,
            ttft_attainment=low["ttft_attainment"],
            itl_attainment=low["itl_attainment"],
            per_tenant=low["per_tenant"],
            overload_slo_attainment=high["slo_attainment"],
        )
    except Exception as e:  # noqa: BLE001 — always JSON lines, rc 0
        err = str(e)[-300:]
        for metric, unit in (
            ("load_goodput_tokens_s",
             "tokens/s inside budget at the under-capacity rate"),
            ("load_slo_attainment",
             "fraction of requests meeting their SLO at the "
             "under-capacity rate"),
        ):
            print(
                json.dumps(
                    {"metric": metric, "value": 0.0, "unit": unit,
                     "vs_baseline": 0.0, "error": err}
                ),
                flush=True,
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
