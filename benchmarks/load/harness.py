"""Closed-loop load harness: real batcher ticks under open-loop traffic.

``drive_phase`` submits one :mod:`benchmarks.load.workload` schedule
against a live ``ContinuousBatcher`` on the wall clock (arrivals are
open-loop: the server being slow never slows the offered load), drives
the synchronous tick loop until the phase drains, and reads the phase's
telemetry through the ``MetricsRegistry`` windowed snapshot-delta API —
so TTFT/ITL percentiles, SLO attainment and goodput are THIS phase's,
not cumulative-since-boot. ``run_sweep`` chains phases over an
arrival-rate ladder on ONE batcher (jit caches are per-instance; a
fresh batcher per point would re-pay every compile) and emits the
goodput-vs-offered-load curve as a BENCH-style report, each point
annotated with the roofline gauges (``engine.mbu``/``engine.mfu`` —
how bandwidth-bound the engine actually was at that load).

Determinism contract (pinned in ``tests/test_load.py``): the schedule
is a pure function of ``(spec, seed)``, greedy streams are
request-deterministic whatever the slot scheduling, and cancel marks
live in TOKEN space — two runs submit identical requests and finish
with identical per-request token counts. Wall-clock things (latencies,
goodput) are measurements, not replayable values.

Driver usage (one BENCH-style JSON line on stdout)::

    python benchmarks/load/harness.py --rates 4,8,16,32 --seed 0
    python benchmarks/load/harness.py --rates 8 --cancel-pct 50
    python benchmarks/load/harness.py --preset corpus --cache-tier on
    python benchmarks/load/harness.py --preset agent_trace --fanout on
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks.common import int_flag, str_flag  # noqa: E402
from benchmarks.load.workload import (  # noqa: E402
    Arrival,
    WorkloadSpec,
    build_schedule,
    offered_tokens,
    schedule_digest,
)

#: Per-phase wall guard: a wedged phase (stuck tick, runaway compile)
#: must fail the run loudly, not hang CI.
PHASE_WALL_GUARD_S = 300.0


def warmup(bat, vocab: int, steps_max: int, prompt_max: int) -> None:
    """Pre-pay the compile cost the first phase would otherwise eat as
    fake TTFT: one admission per prompt bucket the workload can hit
    (prefill variants — including the LONG-CONTEXT pow2 buckets: the
    chunked-prefill window variants, the sp-prefill program + its
    adopt-pages bucket and the pow2-padded suffix variant all compile
    on that bucket's admission), with step counts covering the
    key-block power-of-two buckets (``_stage_slot`` variants), then
    drain.

    The largest bucket is warmed too: a prompt of ``bucket`` tokens
    leaves no decode room when ``bucket == max_len``, so the admission
    shrinks to ``max_len - 1`` tokens while still mapping into that
    bucket — previously the loop broke there and a first max-bucket
    admission (a 32k prompt on a long-context config) paid its whole
    compile stack mid-phase, measured as fake TTFT."""
    import numpy as np

    rng = np.random.RandomState(0)
    max_len = bat.lm.max_len
    # Chunked-prefill batchers compile one FINAL-chunk variant per
    # (last-chunk page class): a prompt's last pass runs cbucket =
    # ceil((s0 mod chunk)/page)*page tokens, so lengths differing by a
    # page can hit different variants. Warm every class per bucket by
    # admitting page-stepped lengths, not just the bucket length.
    chunk = getattr(bat, "_prefill_chunk", None)
    page = getattr(bat, "_page", 0)
    # Sequence-parallel batchers route warmup admissions >= the sp
    # threshold through the sp program — which warms the sp/adopt/
    # suffix families but leaves the threshold's bucket COLD for the
    # chunked classes sub-threshold phase prompts hit. Warm those with
    # page-stepped lengths just under the threshold too.
    sp_cfg = getattr(bat, "_sp_cfg", None)
    sp_thr = (
        sp_cfg.sp_threshold
        if sp_cfg is not None and getattr(bat, "_sp", None) is not None
        else None
    )
    # One admission per reachable prompt bucket (prefill variants).
    for bucket in bat.prompt_buckets:
        plen = min(bucket, max_len - 1)
        if next(b for b in bat.prompt_buckets if b >= plen) != bucket:
            break  # shrunk length falls into an earlier bucket: done
        lens = {plen}
        if chunk and page:
            for c in range(1, chunk // page):
                shorter = plen - c * page
                if shorter > 0 and next(
                    b for b in bat.prompt_buckets if b >= shorter
                ) == bucket:
                    lens.add(shorter)
        if sp_thr is not None and plen >= sp_thr:
            steps_below = (chunk // page) if (chunk and page) else 1
            for c in range(steps_below):
                shorter = sp_thr - 1 - c * page
                if shorter > 0 and next(
                    b for b in bat.prompt_buckets if b >= shorter
                ) == bucket:
                    lens.add(shorter)
        for length in sorted(lens):
            n_steps = min(2, max_len - length)
            bat.submit(
                rng.randint(0, vocab, size=length).astype(np.int32),
                n_steps,
            )
        if bucket >= prompt_max:
            break  # later buckets are unreachable for this workload
    # Every key-block power-of-two bucket a step count in
    # [1, steps_max] can map to (nkb = pow2ceil(steps)), so no phase
    # admission compiles a fresh _stage_slot variant mid-measurement.
    s = 1
    while True:
        bat.submit(rng.randint(0, vocab, size=2).astype(np.int32),
                   min(s, steps_max))
        if s >= steps_max:
            break
        s *= 2
    bat.run()


def warmup_disagg(srv, vocab: int, steps_max: int,
                  prompt_max: int) -> None:
    """Disaggregated-server warmup: the shared :func:`warmup` pass with
    placement forced COLLOCATED (decode-side prefill buckets + key
    blocks), then one disagg-path admission per reachable full-page
    count — the prefill worker's chunk programs, the adopt-pages
    buckets and the decode side's per-page-count suffix variants all
    compile here instead of as fake mid-phase stalls."""
    import numpy as np

    from adapt_tpu.config import DisaggConfig

    real = srv.cfg
    srv.cfg = DisaggConfig(
        prompt_threshold=10**6, busy_prompt_threshold=10**6
    )
    try:
        warmup(srv, vocab, steps_max, prompt_max)
    finally:
        srv.cfg = real
    P = srv.decode._page
    thr = min(real.prompt_threshold, real.busy_prompt_threshold)
    m_lo = max(1, (thr - 1) // P)
    m_hi = (prompt_max - 1) // P
    # Which page counts to warm. The compiled families key on POWERS
    # OF TWO (worker chunk windows, adopt-pages buckets, the
    # pow2-padded decode-side suffix window) plus the worker's
    # last-chunk remainder class (m mod chunk-pages), so a
    # long-context config (m_hi in the hundreds) warms a pow2/pow2-1
    # LADDER + a dense residue head instead of every page count — the
    # per-m loop that was fine at 8 pages is 500 admissions at 64k
    # tokens. Short configs keep the exact per-m loop.
    if m_hi - m_lo <= 16:
        ms = list(range(m_lo, m_hi + 1))
    else:
        cpp = max(1, (srv.prefill._chunk or P) // P)
        picked = set(range(m_lo, min(m_lo + 2 * cpp, m_hi) + 1))
        p2 = 1
        while p2 <= m_hi:
            for m in (p2 - 1, p2):
                if m_lo <= m <= m_hi:
                    picked.add(m)
            p2 *= 2
        picked.add(m_hi)
        ms = sorted(picked)
    rng = np.random.RandomState(1)
    # Pin BOTH thresholds to the lower (busy) one for the warmup loop:
    # warmup runs at zero occupancy, where the real config would apply
    # only prompt_threshold and silently collocate the busy-tier
    # lengths — leaving their adopt/suffix variants to compile
    # mid-phase, the exact fake stall this function exists to prevent.
    srv.cfg = DisaggConfig(prompt_threshold=thr, busy_prompt_threshold=thr)
    try:
        for m in ms:
            # Smallest prompt with m full pages the policy will
            # actually disaggregate (at least the threshold).
            s0 = min(max(m * P + 1, thr), prompt_max)
            if (s0 - 1) // P != m:
                continue
            srv.submit(
                rng.randint(0, vocab, size=s0).astype(np.int32), 2
            )
        srv.run()
    finally:
        srv.cfg = real


def drive_phase(
    bat,
    schedule: list[Arrival],
    spec: WorkloadSpec,
    registry=None,
    wall_guard_s: float = PHASE_WALL_GUARD_S,
    fanout: bool = False,
) -> dict:
    """Run one phase to drain; returns the phase report (windowed
    metrics + per-request token counts + digests).

    ``fanout=True`` (the ``--fanout on`` arm) submits each run of
    consecutive same-``Arrival.group`` arrivals through ONE
    ``submit_fanout`` call (copy-on-write page sharing across the
    branches); ``fanout=False`` submits the identical schedule
    serially — the two arms ``benchmarks/load/fanout_smoke.py``
    compares. Ungrouped arrivals (``group == -1``) always submit
    serially."""
    import numpy as np

    from adapt_tpu.config import SLOSpec
    from adapt_tpu.utils.metrics import global_metrics
    from adapt_tpu.utils.tracing import global_flight_recorder

    from adapt_tpu.runtime.scheduler import QueueFullError

    reg = registry if registry is not None else global_metrics()
    recorder = global_flight_recorder()
    finishes0 = recorder.kind_counts().get("finish", 0)
    n = len(schedule)
    counts = [0] * n  # emitted tokens per scheduled request
    cancelled = [False] * n
    #: Admission-control rejections (bounded queue / burst caps /
    #: best-effort shed — traffic-control arms only). A rejected
    #: request never produces a finish edge, so the drain loop and
    #: the per-request books both subtract it.
    rejected = [False] * n
    submit_wall = [0.0] * n
    ttfts: list[float | None] = [None] * n
    #: Per-request emitted tokens, in commit order — the bit-identity
    #: half of the determinism contract (A/B smokes compare these
    #: between arms; the per-token append is trivial at bench scale).
    streams: list[list[int]] = [[] for _ in range(n)]

    def make_cb(i: int, a: Arrival):
        def cb(rid, tok, idx, _i=i, _c=a.cancel_after):
            if ttfts[_i] is None:
                # Driver-side per-request TTFT (wall clock from the
                # scheduled submit): the per-TENANT attainment split
                # the overload gate needs, without growing registry
                # cardinality per tenant.
                ttfts[_i] = time.perf_counter() - submit_wall[_i]
            streams[_i].append(int(tok))
            counts[_i] += 1
            if _c is not None and counts[_i] == _c:
                # Token-space cancel mark: the marker is consumed
                # at the next commit boundary, so the final stream
                # length is deterministic (exactly _c tokens).
                cancelled[_i] = True
                bat.cancel(rid)
        return cb

    win = reg.snapshot(window=True)
    t0 = time.perf_counter()
    pi = 0
    stats0 = bat.stats()
    ticks0 = stats0["ticks"]
    sp0 = stats0.get("sp_prefills", 0)
    cow0 = stats0.get("cow_forks", 0)
    #: rid -> per-arrival callback for fan-out groups (one shared
    #: on_token per group; siblings are told apart by request id).
    #: Filled right after submit_fanout returns — safe because the
    #: drive loop is single-threaded, so no tick (hence no token)
    #: can land between the call and the map fill.
    fan_cbs: dict[int, object] = {}

    def fan_cb(rid, tok, idx):
        cb = fan_cbs.get(rid)
        if cb is not None:
            cb(rid, tok, idx)

    while True:
        now = time.perf_counter() - t0
        while pi < n and schedule[pi].t <= now:
            a = schedule[pi]
            slo = SLOSpec(
                ttft_budget_s=spec.ttft_budget_s,
                itl_budget_s=spec.itl_budget_s,
                tenant=a.tenant,
                priority=a.priority,
            )
            if fanout and a.group >= 0:
                # One submit_fanout per run of same-group arrivals
                # (build_schedule emits them contiguously at one t).
                idxs = [pi]
                while (
                    pi + len(idxs) < n
                    and schedule[pi + len(idxs)].group == a.group
                ):
                    idxs.append(pi + len(idxs))
                wall = time.perf_counter()
                for i in idxs:
                    submit_wall[i] = wall
                try:
                    rids = bat.submit_fanout(
                        np.asarray(a.prompt, np.int32),
                        len(idxs),
                        a.steps,
                        slo=slo,
                        on_token=fan_cb,
                    )
                    for rid, i in zip(rids, idxs):
                        fan_cbs[rid] = make_cb(i, schedule[i])
                except QueueFullError:
                    # Mid-group raises lose the queued siblings' ids;
                    # the fan-out arms run without a bounded queue, so
                    # this is a whole-group reject in practice.
                    for i in idxs:
                        rejected[i] = True
                pi += len(idxs)
                continue
            submit_wall[pi] = time.perf_counter()
            try:
                bat.submit(
                    np.asarray(a.prompt, np.int32),
                    a.steps,
                    slo=slo,
                    on_token=make_cb(pi, a),
                )
            except QueueFullError:
                rejected[pi] = True
            pi += 1
        finished = recorder.kind_counts().get("finish", 0) - finishes0
        if pi >= n and finished >= n - sum(rejected):
            break
        if now > wall_guard_s:
            raise RuntimeError(
                f"phase wall guard ({wall_guard_s:.0f}s) exceeded: "
                f"{finished}/{n} finished, {pi}/{n} submitted"
            )
        if pi < n and finished == pi:
            # Fully drained but the next arrival is in the future:
            # nap until it (bounded) instead of busy-spinning ticks.
            gap = schedule[pi].t - (time.perf_counter() - t0)
            if gap > 0:
                time.sleep(min(gap, 0.01))
        bat.tick()
    # Pipelined runtimes (config.RuntimeConfig) may hold one garbage
    # tick in flight after the last finish edge — drain it so the
    # phase's windowed snapshot (and the next phase) start clean.
    drain = getattr(bat, "drain", None)
    if drain is not None:
        drain()
    wall_s = time.perf_counter() - t0

    delta = reg.snapshot(since=win)
    c = delta["counters"]
    window_s = delta["window_s"]

    def attainment(prefix: str) -> float | None:
        met = c.get(f"slo.{prefix}_met_total", 0.0)
        missed = c.get(f"slo.{prefix}_missed_total", 0.0)
        return met / (met + missed) if met + missed else None

    per_tenant: dict[str, dict[str, float]] = {}
    for key, v in c.items():
        for kind in ("met", "missed"):
            pre = f"slo.{kind}_total."
            if key.startswith(pre):
                per_tenant.setdefault(
                    key[len(pre):], {"met": 0.0, "missed": 0.0}
                )[kind] = v
    req_met = sum(t["met"] for t in per_tenant.values())
    req_missed = sum(t["missed"] for t in per_tenant.values())

    def pct(hname: str) -> dict:
        h = delta["histograms"].get(hname, {})
        return {
            k: round(h[k], 6) for k in ("p50", "p99", "max") if k in h
        }

    roofline = {
        k: v
        for k, v in delta["gauges"].items()
        if k.startswith(
            ("engine.mbu", "engine.mfu", "engine.flops",
             "engine.bytes_accessed")
        )
    }
    # Prefill/decode token-rate SPLIT: one blended tokens/s hides
    # exactly the ratio disaggregation changes, so report prompt
    # positions prefilled per second (decode-tick prefill work plus
    # any prefill-tier work) next to committed decode tokens per
    # second. The stall histogram is the decode-delay the in-tick
    # share of that prefill work caused.
    prefill_tokens = c.get("continuous.prefill_tokens_total", 0.0) + c.get(
        "disagg.prefill_tokens_total", 0.0
    )
    stall = delta["histograms"].get("continuous.prefill_stall_s", {})
    # decode_tokens_s IS throughput_tokens_s today (committed decode
    # tokens over the window); both keys ship so the prefill/decode
    # split reads naturally next to prefill_tokens_s, computed once.
    decode_tokens_s = round(
        c.get("continuous.tokens_total", 0.0) / window_s, 2
    )
    # Prefill-TIER telemetry (disagg arms): the windowed disagg.*
    # counter deltas + handoff-wall percentiles, so a disagg phase
    # report carries the tier's own numbers (placement split, pages
    # and bytes streamed, failed handoffs) next to the decode-side
    # stall histogram it exists to shrink — instead of reporting the
    # stall win with the tier that produced it invisible.
    disagg = {
        k[len("disagg."):]: round(v, 3)
        for k, v in c.items()
        if k.startswith("disagg.") and v
    }
    if disagg:
        disagg["handoff_s"] = pct("disagg.handoff_s")
    return {
        "requests": n,
        "offered_rps": round(n / spec.duration_s, 4),
        "offered_tokens_s": round(
            offered_tokens(schedule) / spec.duration_s, 2
        ),
        "goodput_tokens_s": round(
            c.get("continuous.good_tokens_total", 0.0) / window_s, 2
        ),
        "throughput_tokens_s": decode_tokens_s,
        "decode_tokens_s": decode_tokens_s,
        "prefill_tokens_s": round(prefill_tokens / window_s, 2),
        "prefill_stall_s": {
            k: round(stall[k], 6)
            for k in ("p50", "p99", "max", "sum", "count")
            if k in stall
        },
        "slo_attainment": (
            round(req_met / (req_met + req_missed), 4)
            if req_met + req_missed
            else None
        ),
        "ttft_attainment": attainment("ttft"),
        "itl_attainment": attainment("itl"),
        "per_tenant": per_tenant,
        "ttft_s": pct("continuous.ttft_s"),
        "itl_s": pct("continuous.itl_s"),
        "queue_wait_s": pct("continuous.queue_wait_s"),
        "cancelled": int(sum(cancelled)),
        "rejected": int(sum(rejected)),
        "tokens_delivered": int(sum(counts)),
        "token_counts": counts,
        "token_streams": streams,
        "request_ttfts": ttfts,
        "rejected_flags": rejected,
        "ticks": bat.stats()["ticks"] - ticks0,
        # Sequence-parallel prefill books for the phase (0 on sp-off
        # arms — the long_context A/B's structural check that the sp
        # arm actually took the sp path).
        "sp_prefills": bat.stats().get("sp_prefills", 0) - sp0,
        "sp_width": bat.stats().get("sp_width", 1),
        # Copy-on-write fork count for the phase (0 on --fanout off /
        # dense arms — fanout_smoke's structural check that the fan-out
        # arm actually shared pages instead of prefilling N times).
        "cow_forks": bat.stats().get("cow_forks", 0) - cow0,
        "wall_s": round(wall_s, 3),
        "window_s": round(window_s, 3),
        "roofline": roofline,
        "disagg": disagg,
        "schedule_digest": schedule_digest(schedule),
    }


def run_sweep(
    bat,
    spec: WorkloadSpec,
    rates: list[float],
    seed: int,
    registry=None,
    fanout: bool = False,
) -> list[dict]:
    """One phase per offered rate on ONE batcher (phase seeds derive
    from ``seed`` + the rate index, so every point is independently
    deterministic). Returns the curve points in sweep order."""
    points = []
    for i, rate in enumerate(rates):
        pspec = dataclasses.replace(spec, rate_rps=float(rate))
        schedule = build_schedule(pspec, seed + i)
        report = drive_phase(
            bat, schedule, pspec, registry=registry, fanout=fanout
        )
        report["rate_rps"] = float(rate)
        report["seed"] = seed + i
        points.append(report)
    return points


def build_batcher(
    vocab: int,
    max_len: int,
    slots: int,
    chunk: int,
    layout: str = "slots",
    page_size: int = 128,
    scheduler=None,
    pool_pages: int | None = None,
    cache_tier=None,
    prefill=None,
    prefill_chunk: int | None = None,
    runtime=None,
):
    """The harness's model+batcher factory (CPU-forced; tiny LM — the
    harness measures the serving tier's behavior under load, not model
    quality). ``scheduler`` (a ``config.SchedulerConfig``) turns the
    traffic-control tier on — the quota-on arm of an overload A/B.
    ``cache_tier`` (a ``config.CacheTierConfig``; paged only) turns
    the host-DRAM spill tier on — the tier-on arm of the corpus A/B —
    and ``pool_pages`` pins the HBM budget so both arms run flat.
    ``prefill`` (a ``config.PrefillConfig``; paged only) turns the
    sequence-parallel long-context prefill path on — the sp-on arm of
    the long_context A/B (the caller must provision
    ``sp_width`` virtual devices first, e.g.
    ``benchmarks.common.force_cpu_mesh``). ``runtime`` (a
    ``config.RuntimeConfig``) selects the tick runtime — depth 2 is
    the pipelined/async arm of the runtime A/B."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_platforms", "cpu")
    from adapt_tpu.models.transformer_lm import lm_tiny
    from adapt_tpu.runtime.continuous import ContinuousBatcher

    lm = lm_tiny(vocab=vocab, max_len=max_len)
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    kw = {"page_size": page_size} if layout == "paged" else {}
    if pool_pages is not None and layout == "paged":
        kw["pool_pages"] = pool_pages
    if cache_tier is not None:
        kw["cache_tier"] = cache_tier
    if scheduler is not None:
        kw["scheduler"] = scheduler
    if prefill is not None and layout == "paged":
        kw["prefill"] = prefill
    if prefill_chunk is not None and layout == "paged":
        kw["prefill_chunk"] = prefill_chunk
    if runtime is not None:
        kw["runtime"] = runtime
    return ContinuousBatcher(
        lm, variables, slots=slots, chunk=chunk, kv_layout=layout, **kw
    )


def build_disagg(
    vocab: int,
    max_len: int,
    slots: int,
    chunk: int,
    page_size: int = 16,
    prefill_chunk: int | None = None,
    prompt_threshold: int = 48,
    busy_prompt_threshold: int | None = None,
    scheduler=None,
    prefill=None,
    runtime=None,
):
    """The disaggregated counterpart of :func:`build_batcher`: a paged
    decode batcher, a chunked ``PrefillWorker`` and the
    ``DisaggServer`` placement policy in front — same driver surface,
    so ``drive_phase``/``run_sweep`` run the SAME schedule through
    either placement for an apples-to-apples curve. ``prefill_chunk``
    defaults to two pages (the per-tick stall bound)."""
    decode = build_batcher(
        vocab, max_len, slots, chunk, layout="paged",
        page_size=page_size, scheduler=scheduler, runtime=runtime,
    )
    from adapt_tpu.config import DisaggConfig
    from adapt_tpu.runtime.disagg import DisaggServer, PrefillWorker

    worker = PrefillWorker(
        decode.lm,
        decode.variables,
        page_size=page_size,
        prefill_chunk=prefill_chunk or 2 * page_size,
        # Sequence-parallel long-context jobs run sp-sharded in the
        # TIER (`--sp on --placement disagg`): the worker's step()
        # dispatches them through the sp program instead of the chunk
        # loop, and prompts past the pool bound stay servable.
        prefill=prefill,
    )
    # Default busy threshold: two pages, capped at the main threshold.
    # A/B drivers pass busy == prompt_threshold instead, which makes
    # the placement a PURE function of the schedule (occupancy plays
    # no role) — run-to-run comparable.
    cfg = DisaggConfig(
        prompt_threshold=prompt_threshold,
        busy_prompt_threshold=(
            busy_prompt_threshold
            if busy_prompt_threshold is not None
            else min(prompt_threshold, 2 * page_size)
        ),
    )
    return DisaggServer(decode, worker, cfg)


def main() -> int:
    rates_arg = str_flag(sys.argv, "--rates", "4,8,16,32")
    seed = int_flag(sys.argv, "--seed", 0)
    slots = int_flag(sys.argv, "--slots", 4)
    chunk = int_flag(sys.argv, "--chunk", 8)
    duration = int_flag(sys.argv, "--duration", 3)
    cancel_pct = int_flag(sys.argv, "--cancel-pct", 0)
    layout = str_flag(
        sys.argv, "--layout", "slots", choices=("slots", "paged")
    )
    preset_name = str_flag(sys.argv, "--preset", "")
    placement = str_flag(
        sys.argv, "--placement", "collocated",
        choices=("collocated", "disagg"),
    )
    # Traffic control: "on" fronts admission with the default
    # SchedulerConfig (bounded queue, WFQ, preemption, degradation) so
    # the SAME seeded schedule drives quota-on vs quota-off runs —
    # e.g. `--preset overload --scheduler on` vs `--scheduler off`.
    sched_arg = str_flag(
        sys.argv, "--scheduler", "off", choices=("off", "on")
    )
    # Hierarchical KV: "on" puts the host-DRAM spill tier under the
    # paged prefix cache (default CacheTierConfig) so the SAME seeded
    # schedule drives tier-on vs tier-off arms — e.g.
    # `--preset corpus --cache-tier on` vs `--cache-tier off`
    # (implies --layout paged; the tier has no dense analog).
    tier_arg = str_flag(
        sys.argv, "--cache-tier", "off", choices=("off", "on")
    )
    # Sequence-parallel prefill: "on" routes prompts of at least
    # --sp-threshold tokens through the sp-sharded prefill program at
    # --sp-width ring ranks (implies --layout paged) — the sp-on arm
    # of the long_context A/B, e.g.
    # `--preset long_context --sp on` vs `--sp off`. Virtual CPU
    # devices are provisioned automatically (force_cpu_mesh).
    # Copy-on-write fan-out: "on" submits each same-group run of
    # arrivals (the agent_trace preset's branches) through ONE
    # submit_fanout call — shared prefix pages, CoW forks on
    # divergence (implies --layout paged); "off" submits the identical
    # schedule serially. `--preset agent_trace --fanout on` vs
    # `--fanout off` is the pair benchmarks/load/fanout_smoke.py gates.
    fanout_arg = str_flag(
        sys.argv, "--fanout", "off", choices=("off", "on")
    )
    sp_arg = str_flag(sys.argv, "--sp", "off", choices=("off", "on"))
    sp_width = int_flag(sys.argv, "--sp-width", 2)
    sp_threshold = int_flag(sys.argv, "--sp-threshold", 4096)
    # Tick runtime: "async" runs the pipelined depth-2 runtime
    # (config.RuntimeConfig(pipeline_depth=2) — host scheduling of
    # tick t+1 overlaps tick t's device programs) so the SAME seeded
    # schedule drives async-vs-sync arms, e.g. `--runtime async` vs
    # `--runtime sync` (see load/async_ratio.py for the gated ratio).
    runtime_arg = str_flag(
        sys.argv, "--runtime", "sync", choices=("sync", "async")
    )
    out = str_flag(sys.argv, "--out", "")
    try:
        rates = [float(r) for r in rates_arg.split(",") if r]
        if preset_name:
            from benchmarks.load.workload import preset

            spec = preset(
                preset_name,
                duration_s=float(duration),
                cancel_fraction=cancel_pct / 100.0,
            )
        else:
            spec = WorkloadSpec(
                duration_s=float(duration),
                cancel_fraction=cancel_pct / 100.0,
            )
        from adapt_tpu.utils.profiling import global_engine_obs

        scheduler = None
        if sched_arg == "on":
            from adapt_tpu.config import SchedulerConfig

            scheduler = SchedulerConfig()
        cache_tier = None
        if tier_arg == "on":
            from adapt_tpu.config import CacheTierConfig

            cache_tier = CacheTierConfig()
            layout = "paged"
        if fanout_arg == "on":
            layout = "paged"
        sp_cfg = None
        if sp_arg == "on":
            from benchmarks.common import force_cpu_mesh

            from adapt_tpu.config import PrefillConfig

            force_cpu_mesh(max(2, sp_width))
            sp_cfg = PrefillConfig(
                sp_threshold=sp_threshold, sp_width=sp_width
            )
            layout = "paged"
        runtime = None
        if runtime_arg == "async":
            from adapt_tpu.config import RuntimeConfig

            runtime = RuntimeConfig(pipeline_depth=2)
        if placement == "disagg":
            # Same schedule, disaggregated serving path (paged decode +
            # prefill tier) — the apples-to-apples arm of the
            # long-tail-prefill comparison (see load/disagg_smoke.py).
            bat = build_disagg(
                spec.vocab,
                spec.prompt_max + spec.steps_max + 8,
                slots,
                chunk,
                scheduler=scheduler,
                prefill=sp_cfg,
                runtime=runtime,
            )
        else:
            bat = build_batcher(
                spec.vocab,
                spec.prompt_max + spec.steps_max + 8,
                slots,
                chunk,
                layout,
                scheduler=scheduler,
                cache_tier=cache_tier,
                prefill=sp_cfg,
                runtime=runtime,
            )
        # Phase timing on: every curve point gets its roofline
        # annotation (mbu/mfu need measured phase walls).
        global_engine_obs().enabled = True
        if placement == "disagg":
            # The disagg-aware warmup: prefill-worker chunk programs,
            # adopt-pages buckets and per-page-count suffix variants
            # must compile here, not as fake mid-phase stalls.
            warmup_disagg(bat, spec.vocab, spec.steps_max, spec.prompt_max)
        else:
            warmup(bat, spec.vocab, spec.steps_max, spec.prompt_max)
        points = run_sweep(
            bat, spec, rates, seed, fanout=fanout_arg == "on"
        )
        peak = max(p["goodput_tokens_s"] for p in points)
        report = {
            "metric": "load_goodput_curve",
            "value": peak,
            "unit": "tokens/s (peak goodput over the sweep)",
            "vs_baseline": 0.0,
            "rates_rps": rates,
            "seed": seed,
            "slots": slots,
            "chunk": chunk,
            "layout": layout,
            "placement": placement,
            "scheduler": sched_arg,
            "fanout": fanout_arg,
            "sp": sp_arg,
            "runtime": runtime_arg,
            "prefill_cfg": (
                dataclasses.asdict(sp_cfg) if sp_cfg else None
            ),
            # Stamp the ACTIVE CacheTierConfig (capacity/codec/budgets)
            # so perf rows stay comparable across runs — a tier-on row
            # and a tier-off row are different serving configs.
            "cache_tier": (
                dataclasses.asdict(cache_tier) if cache_tier else None
            ),
            "preset": preset_name or None,
            "spec": dataclasses.asdict(spec),
            "points": [
                {k: v for k, v in p.items()
                 if k not in ("token_counts", "token_streams",
                              "request_ttfts", "rejected_flags")}
                for p in points
            ],
        }
        print(json.dumps(report), flush=True)
        if out:
            os.makedirs(os.path.dirname(out) or ".", exist_ok=True)
            with open(out, "w", encoding="utf-8") as f:
                json.dump(report, f, indent=1)
                f.write("\n")
    except Exception as e:  # noqa: BLE001 — always one JSON line, rc 0
        print(
            json.dumps(
                {
                    "metric": "load_goodput_curve",
                    "value": 0.0,
                    "unit": "tokens/s (peak goodput over the sweep)",
                    "vs_baseline": 0.0,
                    "error": str(e)[-300:],
                }
            ),
            flush=True,
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
