"""Seeded workload generation: open-loop arrivals, heavy tails, skew.

The schedule is built ENTIRELY up front from ``(WorkloadSpec, seed)`` —
arrival times, prompts, output lengths, tenants, cancel marks — so two
runs of the same spec offer bit-identical traffic (the harness's
determinism contract) and the arrival process stays OPEN-LOOP: a slow
server does not slow the offered load down, which is exactly what makes
overload visible (closed-loop clients self-throttle and hide it).

Length distributions are lognormal (the classic heavy-tailed fit for
both prompt and output lengths in production traces): most requests are
short, a deterministic-seeded minority are many times the median, which
is what makes head-of-line and slot-occupancy effects show up at
moderate mean load. Tenant choice is Zipf-weighted (rank ``r`` gets
weight ``1/r^skew``) so one tenant dominates — the skew the per-tenant
``slo.{met,missed}_total`` counters exist to expose. A ``cancel_mark``
on a request means the DRIVER cancels it after that many emitted
tokens; marking in token space (not wall time) keeps the resulting
token counts deterministic across runs.
"""

from __future__ import annotations

import dataclasses
import hashlib

import numpy as np


@dataclasses.dataclass(frozen=True)
class WorkloadSpec:
    """One load phase's traffic recipe (all knobs seed-deterministic)."""

    #: "poisson" (exponential inter-arrivals — bursty, the honest
    #: default) or "deterministic" (fixed spacing — isolates queueing
    #: from burstiness).
    arrival: str = "poisson"
    #: Offered arrival rate, requests/second (open-loop).
    rate_rps: float = 8.0
    #: Arrival-window length in seconds: requests arrive in [0, T);
    #: the phase then drains.
    duration_s: float = 4.0
    #: Token-id universe for synthetic prompts.
    vocab: int = 37
    #: Prompt length: lognormal(median=prompt_median, sigma), clipped
    #: to [1, prompt_max]. sigma is the heavy-tail knob (0 = constant).
    prompt_median: int = 8
    prompt_sigma: float = 0.6
    prompt_max: int = 48
    #: Output length (decode steps), same shape of distribution.
    steps_median: int = 24
    steps_sigma: float = 0.6
    steps_max: int = 96
    #: Tenant labels, Zipf-weighted by list rank (rank r ~ 1/r^skew).
    tenants: tuple[str, ...] = ("t0", "t1", "t2", "t3")
    tenant_skew: float = 1.5
    #: Scheduling class per tenant (``config.SLOSpec.priority``;
    #: unlisted tenants ride class 0). The traffic-control tier's
    #: priority mixes come from here — e.g. the "overload" preset's
    #: protected-gold / best-effort-flood split.
    tenant_priorities: tuple[tuple[str, int], ...] = ()
    #: Per-request latency budgets (None disables that budget).
    ttft_budget_s: float | None = 1.0
    itl_budget_s: float | None = 0.5
    #: Cancel storm: this fraction of requests is marked for driver
    #: cancellation after ``cancel_after_tokens`` emitted tokens.
    cancel_fraction: float = 0.0
    cancel_after_tokens: int = 4
    #: Recurring-prefix corpus (the "corpus" preset): when
    #: ``prefix_pool`` > 0, every prompt is one of ``prefix_pool``
    #: deterministic shared prefixes of ``prefix_len`` tokens
    #: (Zipf-weighted by rank at ``prefix_skew`` — conversation
    #: histories recur skewed, not uniformly) followed by a fresh
    #: lognormal tail — so the paged prefix cache sees the same full
    #: pages again and again, and total distinct prefix pages can be
    #: sized to exceed the HBM pool several-fold (what the host-tier
    #: A/B needs).
    prefix_pool: int = 0
    prefix_len: int = 0
    prefix_skew: float = 0.8
    #: Multi-turn conversations (the "multi_turn" preset): each base
    #: arrival becomes a chain of ``turns`` requests, every follow-up
    #: re-entering with the WHOLE conversation so far (previous prompt
    #: + a seeded stand-in for the model's reply + a fresh user turn)
    #: after ``turn_gap_s`` seconds — so the radix prefix cache sees
    #: each conversation's hot node path again and again, at depths
    #: whole-run keying cannot match (the re-entry is a PARTIAL hit:
    #: old prompt pages resident, reply + new-turn pages fresh).
    #: Chains stop early when the prompt would exceed ``prompt_max``.
    turns: int = 1
    turn_gap_s: float = 0.25
    #: Agent-style branching (the "agent_trace" preset): each arrival
    #: fans out into ``branches`` identical-prompt requests sharing an
    #: ``Arrival.group`` id at the same instant — the shape
    #: ``ContinuousBatcher.submit_fanout`` serves with copy-on-write
    #: page sharing, and the ``harness.py --fanout`` arm drives
    #: grouped-vs-serial over one schedule.
    branches: int = 1

    def __post_init__(self):
        if self.arrival not in ("poisson", "deterministic"):
            raise ValueError(
                f"arrival={self.arrival!r}: expected 'poisson' or "
                "'deterministic'"
            )
        if self.rate_rps <= 0:
            raise ValueError(f"rate_rps must be > 0, got {self.rate_rps}")
        if self.duration_s <= 0:
            raise ValueError(
                f"duration_s must be > 0, got {self.duration_s}"
            )
        if not 0.0 <= self.cancel_fraction <= 1.0:
            raise ValueError(
                f"cancel_fraction must be in [0, 1], got "
                f"{self.cancel_fraction}"
            )
        if not self.tenants:
            raise ValueError("tenants must be non-empty")
        if self.prefix_pool < 0 or self.prefix_len < 0:
            raise ValueError("prefix_pool/prefix_len must be >= 0")
        if bool(self.prefix_pool) != bool(self.prefix_len):
            raise ValueError(
                "prefix_pool and prefix_len must be set together"
            )
        if self.prefix_len and self.prefix_len >= self.prompt_max:
            raise ValueError(
                f"prefix_len {self.prefix_len} leaves no room for a "
                f"tail under prompt_max {self.prompt_max}"
            )
        if self.turns < 1:
            raise ValueError(f"turns must be >= 1, got {self.turns}")
        if self.turns > 1 and self.turn_gap_s <= 0:
            raise ValueError(
                f"turn_gap_s must be > 0, got {self.turn_gap_s}"
            )
        if self.branches < 1:
            raise ValueError(
                f"branches must be >= 1, got {self.branches}"
            )


#: Named workload presets (``preset(name)`` materializes one).
#: ``disagg`` is the LONG-TAIL PREFILL mix that reproduces the
#: decode-stall pathology on the collocated serving path: heavy-tailed
#: prompt lengths with a fat p99 (sigma 1.6 around a short median —
#: most prompts are a few pages, the tail is an order of magnitude
#: longer) and SHORT outputs, so decode ticks are cheap and any ITL
#: p99 inflation is attributable to in-tick prefill work
#: (``continuous.prefill_stall_s``). ``benchmarks/load/disagg_smoke``
#: runs the same schedule through both placements and gates the
#: disaggregated win on it.
PRESETS: dict[str, dict] = {
    "disagg": dict(
        prompt_median=24,
        prompt_sigma=1.6,
        prompt_max=1024,
        steps_median=24,
        steps_sigma=0.3,
        steps_max=48,
        ttft_budget_s=3.0,
        itl_budget_s=2.0,
    ),
    # The OVERLOAD preset: 2x the measured saturation rate of the
    # smoke-scale serving config (4 slots, chunk 8, tiny LM), with a
    # TWO-TENANT PRIORITY MIX and heavy-tailed lengths: "free" floods
    # (~89% of arrivals, Zipf rank 0 at skew 3) at the ordinary
    # class, "gold" is the protected ~11% minority in a strictly
    # higher class — small enough that gold's own offered load
    # (~0.22x capacity at the 2x point) always fits, which is what
    # makes "protect gold" a scheduling problem rather than a
    # capacity one. Under FIFO this mix drowns gold's TTFT budget
    # (queue wait at 2x overload grows past the 1s budget mid-phase);
    # the traffic-control tier (quotas + WFQ + preemption) must keep
    # gold inside budget while aggregate goodput degrades gracefully.
    # rate_rps here is 2x the saturation measured on an IDLE CI
    # container (throughput plateaus ~9.5-10k tok/s == ~480 rps) —
    # the right default for manual `harness.py --preset overload`
    # runs; benchmarks/load/overload_smoke.py instead CALIBRATES the
    # rate per run (a saturating burst measures the box's actual
    # capacity, then the schedule offers exactly 2x it), so the gate
    # holds on loaded CI boxes where the idle number is 3-5x off.
    # The CORPUS preset: a tenant-skewed conversation corpus of
    # RECURRING prefixes whose total full pages are sized (by the
    # driver's pool_pages choice) to exceed the HBM pool several-fold
    # — the regime ROADMAP item 3 names, where the prefix LRU alone
    # cannot keep the working corpus warm and evicted pages either die
    # (tier off) or spill to host DRAM and readmit (tier on).
    # benchmarks/load/tier_smoke.py drives the same seeded schedule
    # through both arms (`harness.py --preset corpus --cache-tier
    # on|off` reproduces them by hand) and gates the servable-prefix
    # multiplier at flat HBM budget.
    "corpus": dict(
        rate_rps=24.0,
        prompt_median=4,
        prompt_sigma=0.5,
        prompt_max=160,
        steps_median=6,
        steps_sigma=0.4,
        steps_max=12,
        prefix_pool=12,
        prefix_len=96,
        prefix_skew=0.6,
        ttft_budget_s=3.0,
        itl_budget_s=2.0,
    ),
    # The LONG-CONTEXT preset: the long-document / agent-trace
    # workload class ROADMAP item 5 names — lognormal 8k-64k prompts
    # (median 16k, a heavy right tail capped at 64k) with SHORT
    # outputs, so virtually all of the serving work is the prefill
    # wall and TTFT is dominated by how fast one prompt's O(S^2)
    # attention runs. This is the regime the sequence-parallel prefill
    # path (`config.PrefillConfig{sp_threshold, sp_width}`,
    # `harness.py --sp on|off`) exists for: one seeded schedule drives
    # sp-on vs sp-off arms and the report carries TTFT percentiles
    # for both. Offered rate is LOW by construction (long prompts are
    # slow); budgets are prefill-scaled. benchmarks/load/sp_smoke.py
    # runs a scaled-down instance of this shape as the CI arm.
    "long_context": dict(
        rate_rps=0.5,
        duration_s=8.0,
        prompt_median=16384,
        prompt_sigma=0.7,
        prompt_max=65536,
        steps_median=16,
        steps_sigma=0.4,
        steps_max=32,
        ttft_budget_s=60.0,
        itl_budget_s=2.0,
    ),
    # The MULTI-TURN preset: short conversational opens that re-enter
    # 3 more times each, every follow-up carrying the WHOLE
    # conversation so far. Re-entries are the radix prefix cache's
    # signature workload — the resident pages cover a strict PREFIX of
    # the grown prompt (a partial hit whole-run content keys score as
    # a miss), so token-weighted `paged.prefix_hits` under radix
    # keying beats whole-run keying here by construction.
    # benchmarks/micro/radix_prefix.py gates that gap in CI.
    "multi_turn": dict(
        rate_rps=12.0,
        turns=4,
        turn_gap_s=0.25,
        prompt_median=6,
        prompt_sigma=0.5,
        prompt_max=96,
        steps_median=6,
        steps_sigma=0.4,
        steps_max=12,
        ttft_budget_s=3.0,
        itl_budget_s=2.0,
    ),
    # The AGENT-TRACE preset: every arrival fans out into 4 branches
    # with identical prompts at the same instant (tool-call / search
    # style exploration), tied by `Arrival.group`. The harness's
    # `--fanout on` arm submits each group via `submit_fanout` (width
    # N costs ~1x the shared prefix pages, CoW forks on divergence);
    # `--fanout off` submits the same schedule serially.
    # benchmarks/load/fanout_smoke.py drives both arms and gates
    # stream identity + the page-cost ratio.
    "agent_trace": dict(
        rate_rps=8.0,
        branches=4,
        prompt_median=12,
        prompt_sigma=0.5,
        prompt_max=96,
        steps_median=6,
        steps_sigma=0.4,
        steps_max=12,
        ttft_budget_s=3.0,
        itl_budget_s=2.0,
    ),
    "overload": dict(
        rate_rps=960.0,
        prompt_median=6,
        prompt_sigma=0.8,
        prompt_max=16,
        steps_median=16,
        steps_sigma=0.8,
        steps_max=48,
        tenants=("free", "gold"),
        tenant_skew=3.0,
        tenant_priorities=(("gold", 10),),
        ttft_budget_s=1.0,
        itl_budget_s=2.0,
    ),
}


def preset(name: str, **overrides) -> WorkloadSpec:
    """A named :class:`WorkloadSpec` preset, with per-field overrides
    (``preset("disagg", duration_s=4.0)``)."""
    try:
        base = PRESETS[name]
    except KeyError:
        raise ValueError(
            f"unknown preset {name!r}; have {sorted(PRESETS)}"
        ) from None
    return WorkloadSpec(**{**base, **overrides})


def schedule_prefixes(
    spec: WorkloadSpec, seed: int
) -> list[tuple[int, ...]]:
    """The corpus preset's shared prefixes — a pure function of
    ``(spec, seed)`` on its OWN rng stream (decoupled from the
    arrival stream, so a driver can reconstruct the prefix list to
    probe servability without replaying the whole schedule)."""
    if not spec.prefix_pool:
        return []
    rng = np.random.RandomState(seed * 1_000_003 + 17)
    return [
        tuple(
            int(x) for x in rng.randint(0, spec.vocab, size=spec.prefix_len)
        )
        for _ in range(spec.prefix_pool)
    ]


@dataclasses.dataclass(frozen=True)
class Arrival:
    """One scheduled request (everything the driver needs to submit)."""

    t: float  # arrival offset from phase start, seconds
    prompt: tuple[int, ...]
    steps: int
    tenant: str
    #: Driver cancels after this many emitted tokens (None = run out).
    cancel_after: int | None
    #: Scheduling class (rides ``SLOSpec.priority`` at submit).
    priority: int = 0
    #: Fan-out group id: arrivals sharing a non-negative ``group``
    #: carry identical prompts at the same instant (the "agent_trace"
    #: preset's branch fan-out). The harness's ``--fanout on`` arm
    #: submits each group through ``submit_fanout`` (copy-on-write
    #: page sharing); ``--fanout off`` submits the same arrivals
    #: serially. -1 = ordinary ungrouped request.
    group: int = -1


def _lognormal_len(
    rng: np.random.RandomState, median: int, sigma: float, cap: int
) -> int:
    if sigma <= 0:
        return min(median, cap)
    v = int(round(rng.lognormal(mean=np.log(median), sigma=sigma)))
    return int(np.clip(v, 1, cap))


def build_schedule(spec: WorkloadSpec, seed: int) -> list[Arrival]:
    """The whole phase's traffic, sorted by arrival time. Pure function
    of ``(spec, seed)`` — the determinism contract the harness pins."""
    rng = np.random.RandomState(seed)
    times: list[float] = []
    if spec.arrival == "poisson":
        t = 0.0
        while True:
            t += float(rng.exponential(1.0 / spec.rate_rps))
            if t >= spec.duration_s:
                break
            times.append(t)
    else:
        step = 1.0 / spec.rate_rps
        times = list(np.arange(0.0, spec.duration_s, step))
    weights = np.array(
        [1.0 / (r + 1) ** spec.tenant_skew
         for r in range(len(spec.tenants))]
    )
    weights /= weights.sum()
    prio_map = dict(spec.tenant_priorities)
    prefixes = schedule_prefixes(spec, seed)
    if prefixes:
        pweights = np.array(
            [1.0 / (r + 1) ** spec.prefix_skew
             for r in range(len(prefixes))]
        )
        pweights /= pweights.sum()
    out: list[Arrival] = []
    for t in times:
        plen = _lognormal_len(
            rng, spec.prompt_median, spec.prompt_sigma, spec.prompt_max
        )
        steps = _lognormal_len(
            rng, spec.steps_median, spec.steps_sigma, spec.steps_max
        )
        if prefixes:
            # Recurring-prefix prompt: shared prefix + fresh tail (the
            # lognormal draw above becomes the TAIL length, capped so
            # the whole prompt stays under prompt_max).
            head = prefixes[
                int(rng.choice(len(prefixes), p=pweights))
            ]
            tail_len = min(plen, spec.prompt_max - spec.prefix_len)
            prompt = head + tuple(
                int(x) for x in rng.randint(0, spec.vocab, size=tail_len)
            )
        else:
            prompt = tuple(
                int(x) for x in rng.randint(0, spec.vocab, size=plen)
            )
        tenant = spec.tenants[
            int(rng.choice(len(spec.tenants), p=weights))
        ]
        cancel_after = None
        if spec.cancel_fraction and (
            rng.uniform() < spec.cancel_fraction
        ):
            # Token-space mark (never wall clock): the cancel lands at
            # a commit boundary after exactly this many tokens, so the
            # cancelled stream's length is run-to-run deterministic.
            cancel_after = max(
                1, min(spec.cancel_after_tokens, steps - 1)
            ) if steps > 1 else 1
        out.append(
            Arrival(
                t=float(t),
                prompt=prompt,
                steps=steps,
                tenant=tenant,
                cancel_after=cancel_after,
                priority=prio_map.get(tenant, 0),
            )
        )
    if spec.turns > 1:
        # Multi-turn chaining: every base arrival re-enters turns-1
        # more times, each follow-up prompt = the whole conversation so
        # far (previous prompt + a seeded stand-in for the model's
        # reply, one token per decode step + a fresh user turn). The
        # re-entry is exactly the radix cache's partial-hit shape: the
        # old prompt's pages are resident, the reply/new-turn tokens
        # are fresh. Chains stop early at prompt_max.
        chained: list[Arrival] = []
        for a in out:
            chained.append(a)
            prev = a
            for _ in range(spec.turns - 1):
                user_len = _lognormal_len(
                    rng, spec.prompt_median, spec.prompt_sigma,
                    spec.prompt_max,
                )
                prompt = prev.prompt + tuple(
                    int(x) for x in rng.randint(
                        0, spec.vocab, size=prev.steps + user_len
                    )
                )
                if len(prompt) > spec.prompt_max:
                    break
                steps = _lognormal_len(
                    rng, spec.steps_median, spec.steps_sigma,
                    spec.steps_max,
                )
                prev = Arrival(
                    t=prev.t + spec.turn_gap_s,
                    prompt=prompt,
                    steps=steps,
                    tenant=a.tenant,
                    cancel_after=None,
                    priority=a.priority,
                )
                chained.append(prev)
        chained.sort(key=lambda a: a.t)
        out = chained
    if spec.branches > 1:
        # Branch fan-out: each arrival becomes `branches` siblings with
        # identical prompts at the same instant, tied by a group id —
        # the submit_fanout shape (shared prefix pages, CoW forks).
        out = [
            dataclasses.replace(a, group=gid)
            for gid, a in enumerate(out)
            for _ in range(spec.branches)
        ]
    return out


def schedule_digest(schedule: list[Arrival]) -> str:
    """Stable hash of every schedule field — the 'identical request
    schedules' half of the determinism acceptance check."""
    h = hashlib.sha256()
    for a in schedule:
        h.update(
            repr(
                (round(a.t, 9), a.prompt, a.steps, a.tenant,
                 a.cancel_after, a.priority, a.group)
            ).encode()
        )
    return h.hexdigest()[:16]


def offered_tokens(schedule: list[Arrival]) -> int:
    """Total decode tokens the schedule asks for (cancel marks NOT
    subtracted — offered load is what the clients wanted, goodput is
    what the server delivered inside budget)."""
    return sum(a.steps for a in schedule)
