"""Agent-trace A/B: the SAME branching schedule submitted through
``submit_fanout`` (copy-on-write page sharing) vs serially.

The ``agent_trace`` workload preset fans every arrival into 4
identical-prompt branches tied by ``Arrival.group`` — the tool-call /
search exploration shape. The harness's ``--fanout on`` arm groups
each branch set into ONE ``submit_fanout`` call; ``--fanout off``
submits the identical arrivals one by one. Greedy fan-out is
contractually bit-identical to serial submits, so the whole A/B is a
correctness gate with a perf headline on top. Two gated records:

- ``load_fanout_identity_exact`` — 1.0 when the fan-out arm's
  per-request token streams are BIT-IDENTICAL to the serial arm's,
  the fan-out arm actually forked (``cow_forks`` > 0; a zero means
  every branch re-ran its suffix prefill and the arm measured
  nothing), the serial arm recorded none, and both arms drain with
  the pool partition exact and zero leaked page claims. Any violation
  becomes an ``error`` record the gate always fails.
- ``load_fanout_prefill_ratio`` — prompt positions prefilled in-tick,
  serial / fan-out: each CoW fork skips a whole suffix pass, so the
  fan-out arm must prefill strictly fewer positions over the same
  schedule. Deterministic (schedule-derived counts, not wall clock).

Usage: ``python benchmarks/load/fanout_smoke.py [--seed 0]``
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks.common import emit, int_flag  # noqa: E402
from benchmarks.load.harness import (  # noqa: E402
    build_batcher,
    drive_phase,
    warmup,
)
from benchmarks.load.workload import build_schedule, preset  # noqa: E402

DURATION_S = 2.0
SLOTS = 4
CHUNK = 4
PAGE = 16
#: Covers the 4 slots' worst case (ceil(116/16) = 8 pages each) plus
#: prefix-LRU headroom so branch groups admit without pool pressure.
POOL_PAGES = 48

_METRICS = (
    ("load_fanout_identity_exact", "bool"),
    ("load_fanout_prefill_ratio",
     "x (in-tick prefill positions, serial / fan-out)"),
)


def _emit_errors(err: str) -> None:
    for metric, unit in _METRICS:
        print(
            json.dumps(
                {"metric": metric, "value": 0.0, "unit": unit,
                 "vs_baseline": 0.0, "error": err}
            ),
            flush=True,
        )


def main() -> int:
    seed = int_flag(sys.argv, "--seed", 0)
    try:
        from adapt_tpu.utils.profiling import global_compile_sentinel

        # Two fresh batchers (one per arm) in one process: the second
        # arm's warmup compiles are legitimate — disarm the alarm (the
        # kv_tiers rationale).
        global_compile_sentinel().warmup_samples = 10**9
        spec = preset("agent_trace", duration_s=DURATION_S)
        schedule = build_schedule(spec, seed)
        max_len = spec.prompt_max + spec.steps_max + 8
        arms: dict[str, dict] = {}
        for arm in ("serial", "fanout"):
            bat = build_batcher(
                spec.vocab, max_len, SLOTS, CHUNK, layout="paged",
                page_size=PAGE, pool_pages=POOL_PAGES,
            )
            warmup(bat, spec.vocab, spec.steps_max, spec.prompt_max)
            pf0 = bat.stats()["prefill_tokens"]
            report = drive_phase(
                bat, schedule, spec, fanout=arm == "fanout"
            )
            st = bat.stats()
            arms[arm] = {
                "streams": report["token_streams"],
                "prefill_tokens": st["prefill_tokens"] - pf0,
                "cow_forks": st["cow_forks"],
                "pages_in_use": st["pages_in_use"],
                "partition_ok": (
                    st["pages_in_use"] + st["pages_free"]
                    == st["pool_pages"] - 1
                ),
                "fanout_groups": st["fanout_groups"],
                "report": {
                    k: report[k]
                    for k in ("goodput_tokens_s", "ttft_s", "itl_s",
                              "wall_s", "cow_forks", "schedule_digest")
                },
            }
            bat.close()

        errors: list[str] = []
        ser, fan = arms["serial"], arms["fanout"]
        if fan["cow_forks"] == 0:
            errors.append(
                "fan-out arm never forked a page — every branch "
                "re-ran its suffix prefill, the arm measures nothing"
            )
        if ser["cow_forks"] != 0:
            errors.append(
                f"serial arm booked {ser['cow_forks']} cow forks"
            )
        for arm, d in arms.items():
            if not d["partition_ok"]:
                errors.append(f"{arm} arm: pool partition broke")
            if d["pages_in_use"] != 0 or d["fanout_groups"] != 0:
                errors.append(
                    f"{arm} arm leaked page claims at drain "
                    f"({d['pages_in_use']} in use, "
                    f"{d['fanout_groups']} groups)"
                )
        diverged = sum(
            1 for a, b in zip(ser["streams"], fan["streams"]) if a != b
        )
        if diverged:
            errors.append(
                f"{diverged}/{len(schedule)} request streams diverged "
                "between the serial and fan-out arms"
            )
        if fan["prefill_tokens"] >= ser["prefill_tokens"]:
            errors.append(
                f"fan-out arm prefilled {fan['prefill_tokens']} "
                f"positions vs serial {ser['prefill_tokens']} — the "
                "forks saved nothing"
            )
        if errors:
            _emit_errors("; ".join(errors)[-300:])
            return 0

        extras = {
            arm: {k: v for k, v in d.items() if k != "streams"}
            for arm, d in arms.items()
        }
        emit(
            "load_fanout_identity_exact", 1.0, _METRICS[0][1], 0.0,
            seed=seed, requests=len(schedule),
            cow_forks=fan["cow_forks"], arms=extras,
        )
        ratio = ser["prefill_tokens"] / max(fan["prefill_tokens"], 1)
        emit(
            "load_fanout_prefill_ratio",
            round(ratio, 4),
            _METRICS[1][1],
            round(ratio - 1.0, 4),
            seed=seed,
            prefill_serial=ser["prefill_tokens"],
            prefill_fanout=fan["prefill_tokens"],
        )
    except Exception as e:  # noqa: BLE001 — always one JSON line, rc 0
        _emit_errors(str(e)[-300:])
    return 0


if __name__ == "__main__":
    sys.exit(main())
