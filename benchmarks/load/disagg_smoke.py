"""Long-tail prefill A/B: the SAME traffic through the collocated and
disaggregated placements, gated on the disaggregated win.

The ``disagg`` workload preset (``benchmarks/load/workload.PRESETS``)
is heavy-tailed prompt lengths with a fat p99 and short outputs — the
mix where the collocated ``ContinuousBatcher`` serializes decode ticks
behind long in-tick prefills (the PR-7 pathology,
``continuous.prefill_stall_s``). This driver runs both placements on
identical decode configs and emits TWO gated records plus a
structural check:

- ``load_disagg_interference_itl_ratio`` — the p99-tail ITL win,
  measured as a CONTROLLED interference experiment so the gate is
  repeatable: background requests decode while the preset's longest
  prompt (~1k tokens, the schedule's actual p99 tail) is admitted;
  the metric is the worst inter-token gap the background requests
  experience, collocated / disaggregated. Collocated, that gap IS the
  whole-prompt prefill wall; disaggregated it is bounded by one
  prefill chunk + the handoff landing. Gated well above parity — the
  ratio collapsing to ~1 means decode ticks are paying the prefill
  tail again. (An open-loop phase's p99-of-all-samples sits exactly
  on the boundary between stall-affected and ordinary samples at this
  scale and flips run to run — measured 0.6-2.4x on an idle box —
  which is why the gate uses the controlled tail measurement; the
  phase percentiles still ride along as extras.)
- ``load_disagg_stall_ratio`` — the mechanism number, measured in the
  same controlled windows: the largest single
  ``continuous.prefill_stall_s`` sample while the tail prompt admits,
  collocated / disaggregated (median over reps). Collocated that IS
  the whole-prompt prefill; disaggregated the decode tick sees only
  the suffix pass. The open-loop phase's stall totals ride as extras
  (``phase_stall_share``): their ratio depends on which stalls happen
  to overlap a decoding request, which flips run to run. A collocated
  arm that records NO stall in phase or interference means the
  pathology stopped reproducing — an error record, not a pass.
- Bit-identity: a deterministic subset of the schedule (the longest
  prompts included) is replayed greedily through both paths and
  compared token-for-token; any divergence becomes an error record on
  both metrics (the gate always fails error records).

Usage: ``python benchmarks/load/disagg_smoke.py [--seed 0]``
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks.common import emit, int_flag  # noqa: E402
from benchmarks.load.workload import build_schedule, preset  # noqa: E402

RATE_RPS = 40.0
DURATION_S = 2.5
SLOTS = 4
CHUNK = 8
PAGE = 64
PROMPT_THRESHOLD = 192
#: Requests replayed for the bit-identity check (longest-first).
BIT_CHECK_N = 6
#: Background decoders held live through the interference experiment.
BG_N = 3
BG_STEPS = 220
#: Interference repetitions per arm (median taken — single gaps jitter
#: with host-tick alignment; each rep uses a FRESH long prompt so no
#: rep admits through the prefix cache).
INTERFERENCE_REPS = 3

_METRICS = (
    ("load_disagg_interference_itl_ratio",
     "worst background ITL gap during a ~1k-token admission, "
     "collocated / disaggregated"),
    ("load_disagg_stall_ratio",
     "max decode-tick prefill stall during a ~1k-token admission, "
     "collocated / disaggregated"),
)


def _emit_errors(err: str) -> None:
    for metric, unit in _METRICS:
        print(
            json.dumps(
                {"metric": metric, "value": 0.0, "unit": unit,
                 "vs_baseline": 0.0, "error": err}
            ),
            flush=True,
        )


def interference_gap(server, vocab: int, long_prompt) -> tuple:
    """The controlled tail measurement: admit ``BG_N`` short-prompt
    decoders, let them reach steady state, then submit ``long_prompt``
    and return ``(worst_gap_s, stall_max_s)`` — the WORST inter-token
    wall gap any background request experiences until the long request
    emits its first token (plus a settling tick), and the largest
    single ``continuous.prefill_stall_s`` sample recorded in the same
    window (a metrics-registry window isolates it). ``server`` is
    anything with the batcher driver surface — the collocated batcher
    or the DisaggServer."""
    import numpy as np

    from adapt_tpu.utils.metrics import global_metrics

    rng = np.random.RandomState(123)
    last: dict[int, float] = {}
    gaps: dict[int, float] = {}
    armed = [False]

    def cb(rid, tok, idx):
        now = time.perf_counter()
        if armed[0] and rid in last:
            gap = now - last[rid]
            if gap > gaps.get(rid, 0.0):
                gaps[rid] = gap
        last[rid] = now

    bg = [
        server.submit(
            rng.randint(0, vocab, size=6).astype(np.int32), BG_STEPS,
            on_token=cb,
        )
        for _ in range(BG_N)
    ]
    for _ in range(4):  # admit + settle out of the measured window
        server.tick()
    armed[0] = True
    win = global_metrics().snapshot(window=True)
    first_len = [None]

    def long_cb(rid, tok, idx, _t0=time.perf_counter()):
        if first_len[0] is None:
            first_len[0] = time.perf_counter() - _t0

    sid = server.submit(
        np.asarray(long_prompt, np.int32), 4, on_token=long_cb
    )
    ticks = 0
    while first_len[0] is None:
        server.tick()
        ticks += 1
        if ticks > 2000:
            raise RuntimeError("interference long request never started")
    server.tick()  # one settling tick past the first token
    armed[0] = False
    delta = global_metrics().snapshot(since=win)
    stall_max = delta["histograms"].get(
        "continuous.prefill_stall_s", {}
    ).get("max", 0.0)
    for rid in bg:
        server.cancel(rid)
    server.run()
    if not gaps:
        raise RuntimeError("no background ITL gaps observed")
    return max(gaps.values()), stall_max


def main() -> int:
    seed = int_flag(sys.argv, "--seed", 0)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import numpy as np

        from benchmarks.load.harness import (
            build_batcher,
            build_disagg,
            drive_phase,
            warmup,
            warmup_disagg,
        )

        # Longer outputs than the preset default keep the decode tier
        # consistently occupied through the phase, so long admissions
        # reliably stall a decoding request instead of landing in an
        # idle gap.
        spec = preset(
            "disagg", duration_s=DURATION_S, rate_rps=RATE_RPS,
            steps_median=48, steps_max=96,
        )
        schedule = build_schedule(spec, seed)
        max_len = spec.prompt_max + spec.steps_max + 8

        # -- collocated arm: identical decode config, whole-prompt
        # admission (the documented pathology) --------------------------
        bat = build_batcher(
            spec.vocab, max_len, SLOTS, CHUNK, layout="paged",
            page_size=PAGE,
        )
        warmup(bat, spec.vocab, spec.steps_max, spec.prompt_max)
        colo = drive_phase(bat, schedule, spec)

        # -- disaggregated arm: same decode config behind the
        # placement policy + prefill tier -------------------------------
        # busy == prompt threshold: placement is a pure function of the
        # schedule (the occupancy knob is unit-tested; a gate must not
        # let timing decide WHICH requests disaggregate).
        srv = build_disagg(
            spec.vocab, max_len, SLOTS, CHUNK, page_size=PAGE,
            prompt_threshold=PROMPT_THRESHOLD,
            busy_prompt_threshold=PROMPT_THRESHOLD,
        )
        warmup_disagg(srv, spec.vocab, spec.steps_max, spec.prompt_max)
        disagg0 = srv.disaggregated  # warmup's own submissions excluded
        dis = drive_phase(srv, schedule, spec)
        phase_disagg = srv.disaggregated - disagg0

        # -- bit-identity: longest prompts, replayed greedily ------------
        check = sorted(
            schedule, key=lambda a: len(a.prompt), reverse=True
        )[:BIT_CHECK_N]
        rids = [bat.submit(np.asarray(a.prompt, np.int32), a.steps)
                for a in check]
        ref = bat.run()
        sids = [srv.submit(np.asarray(a.prompt, np.int32), a.steps)
                for a in check]
        got = srv.run()
        mismatches = sum(
            not np.array_equal(ref[r], got[s])
            for r, s in zip(rids, sids)
        )

        colo_stall = colo["prefill_stall_s"].get("sum", 0.0)
        dis_stall = dis["prefill_stall_s"].get("sum", 0.0)

        err = None
        if mismatches:
            err = (
                f"{mismatches}/{len(check)} greedy streams diverge "
                "between placements (bit-identity violation)"
            )
        elif not colo_stall:
            err = (
                "collocated arm recorded zero prefill stall — the "
                "long-tail preset no longer reproduces the pathology"
            )
        if err:
            _emit_errors(err)
            return 0

        # -- controlled tail interference (the gated ITL number) ---------
        # FRESH tokens at the schedule's p99-tail length per rep: the
        # phase and bit-check cached the schedule's own prompts, and a
        # prefix-hit admission would measure the suffix pass, not the
        # pathology.
        tail_len = len(check[0].prompt)

        def gap_median(server):
            reps = [
                interference_gap(
                    server, spec.vocab,
                    np.random.RandomState(999 + rep).randint(
                        0, spec.vocab, size=tail_len
                    ).astype(np.int32),
                )
                for rep in range(INTERFERENCE_REPS)
            ]
            gaps = sorted(g for g, _ in reps)
            stalls = sorted(s for _, s in reps)
            return gaps[len(gaps) // 2], stalls[len(stalls) // 2]

        colo_gap, colo_stall_max = gap_median(bat)
        dis_gap, dis_stall_max = gap_median(srv)
        if not colo_stall_max:
            _emit_errors(
                "collocated interference admission recorded no "
                "decode-tick stall — the controlled pathology vanished"
            )
            return 0

        itl_ratio = colo_gap / dis_gap
        # A disagg arm with NO in-tick stall at all is a perfect win;
        # floor the denominator so the ratio stays finite.
        stall_ratio = colo_stall_max / max(dis_stall_max, 1e-4)
        stall_share = dis_stall / colo_stall
        extras = {
            "seed": seed,
            "rate_rps": RATE_RPS,
            "requests": colo["requests"],
            "interference_prompt_len": tail_len,
            "collocated_worst_gap_s": round(colo_gap, 6),
            "disagg_worst_gap_s": round(dis_gap, 6),
            "collocated_stall_max_s": round(colo_stall_max, 6),
            "disagg_stall_max_s": round(dis_stall_max, 6),
            "phase_stall_share": round(stall_share, 4),
            "collocated_itl_p99_s": colo["itl_s"].get("p99"),
            "disagg_itl_p99_s": dis["itl_s"].get("p99"),
            "collocated_stall_s": round(colo_stall, 6),
            "disagg_stall_s": round(dis_stall, 6),
            "collocated_prefill_tokens_s": colo["prefill_tokens_s"],
            "disagg_prefill_tokens_s": dis["prefill_tokens_s"],
            "collocated_decode_tokens_s": colo["decode_tokens_s"],
            "disagg_decode_tokens_s": dis["decode_tokens_s"],
            "disagg_requests": phase_disagg,
            "handoffs": srv.prefill.handoffs,
            "bit_check_requests": len(check),
            "schedule_digest": colo["schedule_digest"],
        }
        emit(
            _METRICS[0][0], round(itl_ratio, 4), _METRICS[0][1],
            round(itl_ratio - 1.0, 4), **extras,
        )
        emit(
            _METRICS[1][0], round(stall_ratio, 4), _METRICS[1][1],
            round(stall_ratio - 1.0, 4),
            seed=seed,
            collocated_stall_max_s=round(colo_stall_max, 6),
            disagg_stall_max_s=round(dis_stall_max, 6),
            phase_stall_share=round(stall_share, 4),
            phase_collocated_stall_s=round(colo_stall, 6),
            phase_disagg_stall_s=round(dis_stall, 6),
        )
    except Exception as e:  # noqa: BLE001 — always JSON lines, rc 0
        _emit_errors(str(e)[-300:])
    return 0


if __name__ == "__main__":
    sys.exit(main())
