"""Capacity-plane smoke: forecast self-calibration + sketch affinity.

Two gated records for the ``runtime/capacity`` signal plane
(``CapacityModel`` — the self-describing replica a router places on):

- ``load_capacity_forecast_within_2x`` — an HONEST train-then-measure
  protocol on the smoke-preset workload shape: one phase of seeded
  open-loop traffic trains the TTFT forecaster (queue-wait EWMA,
  per-bucket prefill walls, tick gap, bias corrector), then the
  calibration window is reset and a SECOND phase (fresh seed) is
  measured — the gate is the fraction of that phase's admissions whose
  realized TTFT landed within 2x of the forecast made at their own
  submit. Cold admissions (forecast 0.0 — nothing learned yet) never
  enter the books, and a measure phase with ZERO scored admissions
  reports 0.0, not the empty-window default of 1.0.
- ``load_capacity_affinity_picks_resident`` — structural: the corpus
  preset's recurring prefixes run against a paged replica, its
  prefix-affinity sketch is exported (``sketch_from_pager`` — hashed
  content keys only), and ``affinity_score`` must rank that replica
  above a COLD replica with free slots for a corpus-prefix prompt,
  from the sketches alone (no prompt round-trip). The sketch must also
  stay bounded (<= sketch_k entries) after adversarial prefix churn
  (a burst of distinct never-repeated prompts).

Usage: ``python benchmarks/load/capacity_smoke.py [--seed 0]``
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks.common import emit, int_flag  # noqa: E402
from benchmarks.load.workload import (  # noqa: E402
    WorkloadSpec,
    build_schedule,
    preset,
    schedule_prefixes,
)

#: Forecast arm: the smoke-preset shape at its under-capacity rate.
RATE_RPS = 8.0
#: Affinity arm page size — 6 full pages per 96-token corpus prefix.
PAGE = 16

_METRICS = (
    ("load_capacity_forecast_within_2x",
     "fraction of measure-phase admissions with realized TTFT within "
     "2x of their submit-time forecast"),
    ("load_capacity_affinity_picks_resident",
     "1.0 = sketch-only affinity ranks the prefix-resident replica "
     "above a cold one AND the sketch stays bounded under churn"),
)


def _emit_errors(err: str) -> None:
    for metric, unit in _METRICS:
        print(
            json.dumps(
                {"metric": metric, "value": 0.0, "unit": unit,
                 "vs_baseline": 0.0, "error": err}
            ),
            flush=True,
        )


def main() -> int:
    seed = int_flag(sys.argv, "--seed", 0)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import numpy as np

        from benchmarks.load.harness import (
            build_batcher,
            drive_phase,
            warmup,
        )

        from adapt_tpu.config import CapacityConfig
        from adapt_tpu.runtime.capacity import (
            affinity_score,
            sketch_from_pager,
        )

        # ---- arm 1: forecast self-calibration (train, reset, measure)
        spec = WorkloadSpec(
            duration_s=2.0,
            rate_rps=RATE_RPS,
            prompt_median=6,
            prompt_max=16,
            steps_median=16,
            steps_sigma=0.4,
            steps_max=48,
            ttft_budget_s=3.0,
            itl_budget_s=2.0,
        )
        bat = build_batcher(
            spec.vocab, spec.prompt_max + spec.steps_max + 8,
            slots=4, chunk=8,
        )
        cap = bat._capacity
        if cap is None:
            raise RuntimeError("capacity plane disabled on the batcher")
        warmup(bat, spec.vocab, spec.steps_max, spec.prompt_max)
        train = drive_phase(bat, build_schedule(spec, seed), spec)
        # Train-then-measure: drop the training verdicts (warmup's
        # compile-scale queue waits poison the early forecasts; the
        # EWMAs and bias they trained SURVIVE the reset) and score
        # only the fresh phase.
        cap.reset_calibration()
        measure = drive_phase(bat, build_schedule(spec, seed + 100), spec)
        # One idle tick so the last admissions' pending (forecast,
        # realized) pairs drain into the calibration window.
        bat.tick()
        scored = len(cap.forecaster._within)
        calibration = cap.calibration() if scored else 0.0
        bat.close()
        emit(
            _METRICS[0][0],
            round(calibration, 4),
            _METRICS[0][1],
            round(calibration - 1.0, 4),
            seed=seed,
            rate_rps=RATE_RPS,
            scored_admissions=scored,
            measure_requests=measure["requests"],
            train_requests=train["requests"],
            forecast=cap.forecaster.snapshot(),
            ttft_p99_s=measure["ttft_s"].get("p99"),
        )

        # ---- arm 2: sketch-only affinity, resident vs cold ----------
        cspec = preset("corpus", duration_s=1.5)
        sketch_k = CapacityConfig().sketch_k
        resident = build_batcher(
            cspec.vocab, cspec.prompt_max + cspec.steps_max + 8,
            slots=2, chunk=4, layout="paged", page_size=PAGE,
        )
        warmup(resident, cspec.vocab, cspec.steps_max, cspec.prompt_max)
        drive_phase(resident, build_schedule(cspec, seed), cspec)
        prefixes = schedule_prefixes(cspec, seed)
        # Probe prompts: each corpus prefix plus a fresh tail — the
        # shapes a router would place. Score the max: the pool is
        # smaller than the corpus working set so LRU evicts SOME
        # prefixes, but a router only needs one hot prefix to rank the
        # resident replica above a cold one.
        probes = [
            np.asarray(tuple(p) + (1, 2, 3), np.int32)
            for p in prefixes
        ]
        resident_sketch = sketch_from_pager(resident._pager, sketch_k)
        score_resident = max(
            affinity_score(resident_sketch, p) for p in probes
        )
        # The cold replica: same shape, zero traffic. Its sketch is
        # what a fresh pager exports — free slots, no affinity.
        cold = build_batcher(
            cspec.vocab, cspec.prompt_max + cspec.steps_max + 8,
            slots=2, chunk=4, layout="paged", page_size=PAGE,
        )
        cold_sketch = sketch_from_pager(cold._pager, sketch_k)
        score_cold = max(
            affinity_score(cold_sketch, p) for p in probes
        )
        cold.close()
        # Adversarial prefix churn: a burst of distinct never-repeated
        # prompts, then the bound check — top-K by construction, but
        # the gate pins it against regression.
        rng = np.random.default_rng(seed + 7)
        for _ in range(64):
            resident.submit(
                rng.integers(1, cspec.vocab, size=3 * PAGE).astype(
                    np.int32
                ),
                2,
            )
        resident.run()
        churned_sketch = sketch_from_pager(resident._pager, sketch_k)
        bounded = len(churned_sketch["entries"]) <= sketch_k
        resident.close()
        ok = (
            score_resident > score_cold
            and score_resident > 0.0
            and bounded
        )
        emit(
            _METRICS[1][0],
            1.0 if ok else 0.0,
            _METRICS[1][1],
            (1.0 if ok else 0.0) - 1.0,
            seed=seed,
            score_resident=round(score_resident, 4),
            score_cold=round(score_cold, 4),
            sketch_entries=len(resident_sketch["entries"]),
            churned_entries=len(churned_sketch["entries"]),
            sketch_k=sketch_k,
            corpus_prefixes=len(prefixes),
        )
    except Exception as e:  # noqa: BLE001 — always JSON lines, rc 0
        _emit_errors(str(e)[-300:])
    return 0


if __name__ == "__main__":
    sys.exit(main())
