"""Corpus-preset A/B: the SAME tenant-skewed recurring-prefix traffic
with and without the host-DRAM KV cache tier, at a FLAT HBM budget.

The ``corpus`` workload preset (``benchmarks/load/workload.PRESETS``)
recurs shared 96-token prefixes (6 full pages each at this driver's
page size; the driver widens the corpus to 20 prefixes — 120 distinct
prefix pages) against an HBM pool deliberately sized several-fold
smaller (31 allocatable pages), so the prefix LRU alone cannot keep
the corpus warm: tier OFF, evicted pages die and a returning prefix
recomputes; tier ON, they spill to host DRAM and readmit through the
``adopt_cached`` landing path. Two gated records:

- ``load_tier_prefix_multiplier`` — SERVABLE cached prefixes (all 6
  full pages answerable from the cache hierarchy without recompute,
  ``ContinuousBatcher.prefix_cached`` at phase drain — a structural
  capacity count, not a wall-clock one), tier-on / tier-off, the
  ROADMAP item-3 pin (>= 4x at flat HBM budget: the off arm is bounded
  by the pool — at most 5 full prefixes can be HBM-resident — while
  the on arm's host tier holds the whole corpus the phase touched).
  The driver converts structural failures into error records the gate
  always fails: an off arm that never evicts (the pool is not under
  pressure), an on arm that never spills or readmits, or the probe
  pass's streams diverging between arms (lossless readmits must be
  bit-exact — every corpus prefix is re-referenced through both arms
  after the count and compared token-for-token).
- ``load_tier_itl_p99_ratio`` — the off arm's phase ITL p99 over the
  on arm's: spill/readmit work is budgeted per tick, so the tier must
  not inflate decode-tick latency. Gated LOOSELY (CPU wall clock under
  shared CI; the regression mode is the tier stalling decode ticks by
  multiples, not jitter).

Usage: ``python benchmarks/load/tier_smoke.py [--seed 0]``
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks.common import emit, int_flag  # noqa: E402
from benchmarks.load.harness import (  # noqa: E402
    build_batcher,
    drive_phase,
    warmup,
)
from benchmarks.load.workload import (  # noqa: E402
    build_schedule,
    preset,
    schedule_prefixes,
)

DURATION_S = 2.0
SLOTS = 2
CHUNK = 4
PAGE = 16
#: Corpus widened past the preset default: 20 prefixes x 6 pages =
#: 120 distinct prefix pages vs the 31-page pool, with a flat-ish
#: prefix skew so the whole corpus is touched within the phase.
PREFIX_POOL = 20
PREFIX_SKEW = 0.4
RATE_RPS = 40.0
#: Flat HBM budget for BOTH arms: covers the 2 slots' worst case
#: (ceil(188/16) = 12 pages each) plus a thin prefix LRU — far below
#: the corpus's 120 distinct prefix pages. 29 allocatable pages bound
#: the off arm at floor(29/6) = 4 fully-resident prefixes by
#: construction, which is what keeps the >= 4x gate's margin
#: structural rather than luck.
POOL_PAGES = 30
#: Full pages per corpus prefix ((96 + 1 probe token - 1) // 16).
PREFIX_PAGES = 6
PROBE_STEPS = 2

_METRICS = (
    ("load_tier_prefix_multiplier",
     "servable cached corpus prefixes, tier-on / tier-off"),
    ("load_tier_itl_p99_ratio",
     "phase ITL p99, tier-off / tier-on"),
)


def _emit_errors(err: str) -> None:
    for metric, unit in _METRICS:
        print(
            json.dumps(
                {"metric": metric, "value": 0.0, "unit": unit,
                 "vs_baseline": 0.0, "error": err}
            ),
            flush=True,
        )


def _probe_prompts(prefixes, vocab: int):
    import numpy as np

    return [
        np.asarray(tuple(head) + (int(head[0]) % vocab,), np.int32)
        for head in prefixes
    ]


def _count_servable(bat, prompts) -> int:
    """Structural capacity count at phase drain: prefixes whose full
    6 pages the cache hierarchy can answer without recompute
    (``prefix_cached`` — read-only, so the count itself cannot evict
    anything)."""
    return sum(
        1 for p in prompts if bat.prefix_cached(p) >= PREFIX_PAGES
    )


def _probe_streams(bat, prompts):
    """Re-reference every corpus prefix (hottest first) and collect
    the greedy streams — the bit-identity validation pass (run AFTER
    the servable count; probes churn the caches)."""
    streams = []
    for p in prompts:
        rid = bat.submit(p, PROBE_STEPS)
        streams.append(bat.run()[rid])
    return streams


def main() -> int:
    seed = int_flag(sys.argv, "--seed", 0)
    try:
        from adapt_tpu.config import CacheTierConfig

        spec = preset(
            "corpus",
            duration_s=DURATION_S,
            rate_rps=RATE_RPS,
            prefix_pool=PREFIX_POOL,
            prefix_skew=PREFIX_SKEW,
        )
        schedule = build_schedule(spec, seed)
        prefixes = schedule_prefixes(spec, seed)
        max_len = spec.prompt_max + spec.steps_max + 8
        tier = CacheTierConfig(
            spill_pages_per_tick=16, readmit_pages_per_tick=16
        )
        arms: dict[str, dict] = {}
        for arm, cfg in (("off", None), ("on", tier)):
            bat = build_batcher(
                spec.vocab, max_len, SLOTS, CHUNK, layout="paged",
                page_size=PAGE, pool_pages=POOL_PAGES, cache_tier=cfg,
            )
            warmup(bat, spec.vocab, spec.steps_max, spec.prompt_max)
            report = drive_phase(bat, schedule, spec)
            prompts = _probe_prompts(prefixes, spec.vocab)
            servable = _count_servable(bat, prompts)
            streams = _probe_streams(bat, prompts)
            st = bat.stats()
            arms[arm] = {
                "servable": servable,
                "streams": streams,
                "itl_p99": report["itl_s"].get("p99"),
                "report": {
                    k: report[k]
                    for k in ("goodput_tokens_s", "throughput_tokens_s",
                              "ttft_s", "itl_s", "wall_s",
                              "schedule_digest")
                },
                "prefix_hits": st["prefix_hits"],
                "prefix_misses": st["prefix_misses"],
                "spilled": st.get("tier_spilled", 0),
                "readmitted": st.get("tier_readmitted", 0),
                "dropped": st.get("tier_dropped", 0),
                "host_pages": st.get("host_pages", 0),
            }
            bat.close()

        errors: list[str] = []
        off, on = arms["off"], arms["on"]
        if off["prefix_misses"] <= len(prefixes):
            errors.append(
                "off arm barely missed — the pool is not under "
                f"pressure (misses {off['prefix_misses']})"
            )
        if on["spilled"] == 0 or on["readmitted"] == 0:
            errors.append(
                f"tier never engaged (spilled {on['spilled']}, "
                f"readmitted {on['readmitted']})"
            )
        if off["servable"] >= len(prefixes):
            errors.append(
                "off arm served the whole corpus from HBM — shrink "
                "POOL_PAGES, the A/B measures nothing"
            )
        import numpy as np

        for i, (a, b) in enumerate(zip(off["streams"], on["streams"])):
            if not np.array_equal(a, b):
                errors.append(
                    f"probe {i} streams diverged between arms"
                )
                break
        if errors:
            _emit_errors("; ".join(errors)[-300:])
            return 0

        multiplier = on["servable"] / max(off["servable"], 1)
        extras = {
            arm: {k: v for k, v in d.items() if k != "streams"}
            for arm, d in arms.items()
        }
        emit(
            "load_tier_prefix_multiplier",
            round(multiplier, 4),
            "x (servable cached prefixes, on/off)",
            round(multiplier - 4.0, 4),
            seed=seed,
            corpus_prefixes=len(prefixes),
            pool_pages=POOL_PAGES,
            servable_on=on["servable"],
            servable_off=off["servable"],
            arms=extras,
        )
        p99_off = off["itl_p99"] or 0.0
        p99_on = on["itl_p99"] or 0.0
        ratio = (p99_off / p99_on) if p99_on else 1.0
        emit(
            "load_tier_itl_p99_ratio",
            round(ratio, 4),
            "x (off/on; < 1 means the tier slowed decode ticks)",
            round(ratio - 1.0, 4),
            seed=seed,
            itl_p99_off=p99_off,
            itl_p99_on=p99_on,
        )
    except Exception as e:  # noqa: BLE001 — always one JSON line, rc 0
        _emit_errors(str(e)[-300:])
    return 0


if __name__ == "__main__":
    sys.exit(main())
