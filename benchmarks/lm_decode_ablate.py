"""Decode-MBU gap accounting by ablation (VERDICT r4 #4).

``lm_decode.py`` reports MBU against a THEORETICAL ceiling (all param +
cache bytes at the 819 GB/s spec sheet number). This driver decomposes
the gap with four measured scans at the same GPT-2-small widths, each a
``lax.scan`` whose carry forces every step to re-stream its weights:

  stream    vector@matrix over EVERY weight matrix, nothing else — the
            measured ACHIEVABLE streaming bandwidth of this chip for
            decode-shaped (skinny) matmuls. spec/stream is the part of
            the "gap" that is the spec sheet, not the program.
  mlp       the 12 blocks' MLP matmuls only (fc + proj per block)
  attn      qkv/out projections + cached attention over a max_len cache
  head      final (b,1,d) @ (d,V) logits projection only

Accounting: if step_time(full) ~= step_time(mlp) + step_time(attn) +
step_time(head) (each measured alone), the loop is bandwidth-additive
and the gap vs the stream row is per-op efficiency; a large
super-additive residual means scheduling/fusion overhead between
components. Every variant reports its own bytes and achieved GB/s, so
the artifact directly names where the 0.43 went.

One JSON line; vs_baseline = full-model achieved GB/s / stream-test
achieved GB/s (how close the real decode loop gets to what the chip
demonstrably sustains).

Usage: ``python benchmarks/lm_decode_ablate.py [--batch 8] [--steps 64]
[--maxlen 256] [--trials 3]``
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.common import int_flag, run_child_json  # noqa: E402

VOCAB, DIM, DEPTH, HEADS, MLP = 50257, 768, 12, 12, 3072
TPU_V5E_HBM_BYTES_PER_S = 819e9


def _child(batch: int, steps: int, max_len: int, trials: int) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    key = jax.random.PRNGKey(0)
    hd = DIM // HEADS

    def mk(*shape):
        nonlocal key
        key, sub = jax.random.split(key)
        return jax.random.normal(sub, shape, jnp.bfloat16) * 0.02

    blocks = [
        {
            "qkv": mk(DIM, 3 * DIM),
            "out": mk(DIM, DIM),
            "fc": mk(DIM, MLP),
            "proj": mk(MLP, DIM),
        }
        for _ in range(DEPTH)
    ]
    w_head = mk(DIM, VOCAB)
    w_embed = mk(VOCAB, DIM)
    caches = [
        (mk(batch, HEADS, max_len, hd), mk(batch, HEADS, max_len, hd))
        for _ in range(DEPTH)
    ]

    def bytes_of(tree):
        return sum(
            x.size * x.dtype.itemsize for x in jax.tree.leaves(tree)
        )

    # -- variants: each a (carry x) -> (carry', token-ish scalar) step ----
    def step_mlp(x):
        for b in blocks:
            h = jax.nn.gelu(x @ b["fc"])
            x = x + h @ b["proj"]
        return x

    def step_attn(x, index):
        for b, (ck, cv) in zip(blocks, caches):
            qkv = x @ b["qkv"]  # (B, 1, 3D)
            q, k, v = jnp.split(qkv, 3, axis=-1)
            q = q.reshape(batch, 1, HEADS, hd).transpose(0, 2, 1, 3)
            # bf16 operands + f32 accumulation via preferred_element_type:
            # an .astype(f32) on the loop-invariant cache would be HOISTED
            # by XLA into a materialized f32 copy, silently doubling the
            # bytes each step streams vs what the row is charged.
            s = jnp.einsum(
                "bhqd,bhkd->bhqk", q, ck,
                preferred_element_type=jnp.float32,
            ) / np.sqrt(hd)
            mask = jnp.arange(max_len) <= index
            s = jnp.where(mask[None, None, None, :], s, -1e30)
            p = jax.nn.softmax(s, axis=-1).astype(jnp.bfloat16)
            o = jnp.einsum(
                "bhqk,bhkd->bhqd", p, cv,
                preferred_element_type=jnp.float32,
            ).astype(jnp.bfloat16)
            o = o.transpose(0, 2, 1, 3).reshape(batch, 1, DIM)
            x = x + o @ b["out"]
        return x

    def _logits(x):
        # bf16 matmul, f32 accumulate — same convert-hoisting hazard as
        # the cache above (w_head is 77 MB; an f32 copy would be 154).
        return jax.lax.dot_general(
            x, w_head, (((2,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (B, 1, V) f32

    def step_head(x):
        # Consume EVERY logits column (a [..., :DIM] slice would let
        # XLA rewrite slice(dot) into a dot over 1.5% of w_head and
        # fake a 60x-faster head).
        m = _logits(x).max(axis=-1, keepdims=True)  # (B, 1, 1)
        return x * jnp.bfloat16(0.5) + m.astype(jnp.bfloat16) * 1e-9

    def step_full(x, index):
        x = step_attn(x, index)
        x = step_mlp(x)
        tok = jnp.argmax(_logits(x), axis=-1)  # (B, 1)
        # Re-embed the argmax: the real loop's token->embedding data
        # dependency, defeating cross-step pipelining XLA couldn't do
        # for the real model either.
        return x * 0.5 + w_embed[tok[:, 0]][:, None, :].astype(jnp.bfloat16)

    def step_stream(v):
        # v: (DIM,) carry. One skinny matmul per weight matrix: the
        # chip streams every byte, compute is negligible, and the carry
        # dependency defeats hoisting.
        acc = jnp.zeros((), jnp.float32)
        for b in blocks:
            for w in b.values():
                acc = acc + (v @ w.reshape(DIM, -1).astype(jnp.bfloat16))[
                    0
                ].astype(jnp.float32)
        acc = acc + (v @ w_head)[0].astype(jnp.float32)
        acc = acc + (v @ w_embed.T.reshape(DIM, -1))[0].astype(jnp.float32)
        return v * jnp.bfloat16(0.999) + acc.astype(jnp.bfloat16) * 1e-9

    x0 = mk(batch, 1, DIM)
    v0 = mk(DIM)

    # Each variant is jitted as a function of its INITIAL carry so
    # trials can perturb the input — repeat executions of identical
    # (fn, args) can be deduplicated under this image's remote-execution
    # tunnel (same countermeasure as lm_decode.py's timed()).
    variants = {}
    variants["stream"] = (
        lambda init: lax.scan(
            lambda c, _: (step_stream(c), ()), init, None, length=steps
        )[0],
        v0,
        bytes_of((blocks, w_head, w_embed)),
    )
    variants["mlp"] = (
        lambda init: lax.scan(
            lambda c, _: (step_mlp(c), ()), init, None, length=steps
        )[0],
        x0,
        bytes_of([(b["fc"], b["proj"]) for b in blocks]),
    )
    variants["attn"] = (
        lambda init: lax.scan(
            lambda c, i: (step_attn(c, i), ()),
            init,
            jnp.arange(steps),
        )[0],
        x0,
        bytes_of([(b["qkv"], b["out"]) for b in blocks])
        + bytes_of(caches),
    )
    variants["head"] = (
        lambda init: lax.scan(
            lambda c, _: (step_head(c), ()), init, None, length=steps
        )[0],
        x0,
        bytes_of(w_head),
    )
    variants["full"] = (
        lambda init: lax.scan(
            lambda c, i: (step_full(c, i), ()), init, jnp.arange(steps)
        )[0],
        x0,
        # w_embed is read one GATHERED row per batch element per step,
        # not wholesale — charging the full 77 MB table would overstate
        # the achieved bandwidth ~20%.
        bytes_of((blocks, w_head))
        + bytes_of(caches)
        + batch * DIM * 2,
    )

    rows = {}
    for name, (fn, init, nbytes) in variants.items():
        jfn = jax.jit(fn)
        np.asarray(jfn(init))  # compile + warm
        times = []
        for t in range(trials):
            perturbed = init + jnp.bfloat16(1e-6 * (t + 1))
            t0 = time.perf_counter()
            np.asarray(jfn(perturbed))
            times.append(time.perf_counter() - t0)
        per_step = statistics.median(times) / steps
        rows[name] = {
            "ms_per_step": round(per_step * 1e3, 4),
            "bytes_per_step": nbytes,
            "achieved_gb_s": round(nbytes / per_step / 1e9, 1),
            "mbu_vs_spec": round(
                (nbytes / per_step) / TPU_V5E_HBM_BYTES_PER_S, 4
            ),
        }

    parts = sum(rows[k]["ms_per_step"] for k in ("mlp", "attn", "head"))
    rows["additivity"] = {
        "parts_ms": round(parts, 4),
        "full_ms": rows["full"]["ms_per_step"],
        # >0: scheduling/fusion overhead beyond the parts; <0: fusion
        # across components actually HELPS the full program.
        "residual_ms": round(rows["full"]["ms_per_step"] - parts, 4),
    }
    print(
        json.dumps(
            {
                "metric": f"lm_decode_ablate_bs{batch}_full_vs_stream",
                "value": rows["full"]["achieved_gb_s"],
                "unit": "GB/s",
                "vs_baseline": round(
                    rows["full"]["achieved_gb_s"]
                    / max(rows["stream"]["achieved_gb_s"], 1e-9),
                    4,
                ),
                "baseline": "the stream variant's measured achievable "
                f"bandwidth ({rows['stream']['achieved_gb_s']} GB/s; "
                "spec sheet 819)",
                "platform": jax.devices()[0].platform,
                "batch": batch,
                "steps": steps,
                "max_len": max_len,
                "rows": rows,
            }
        ),
        flush=True,
    )


def main() -> int:
    batch = int_flag(sys.argv, "--batch", 8)
    steps = int_flag(sys.argv, "--steps", 64)
    max_len = int_flag(sys.argv, "--maxlen", 256)
    trials = int_flag(sys.argv, "--trials", 3)
    if "--child" in sys.argv:
        _child(batch, steps, max_len, trials)
        return 0
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--batch", str(batch), "--steps", str(steps),
           "--maxlen", str(max_len), "--trials", str(trials)]
    return run_child_json(
        cmd,
        metric=f"lm_decode_ablate_bs{batch}_full_vs_stream",
        unit="GB/s",
        timeout_s=1800,
    )


if __name__ == "__main__":
    sys.exit(main())
