"""Prefill interference: what a long admission does to running decodes.

The latency story behind chunked prefill (Sarathi-style): with
whole-prompt prefill, a running request's next tick stalls for the full
prompt forward when a long request admits; with ``prefill_chunk``, the
admission spreads over page-aligned chunk passes and the running
request keeps emitting between them. This driver measures PER-TICK
latency of a steady decode stream while long prompts arrive, for both
modes, and reports the p99 tick latency ratio (chunked / whole) — the
number that should drop well below 1 as prompt length grows.

Method: one long-running greedy request decodes through a paged
batcher; every ``gap`` ticks a long-prompt request is submitted. Tick
wall-times are recorded around ``bat.tick()`` (each tick = admission +
prefill work + one decode chunk). Same traffic, same model, two
batchers — only ``prefill_chunk`` differs.

One JSON line (the chunked mode's p99 tick seconds; ``vs_baseline`` =
whole-prompt p99 / chunked p99, >1 means chunking wins); a JSONL row
appends to ``results/r04/prefill_interference.json``. ``--cpu`` runs
the small validation model (dispatch overhead dominates there — the
TPU row is the evidence, same caveat as continuous_serve).

Usage: ``python benchmarks/prefill_interference.py [--long 1536]
[--chunk 256] [--cpu]``
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.common import (  # noqa: E402  (imports no JAX)
    int_flag,
    out_path,
    run_child_json,
)

VOCAB, DIM, DEPTH, HEADS, MLP = 50257, 768, 12, 12, 3072
OUT = out_path("prefill_interference.json")


def _run_mode(ContinuousBatcher, np, lm, variables, long_len, n_long,
              gap, prefill_chunk, page):
    rng = np.random.RandomState(0)
    steady = rng.randint(0, lm.vocab, size=8).astype(np.int32)
    longs = [
        rng.randint(0, lm.vocab, size=long_len).astype(np.int32)
        for _ in range(n_long)
    ]
    bat = ContinuousBatcher(
        lm, variables, slots=4, chunk=4, kv_layout="paged",
        page_size=page, prefill_chunk=prefill_chunk,
    )
    # Warm every compiled piece (long-prefill variants + decode chunk)
    # untimed — with a DEDICATED prompt: warming with a timed prompt
    # would register its pages in the prefix cache and turn the timed
    # admission into a near-free hit.
    warm_p = rng.randint(0, lm.vocab, size=long_len).astype(np.int32)
    warm = bat.submit(warm_p, 2)
    bat.run()
    bat.submit(steady, 4000)
    bat.tick()
    ticks = []
    li = 0
    t_all0 = time.perf_counter()
    for i in range(n_long * gap + 24):
        if i % gap == 0 and li < n_long:
            bat.submit(longs[li], 8)
            li += 1
        t0 = time.perf_counter()
        bat.tick()
        ticks.append(time.perf_counter() - t0)
    total_s = time.perf_counter() - t_all0
    del warm
    ticks = sorted(ticks)
    p99 = ticks[min(len(ticks) - 1, int(0.99 * len(ticks)))]
    p50 = ticks[len(ticks) // 2]
    return {"p99_tick_s": p99, "p50_tick_s": p50, "total_s": total_s}


def _child(long_len: int, chunk: int, small: bool) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from adapt_tpu.models.transformer_lm import transformer_lm
    from adapt_tpu.runtime.continuous import ContinuousBatcher

    page = 128
    if small:
        page = 16
        lm = transformer_lm(512, 128, 4, 4, 512, max_len=4096)
    else:
        lm = transformer_lm(
            VOCAB, DIM, DEPTH, HEADS, MLP, max_len=4096,
            dtype=jnp.bfloat16,
        )
    variables = jax.jit(lm.graph.init)(
        jax.random.PRNGKey(1), jnp.zeros((1, 8), jnp.int32)
    )
    if not small:
        variables = jax.tree.map(
            lambda x: x.astype(jnp.bfloat16)
            if x.dtype == jnp.float32 else x,
            variables,
        )
    n_long, gap = 4, 12
    whole = _run_mode(ContinuousBatcher, np, lm, variables, long_len,
                      n_long, gap, None, page)
    chunked = _run_mode(ContinuousBatcher, np, lm, variables, long_len,
                        n_long, gap, chunk, page)
    print(
        json.dumps(
            {
                "metric": "prefill_interference_p99_tick_s",
                "value": round(chunked["p99_tick_s"], 5),
                "unit": "s",
                "vs_baseline": round(
                    whole["p99_tick_s"] / max(chunked["p99_tick_s"], 1e-9),
                    3,
                ),
                "baseline": "whole-prompt prefill p99 tick "
                f"({whole['p99_tick_s']:.5f}s; p50 "
                f"{whole['p50_tick_s']:.5f}s vs chunked p50 "
                f"{chunked['p50_tick_s']:.5f}s) — >1 means chunked "
                "prefill shields running decodes from long admissions",
                "platform": jax.devices()[0].platform,
                "long_prompt": long_len,
                "prefill_chunk": chunk,
                "whole": whole,
                "chunked": chunked,
            }
        ),
        flush=True,
    )


def main() -> int:
    long_len = int_flag(sys.argv, "--long", 1536)
    chunk = int_flag(sys.argv, "--chunk", 256)
    cpu = "--cpu" in sys.argv
    if "--child" in sys.argv:
        _child(long_len, chunk, cpu)
        return 0
    env = dict(os.environ)
    if cpu:
        env.pop("PYTHONPATH", None)
        env["JAX_PLATFORMS"] = "cpu"
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--long", str(long_len), "--chunk", str(chunk)]
    if cpu:
        cmd.append("--cpu")
    return run_child_json(
        cmd,
        metric="prefill_interference_p99_tick_s",
        unit="s",
        timeout_s=2400,
        env=env,
        allow_cpu=cpu,
        out_path=OUT,
    )


if __name__ == "__main__":
    sys.exit(main())
