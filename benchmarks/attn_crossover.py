"""Flash-kernel vs XLA-attention crossover sweep on the real chip.

The ViT-B/16 re-measure after the ragged-sequence fix showed the Pallas
flash kernel LOSING to XLA's fused attention at seq 197 (1,762 vs
3,373 img/s end-to-end): at short sequences the S x S score matrix fits
in VMEM anyway, XLA emits one large batched matmul chain, and the flash
grid (batch*heads tiny programs, each re-DMAing full K/V) pays more in
program overhead than it saves in HBM traffic. The kernel's reason to
exist is long sequences — O(S*D) memory where XLA's materialized S x S
scores blow past VMEM.

This driver measures both paths at several sequence lengths on the real
TPU; together with the end-to-end A/B (``tpu_vit_b16_ab.json``) and the
long-sequence sweep (``attn_longseq.json``) it backs the dispatch in
``adapt_tpu.ops.attention`` (``FLASH_SCORE_BYTES_BUDGET`` +
``FLASH_MIN_SEQ`` guard). Perf-first dispatch, backed by artifacts
rather than folklore — note the caveat recorded in this artifact: at
small shapes these standalone micro-timings are relay-overhead-dominated
and the END-TO-END A/B is the authority.

Usage: ``python benchmarks/attn_crossover.py --out benchmarks/results/r03/attn_crossover.json``
"""

from __future__ import annotations

import argparse
import json
import os
import statistics
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
#: (batch, heads, seq, head_dim) — ViT-B/16-like width, seq swept from the
#: ViT shape into long-context territory. Batch shrinks as seq grows to
#: keep the working set sane.
SHAPES = [
    (32, 12, 197, 64),
    (32, 12, 256, 64),
    (16, 12, 512, 64),
    (8, 12, 1024, 64),
    (4, 12, 2048, 64),
    (2, 12, 4096, 64),
    (1, 12, 8192, 64),
]


def _child(out_path: str) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from adapt_tpu.ops.attention import _flash_impl, attention_reference

    def timed(fn, q, k, v, iters=20, trials=3):
        """Same honest timed region as bench.py: the iteration loop lives
        on-device in a lax.scan with a data-dependent carry, timed around
        a host fetch."""

        def body(c, _):
            o = fn(c, k, v)
            return c * 0.999 + (jnp.mean(o) * 1e-6).astype(c.dtype), ()

        run = jax.jit(lambda q: lax.scan(body, q, None, length=iters)[0])
        np.asarray(run(q))  # compile + warm
        times = []
        for t in range(trials):
            qt = q + (t + 1) * 1e-6
            t0 = time.perf_counter()
            np.asarray(run(qt))
            times.append(time.perf_counter() - t0)
        return statistics.median(times) / iters

    rows = []
    for b, h, s, d in SHAPES:
        key = jax.random.PRNGKey(0)
        q, k, v = (
            jax.random.normal(jax.random.fold_in(key, i), (b, h, s, d), jnp.bfloat16)
            for i in range(3)
        )
        row = {"batch": b, "heads": h, "seq": s, "head_dim": d}
        try:
            row["flash_ms"] = timed(
                lambda q_, k_, v_: _flash_impl(q_, k_, v_), q, k, v
            ) * 1e3
        except Exception as e:  # noqa: BLE001
            row["flash_error"] = str(e)[-200:]
        try:
            row["xla_ms"] = timed(attention_reference, q, k, v) * 1e3
        except Exception as e:  # noqa: BLE001
            row["xla_error"] = str(e)[-200:]
        if "flash_ms" in row and "xla_ms" in row:
            row["flash_speedup"] = round(row["xla_ms"] / row["flash_ms"], 3)
        rows.append(row)
        print(json.dumps(row), flush=True)

    artifact = {
        "platform": jax.devices()[0].platform,
        "device": str(jax.devices()[0]),
        "rows": rows,
        "methodology": "on-device lax.scan (20 iters, data-dependent carry), "
        "median of 3 trials, timed around host fetch; bf16; "
        "_flash_impl called directly (bypasses the dispatch heuristic "
        "this sweep calibrates)",
    }
    with open(out_path, "w") as f:
        json.dump(artifact, f, indent=1)


def main() -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--out", required=True)
    p.add_argument("--child", action="store_true")
    args = p.parse_args()
    if args.child:
        _child(args.out)
        return 0
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--out", args.out,
             "--child"],
            capture_output=True, text=True, timeout=1800, cwd=REPO,
        )
        sys.stdout.write(proc.stdout)
        if proc.returncode != 0:
            sys.stderr.write((proc.stderr or "")[-500:])
    except subprocess.TimeoutExpired:
        print(json.dumps({"error": "attn crossover sweep timed out"}))
    return 0


if __name__ == "__main__":
    sys.exit(main())
