"""Hierarchical KV cache tier: spill -> evict -> readmit structural
checks across the native/int8 pool grid, plus the lossy COLD-codec
quality bar.

The host tier's claims are STRUCTURAL, like the quant/tp drivers': an
evicted prefix page spills to host DRAM instead of dying, readmits
through the ``Pager.adopt_cached``/``_adopt_pages`` landing path on the
next prefix probe, and the readmitted stream is BIT-IDENTICAL to an
uninterrupted big-pool run at lossless settings — while spill work
stays inside the per-tick budget. This driver pins all of it on a tiny
paged batcher and emits TWO gated records:

- ``micro_kv_tiers_roundtrip_exact`` — 1.0 when, for BOTH pool dtypes
  (native f32 and int8 values+scales):
  (a) a prefix whose pages were evicted under flood pressure and
      host-spilled readmits on re-reference (``cache_tier.readmitted``
      > 0) and the re-referenced greedy stream equals the
      uninterrupted run token-for-token;
  (b) readmits land as prefix-cache hits (``paged.prefix_hits`` moves);
  (c) the per-tick spill budget is respected (no tick spills more than
      ``spill_pages_per_tick``; evictions past it count ``dropped``);
  (d) the pool partition (used + free + cached == allocatable) stays
      exact with the tier attached.
  Any violation becomes an ``error`` record the gate always fails.
- ``micro_kv_tiers_cold_top1_agreement`` — greedy-stream top-1
  agreement of a readmit through a LOSSY cold tier (warm capacity 0,
  ``cold_codec="int8"`` — the per-vector absmax lattice) vs the
  uncompressed reference stream; gated >= 0.95, the same bar as the
  int4 KV pools. Lossy codecs only ever touch rc=0 spilled pages —
  live-slot state never routes through them (pinned in
  tests/test_kv_tiers.py).

Usage: ``python benchmarks/micro/kv_tiers.py [--floods 4]``
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks.common import emit, int_flag  # noqa: E402

PAGE = 8
POOL_PAGES = 12  # allocatable 11: one slot's worst case + a thin LRU
STEPS = 8


def _mk(lm, variables, pool_pages, tier=None, dtype="native"):
    from adapt_tpu.runtime.continuous import ContinuousBatcher

    kw = dict(
        slots=1, chunk=4, kv_layout="paged", page_size=PAGE,
        pool_pages=pool_pages, kv_cache_dtype=dtype,
    )
    if tier is not None:
        kw["cache_tier"] = tier
    return ContinuousBatcher(lm, variables, **kw)


def _roundtrip(lm, variables, dtype, tier, floods, errors, extras):
    """Flood-evict a registered prefix, re-reference it, compare to the
    uninterrupted big-pool stream. Returns the tier batcher's stats."""
    import numpy as np

    rng = np.random.RandomState(7)
    A = rng.randint(0, 61, size=2 * PAGE + 4).astype(np.int32)
    flood = [
        rng.randint(0, 61, size=2 * PAGE + 4).astype(np.int32)
        for _ in range(floods)
    ]
    tag = f"{dtype}"
    ref = _mk(lm, variables, 64, dtype=dtype)
    ref.submit(A, STEPS)
    ref.run()
    for p in flood:
        ref.submit(p, STEPS)
    ref.run()
    r2 = ref.submit(A, STEPS)
    want = ref.run()[r2]
    ref.close()

    bat = _mk(lm, variables, POOL_PAGES, tier=tier, dtype=dtype)
    bat.submit(A, STEPS)
    bat.run()
    spilled_last, budget = bat.stats()["tier_spilled"], (
        tier.spill_pages_per_tick
    )
    for p in flood:
        bat.submit(p, STEPS)
        # Budget check at every tick boundary while the flood evicts.
        while bat.tick() or bat.stats()["queued"]:
            s = bat.stats()["tier_spilled"]
            if s - spilled_last > budget:
                errors.append(
                    f"{tag}: tick spilled {s - spilled_last} > budget "
                    f"{budget}"
                )
            spilled_last = s
    st = bat.stats()
    hits0 = st["prefix_hits"]
    if st["tier_spilled"] == 0:
        errors.append(f"{tag}: flood evicted without a single spill")
    b2 = bat.submit(A, STEPS)
    got = bat.run()[b2]
    st = bat.stats()
    if not np.array_equal(got, want):
        errors.append(
            f"{tag}: readmitted stream diverged "
            f"({got.tolist()} vs {want.tolist()})"
        )
    if st["tier_readmitted"] < 1:
        errors.append(f"{tag}: re-reference readmitted nothing")
    if st["prefix_hits"] - hits0 < st["tier_readmitted"]:
        errors.append(
            f"{tag}: readmits not counted as prefix hits "
            f"({st['prefix_hits'] - hits0} hits for "
            f"{st['tier_readmitted']} readmits)"
        )
    alloc = st["pool_pages"] - 1
    if st["pages_in_use"] + (st["pages_free"] - st["pages_cached"]) \
            + st["pages_cached"] != alloc:
        errors.append(f"{tag}: pool partition broke: {st}")
    extras[f"{tag}_spilled"] = st["tier_spilled"]
    extras[f"{tag}_readmitted"] = st["tier_readmitted"]
    extras[f"{tag}_dropped"] = st["tier_dropped"]
    extras[f"{tag}_host_bytes"] = st["host_bytes"]
    bat.close()
    return want


def main() -> int:
    floods = int_flag(sys.argv, "--floods", 4)
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np

        from adapt_tpu.config import CacheTierConfig
        from adapt_tpu.models.transformer_lm import transformer_lm
        from adapt_tpu.utils.profiling import global_compile_sentinel

        # Many fresh batchers in one process: their first compiles are
        # legitimate — disarm the alarm (the quant_serving rationale).
        global_compile_sentinel().warmup_samples = 10**9
        lm = transformer_lm(61, 32, 2, 2, 64, max_len=64)
        variables = lm.graph.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
        )
        errors: list[str] = []
        extras: dict = {}
        tier = CacheTierConfig(
            host_capacity_pages=64,
            warm_capacity_pages=64,
            spill_pages_per_tick=4,
            readmit_pages_per_tick=8,
        )
        want = None
        for dtype in ("native", "int8"):
            w = _roundtrip(
                lm, variables, dtype, tier, floods, errors, extras
            )
            if dtype == "native":
                want = w

        # Lossy COLD arm: warm capacity 0 demotes every spill straight
        # through the int8 page codec; the readmitted stream's top-1
        # agreement vs the uncompressed reference gates >= 0.95.
        cold = CacheTierConfig(
            host_capacity_pages=64,
            warm_capacity_pages=0,
            cold_codec="int8",
            spill_pages_per_tick=8,
            readmit_pages_per_tick=8,
        )
        rng = np.random.RandomState(7)
        A = rng.randint(0, 61, size=2 * PAGE + 4).astype(np.int32)
        flood = [
            rng.randint(0, 61, size=2 * PAGE + 4).astype(np.int32)
            for _ in range(floods)
        ]
        bat = _mk(lm, variables, POOL_PAGES, tier=cold)
        bat.submit(A, STEPS)
        bat.run()
        for p in flood:
            bat.submit(p, STEPS)
        bat.run()
        b2 = bat.submit(A, STEPS)
        got = bat.run()[b2]
        st = bat.stats()
        if st["tier_readmitted"] < 1:
            errors.append("cold arm: re-reference readmitted nothing")
        n = min(len(got), len(want))
        agreement = (
            float((got[:n] == want[:n]).sum()) / n if n else 0.0
        )
        extras["cold_agreement_tokens"] = n
        extras["cold_readmitted"] = st["tier_readmitted"]
        bat.close()

        if errors:
            err = "; ".join(errors)[-300:]
            emit("micro_kv_tiers_roundtrip_exact", 0.0, "bool", 0.0,
                 error=err, **extras)
            emit("micro_kv_tiers_cold_top1_agreement", 0.0, "fraction",
                 0.0, error=err)
            return 0
        emit(
            "micro_kv_tiers_roundtrip_exact", 1.0, "bool", 0.0,
            floods=floods, pool_pages=POOL_PAGES, **extras,
        )
        emit(
            "micro_kv_tiers_cold_top1_agreement",
            round(agreement, 4),
            "fraction",
            round(agreement - 0.95, 4),
            floods=floods,
        )
    except Exception as e:  # noqa: BLE001 — always one JSON line, rc 0
        emit("micro_kv_tiers_roundtrip_exact", 0.0, "bool", 0.0,
             error=str(e)[-300:])
        emit("micro_kv_tiers_cold_top1_agreement", 0.0, "fraction", 0.0,
             error=str(e)[-300:])
    return 0


if __name__ == "__main__":
    sys.exit(main())
