"""Observability overhead on the steady-state decode tick.

The instrumentation contract (ISSUE 2, extended by the engine tier):
request timelines, engine phase timing and tracing must be cheap enough
to leave on. Disabled, the only residue is one branch per site
(``obs_timeline`` False + ``obs_engine`` off + tracer off == pre-PR
tick); enabled, the budget is < 5% added tick wall time on CPU.

Four configurations over the SAME ContinuousBatcher steady state
(all slots decoding, no admissions, chunked ticks):

- ``off``     — ``obs_timeline=False``, engine obs off, tracer disabled
  (the floor; the always-on compile-sentinel sample per tick is part of
  this floor by design).
- ``timeline``— default serving config: TTFT/ITL/queue-wait histograms
  + flight-recorder lifecycle events (engine + tracer still off).
  Every request carries an ``SLOSpec``, so this config ALSO pays the
  per-commit SLO evaluation + the per-tick goodput/attainment flush —
  the budget below covers SLO tracking, not just the bare histograms.
- ``engine``  — timeline + ``obs_engine`` per-phase histograms
  (``engine.phase.{admit,prefill,decode,commit,update}_s``).
- ``trace``   — engine + the span ring (prefill/decode-chunk spans).
- ``federation`` — trace + the telemetry-federation REPORT PATH
  (``utils/telemetry``): a ``TelemetryReporter.collect()`` (windowed
  snapshot delta + reservoir serialization + flight/span drain) folded
  into a ``FederatedStore`` every ``REPORT_EVERY`` ticks — the
  worker-side collect and the parent-side ingest of one report, i.e.
  both halves of the fleet path, timed inside the serving loop.

THREE JSON lines: ``micro_obs_overhead_pct`` (fully-enabled "trace"
overhead vs the floor, percent; ``vs_baseline`` = the 5% budget minus
the measured overhead, positive = within budget),
``micro_obs_federation_pct`` (federation config vs the same floor,
same budget — gated via benchmarks/baselines/seed.json) and
``micro_obs_overhead_async_pct`` (the same off-vs-trace delta measured
on a SECOND batcher running the pipelined tick runtime,
``RuntimeConfig(pipeline_depth=2)`` — the async loop moves the
``_obs_flush``/SLO arithmetic onto the deferred commit half, and this
row holds that seam to the SAME < 5% budget). Per-config per-tick
means and the engine-only overhead ride in extras.

A FOURTH gated line, ``micro_obs_overhead_capacity_pct``, measures the
capacity/placement-signal plane (``runtime/capacity.CapacityModel``)
on a PAIR of fresh paged batchers: one with
``CapacityConfig(enabled=False)`` (the floor — no model attached, zero
extra work anywhere) and one with ``refresh_s=0.0`` (book + sketch
rebuilt EVERY flush — far more aggressive than the production 0.25 s
cadence, so the measured overhead upper-bounds the real one). Both run
with the default timeline config so the delta isolates the capacity
arm alone. Same < 5% budget.

Timing note (benchmarks/common.py): ticks end in a real host fetch of
the chunk's tokens, so the region is honestly bounded per tick.

Usage: ``python benchmarks/micro/obs_overhead.py [--slots 4]
[--ticks 40] [--trials 5]``
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks.common import emit, int_flag  # noqa: E402

BUDGET_PCT = 5.0
#: Telemetry-report cadence in TICKS for the federation config — far
#: more aggressive than production (reports go out on a seconds-scale
#: wall cadence there), so the measured overhead upper-bounds the
#: real one.
REPORT_EVERY = 4


def main() -> int:
    slots = int_flag(sys.argv, "--slots", 4)
    n_ticks = int_flag(sys.argv, "--ticks", 40)
    trials = int_flag(sys.argv, "--trials", 5)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import jax
        import numpy as np

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp

        from adapt_tpu.config import SLOSpec
        from adapt_tpu.models.transformer_lm import lm_tiny
        from adapt_tpu.runtime.continuous import ContinuousBatcher
        from adapt_tpu.utils.tracing import global_tracer

        from adapt_tpu.utils.profiling import global_engine_obs

        chunk = 8
        # Requests must OUTLIVE every measured window (warmup + 5
        # configs x trials x n_ticks), or late ticks measure an idle
        # batcher: size max_len from the measurement plan.
        total_ticks = n_ticks * (5 * trials + 1) + 8
        steps = total_ticks * chunk
        lm = lm_tiny(vocab=37, max_len=steps + 16)
        variables = lm.graph.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
        )
        bat = ContinuousBatcher(lm, variables, slots=slots, chunk=chunk)
        rng = np.random.RandomState(0)
        # Generous budgets that never miss: the measured cost is the
        # EVALUATION (two comparisons per commit + the per-tick flush),
        # which is identical met or missed — minus one flight event.
        slo = SLOSpec(ttft_budget_s=3600.0, itl_budget_s=3600.0)
        for _ in range(slots):
            bat.submit(
                rng.randint(0, 37, size=6).astype(np.int32), steps,
                slo=slo,
            )
        bat.tick()  # admission burst + compiles
        bat.tick()

        tracer = global_tracer()
        eobs = global_engine_obs()
        for _ in range(n_ticks):  # warm caches before ANY timed window
            bat.tick()

        from adapt_tpu.utils.telemetry import (
            FederatedStore,
            TelemetryReporter,
        )

        store = FederatedStore()
        reporter = TelemetryReporter("bench", "obs0")

        configs = {  # name -> (obs_timeline, obs_engine, tracer.enabled)
            "off": (False, False, False),
            "timeline": (True, False, False),
            "engine": (True, True, False),
            "trace": (True, True, True),
            "federation": (True, True, True),
        }
        best = {name: float("inf") for name in configs}
        # Round-robin trials + best-of, ROTATING the config order each
        # trial: tick cost grows with sequence position (longer
        # attention window), so a fixed order would hand the
        # first-measured config the cheapest positions every trial.
        names = list(configs)
        n = len(names)
        for t in range(trials):
            for name in names[t % n:] + names[: t % n]:
                timeline, engine, trace = configs[name]
                bat.obs_timeline = timeline
                eobs.enabled = engine
                tracer.enabled = trace
                federate = name == "federation"
                t0 = time.perf_counter()
                for i in range(n_ticks):
                    bat.tick()
                    if federate and i % REPORT_EVERY == 0:
                        # Both halves of the fleet report path inside
                        # the timed region: the worker-side collect
                        # (windowed delta + reservoir serialization)
                        # and the parent-side ingest.
                        store.ingest(reporter.collect())
                best[name] = min(
                    best[name], (time.perf_counter() - t0) / n_ticks
                )
                if federate:
                    # Close the chained snapshot window OUTSIDE the
                    # timed region: an open window's reservoir forks
                    # would tax every OTHER config's observe() calls.
                    reporter.close()
        t_off, t_timeline, t_engine, t_trace = (
            best["off"], best["timeline"], best["engine"], best["trace"]
        )
        t_fed = best["federation"]
        tracer.enabled = False
        eobs.enabled = False
        still_active = bat.stats()["active"]
        if still_active != slots:
            raise RuntimeError(
                f"batcher fell out of steady state mid-measure "
                f"({still_active}/{slots} slots active)"
            )
        overhead_pct = (t_trace / t_off - 1.0) * 100.0
        federation_pct = (t_fed / t_off - 1.0) * 100.0
        emit(
            "micro_obs_overhead_pct",
            overhead_pct,
            "% tick wall time (trace+engine+timeline vs off)",
            BUDGET_PCT - overhead_pct,
            budget_pct=BUDGET_PCT,
            tick_off_ms=round(t_off * 1e3, 4),
            tick_timeline_ms=round(t_timeline * 1e3, 4),
            tick_engine_ms=round(t_engine * 1e3, 4),
            tick_trace_ms=round(t_trace * 1e3, 4),
            timeline_only_pct=round((t_timeline / t_off - 1.0) * 100.0, 3),
            engine_pct=round((t_engine / t_off - 1.0) * 100.0, 3),
            slots=slots,
            ticks=n_ticks,
            trials=trials,
            chunk=bat.chunk,
        )
        emit(
            "micro_obs_federation_pct",
            federation_pct,
            "% tick wall time (trace + telemetry report path vs off)",
            BUDGET_PCT - federation_pct,
            budget_pct=BUDGET_PCT,
            tick_federation_ms=round(t_fed * 1e3, 4),
            report_every_ticks=REPORT_EVERY,
            reports_ingested=store.sources()
            .get("bench:obs0:%d" % os.getpid(), {})
            .get("reports", 0),
        )

        # Async-runtime arm: off vs trace on a pipelined (depth-2)
        # batcher. The deferred commit half carries the _obs_flush +
        # SLO arithmetic there — same budget, measured separately so a
        # regression on the deferred seam can't hide behind the sync
        # numbers above. Same lm (its max_len covers this shorter
        # plan); fresh batcher so jit caches and KV state don't cross.
        from adapt_tpu.config import RuntimeConfig

        bat.close()
        abat = ContinuousBatcher(
            lm, variables, slots=slots, chunk=chunk,
            runtime=RuntimeConfig(pipeline_depth=2),
        )
        asteps = (n_ticks * (2 * trials + 1) + 8) * chunk
        for _ in range(slots):
            abat.submit(
                rng.randint(0, 37, size=6).astype(np.int32), asteps,
                slo=slo,
            )
        abat.tick()  # admission burst + this batcher's compiles
        abat.tick()
        for _ in range(n_ticks):  # warm before any timed window
            abat.tick()
        abest = {"off": float("inf"), "trace": float("inf")}
        for t in range(trials):
            order = (
                ("off", "trace") if t % 2 == 0 else ("trace", "off")
            )
            for name in order:
                on = name == "trace"
                abat.obs_timeline = on
                eobs.enabled = on
                tracer.enabled = on
                t0 = time.perf_counter()
                for _ in range(n_ticks):
                    abat.tick()
                abest[name] = min(
                    abest[name], (time.perf_counter() - t0) / n_ticks
                )
        tracer.enabled = False
        eobs.enabled = False
        if abat.stats()["active"] != slots:
            raise RuntimeError(
                "async batcher fell out of steady state mid-measure"
            )
        abat.close()
        async_pct = (abest["trace"] / abest["off"] - 1.0) * 100.0
        emit(
            "micro_obs_overhead_async_pct",
            async_pct,
            "% tick wall time (trace vs off, pipelined depth-2 runtime)",
            BUDGET_PCT - async_pct,
            budget_pct=BUDGET_PCT,
            tick_off_ms=round(abest["off"] * 1e3, 4),
            tick_trace_ms=round(abest["trace"] * 1e3, 4),
            slots=slots,
            ticks=n_ticks,
            trials=trials,
        )

        # Capacity-plane arm: a fresh PAGED batcher pair (paged so the
        # book rebuild pays the full bill — headroom from Pager.stats
        # plus the radix affinity sketch). The floor batcher has the
        # plane disabled (no model attached); the hot one rebuilds the
        # book on EVERY flush (refresh_s=0.0, vs 0.25 s in production),
        # so this upper-bounds the steady-state cost. Both keep the
        # default timeline config: the delta is the capacity arm alone.
        from adapt_tpu.config import CapacityConfig

        page = 16
        csteps = (n_ticks * (trials + 1) + 8) * chunk
        pool = slots * ((csteps + 48 + page) // page + 1) + 8
        cbats = {}
        for cname, ccfg in (
            ("off", CapacityConfig(enabled=False)),
            ("on", CapacityConfig(refresh_s=0.0)),
        ):
            cb = ContinuousBatcher(
                lm, variables, slots=slots, chunk=chunk,
                kv_layout="paged", page_size=page, pool_pages=pool,
                capacity=ccfg,
            )
            for _ in range(slots):
                # 3-page prompts so the radix tree (and therefore the
                # sketch rebuild) has real content to walk.
                cb.submit(
                    rng.randint(0, 37, size=3 * page).astype(np.int32),
                    csteps, slo=slo,
                )
            cb.tick()  # admission burst + paged-program compiles
            cb.tick()
            for _ in range(n_ticks):  # warm before any timed window
                cb.tick()
            cbats[cname] = cb
        cbest = {"off": float("inf"), "on": float("inf")}
        for t in range(trials):
            order = ("off", "on") if t % 2 == 0 else ("on", "off")
            for cname in order:
                cb = cbats[cname]
                t0 = time.perf_counter()
                for _ in range(n_ticks):
                    cb.tick()
                cbest[cname] = min(
                    cbest[cname], (time.perf_counter() - t0) / n_ticks
                )
        for cname, cb in cbats.items():
            if cb.stats()["active"] != slots:
                raise RuntimeError(
                    f"capacity-{cname} batcher fell out of steady "
                    "state mid-measure"
                )
        book = cbats["on"].capacity_book() or {}
        for cb in cbats.values():
            cb.close()
        capacity_pct = (cbest["on"] / cbest["off"] - 1.0) * 100.0
        emit(
            "micro_obs_overhead_capacity_pct",
            capacity_pct,
            "% tick wall time (capacity book rebuilt every flush vs "
            "plane disabled, paged batcher)",
            BUDGET_PCT - capacity_pct,
            budget_pct=BUDGET_PCT,
            tick_capacity_off_ms=round(cbest["off"] * 1e3, 4),
            tick_capacity_on_ms=round(cbest["on"] * 1e3, 4),
            refresh_s=0.0,
            sketch_entries=len(
                book.get("sketch", {}).get("entries", ())
            ),
            slots=slots,
            ticks=n_ticks,
            trials=trials,
        )
    except Exception as e:  # noqa: BLE001 — always one JSON line, rc 0
        emit(
            "micro_obs_overhead_pct", 0.0,
            "% tick wall time (trace+engine+timeline vs off)", 0.0,
            error=str(e)[-300:],
        )
        emit(
            "micro_obs_federation_pct", 0.0,
            "% tick wall time (trace + telemetry report path vs off)",
            0.0,
            error=str(e)[-300:],
        )
        emit(
            "micro_obs_overhead_async_pct", 0.0,
            "% tick wall time (trace vs off, pipelined depth-2 runtime)",
            0.0,
            error=str(e)[-300:],
        )
        emit(
            "micro_obs_overhead_capacity_pct", 0.0,
            "% tick wall time (capacity book rebuilt every flush vs "
            "plane disabled, paged batcher)",
            0.0,
            error=str(e)[-300:],
        )
    return 0


if __name__ == "__main__":
    sys.exit(main())
