"""Sequence-parallel prefill micro-benchmark: byte-equality + the
per-device prefill-wall split (ROADMAP item 5 / ISSUE 15).

Two gated records (``benchmarks/baselines/seed.json``):

- ``micro_sp_prefill_pages_exact`` — STRUCTURAL, exactly 1.0: over the
  native/int8 grid, the sp=2 prefiller's page-major blocks are
  BYTE-EQUAL to the single-device chunked prefill's pages
  (``PrefillWorker``, page-sized chunks), and greedy streams through
  an sp-enabled batcher are BIT-IDENTICAL to the plain batcher on the
  same prompts. Any mismatch becomes an error record the gate always
  fails.
- ``micro_sp_prefill_flops_ratio`` — the prefill-wall split, measured
  structurally: compiled-module ``cost_analysis`` flops of the
  single-device whole-span prefill program divided by the sp=2
  program's PER-DEVICE flops at a 64-page span (~1.95: each ring rank
  computes half the O(S^2) score block plus the ring/psum overhead).
  Gated >= ~1.5 — the "sp=2 at least 1.5x faster than sp=1" pin,
  expressed as the per-chip work ratio because THIS CI box has ONE
  core: its virtual devices serialize, so a wall-clock A/B here
  measures scheduling noise, not the split (the same
  pending-real-hardware discipline as the ``engine.mbu`` gate). The
  wall ratio still rides as an ungated extra so a multi-core or TPU
  run shows up in the record.

Usage: ``python benchmarks/micro/sp_prefill.py [--pages 64]``
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks.common import emit, force_cpu_mesh, int_flag  # noqa: E402

VOCAB = 61
PAGE = 8


def main() -> int:
    pages = int_flag(sys.argv, "--pages", 64)
    try:
        force_cpu_mesh(4)
        import jax
        import jax.numpy as jnp
        import numpy as np

        from adapt_tpu.config import PrefillConfig
        from adapt_tpu.models.transformer_lm import transformer_lm
        from adapt_tpu.parallel.sp_prefill import SPPrefiller, build_sp_mesh
        from adapt_tpu.runtime.continuous import ContinuousBatcher
        from adapt_tpu.runtime.disagg import PrefillWorker
        from adapt_tpu.utils.profiling import global_compile_sentinel

        # The driver builds several batchers/prefillers on purpose —
        # their first compiles are legitimate (tp_decode's rule).
        global_compile_sentinel().warmup_samples = 10**9
        rng = np.random.RandomState(0)

        # -- byte-equality grid (small LM: equality is scale-pinned) --
        lm = transformer_lm(VOCAB, 32, 2, 2, 64, max_len=96,
                            name="spp_lm")
        variables = lm.graph.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
        )
        prompt = rng.randint(1, VOCAB, size=41).astype(np.int32)
        violations: list[str] = []
        for dtype in ("native", "int8"):
            w = PrefillWorker(
                lm, variables, page_size=PAGE, prefill_chunk=PAGE,
                kv_cache_dtype=dtype, name=f"ref-{dtype}",
            )
            w.submit(1, prompt)
            outs = []
            while not outs:
                outs = w.step()
            ref = outs[0].blocks
            pf = SPPrefiller(
                lm, variables, build_sp_mesh(2), PAGE,
                kv_cache_dtype=dtype, name=f"sp-{dtype}",
            )
            _, blocks = pf.prefill(prompt)
            for a, b in zip(jax.tree.leaves(ref), jax.tree.leaves(blocks)):
                if not np.array_equal(np.asarray(a), np.asarray(b)):
                    violations.append(
                        f"{dtype}: sp=2 pages differ from the "
                        "single-device chunked prefill"
                    )
                    break
            pf.close()

        # -- greedy-stream bit-identity through the batcher ------------
        prompts = [rng.randint(1, VOCAB, size=n).astype(np.int32)
                   for n in (41, 7, 33, 25)]

        def run_streams(sp_cfg):
            kw = dict(slots=2, chunk=4, kv_layout="paged",
                      page_size=PAGE, prefill_chunk=2 * PAGE)
            if sp_cfg is not None:
                kw["prefill"] = sp_cfg
            bat = ContinuousBatcher(lm, variables, **kw)
            rids = [bat.submit(p, 8) for p in prompts]
            outs = bat.run()
            st = bat.stats()
            bat.close()
            return [outs[r] for r in rids], st

        ref_streams, _ = run_streams(None)
        sp_streams, sp_st = run_streams(
            PrefillConfig(sp_threshold=24, sp_width=2)
        )
        for i, (a, b) in enumerate(zip(ref_streams, sp_streams)):
            if not np.array_equal(a, b):
                violations.append(f"stream {i} diverged under sp prefill")
        if sp_st.get("sp_prefills", 0) != 3:
            violations.append(
                f"expected 3 sp admissions, saw "
                f"{sp_st.get('sp_prefills')}"
            )

        # -- per-device prefill-wall split (compiled cost analysis) ----
        lm2 = transformer_lm(VOCAB, 64, 2, 4, 128,
                             max_len=pages * PAGE + 8, kv_heads=2,
                             name="spp_lm2")
        vars2 = lm2.graph.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
        )
        span = pages * PAGE
        long_prompt = rng.randint(1, VOCAB, size=span + 1).astype(np.int32)

        def compiled_flops(comp):
            ca = comp.cost_analysis()
            if isinstance(ca, list):
                ca = ca[0] if ca else {}
            return float(ca.get("flops", 0.0))

        # sp=1 arm: the single-device whole-span program (the worker's
        # one-pass chunk body — the exact math the sp program splits).
        w1 = PrefillWorker(lm2, vars2, page_size=PAGE,
                           prefill_chunk=None, pool_pages=pages + 1,
                           name="sp1-arm")
        fn1 = w1._chunk_fn(span, pages)
        f1 = compiled_flops(
            fn1.lower(
                w1.variables, w1._pools,
                jnp.zeros((pages,), jnp.int32),
                jnp.zeros((1, span), jnp.int32),
                jnp.zeros((1,), jnp.int32),
            ).compile()
        )
        # sp=1 wall: run it (distinct inputs defeat dedup).
        w1.submit(1, long_prompt)
        t0 = time.perf_counter()
        outs = []
        while not outs:
            outs = w1.step()
        wall_sp1 = time.perf_counter() - t0

        pf2 = SPPrefiller(lm2, vars2, build_sp_mesh(2), PAGE,
                          name="sp2-arm")
        fn2 = pf2._sp_fn(pages)
        f2 = compiled_flops(
            fn2.lower(
                pf2._variables,
                jax.device_put(
                    np.zeros((1, span), np.int32), pf2._repl
                ),
            ).compile()
        )
        t0 = time.perf_counter()
        pf2.prefill(long_prompt)
        wall_sp2 = time.perf_counter() - t0
        pf2.close()
        flops_ratio = f1 / f2 if f2 else 0.0

        if violations:
            for metric in ("micro_sp_prefill_pages_exact",
                           "micro_sp_prefill_flops_ratio"):
                emit(metric, 0.0, "structural", 0.0,
                     error="; ".join(violations)[:300])
            return 0
        emit(
            "micro_sp_prefill_pages_exact", 1.0,
            "1.0 = sp pages byte-equal + greedy streams bit-identical",
            0.0,
            grid="{native,int8} pages x {41,7,33,25}-token streams",
            sp_width=2,
        )
        emit(
            "micro_sp_prefill_flops_ratio", flops_ratio,
            "single-device / per-device sp=2 compiled prefill flops",
            0.0,
            span_tokens=span,
            flops_sp1=f1,
            flops_sp2_per_device=f2,
            # Ungated context: on this 1-core box the virtual devices
            # serialize, so wall_ratio ~<= 1 is EXPECTED; on real
            # parallel hardware it tracks the flops ratio.
            wall_sp1_s=round(wall_sp1, 4),
            wall_sp2_s=round(wall_sp2, 4),
            wall_ratio=round(wall_sp1 / wall_sp2, 4) if wall_sp2 else 0.0,
        )
    except Exception as e:  # noqa: BLE001 — always one JSON line, rc 0
        for metric in ("micro_sp_prefill_pages_exact",
                       "micro_sp_prefill_flops_ratio"):
            emit(metric, 0.0, "structural", 0.0, error=str(e)[-300:])
    return 0


if __name__ == "__main__":
    sys.exit(main())
