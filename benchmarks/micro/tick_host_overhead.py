"""Host→device staging transfers per ContinuousBatcher.tick.

The fused-staging contract (``runtime/continuous.py`` "Device-resident
hot path"): every per-slot sampling input lives in pre-allocated batched
device arrays, staged ONCE per admission by donated jitted setters — so
a steady-state decode tick stages ZERO host scalars. The old path
rebuilt and transferred 7 host arrays per tick (tokens, pos, keys,
temps, top_ks, top_ps, greedy — O(slots×fields) scalar staging).

Measured, not inferred: every ``jnp.asarray``/``device_put`` the batcher
issues funnels through its ``_h2d`` counter, surfaced as
``stats()["h2d_transfers"]``. This driver fills all slots, lets the
batch reach steady state, then counts transfers across N pure-decode
ticks and across the admission burst.

One JSON line: value = steady-state transfers per tick (contract: 0.0),
``vs_baseline`` = old-path transfers per tick (7) − new (i.e. transfers
eliminated per tick).

Usage: ``python benchmarks/micro/tick_host_overhead.py [--slots 4]
[--ticks 16]``
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks.common import emit, int_flag  # noqa: E402

#: Per-tick host arrays the pre-fused path staged (tokens, pos, keys,
#: temps, top_ks, top_ps, greedy — git history of tick()).
OLD_PER_TICK = 7


def main() -> int:
    slots = int_flag(sys.argv, "--slots", 4)
    n_ticks = int_flag(sys.argv, "--ticks", 16)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import jax
        import numpy as np

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp

        from adapt_tpu.models.transformer_lm import lm_tiny
        from adapt_tpu.runtime.continuous import ContinuousBatcher

        lm = lm_tiny(vocab=37, max_len=192)
        variables = lm.graph.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
        )
        bat = ContinuousBatcher(lm, variables, slots=slots, chunk=4)
        rng = np.random.RandomState(0)
        # Decode lengths long enough that no request retires while the
        # steady-state window is being measured (a retirement is a
        # legitimate O(1) _clear_slot upload, but it isn't steady state).
        steps = (n_ticks + 8) * bat.chunk
        before_admit = bat.stats()["h2d_transfers"]
        for _ in range(slots):
            bat.submit(rng.randint(0, 37, size=6).astype(np.int32), steps)
        bat.tick()  # admission burst: prefills + fused row staging
        admit_transfers = bat.stats()["h2d_transfers"] - before_admit
        bat.tick()  # flush any admission stragglers before measuring
        before = bat.stats()["h2d_transfers"]
        for _ in range(n_ticks):
            bat.tick()
        per_tick = (bat.stats()["h2d_transfers"] - before) / n_ticks
        emit(
            "micro_tick_h2d_per_tick",
            per_tick,
            "h2d_transfers/tick",
            OLD_PER_TICK - per_tick,
            old_per_tick=OLD_PER_TICK,
            per_admission=round(admit_transfers / slots, 2),
            slots=slots,
            ticks=n_ticks,
            chunk=bat.chunk,
        )
    except Exception as e:  # noqa: BLE001 — always one JSON line, rc 0
        emit("micro_tick_h2d_per_tick", 0.0, "h2d_transfers/tick", 0.0,
             error=str(e)[-300:])
    return 0


if __name__ == "__main__":
    sys.exit(main())
