"""Quantized KV serving across the layout/mode grid: per-slot KV bytes
ratio, tick wall, h2d/tick, churn compiles.

The capacity claim int8 KV makes is STRUCTURAL, like tp_decode's: the
batcher's caches — dense slot strips AND paged pools — become
``(int8 values, f32 scales)`` pairs, so resident cache bytes drop to
``(hd + 4) / (hd * native_itemsize)`` of the native layout (0.3125 at
f32/hd=16) whatever the traffic, and the counter-based hot-path
contracts must survive the composition. This driver runs the full
dense/paged x native/int8/int4 x plain/spec grid (one small model,
identical traffic; int4 packs two nibbles per int8 lane for
``(hd/2 + 4) / (hd * 4)`` = 0.1875 at f32/hd=16, gated as a second
record ``micro_quant_int4_kv_bytes_ratio`` <= 0.2) and reports per
config:

- ``<cfg>_kv_bytes`` — ``stats()["cache_bytes"]`` (scale planes
  INCLUDED — the honest number the memory.kv_bytes gauges serve);
- ``<cfg>_tick_ms`` — decode tick wall (CPU-noisy; the interpreter-mode
  attention oracle is the schedule-sanity number, not the TPU win);
- ``<cfg>_h2d_per_tick`` — the fused-staging contract under
  quantization: 0 per steady-state tick;
- per-config compile growth across churn (admit/retire/re-admit): the
  two-program steady state must hold over quantized caches.

Structural violations (h2d > 0, compile growth, int8 not actually
smaller, int8/native ratio off the analytic value) become ``error``
records the gate always fails. The headline ``value`` is the WORST
(largest) int8/native cache-bytes ratio across layouts and modes —
gated ``<= 0.55`` in ``benchmarks/baselines/seed.json`` (analytic:
0.3125 at f32/hd=16). A bf16-native model's ratio would be 0.625 and
fail the gate by design — the scale-plane overhead is relatively
larger there, so the baseline must be consciously re-measured, not
silently absorbed, if this driver's model ever goes bf16.

Usage: ``python benchmarks/micro/quant_serving.py [--ticks 4]``
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks.common import emit, int_flag  # noqa: E402


def _measure(bat, slots: int, n_ticks: int, steps: int):
    """Fill every slot, settle, measure N steady-state ticks."""
    import numpy as np

    rng = np.random.RandomState(0)
    for _ in range(slots):
        bat.submit(rng.randint(0, 61, size=6).astype(np.int32), steps)
    bat.tick()  # admissions
    bat.tick()  # settle
    h2d0 = bat.stats()["h2d_transfers"]
    t0 = time.perf_counter()
    for _ in range(n_ticks):
        bat.tick()
    wall = time.perf_counter() - t0
    h2d = (bat.stats()["h2d_transfers"] - h2d0) / n_ticks
    return wall * 1e3 / n_ticks, h2d


def main() -> int:
    n_ticks = int_flag(sys.argv, "--ticks", 4)
    slots = 2
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np

        from adapt_tpu.config import SpeculativeConfig
        from adapt_tpu.models.transformer_lm import transformer_lm
        from adapt_tpu.runtime.continuous import ContinuousBatcher
        from adapt_tpu.utils.profiling import global_compile_sentinel

        # Requests must OUTLIVE the measured window (a retirement
        # inside it is a legitimate +1 h2d row-clear, not a violation):
        # admission + settle + n_ticks measured ticks at chunk=8.
        steps = 8 * (n_ticks + 2) + 8
        lm = transformer_lm(61, 32, 2, 2, 64, max_len=steps + 16)
        variables = lm.graph.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
        )
        sentinel = global_compile_sentinel()
        # This driver provokes legitimate compiles (8 batcher
        # instances, churn probes); assert the deltas explicitly,
        # disarm the alarm (the tp_decode rationale).
        sentinel.warmup_samples = 10**9
        errors: list[str] = []
        extras: dict = {}
        kv_bytes: dict[tuple, int] = {}
        for layout in ("slots", "paged"):
            for dtype in ("native", "int8", "int4"):
                for spec in (False, True):
                    tag = (
                        f"{'paged' if layout == 'paged' else 'dense'}"
                        f"_{dtype}{'_spec' if spec else ''}"
                    )
                    kw: dict = dict(kv_cache_dtype=dtype, chunk=8)
                    if layout == "paged":
                        kw.update(kv_layout="paged", page_size=16)
                    prog = "continuous.step_chunk"
                    if spec:
                        # Self-draft: perfect acceptance, no second
                        # model's compile bill — the quantization
                        # composition is what's measured here.
                        kw.update(
                            draft_lm=lm, draft_variables=variables,
                            speculative=SpeculativeConfig(draft_k=3),
                        )
                        prog = "continuous.spec_verify"
                    bat = ContinuousBatcher(
                        lm, variables, slots=slots, **kw
                    )
                    tick_ms, h2d = _measure(bat, slots, n_ticks, steps)
                    st = bat.stats()
                    kv_bytes[(layout, dtype, spec)] = st["cache_bytes"]
                    extras[f"{tag}_kv_bytes"] = st["cache_bytes"]
                    extras[f"{tag}_tick_ms"] = round(tick_ms, 3)
                    extras[f"{tag}_h2d_per_tick"] = h2d
                    if h2d != 0:
                        errors.append(f"{tag}: steady tick staged {h2d}")
                    entries = sentinel.compiles(prog)
                    bat.submit(np.arange(1, 6, dtype=np.int32), 4)
                    bat.run()
                    grew = sentinel.compiles(prog) - entries
                    if grew:
                        errors.append(
                            f"{tag}: churn compiled {grew} variants"
                        )
                    bat.close()
        ratios = []
        ratios4 = []
        for layout in ("slots", "paged"):
            for spec in (False, True):
                n = kv_bytes[(layout, "native", spec)]
                q = kv_bytes[(layout, "int8", spec)]
                q4 = kv_bytes[(layout, "int4", spec)]
                ratios.append(q / n)
                ratios4.append(q4 / n)
                if q >= n:
                    errors.append(
                        f"{layout}{'_spec' if spec else ''}: int8 cache "
                        f"{q} not smaller than native {n}"
                    )
                if q4 >= q:
                    errors.append(
                        f"{layout}{'_spec' if spec else ''}: int4 cache "
                        f"{q4} not smaller than int8 {q}"
                    )
        ratio = max(ratios)
        ratio4 = max(ratios4)
        extras["kv_bytes_ratio_min"] = round(min(ratios), 4)
        extras["int4_kv_bytes_ratio_min"] = round(min(ratios4), 4)
        if errors:
            emit(
                "micro_quant_kv_bytes_ratio", 1.0, "x", 0.0,
                error="; ".join(errors)[-300:], **extras,
            )
            emit(
                "micro_quant_int4_kv_bytes_ratio", 1.0, "x", 0.0,
                error="; ".join(errors)[-300:],
            )
            return 0
        emit(
            "micro_quant_kv_bytes_ratio",
            round(ratio, 4),
            "x",
            round(0.5 - ratio, 4),
            ticks=n_ticks,
            slots=slots,
            **extras,
        )
        # Second gated record: the int4 grid's worst per-slot KV bytes
        # ratio vs native (analytic (hd/2 + 4) / (hd * 4) = 0.1875 at
        # f32/hd=16; the ISSUE-12 capacity pin is <= 0.2).
        emit(
            "micro_quant_int4_kv_bytes_ratio",
            round(ratio4, 4),
            "x",
            round(0.2 - ratio4, 4),
            ticks=n_ticks,
            slots=slots,
        )
    except Exception as e:  # noqa: BLE001 — always one JSON line, rc 0
        emit("micro_quant_kv_bytes_ratio", 1.0, "x", 0.0,
             error=str(e)[-300:])
    return 0


if __name__ == "__main__":
    sys.exit(main())
