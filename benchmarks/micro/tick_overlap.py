"""Sync-vs-async tick walls and the pipelined runtime's invariants.

The pipelined tick runtime (``config.RuntimeConfig(pipeline_depth=2)``,
``runtime/continuous.py``) dispatches tick *t*'s device programs, then
commits tick *t−1* while *t* runs — host scheduling overlaps device
compute instead of alternating with it. This driver measures both arms
over the SAME steady-state decode workload (all slots busy, no
admissions, fresh batcher per arm so jit caches don't cross) and
checks the invariants the overlap must NOT cost:

- steady-state depth-2 ticks stage ZERO host arrays (``_h2d`` counter,
  exactly like micro/tick_host_overhead.py for the sync loop) — this
  is the gated value;
- churn (retire + re-admit) under depth 2 adds ZERO step-chunk compile
  variants (frozen compile footprint) — violation raises, so it lands
  as an error record;
- ``runtime.overlap_ratio`` (share of the dispatch→commit wall the
  host did not spend blocked on the result fetch) rides in extras
  next to the per-tick walls of both arms.

One JSON line: value = async steady-state h2d transfers per tick
(contract: 0.0, gated exact in benchmarks/baselines/seed.json);
``vs_baseline`` = sync tick wall minus async tick wall in ms (positive
= the pipelined loop is ahead on this box; CPU walls are advisory —
the gate is the invariant, not the speedup).

Usage: ``python benchmarks/micro/tick_overlap.py [--slots 4]
[--ticks 16] [--trials 3]``
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks.common import emit, int_flag  # noqa: E402

UNIT = "h2d_transfers/tick (async steady state)"


def main() -> int:
    slots = int_flag(sys.argv, "--slots", 4)
    n_ticks = int_flag(sys.argv, "--ticks", 16)
    trials = int_flag(sys.argv, "--trials", 3)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import jax
        import numpy as np

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp

        from adapt_tpu.config import RuntimeConfig
        from adapt_tpu.models.transformer_lm import lm_tiny
        from adapt_tpu.runtime.continuous import ContinuousBatcher
        from adapt_tpu.utils.metrics import global_metrics
        from adapt_tpu.utils.profiling import global_compile_sentinel

        chunk = 4
        # Requests must outlive warmup + every timed window, plus the
        # churn coda on the async arm.
        steps = (n_ticks * (trials + 1) + 16) * chunk
        lm = lm_tiny(vocab=37, max_len=steps + 32)
        variables = lm.graph.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
        )
        rng = np.random.RandomState(0)

        def build(depth: int) -> ContinuousBatcher:
            bat = ContinuousBatcher(
                lm, variables, slots=slots, chunk=chunk,
                runtime=RuntimeConfig(pipeline_depth=depth),
            )
            for _ in range(slots):
                bat.submit(
                    rng.randint(0, 37, size=6).astype(np.int32), steps
                )
            bat.tick()  # admission burst + compiles
            bat.tick()
            for _ in range(n_ticks):  # warm before any timed window
                bat.tick()
            return bat

        arms = {1: build(1), 2: build(2)}
        best = {d: float("inf") for d in arms}
        # Best-of trials, alternating arm order per trial: tick cost
        # grows with sequence position, so a fixed order would hand
        # one arm the cheapest positions every trial.
        for t in range(trials):
            order = (1, 2) if t % 2 == 0 else (2, 1)
            for d in order:
                bat = arms[d]
                t0 = time.perf_counter()
                for _ in range(n_ticks):
                    bat.tick()
                best[d] = min(
                    best[d], (time.perf_counter() - t0) / n_ticks
                )

        # Invariant 1: zero H2D per steady async tick (one tick stays
        # in flight across the window — that IS steady state here).
        bat = arms[2]
        h0 = bat.stats()["h2d_transfers"]
        for _ in range(4):
            bat.tick()
        h2d_per_tick = (bat.stats()["h2d_transfers"] - h0) / 4.0
        overlap = (
            global_metrics()
            .snapshot()["gauges"]
            .get("runtime.overlap_ratio", 0.0)
        )

        # Invariant 2: churn under the pipelined loop adds no compile
        # variant. Retire everything, re-admit, drain — the step-chunk
        # program must hold exactly the variants it already has.
        sentinel = global_compile_sentinel()
        entries = sentinel.compiles("continuous.step_chunk")
        for d in (1, 2):
            arms[d].run()  # retire the measurement requests
        bat.submit(rng.randint(0, 37, size=6).astype(np.int32), 2 * chunk)
        bat.run()
        churn_delta = (
            sentinel.compiles("continuous.step_chunk") - entries
        )
        if churn_delta:
            raise RuntimeError(
                f"churn under pipeline_depth=2 added {churn_delta} "
                f"step-chunk compile variant(s); footprint must stay "
                f"frozen"
            )
        if bat.stats()["inflight"]:
            raise RuntimeError("run() left a tick in flight")
        for d in (1, 2):
            arms[d].close()

        t_sync_ms = best[1] * 1e3
        t_async_ms = best[2] * 1e3
        emit(
            "micro_tick_overlap_h2d_per_tick",
            h2d_per_tick,
            UNIT,
            t_sync_ms - t_async_ms,
            tick_sync_ms=round(t_sync_ms, 4),
            tick_async_ms=round(t_async_ms, 4),
            overlap_ratio=round(float(overlap), 4),
            churn_compile_delta=churn_delta,
            slots=slots,
            ticks=n_ticks,
            trials=trials,
            chunk=chunk,
        )
    except Exception as e:  # noqa: BLE001 — always one JSON line, rc 0
        emit("micro_tick_overlap_h2d_per_tick", 0.0, UNIT, 0.0,
             error=str(e)[-300:])
    return 0


if __name__ == "__main__":
    sys.exit(main())
