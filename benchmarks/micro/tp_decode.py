"""Tensor-parallel serving on the sim mesh: per-device KV bytes + tick
wall time at tp = {1, 2, 4}.

The capacity claim TP serving makes is STRUCTURAL: the batcher's KV
caches (dense slot strips here) shard on their head axis over the
mesh's ``tp`` axis, so each device holds exactly ``logical / tp`` bytes
— a model whose KV residency busts one chip's HBM fits a tp-group, and
like the other micro drivers that counter transfers to the TPU run
directly however noisy the CPU wall clock is. This driver builds the
same GQA model's batcher at tp=1/2/4 on the virtual CPU mesh
(``--xla_force_host_platform_device_count``), runs identical steady
traffic through each, and reports:

- ``tp{n}_kv_bytes_per_device`` — from ``stats()`` (and the
  ``memory.kv_bytes_per_device`` gauge path): MUST equal logical/n;
- ``tp{n}_tick_ms`` — decode tick wall time (honest but CPU-noisy: the
  sim mesh pays real collectives with none of the ICI overlap, so this
  is a schedule-sanity number, not the TPU win);
- ``tp{n}_h2d_per_tick`` — the PR-1 fused-staging contract under a
  mesh: 0 per steady-state tick;
- per-config compile growth across churn (admit/retire/re-admit): the
  two-program steady state must hold under GSPMD.

Structural violations (per-device bytes != logical/tp, h2d > 0, compile
growth) turn into an ``error`` record so ``benchmarks/ci_gate.py``
fails loud. The headline ``value`` is the tp1/tp4 per-device-bytes
ratio — exactly 4.0 when sharding lands (the gated metric in
``benchmarks/baselines/seed.json``).

Usage: ``python benchmarks/micro/tp_decode.py [--slots 4] [--ticks 8]``
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks.common import emit, force_cpu_mesh, int_flag  # noqa: E402

#: Devices the sim mesh needs (tp=4 is the largest config);
#: ``force_cpu_mesh`` provisions them (appending/upgrading the XLA flag
#: without clobbering inherited flags) and fails loudly if a too-small
#: backend was already initialized.
_NDEV = 4


def _measure(bat, slots: int, n_ticks: int, steps: int):
    """Fill every slot, settle, measure N steady-state ticks. Returns
    (tick_ms, h2d_per_tick, tokens)."""
    import numpy as np

    rng = np.random.RandomState(0)
    for _ in range(slots):
        bat.submit(rng.randint(0, 61, size=6).astype(np.int32), steps)
    bat.tick()  # admissions
    bat.tick()  # settle
    h2d0 = bat.stats()["h2d_transfers"]
    tok0 = sum(len(s.tokens) for s in bat.slots if s.req is not None)
    t0 = time.perf_counter()
    for _ in range(n_ticks):
        bat.tick()
    wall = time.perf_counter() - t0
    tok1 = sum(len(s.tokens) for s in bat.slots if s.req is not None)
    h2d = (bat.stats()["h2d_transfers"] - h2d0) / n_ticks
    return wall * 1e3 / n_ticks, h2d, tok1 - tok0


def main() -> int:
    slots = int_flag(sys.argv, "--slots", 4)
    n_ticks = int_flag(sys.argv, "--ticks", 8)
    try:
        force_cpu_mesh(_NDEV)
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh

        from adapt_tpu.config import ParallelConfig
        from adapt_tpu.models.transformer_lm import transformer_lm
        from adapt_tpu.runtime.continuous import ContinuousBatcher
        from adapt_tpu.utils.profiling import global_compile_sentinel

        # GQA target whose kv_heads divide every tp config — the shape
        # class head-sharded serving exists for.
        lm = transformer_lm(61, 64, 2, 8, 128, max_len=128, kv_heads=4)
        variables = lm.graph.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
        )
        sentinel = global_compile_sentinel()
        # This driver deliberately provokes legitimate compiles (three
        # batcher instances, a churn probe with fresh key-bucket and
        # retirement shapes) and asserts the deltas it cares about
        # EXPLICITLY via sentinel.compiles(). Disarm the recompile
        # ALARM for the whole run: with the default 8-sample warmup the
        # churn admissions land post-warmup and every honest run would
        # log "unexpected recompile" WARNINGs and bump
        # engine.compile_events — false positives for anyone alerting
        # on the PR4 telemetry.
        sentinel.warmup_samples = 10**9
        steps = n_ticks * 8 + 32  # outlive the measured window
        errors: list[str] = []
        extras: dict = {}
        kv_pd: dict[int, int] = {}
        for tp in (1, 2, 4):
            mesh = Mesh(np.array(jax.devices()[:tp]), ("tp",))
            bat = ContinuousBatcher(
                lm, variables, slots=slots, chunk=8, mesh=mesh,
                parallel=ParallelConfig(tp=tp),
            )
            tick_ms, h2d, tokens = _measure(bat, slots, n_ticks, steps)
            st = bat.stats()
            kv_pd[tp] = st["cache_bytes_per_device"]
            extras[f"tp{tp}_kv_bytes_per_device"] = kv_pd[tp]
            extras[f"tp{tp}_tick_ms"] = round(tick_ms, 3)
            extras[f"tp{tp}_h2d_per_tick"] = h2d
            extras[f"tp{tp}_toks_per_tick"] = round(
                tokens / n_ticks, 2
            )
            if st["cache_bytes_per_device"] * tp != st["cache_bytes"]:
                errors.append(
                    f"tp{tp}: per-device bytes "
                    f"{st['cache_bytes_per_device']} * {tp} != logical "
                    f"{st['cache_bytes']}"
                )
            if h2d != 0:
                errors.append(f"tp{tp}: steady tick staged {h2d} h2d")
            # Churn must not grow the decode program: the two-program
            # steady state holds under GSPMD partitioning too.
            entries = sentinel.compiles("continuous.step_chunk")
            bat.submit(np.arange(1, 6, dtype=np.int32), 4)
            bat.run()
            grew = sentinel.compiles("continuous.step_chunk") - entries
            if grew:
                errors.append(f"tp{tp}: churn compiled {grew} variants")
            bat.close()
        extras["kv_bytes_logical"] = int(
            kv_pd[1]
        )  # tp=1 per-device == logical by construction
        ratio = kv_pd[1] / kv_pd[4]
        if errors:
            emit(
                "micro_tp_decode_kv_per_device_ratio", 0.0, "x", 0.0,
                error="; ".join(errors)[-300:], **extras,
            )
            return 0
        emit(
            "micro_tp_decode_kv_per_device_ratio",
            round(ratio, 4),
            "x",
            round(ratio - 1.0, 4),
            slots=slots,
            ticks=n_ticks,
            **extras,
        )
    except Exception as e:  # noqa: BLE001 — always one JSON line, rc 0
        emit("micro_tp_decode_kv_per_device_ratio", 0.0, "x", 0.0,
             error=str(e)[-300:])
    return 0


if __name__ == "__main__":
    sys.exit(main())
