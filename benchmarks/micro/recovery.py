"""Elastic-recovery micro-benchmark: kill one simulated device of a
tp=4 serving mesh mid-stream and measure recovery-to-decode.

The ROADMAP pin this drives is the source paper's headline: "<2 s
recovery after one stage kill" — applied to the TENSOR-PARALLEL request
tier (the remote/pipeline tier has its own driver,
``benchmarks/recovery.py``). One run:

1. build a tp=4 ``ContinuousBatcher`` on the virtual CPU mesh, admit
   ``--slots`` requests, run ``--ticks`` steady ticks;
2. ``DeviceHealthMonitor.kill`` one mesh device and time
   **kill -> the first post-recovery tick returning** — detection,
   mesh rebuild (tp=4 -> tp=2), weight re-placement, the explicit
   KV redistribution plan (``parallel.sharding.KVReshardPlan``), AND
   the re-lowering compile of the shrunk decode program: the full
   recovery-to-serving wall;
3. drain, and compare every stream against an uninterrupted tp=4 run.

Reported records (multi-record driver; both gated in
``benchmarks/baselines/seed.json``):

- ``micro_recovery_wall_s`` — the kill->first-tick wall (the <2 s
  budget, sized for CPU re-compile cost; ``reshard_s`` extra carries
  the migration-only span from ``stats()``);
- ``micro_recovery_migrated`` — requests migrated live (STRUCTURAL:
  must equal the in-flight count; replayed/dropped must be 0 under
  the default migrate policy).

Any bit-identity violation or structural mismatch (tp != 2, books
wrong) becomes an ``error`` record the gate always fails.

Usage: ``python benchmarks/micro/recovery.py [--slots 3] [--ticks 2]``
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks.common import emit, force_cpu_mesh, int_flag  # noqa: E402

_NDEV = 4


def main() -> int:
    slots = int_flag(sys.argv, "--slots", 3)
    n_ticks = int_flag(sys.argv, "--ticks", 2)
    try:
        force_cpu_mesh(_NDEV)
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh

        from adapt_tpu.config import ParallelConfig
        from adapt_tpu.control.registry import DeviceHealthMonitor
        from adapt_tpu.models.transformer_lm import transformer_lm
        from adapt_tpu.runtime.continuous import ContinuousBatcher
        from adapt_tpu.utils.profiling import global_compile_sentinel

        lm = transformer_lm(61, 64, 2, 8, 128, max_len=128, kv_heads=4)
        variables = lm.graph.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
        )
        # The driver deliberately provokes legitimate compiles (two
        # batcher instances + the re-lowered post-recovery variants,
        # which recover() re-arms anyway); disarm the alarm so honest
        # runs don't bump engine.compile_events (tp_decode's rule).
        global_compile_sentinel().warmup_samples = 10**9
        rng = np.random.RandomState(0)
        prompts = [
            rng.randint(0, 61, size=4 + 2 * i).astype(np.int32)
            for i in range(slots)
        ]
        steps = [n_ticks * 8 + 24 + 4 * i for i in range(slots)]

        def run(kill: bool):
            mesh = Mesh(np.array(jax.devices()[:_NDEV]), ("tp",))
            mon = DeviceHealthMonitor()
            bat = ContinuousBatcher(
                lm, variables, slots=slots, chunk=8, mesh=mesh,
                parallel=ParallelConfig(tp=4), health=mon,
            )
            ids = [bat.submit(p, s) for p, s in zip(prompts, steps)]
            for _ in range(n_ticks):
                bat.tick()
            wall = None
            if kill:
                mon.kill(jax.devices()[_NDEV - 1])
                t0 = time.perf_counter()
                bat.tick()  # detect -> reshard -> decode on tp=2
                wall = time.perf_counter() - t0
            out = bat.run()
            st = bat.stats()
            bat.close()
            return [out[r] for r in ids], st, wall

        base, _, _ = run(False)
        got, st, wall = run(True)
        errors: list[str] = []
        for i, (a, b) in enumerate(zip(base, got)):
            if not np.array_equal(a, b):
                errors.append(f"req {i} diverged after recovery")
        if st["tp"] != 2:
            errors.append(f"tp after reshard: {st['tp']} != 2")
        if st["recoveries"] != 1:
            errors.append(f"recoveries {st['recoveries']} != 1")
        if st["recovery_replayed"] or st["recovery_dropped"]:
            errors.append(
                f"migrate policy replayed {st['recovery_replayed']} / "
                f"dropped {st['recovery_dropped']} (expected 0/0)"
            )
        if st["cache_bytes_per_device"] * 2 != st["cache_bytes"]:
            errors.append(
                f"per-device bytes {st['cache_bytes_per_device']} * 2 "
                f"!= logical {st['cache_bytes']}"
            )
        extras = {
            "migrated": st["recovery_migrated"],
            "replayed": st["recovery_replayed"],
            "dropped": st["recovery_dropped"],
            "reshard_s": round(st["last_recovery_wall_s"], 4),
            "tp_after": st["tp"],
            "slots": slots,
        }
        if errors:
            emit(
                "micro_recovery_wall_s", 0.0, "s", 0.0,
                error="; ".join(errors)[-300:], **extras,
            )
            return 0
        emit("micro_recovery_wall_s", wall, "s", wall, **extras)
        emit(
            "micro_recovery_migrated",
            float(st["recovery_migrated"]),
            "requests",
            0.0,
            slots=slots,
        )
    except Exception as e:  # noqa: BLE001 — always one JSON line, rc 0
        emit("micro_recovery_wall_s", 0.0, "s", 0.0, error=str(e)[-300:])
    return 0


if __name__ == "__main__":
    sys.exit(main())
