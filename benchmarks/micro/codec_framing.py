"""Framing-layer payload copies per codec pack/unpack.

The zero-copy framing contract (``comm/codec.py``): ``pack_frames``
returns ``[header, *payload views]`` for the scatter send — ZERO payload
copies; ``pack`` assembles one self-describing buffer — exactly ONE
payload copy (the old encode-``tobytes``-then-concat scheme paid TWO);
``unpack`` slices with memoryviews so the raw codec's decode returns an
array SHARING memory with the receive buffer.

Measured, not inferred: the framing layer counts every payload memcpy in
``codec.copy_stats()``; receive-side sharing is proven by mutating the
frame buffer and watching the decoded array change.

One JSON line: value = payload copies per ``pack`` (contract: 1.0),
``vs_baseline`` = old copies / new copies (contract: 2.0). Extra fields
carry the scatter-path count (contract: 0) and the per-codec breakdown.

Usage: ``python benchmarks/micro/codec_framing.py [--mb 4]``
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks.common import emit, int_flag  # noqa: E402

OLD_COPIES_PER_PACK = 2  # encode tobytes + header concat


def main() -> int:
    mb = int_flag(sys.argv, "--mb", 4)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import numpy as np

        from adapt_tpu.comm import codec as codec_lib

        x = np.random.RandomState(0).standard_normal(
            (mb * 256, 1024)
        ).astype(np.float32)  # mb MiB of f32 payload
        per_codec = {}
        for name in ("none", "bf16", "int8", "zfp", "lz"):
            c = codec_lib.get_codec(name)
            codec_lib.reset_copy_stats()
            frames = codec_lib.pack_frames(c, x)
            scatter = codec_lib.copy_stats()
            payload = codec_lib.frames_nbytes(frames) - len(frames[0])
            codec_lib.reset_copy_stats()
            buf = codec_lib.pack(c, x)
            packed = codec_lib.copy_stats()
            y = codec_lib.unpack(buf)
            assert y.shape == x.shape, name
            per_codec[name] = {
                "scatter_copies": scatter["calls"],
                "pack_copied_x": round(packed["bytes"] / max(payload, 1), 3),
            }
        # Receive-side zero copy: flip one payload byte in the raw frame
        # and the decoded array must see it (they share memory).
        raw = codec_lib.get_codec("none")
        buf = codec_lib.pack(raw, x)
        y = codec_lib.unpack(buf)
        buf[-x.itemsize] ^= 0xFF  # last element's first byte
        shares = float(y.flat[-1]) != float(x.flat[-1]) or bool(
            np.isnan(y.flat[-1])
        )
        pack_copies = max(
            v["pack_copied_x"] for v in per_codec.values()
        )
        scatter_copies = max(
            v["scatter_copies"] for v in per_codec.values()
        )
        emit(
            "micro_codec_pack_payload_copies",
            pack_copies,
            "copies/pack",
            OLD_COPIES_PER_PACK / max(pack_copies, 1e-9),
            old_copies=OLD_COPIES_PER_PACK,
            pack_frames_copies=scatter_copies,
            raw_unpack_shares_receive_buffer=bool(shares),
            payload_mib=mb,
            per_codec=per_codec,
        )
    except Exception as e:  # noqa: BLE001 — always one JSON line, rc 0
        emit("micro_codec_pack_payload_copies", 0.0, "copies/pack", 0.0,
             error=str(e)[-300:])
    return 0


if __name__ == "__main__":
    sys.exit(main())
