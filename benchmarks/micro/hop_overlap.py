"""Overlap vs serial SPMD pipeline schedule on a virtual CPU mesh.

The overlap schedule (``parallel/pipeline_spmd.py``) issues each rank's
``collective_permute`` hop inside the same scan step as the NEXT
microbatch's compute, with no data dependency between the two — on TPU,
XLA turns that into an async collective-permute start/done pair running
concurrently with compute, hiding hop latency (each tick costs
max(compute, hop) instead of compute + hop; "On Optimizing the
Communication of Model Parallelism", PAPERS.md).

What CPU can and cannot validate: the CPU backend runs collectives
synchronously, so the wall-clock ratio here only tracks the schedule's
extra ticks (T = M + (P−1)(hop_buffers) vs M + P − 1) — the latency win
is the TPU run's to show. What CPU DOES settle: both schedules produce
BIT-IDENTICAL outputs on the same inputs (also pinned by
``tests/test_parallel.py``), so flipping ``PipelineConfig.schedule`` on
the chip is a pure perf knob.

One JSON line: value = serial/overlap wall-clock ratio (CPU; ≈1 or
slightly below is expected here), extra fields carry tick counts and the
bitwise-equality verdict.

Usage: ``python benchmarks/micro/hop_overlap.py [--ranks 4] [--micro 8]
[--dim 128] [--hop-buffers 2]``
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks.common import emit, force_cpu_mesh, int_flag  # noqa: E402


def main() -> int:
    ranks = int_flag(sys.argv, "--ranks", 4)
    num_micro = int_flag(sys.argv, "--micro", 8)
    dim = int_flag(sys.argv, "--dim", 128)
    hop_buffers = int_flag(sys.argv, "--hop-buffers", 2)
    try:
        force_cpu_mesh(max(ranks, 2))
        import jax
        import jax.numpy as jnp
        import numpy as np
        from jax.sharding import Mesh

        from adapt_tpu.parallel.pipeline_spmd import (
            spmd_pipeline,
            stack_stage_params,
        )

        mesh = Mesh(np.array(jax.devices()[:ranks]), ("pp",))
        key = jax.random.PRNGKey(0)
        blocks = [
            jax.random.normal(jax.random.fold_in(key, i), (dim, dim))
            / np.sqrt(dim)
            for i in range(ranks)
        ]
        stacked = stack_stage_params(blocks)
        xs = jax.random.normal(
            jax.random.fold_in(key, 99), (num_micro, 16, dim)
        )

        def block_fn(p, x):
            return jnp.tanh(x @ p)

        def run(schedule):
            fn = jax.jit(
                lambda s, x: spmd_pipeline(
                    block_fn, s, x, mesh, schedule=schedule,
                    hop_buffers=hop_buffers,
                )
            )
            y = np.asarray(fn(stacked, xs))  # compile + warm
            t0 = time.perf_counter()
            trials = 10
            for i in range(trials):
                # distinct inputs defeat execution dedup (common.py)
                y = np.asarray(fn(stacked, xs + i * 1e-6))
            return y, (time.perf_counter() - t0) / trials

        y_serial, t_serial = run("serial")
        y_overlap, t_overlap = run("overlap")
        bit_identical = bool(
            np.array_equal(y_serial, y_overlap)
        )
        emit(
            "micro_hop_overlap_speedup",
            t_serial / t_overlap,
            "serial/overlap wall ratio",
            t_serial / t_overlap,
            bit_identical=bit_identical,
            ranks=ranks,
            microbatches=num_micro,
            hop_buffers=hop_buffers,
            ticks_serial=num_micro + ranks - 1,
            ticks_overlap=num_micro + (ranks - 1) * hop_buffers,
            t_serial_ms=round(t_serial * 1e3, 3),
            t_overlap_ms=round(t_overlap * 1e3, 3),
            platform=jax.devices()[0].platform,
        )
    except Exception as e:  # noqa: BLE001 — always one JSON line, rc 0
        emit("micro_hop_overlap_speedup", 0.0, "serial/overlap wall ratio",
             0.0, error=str(e)[-300:])
    return 0


if __name__ == "__main__":
    sys.exit(main())
