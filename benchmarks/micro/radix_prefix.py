"""Radix prefix cache + copy-on-write fan-out: structural checks and
the token-weighted radix-vs-whole-run keying gain.

Both claims here are DETERMINISTIC (structural counting, not wall
clock), so the records gate tight:

- ``micro_radix_hit_token_ratio`` — token-weighted prefix-cache hit
  mass under the radix probe over what WHOLE-RUN content keys would
  have scored, on a multi-turn conversation chain (each turn re-enters
  with the whole conversation so far plus fresh tokens). The
  counterfactual is computed with the read-only ``prefix_cached``
  probe before every submit: whole-run keying credits a prompt only
  when ALL its full pages are resident (the grown re-entries score 0),
  the radix probe credits the longest resident prefix. The chain's
  arithmetic makes the ratio exact: radix credits every turn's
  resident prefix, whole-run credits only the final exact repeat.
- ``micro_radix_fanout_exact`` — 1.0 when every structural claim
  holds; any violation becomes an ``error`` record the gate always
  fails:
  (a) each grown turn's in-tick prefill is SUFFIX-ONLY — the
      ``prefill_tokens`` delta per admission equals prompt length
      minus the probe's matched tokens;
  (b) the pager's books agree with the driver's arithmetic
      (``radix_hit_tokens``, ``radix_partial_hits``);
  (c) ``submit_fanout(prompt, n)`` admits n greedy siblings at
      ~1x the shared prefix's pages: distinct in-use pages right
      after the group admits equal ``m + n * (pages0 - m)`` (m shared
      full pages, each sibling's private copy of the partial last
      page), with ``n - 1`` ``cow_forks`` booked — NOT n full page
      sets;
  (d) the pool partition stays exact mid-flight and after retire
      (``in_use + free == allocatable``; rc books balanced — zero
      pages in use once the group drains, no leaked group claims);
  (e) fan-out streams are bit-identical to each other and to n
      independent serial submits of the same prompt (greedy).

Usage: ``python benchmarks/micro/radix_prefix.py [--turns 4]``
"""

from __future__ import annotations

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks.common import emit, int_flag  # noqa: E402

PAGE = 8
POOL_PAGES = 64
STEPS = 4
GROW = 12  # tokens appended per conversation turn (reply + new turn)
FAN_N = 4
#: Fan-out prompt/steps sized so prompt + every decode token stays
#: inside the forked last page (18 + 4 = 22 <= 3 * PAGE): the page
#: cost is then exactly m + n private last-page copies for the WHOLE
#: run, with no per-sibling decode-tail allocations muddying the
#: ~1x-shared-prefix check mid-flight.
FAN_LEN = 2 * PAGE + 2
FAN_STEPS = 4

_METRICS = (
    ("micro_radix_hit_token_ratio",
     "x (token-weighted hit mass, radix / whole-run keying)"),
    ("micro_radix_fanout_exact", "bool"),
)


def _mk(lm, variables, slots):
    from adapt_tpu.runtime.continuous import ContinuousBatcher

    return ContinuousBatcher(
        lm, variables, slots=slots, chunk=4, kv_layout="paged",
        page_size=PAGE, pool_pages=POOL_PAGES,
    )


def _partition_ok(st) -> bool:
    # free already includes the evictable (rc=0 cached) pages.
    return st["pages_in_use"] + st["pages_free"] == st["pool_pages"] - 1


def main() -> int:
    turns = int_flag(sys.argv, "--turns", 4)
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np

        from adapt_tpu.models.transformer_lm import transformer_lm
        from adapt_tpu.utils.profiling import global_compile_sentinel

        # Several fresh batchers in one process: their first compiles
        # are legitimate — disarm the alarm (the kv_tiers rationale).
        global_compile_sentinel().warmup_samples = 10**9
        lm = transformer_lm(61, 32, 2, 2, 64, max_len=96)
        variables = lm.graph.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
        )
        errors: list[str] = []
        extras: dict = {}
        rng = np.random.RandomState(11)

        # --- multi-turn chain: radix vs whole-run token-weighted mass.
        bat = _mk(lm, variables, slots=2)
        prompt = rng.randint(0, 61, size=2 * PAGE + 4).astype(np.int32)
        chain = [prompt]
        for _ in range(turns - 1):
            grown = np.concatenate(
                [chain[-1],
                 rng.randint(0, 61, size=GROW).astype(np.int32)]
            )
            chain.append(grown.astype(np.int32))
        chain.append(chain[-1])  # exact repeat: both keyings credit it

        radix_tokens = 0
        wholerun_tokens = 0
        partials = 0
        for i, p in enumerate(chain):
            full_pages = (len(p) - 1) // PAGE
            cached = min(bat.prefix_cached(p), full_pages)
            radix_tokens += cached * PAGE
            if cached == full_pages:
                wholerun_tokens += cached * PAGE
            elif cached:
                partials += 1
            pf0 = bat.stats()["prefill_tokens"]
            rid = bat.submit(p, STEPS)
            stream = bat.run()[rid]
            pf = bat.stats()["prefill_tokens"] - pf0
            want_pf = len(p) - cached * PAGE
            if pf != want_pf:
                errors.append(
                    f"turn {i}: prefilled {pf} tokens, wanted the "
                    f"{want_pf}-token suffix (cached {cached} pages)"
                )
            if not _partition_ok(bat.stats()):
                errors.append(f"turn {i}: pool partition broke")
        st = bat.stats()
        if st["radix_hit_tokens"] != radix_tokens:
            errors.append(
                f"pager booked {st['radix_hit_tokens']} hit tokens, "
                f"driver counted {radix_tokens}"
            )
        if st["radix_partial_hits"] != partials:
            errors.append(
                f"pager booked {st['radix_partial_hits']} partial "
                f"hits, driver counted {partials}"
            )
        # Bit-identity: the warm repeat's stream vs a cold batcher's.
        ref = _mk(lm, variables, slots=2)
        r = ref.submit(chain[-1], STEPS)
        want = ref.run()[r]
        ref.close()
        rid = bat.submit(chain[-1], STEPS)
        got = bat.run()[rid]
        if not np.array_equal(got, want):
            errors.append("warm repeat stream diverged from cold run")
        # The bit-identity resubmit was one more full-page hit for BOTH
        # keyings — fold it into the driver arithmetic so the emitted
        # ratio covers every admission the batcher saw.
        full_pages = (len(chain[-1]) - 1) // PAGE
        radix_tokens += full_pages * PAGE
        wholerun_tokens += full_pages * PAGE
        extras["radix_hit_tokens"] = radix_tokens
        extras["wholerun_hit_tokens"] = wholerun_tokens
        extras["partial_hits"] = partials
        extras["radix_nodes"] = bat.stats()["radix_nodes"]
        bat.close()

        # --- copy-on-write fan-out: page cost, books, bit-identity.
        bat = _mk(lm, variables, slots=FAN_N)
        fp = rng.randint(0, 61, size=FAN_LEN).astype(np.int32)
        m = (len(fp) - 1) // PAGE  # shared full pages
        pages0 = m + 1  # pages one sibling's prompt occupies
        rids = bat.submit_fanout(fp, FAN_N, FAN_STEPS)
        if len(rids) != FAN_N:
            errors.append(f"submit_fanout returned {len(rids)} ids")
        # Tick until the whole group is admitted, checking the
        # partition at every boundary; then pin the page cost before
        # decode crosses into fresh pages.
        for _ in range(64):
            bat.tick()
            if not _partition_ok(bat.stats()):
                errors.append("fan-out: pool partition broke mid-flight")
                break
            if bat.stats()["active"] == FAN_N:
                break
        st = bat.stats()
        want_pages = m + FAN_N * (pages0 - m)
        if st["active"] == FAN_N and st["pages_in_use"] != want_pages:
            errors.append(
                f"fan-out width {FAN_N} holds {st['pages_in_use']} "
                f"pages, wanted ~1x shared prefix: {want_pages} "
                f"(naive would be {FAN_N * pages0})"
            )
        if st["cow_forks"] != FAN_N - 1:
            errors.append(
                f"{st['cow_forks']} cow forks for a width-{FAN_N} "
                f"greedy group (wanted {FAN_N - 1})"
            )
        streams = bat.run()
        fan_streams = [streams[r] for r in rids]
        st = bat.stats()
        if st["pages_in_use"] != 0 or st["fanout_groups"] != 0:
            errors.append(
                f"rc books unbalanced after retire: {st['pages_in_use']}"
                f" pages in use, {st['fanout_groups']} groups live"
            )
        if not _partition_ok(st):
            errors.append("fan-out: pool partition broke after retire")
        extras["fanout_pages_in_use"] = want_pages
        extras["cow_forks"] = st["cow_forks"]
        bat.close()
        # Serial reference: n independent submits, fresh batcher.
        ref = _mk(lm, variables, slots=FAN_N)
        ref_streams = []
        for _ in range(FAN_N):
            r = ref.submit(fp, FAN_STEPS)
            ref_streams.append(ref.run()[r])
        ref.close()
        for i, (a, b) in enumerate(zip(fan_streams, ref_streams)):
            if not np.array_equal(a, b):
                errors.append(
                    f"fan-out sibling {i} diverged from serial submit"
                )
                break

        if errors:
            err = "; ".join(errors)[-300:]
            for metric, unit in _METRICS:
                emit(metric, 0.0, unit, 0.0, error=err)
            return 0
        ratio = radix_tokens / max(wholerun_tokens, 1)
        emit(
            "micro_radix_hit_token_ratio",
            round(ratio, 4),
            _METRICS[0][1],
            round(ratio - 1.0, 4),
            turns=turns,
            **extras,
        )
        emit(
            "micro_radix_fanout_exact", 1.0, "bool", 0.0,
            fan_n=FAN_N, **extras,
        )
    except Exception as e:  # noqa: BLE001 — always one JSON line, rc 0
        for metric, unit in _METRICS:
            emit(metric, 0.0, unit, 0.0, error=str(e)[-300:])
    return 0


if __name__ == "__main__":
    sys.exit(main())
