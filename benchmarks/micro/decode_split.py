"""Flash-split decode + tree-draft verify micro bench: the ISSUE-12
kernel-push structural grid.

Two claims ride this driver:

1. **Flash-split decode is invariant-preserving.** The split kernels
   (``ops/decode_attention._decode_split_kernel`` + the paged/verify
   wrappers) change only the SCHEDULE of the KV stream — so a batcher
   running them (``KernelConfig(attn_impl="pallas", decode_split=s)``)
   must keep every hot-path contract: greedy streams BIT-IDENTICAL
   across split in {1, 2, 4} and vs the XLA oracle, 0 h2d per steady
   tick, and 0 compile growth across churn. The grid runs split x
   layout (dense/paged) x dtype (native/int8/int4) through the Pallas
   INTERPRETER on CPU — wall numbers are schedule-sanity only (the
   interpreter is orders of magnitude off hardware; the TPU win is the
   parallel split fan-out the partials + rescale combine buy), but the
   counters and the bit-identity are the same code path hardware runs.

2. **Tree drafts raise accepted tokens per verify pass beyond the
   chain ceiling.** At draft_k = 4 the chain's perfect-draft ceiling is
   5.0 committed tokens per target weight stream (``spec_tick``'s gated
   headline). ``SpeculativeConfig(tree_width=1)`` adds the draft's
   top-1 leaf for the post-chain position (harvested from logits the
   draft scan already computes — equal draft FLOPs per committed
   token) and the perfect-draft arm commits ``draft_k + 2`` = 6.0 per
   pass, gated ``> 5.0`` as ``micro_decode_split_tree_tokens_per_pass``.

Emits TWO gated records (one JSON line each):

- ``micro_decode_split_h2d_per_tick`` — worst h2d/steady-tick across
  the whole split grid (contract: exactly 0; any bit-identity or
  compile-growth violation becomes an ``error`` record the gate always
  fails);
- ``micro_decode_split_tree_tokens_per_pass`` — perfect-draft
  committed tokens per verify pass with tree_width=1.

Per-config tick walls and compile counts ride as extras.
``engine.mbu`` gating on the decode program stays PENDING the first
real TPU row (BENCH_r06+ probe rebuild): on CPU there is no honest
peak to divide by (``utils/profiling.roofline_peaks``).

Usage: ``python benchmarks/micro/decode_split.py [--ticks 3]``
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks.common import emit, int_flag  # noqa: E402


def main() -> int:
    n_ticks = int_flag(sys.argv, "--ticks", 3)
    slots = 2
    try:
        import jax
        import jax.numpy as jnp
        import numpy as np

        from adapt_tpu.config import KernelConfig, SpeculativeConfig
        from adapt_tpu.models.transformer_lm import transformer_lm
        from adapt_tpu.runtime.continuous import ContinuousBatcher
        from adapt_tpu.utils.profiling import global_compile_sentinel

        sentinel = global_compile_sentinel()
        sentinel.warmup_samples = 10**9  # this driver compiles a lot

        errors: list[str] = []
        extras: dict = {}

        # -- 1) split grid ---------------------------------------------
        # max_len chosen so BOTH layouts hit supported kernel blocks:
        # dense strips need cache_len % 256 == 0 (cache_len =
        # max_len + 1 -> max_len 255 at chunk granularity), paged pools
        # use 128-token pages. Requests outlive the measured window.
        steps = 2 * (n_ticks + 2) + 2
        chunk = 2
        rng = np.random.RandomState(0)
        prompts = [rng.randint(0, 41, size=5).astype(np.int32)
                   for _ in range(slots)]

        def run_grid(layout, dtype, split):
            max_len = 255 if layout == "dense" else 256
            lm = transformer_lm(41, 32, 2, 2, 64, max_len=max_len)
            variables = lm.graph.init(
                jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
            )
            kw: dict = dict(kv_cache_dtype=dtype, chunk=chunk)
            if layout == "paged":
                kw.update(kv_layout="paged", page_size=128,
                          pool_pages=slots * 3 + 1)
            kern = (
                None if split == "xla"
                else KernelConfig(attn_impl="pallas", decode_split=split)
            )
            bat = ContinuousBatcher(
                lm, variables, slots=slots, kernel=kern, **kw
            )
            ids = [bat.submit(p, steps) for p in prompts]
            bat.tick()
            bat.tick()
            h2d0 = bat.stats()["h2d_transfers"]
            t0 = time.perf_counter()
            for _ in range(n_ticks):
                bat.tick()
            wall = (time.perf_counter() - t0) * 1e3 / n_ticks
            h2d = (bat.stats()["h2d_transfers"] - h2d0) / n_ticks
            entries = sentinel.compiles("continuous.step_chunk")
            out = bat.run()
            grew = sentinel.compiles("continuous.step_chunk") - entries
            bat.close()
            return out, h2d, wall, grew

        worst_h2d = 0.0
        # Dense int8/int4 need cache_len % 1024 == 0 for the scale-tile
        # block — out of range for this tiny config, so the quantized
        # dense cells run the ORACLE fallback (dispatch-gauge territory,
        # not an error); the paged cells drive the quantized kernels.
        grid = (
            [("dense", "native"), ("paged", "native"),
             ("paged", "int8"), ("paged", "int4")]
        )
        for layout, dtype in grid:
            base = None
            for split in ("xla", 1, 2, 4):
                tag = f"{layout}_{dtype}_s{split}"
                out, h2d, wall, grew = run_grid(layout, dtype, split)
                extras[f"{tag}_tick_ms"] = round(wall, 3)
                extras[f"{tag}_h2d_per_tick"] = h2d
                worst_h2d = max(worst_h2d, h2d)
                if h2d != 0:
                    errors.append(f"{tag}: steady tick staged {h2d}")
                if grew:
                    errors.append(f"{tag}: churn compiled {grew}")
                if base is None:
                    base = out
                else:
                    for rid in out:
                        if not np.array_equal(out[rid], base[rid]):
                            errors.append(
                                f"{tag}: stream diverged from the "
                                f"{layout}/{dtype} baseline"
                            )
                            break

        # -- 2) tree-draft acceptance ----------------------------------
        lm = transformer_lm(41, 32, 2, 2, 64, max_len=192)
        variables = lm.graph.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
        )
        per_pass = {}
        for name, w in (("chain", 0), ("tree", 1)):
            bat = ContinuousBatcher(
                lm, variables, slots=slots, draft_lm=lm,
                draft_variables=variables,
                speculative=SpeculativeConfig(draft_k=4, tree_width=w),
            )
            for p in prompts:
                bat.submit(p, 150)
            bat.tick()
            bat.tick()
            e0 = sum(len(s.tokens) for s in bat.slots
                     if s.req is not None)
            rounds = 5
            for _ in range(rounds):
                bat.tick()
            e1 = sum(len(s.tokens) for s in bat.slots
                     if s.req is not None)
            per_pass[name] = (e1 - e0) / (rounds * slots)
            extras[f"{name}_tokens_per_pass"] = round(per_pass[name], 3)
            bat.close()
        if per_pass["tree"] <= per_pass["chain"]:
            errors.append(
                f"tree {per_pass['tree']} did not beat chain "
                f"{per_pass['chain']} on the perfect-draft arm"
            )

        if errors:
            err = "; ".join(errors)[-300:]
            emit("micro_decode_split_h2d_per_tick", 1.0,
                 "transfers/tick", 0.0, error=err, **extras)
            emit("micro_decode_split_tree_tokens_per_pass", 0.0,
                 "tokens/pass", 0.0, error=err)
            return 0
        emit(
            "micro_decode_split_h2d_per_tick",
            worst_h2d,
            "transfers/tick",
            0.0,
            ticks=n_ticks,
            slots=slots,
            **extras,
        )
        emit(
            "micro_decode_split_tree_tokens_per_pass",
            round(per_pass["tree"], 3),
            "tokens/pass",
            round(per_pass["tree"] - 5.0, 3),
            draft_k=4,
            tree_width=1,
        )
    except Exception as e:  # noqa: BLE001 — always one JSON line, rc 0
        emit("micro_decode_split_h2d_per_tick", 1.0, "transfers/tick",
             0.0, error=str(e)[-300:])
        emit("micro_decode_split_tree_tokens_per_pass", 0.0,
             "tokens/pass", 0.0, error=str(e)[-300:])
    return 0


if __name__ == "__main__":
    sys.exit(main())
