"""Tokens-per-weight-stream of the speculative serving tick.

Decode is bandwidth-bound: every verify pass streams the TARGET model's
weights once, so "committed tokens per verify pass" is the structural
speedup batched speculation buys (``runtime/continuous.py`` speculative
mode) — on TPU it converts directly into decode throughput; on this
CPU driver it is measured as a COUNTER (like the other micro benches),
alongside honest wall-clock numbers.

Scenarios, spanning the acceptance range:

- ``plain``     — the ordinary lockstep tick (chunk=1): 1 token per
                  weight stream per slot, the baseline by definition.
- ``perfect``   — draft IS the target (acceptance 1.0): the upper
                  bound, ``draft_k + 1`` tokens per stream.
- ``self_draft``— the target's own first 2 (of 4) blocks as the draft
                  (a truncated-self draft, the classic mid-acceptance
                  regime).
- ``adversarial`` — an independent tiny draft (acceptance ~1/vocab):
                  the floor, ~1 token per stream — speculation's
                  break-even-at-worst contract.

Each scenario fills all slots, reaches steady state, then measures N
ticks: committed tokens / verify passes, wall ms per committed token,
and host->device staging transfers per tick (the PR-1 contract: 0).

One JSON line: value = perfect-draft tokens-per-weight-stream,
``vs_baseline`` = value − 1.0 (the plain tick's ratio is 1 by
definition). Per-scenario numbers ride as extra fields.

Usage: ``python benchmarks/micro/spec_tick.py [--slots 4] [--ticks 12]
[--draft-k 4]``
"""

from __future__ import annotations

import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__)))))

from benchmarks.common import emit, int_flag  # noqa: E402


def _measure(bat, slots, n_ticks, steps):
    """Fill all slots, settle, then measure N steady-state ticks.
    Returns (tokens_per_pass, ms_per_token, h2d_per_tick, acceptance)."""
    import numpy as np

    rng = np.random.RandomState(0)
    for _ in range(slots):
        bat.submit(rng.randint(0, 37, size=6).astype(np.int32), steps)
    bat.tick()  # admissions + first round
    bat.tick()  # settle
    emitted0 = sum(len(s.tokens) for s in bat.slots if s.req is not None)
    h2d0 = bat.stats()["h2d_transfers"]
    t0 = time.perf_counter()
    for _ in range(n_ticks):
        bat.tick()
    wall = time.perf_counter() - t0
    emitted1 = sum(len(s.tokens) for s in bat.slots if s.req is not None)
    tokens = emitted1 - emitted0
    h2d = (bat.stats()["h2d_transfers"] - h2d0) / n_ticks
    # One verify pass (one target weight stream) per tick per measured
    # window; the plain tick's chunk=1 scan is likewise 1 stream/tick.
    per_pass = tokens / (n_ticks * slots)
    ms_tok = wall * 1e3 / max(tokens, 1)
    acc = bat.stats().get("spec_acceptance", None)
    return per_pass, ms_tok, h2d, acc


def main() -> int:
    slots = int_flag(sys.argv, "--slots", 4)
    n_ticks = int_flag(sys.argv, "--ticks", 12)
    draft_k = int_flag(sys.argv, "--draft-k", 4)
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    try:
        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp

        from adapt_tpu.config import SpeculativeConfig
        from adapt_tpu.models.transformer_lm import (
            lm_tiny,
            transformer_lm,
        )
        from adapt_tpu.runtime.continuous import ContinuousBatcher

        lm = lm_tiny(vocab=37, max_len=192)
        variables = lm.graph.init(
            jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
        )
        # Truncated-self draft: the target's own first 2 blocks (and
        # embed/head) — node names line up, so the variables slice
        # directly. Untrained weights, but the layer-prefix correlation
        # gives a genuine mid-range acceptance.
        self_draft = transformer_lm(37, 64, 2, 4, 128, 192,
                                    name="self_draft")
        self_vars = {
            k: variables[k]
            for k in ("embed", "decoder_block_0", "decoder_block_1",
                      "head")
        }
        adv = transformer_lm(37, 32, 2, 2, 64, 192, name="adv_draft")
        adv_vars = adv.graph.init(
            jax.random.PRNGKey(9), jnp.zeros((1, 4), jnp.int32)
        )
        steps = (n_ticks + 8) * (draft_k + 1)
        cfg = SpeculativeConfig(draft_k=draft_k)

        plain = ContinuousBatcher(lm, variables, slots=slots, chunk=1)
        results = {"plain": _measure(plain, slots, n_ticks, steps)}
        for name, d_lm, d_vars in (
            ("perfect", lm, variables),
            ("self_draft", self_draft, self_vars),
            ("adversarial", adv, adv_vars),
        ):
            bat = ContinuousBatcher(
                lm, variables, slots=slots, draft_lm=d_lm,
                draft_variables=d_vars, speculative=cfg,
            )
            results[name] = _measure(bat, slots, n_ticks, steps)

        extras = {}
        for name, (per_pass, ms_tok, h2d, acc) in results.items():
            extras[f"{name}_tokens_per_stream"] = round(per_pass, 3)
            extras[f"{name}_ms_per_token"] = round(ms_tok, 3)
            extras[f"{name}_h2d_per_tick"] = h2d
            if acc is not None:
                extras[f"{name}_acceptance"] = round(acc, 3)
        value = results["perfect"][0]
        emit(
            "micro_spec_tick_tokens_per_stream",
            round(value, 3),
            "tokens/target-weight-stream",
            round(value - results["plain"][0], 3),
            slots=slots,
            ticks=n_ticks,
            draft_k=draft_k,
            **extras,
        )
    except Exception as e:  # noqa: BLE001 — always one JSON line, rc 0
        emit("micro_spec_tick_tokens_per_stream", 0.0,
             "tokens/target-weight-stream", 0.0, error=str(e)[-300:])
    return 0


if __name__ == "__main__":
    sys.exit(main())
