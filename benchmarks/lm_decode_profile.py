"""Capture a jax.profiler trace of the cached decode loop on the chip.

VERDICT r4 #4 workflow: the short-context decode row sits at MBU 0.43
(0.32 at 2k) against the benchmark's own HBM ceiling, and the gap cannot
be attributed without a trace — layout? cache copies in the scan carry?
the LM-head matmul? per-step sampling? This driver runs the exact
``lm_decode.py`` workload under ``jax.profiler.trace`` and commits the
trace directory beside the round's artifacts (the r03 committed-trace
precedent, ``results/r03/trace/``).

The traced region is ONE warm ``generate()`` call (prefill + steps-token
scan): compile happens before tracing starts, so the trace is pure
execution — per-op time in the scan body is then readable in
tensorboard/xprof, and the biggest op's share of step time IS the gap
accounting.

Prints one JSON line: value = traced decode tokens/sec (sanity vs the
lm_decode row), plus the trace path.

Usage: ``python benchmarks/lm_decode_profile.py [--batch 8] [--steps 128]
[--prompt 64] [--maxlen 256] [--kv native|int8] [--out DIR]``
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.common import (  # noqa: E402  (imports no JAX)
    int_flag,
    out_path,
    run_child_json,
    str_flag,
)

VOCAB, DIM, DEPTH, HEADS, MLP = 50257, 768, 12, 12, 3072


def _child(
    batch: int, steps: int, prompt_len: int, max_len: int, kv: str, out: str
) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from adapt_tpu.models.transformer_lm import generate, transformer_lm

    lm = transformer_lm(
        VOCAB, DIM, DEPTH, HEADS, MLP, max_len=max_len, dtype=jnp.bfloat16
    )
    key = jax.random.PRNGKey(0)
    prompt = jax.random.randint(key, (batch, prompt_len), 0, VOCAB)
    variables = jax.jit(lm.graph.init)(jax.random.PRNGKey(1), prompt)
    variables = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16) if x.dtype == jnp.float32 else x,
        variables,
    )
    kv_dtype = "int8" if kv == "int8" else "native"

    def run(p):
        return np.asarray(
            generate(lm, variables, p, steps, kv_cache_dtype=kv_dtype)
        )

    run(prompt)  # compile + warm OUTSIDE the trace
    os.makedirs(out, exist_ok=True)
    with jax.profiler.trace(out):
        t0 = time.perf_counter()
        run((prompt + 1) % VOCAB)  # distinct input (tunnel dedup)
        dt = time.perf_counter() - t0
    print(
        json.dumps(
            {
                "metric": f"lm_decode_profile_bs{batch}_tokens_per_sec",
                "value": round(batch * steps / dt, 2),
                "unit": "tokens/sec",
                "vs_baseline": 1.0,
                "baseline": "sanity check vs the lm_decode row; the "
                "deliverable is the trace",
                "platform": jax.devices()[0].platform,
                "trace_dir": out,
                "config": f"prompt{prompt_len} steps{steps} "
                f"max_len{max_len} kv={kv_dtype}",
                "traced_s": round(dt, 4),
            }
        ),
        flush=True,
    )


def main() -> int:
    batch = int_flag(sys.argv, "--batch", 8)
    steps = int_flag(sys.argv, "--steps", 128)
    prompt_len = int_flag(sys.argv, "--prompt", 64)
    max_len = int_flag(sys.argv, "--maxlen", 256)
    kv = str_flag(sys.argv, "--kv", "native", choices=("native", "int8"))
    out = str_flag(sys.argv, "--out", out_path("trace_decode"))
    if "--child" in sys.argv:
        _child(batch, steps, prompt_len, max_len, kv, out)
        return 0
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--batch", str(batch), "--steps", str(steps),
           "--prompt", str(prompt_len), "--maxlen", str(max_len),
           "--kv", kv, "--out", out]
    return run_child_json(
        cmd,
        metric=f"lm_decode_profile_bs{batch}_tokens_per_sec",
        unit="tokens/sec",
        timeout_s=1500,
    )


if __name__ == "__main__":
    sys.exit(main())
