"""Speculative decoding mechanism bounds on the real chip.

With UNTRAINED weights a draft's acceptance rate is meaningless (it is a
property of trained model pairs), so this driver brackets the MECHANISM
instead of claiming an end-task speedup:

- ``--draft self``: the target drafts for itself — acceptance 1.0 by
  construction, the upper bound: every round emits draft_k+1 tokens for
  one big-model weight stream (plus the draft cost, here equal to the
  target's). The interesting number is tokens/sec vs plain generate().
- ``--draft tiny``: an independent 2-layer draft — acceptance ~0 on
  random weights, the lower bound: one token per round plus pure
  overhead. How much slower than generate() this is = the price of
  mis-speculation.

A trained pair lands between the bounds in proportion to its acceptance.
vs_baseline = speculative/vanilla tokens-per-sec. Artifact:
results/r04/speculative_decode.json (appended per run).

CPU caveat: with the tiny ``--cpu`` validation model, timings are
dominated by XLA-CPU loop/dispatch overheads and can exaggerate (or
invert) ratios — this repo has measured such inversions before
(benchmarks/README "Attention dispatch" caveat). The CPU rows validate
losslessness and the schedule; the TPU rows are the perf evidence.

Usage: ``python benchmarks/speculative_decode.py [--draft self|tiny]
[--k 4] [--steps 128] [--cpu]``
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.common import int_flag, out_path, str_flag  # noqa: E402  (no JAX)

VOCAB, DIM, DEPTH, HEADS, MLP = 50257, 768, 12, 12, 3072
PROMPT_LEN, MAX_LEN = 32, 256
OUT = out_path("speculative_decode.json")


def _child(draft_kind: str, k: int, steps: int, small: bool) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from adapt_tpu.models.speculative import speculative_generate
    from adapt_tpu.models.transformer_lm import generate, transformer_lm

    if small:
        lm = transformer_lm(512, 128, 4, 4, 512, max_len=MAX_LEN)
    else:
        lm = transformer_lm(
            VOCAB, DIM, DEPTH, HEADS, MLP, max_len=MAX_LEN,
            dtype=jnp.bfloat16,
        )
    prompt = jax.random.randint(
        jax.random.PRNGKey(0), (1, PROMPT_LEN), 0, lm.vocab
    )
    variables = jax.jit(lm.graph.init)(jax.random.PRNGKey(1), prompt)
    if draft_kind == "self":
        draft, dvars = lm, variables
    else:
        draft = transformer_lm(
            lm.vocab, 256, 2, 4, 1024, max_len=MAX_LEN, name="draft",
            dtype=jnp.bfloat16 if not small else jnp.float32,
        )
        dvars = jax.jit(draft.graph.init)(jax.random.PRNGKey(2), prompt)

    def timed(fn):
        fn(prompt)  # warm/compile
        t0 = time.perf_counter()
        out = fn((prompt + 1) % lm.vocab)
        return out, time.perf_counter() - t0

    van_out, van_s = timed(
        lambda p: np.asarray(generate(lm, variables, p, steps))
    )
    (spec_out, stats), spec_s = timed(
        lambda p: speculative_generate(
            lm, variables, p, steps, draft, dvars, draft_k=k,
            return_stats=True,
        )
    )
    # Losslessness holds exactly when the chunked verify and the
    # sequential decode produce bitwise-equal logits; XLA may reorder
    # bf16 reductions between the two shapes, so near-tie argmaxes can
    # flip on hardware. Report the count instead of crashing the
    # measurement — 0 is the expectation, nonzero is itself a finding.
    token_mismatches = int((van_out != spec_out).sum())
    van_tps = steps / van_s
    spec_tps = steps / spec_s
    print(
        json.dumps(
            {
                "metric": f"speculative_{draft_kind}_k{k}_tokens_per_sec",
                "value": round(spec_tps, 2),
                "unit": "tokens/sec",
                "vs_baseline": round(spec_tps / van_tps, 4),
                "baseline": f"plain generate() ({van_tps:.1f} tok/s); "
                "self-draft = acceptance-1.0 upper bound, tiny-draft = "
                "acceptance-0 overhead lower bound",
                "platform": jax.devices()[0].platform,
                "draft": draft_kind,
                "draft_k": k,
                "steps": steps,
                "acceptance": round(stats["acceptance"], 4),
                "rounds": stats["rounds"],
                "token_mismatches_vs_generate": token_mismatches,
            }
        ),
        flush=True,
    )


def main() -> int:
    draft_kind = str_flag(sys.argv, "--draft", "self", choices=("self", "tiny"))
    k = int_flag(sys.argv, "--k", 4)
    steps = int_flag(sys.argv, "--steps", 128)
    cpu = "--cpu" in sys.argv
    if "--child" in sys.argv:
        _child(draft_kind, k, steps, cpu)
        return 0
    env = dict(os.environ)
    if cpu:
        env.pop("PYTHONPATH", None)
        env["JAX_PLATFORMS"] = "cpu"
    metric = f"speculative_{draft_kind}_k{k}_tokens_per_sec"
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--draft", draft_kind, "--k", str(k), "--steps", str(steps)]
    if cpu:
        cmd.append("--cpu")
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=2400, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        record = None
        for ln in proc.stdout.splitlines():
            if ln.strip().startswith("{"):
                try:
                    record = json.loads(ln)
                    break
                except json.JSONDecodeError:
                    continue
        if proc.returncode != 0 or record is None:
            record = {"metric": metric, "value": 0.0, "unit": "tokens/sec",
                      "vs_baseline": 0.0,
                      "error": (proc.stderr or proc.stdout or "")[-300:]}
        elif not cpu and record.get("platform") == "cpu":
            record = {"metric": metric, "value": 0.0, "unit": "tokens/sec",
                      "vs_baseline": 0.0,
                      "error": "TPU run fell back to the CPU backend"}
    except subprocess.TimeoutExpired:
        record = {"metric": metric, "value": 0.0, "unit": "tokens/sec",
                  "vs_baseline": 0.0, "error": "child timed out"}
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    mode = "a" if os.path.exists(OUT) else "w"
    with open(OUT, mode) as f:
        json.dump(record, f)
        f.write("\n")
    print(json.dumps(record), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
