"""Compiled on-chip smoke of the decode-attention Pallas kernels.

The decode kernel's one Mosaic-lowering risk is the scale-tile reshape
((8, 128) chunk -> (1, 1024) score-column row); the paged kernel's is
the scalar-prefetched page-table index_map (PrefetchScalarGridSpec).
This driver runs both COMPILED on the real chip across their shape
classes (native/int8, MHA/GQA rows, scalar/per-row index, ragged,
paged) and checks each against the einsum oracle — the same checks
`tests/test_decode_attention.py` / `tests/test_paged.py` run in
interpreter mode. One JSON line; nonzero exit if any class fails to
compile or mismatches.

Usage: ``python benchmarks/decode_attn_smoke.py``
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.common import run_child_json  # noqa: E402


def _child() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from adapt_tpu.ops.decode_attention import (
        decode_attention,
        decode_attention_reference,
    )
    from adapt_tpu.ops.quantize import quantize_kv_vectors

    rng = jax.random.PRNGKey(0)
    cases = []

    def check(name, q, ck, cv, index, valid_from=None, tol=2e-3):
        ref = np.asarray(
            decode_attention_reference(q, ck, cv, index, valid_from)
        )
        out = np.asarray(
            decode_attention(q, ck, cv, index, valid_from, prefer="pallas")
        )
        err = float(np.max(np.abs(out - ref)))
        cases.append({"case": name, "max_err": err, "ok": err < tol})

    b, kvh, hd = 4, 12, 64
    for name, length, g, quantized, per_row, ragged in [
        ("native_mha_2k", 2048, 1, False, False, False),
        ("int8_mha_2k", 2048, 1, True, False, False),
        ("int8_gqa4_4k", 4096, 4, True, False, False),
        ("native_per_row_idx", 2048, 1, False, True, False),
        ("int8_ragged", 2048, 1, True, False, True),
        # The newly-eligible short-native shape class (block_k 256,
        # num_kv=1 grid) — its Mosaic lowering must prove itself here
        # before the queued headline-config A/B spends its slot on it.
        ("native_short_256", 256, 1, False, False, False),
        ("native_short_512_gqa4", 512, 4, False, False, False),
    ]:
        kq, kk, kv_ = jax.random.split(jax.random.fold_in(rng, length + g), 3)
        q = jax.random.normal(kq, (b, kvh, g, hd), jnp.float32)
        k = jax.random.normal(kk, (b, kvh, length, hd), jnp.float32)
        v = jax.random.normal(kv_, (b, kvh, length, hd), jnp.float32)
        ck, cv = (
            (quantize_kv_vectors(k), quantize_kv_vectors(v))
            if quantized
            else (k, v)
        )
        index = (
            jnp.asarray([7, length - 1, length // 2, 1023], jnp.int32)
            if per_row
            else jnp.asarray(length - 1, jnp.int32)
        )
        vf = (
            jnp.asarray([0, 900, 5, 300], jnp.int32) if ragged else None
        )
        check(name, q, ck, cv, index, vf)

    # Paged kernel: same bar against ITS oracle (gather + einsum).
    from adapt_tpu.ops.paged_attention import (
        paged_attention,
        paged_attention_reference,
    )

    kq, kk, kv_ = jax.random.split(jax.random.fold_in(rng, 77), 3)
    npages, page, pps = 40, 128, 8  # b*pps = 32 distinct non-trash pages
    q = jax.random.normal(kq, (b, kvh, 1, hd), jnp.float32)
    kp = jax.random.normal(kk, (npages, kvh, page, hd), jnp.float32)
    vp = jax.random.normal(kv_, (npages, kvh, page, hd), jnp.float32)
    perm = np.random.RandomState(0).permutation(npages - 1) + 1
    table = jnp.asarray(
        perm[: b * pps].reshape(b, pps), jnp.int32
    )
    index = jnp.asarray([1000, 513, 128, 17], jnp.int32)
    ref = np.asarray(paged_attention_reference(q, kp, vp, table, index))
    out = np.asarray(
        paged_attention(q, kp, vp, table, index, prefer="pallas")
    )
    err = float(np.max(np.abs(out - ref)))
    cases.append({"case": "paged_mha_8pages", "max_err": err,
                  "ok": err < 2e-3})

    # Chunk-query paged kernel (incremental prefill's per-row causal).
    from adapt_tpu.ops.paged_attention import (
        paged_chunk_attention,
        paged_chunk_attention_reference,
    )

    kq2 = jax.random.fold_in(rng, 99)
    chunkq = jax.random.normal(kq2, (1, kvh, 2 * 256, hd), jnp.float32)
    cpages = jnp.asarray([5, 9, 2, 11, 0, 0, 0, 0], jnp.int32)
    cref = np.asarray(
        paged_chunk_attention_reference(chunkq, kp, vp, cpages, 256, 256)
    )
    cout = np.asarray(
        paged_chunk_attention(
            chunkq, kp, vp, cpages, 256, 256, prefer="pallas"
        )
    )
    cerr = float(np.max(np.abs(cout - cref)))
    cases.append({"case": "paged_chunk_gqa2_pos256", "max_err": cerr,
                  "ok": cerr < 2e-3})

    # Banded streaming flash (windowed prefill at length): the band
    # mask + two-sided dead-block skip, compiled.
    from adapt_tpu.ops.attention import (
        attention_reference,
        flash_attention,
    )

    kq3, kk3, kv3 = jax.random.split(jax.random.fold_in(rng, 123), 3)
    wq = jax.random.normal(kq3, (1, 4, 2048, hd), jnp.float32)
    wk = jax.random.normal(kk3, (1, 4, 2048, hd), jnp.float32)
    wv = jax.random.normal(kv3, (1, 4, 2048, hd), jnp.float32)
    wref = np.asarray(
        attention_reference(wq, wk, wv, causal=True, window=512)
    )
    wout = np.asarray(
        flash_attention(wq, wk, wv, causal=True, window=512,
                        prefer="pallas")
    )
    werr = float(np.max(np.abs(wout - wref)))
    cases.append({"case": "banded_flash_2k_win512", "max_err": werr,
                  "ok": werr < 2e-3})

    ok = all(c["ok"] for c in cases)
    print(
        json.dumps(
            {
                "metric": "decode_attn_smoke_cases_ok",
                "value": sum(c["ok"] for c in cases),
                "unit": "cases",
                "vs_baseline": 1.0 if ok else 0.0,
                "platform": jax.devices()[0].platform,
                "device": str(jax.devices()[0]),
                "cases": cases,
            }
        ),
        flush=True,
    )
    if not ok:
        raise SystemExit(1)


def main() -> int:
    if "--child" in sys.argv:
        _child()
        return 0
    return run_child_json(
        [sys.executable, os.path.abspath(__file__), "--child"],
        metric="decode_attn_smoke_cases_ok",
        unit="cases",
        timeout_s=800,
    )


if __name__ == "__main__":
    sys.exit(main())
