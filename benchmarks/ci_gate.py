"""Automated perf-regression gate over the micro-benchmark suite.

The BENCH_r*.json trajectory was write-only: every round measured, nothing
compared. This driver closes the loop — it runs the micro-benchmark
drivers (each prints one JSON line in the ``benchmarks/common.emit``
contract), collects the records into one BENCH-style report, compares
each gated metric against a checked-in baseline with per-metric
tolerances, and exits nonzero listing every regressed metric (one
``REGRESSION:`` line on stderr per miss).

Baseline format (``benchmarks/baselines/seed.json``)::

    {
      "suite":   {"<driver>": ["--flag", "value", ...], ...},
      "metrics": {
        "<metric>": {
          "value": <baseline value>,
          "direction": "higher_better" | "lower_better",
          "rel_tol": <fraction of |value| allowed as slack, default 0>,
          "abs_tol": <absolute slack, default 0>
        }, ...
      }
    }

``suite`` names drivers under ``benchmarks/micro/`` (sans ``.py``) with
their args, so the baseline and the workload that produced it travel
together; a ``/`` in the name resolves under ``benchmarks/`` instead
(``"load/smoke"`` -> ``benchmarks/load/smoke.py``). A driver may emit
SEVERAL records — one JSON object per stdout line — and each gates
independently (the load smoke emits goodput AND attainment).
Comparison is ONE-SIDED: a metric only fails when it is worse
than ``value`` by more than ``abs_tol + |value| * rel_tol`` in its
direction — improvements never fail the gate (re-baseline with
``--write-baseline`` when they should become the new floor). A driver
error record (the drivers emit ``{"value": 0, "error": ...}`` instead of
crashing) or a missing metric is always a regression: a gate that can't
measure must fail loud, not pass quiet.

Usage::

    python benchmarks/ci_gate.py --baseline benchmarks/baselines/seed.json
    python benchmarks/ci_gate.py --baseline ... --out gate_report.json
    python benchmarks/ci_gate.py --baseline ... --write-baseline new.json

``scripts/tier1.sh --gate`` runs the tier-1 tests then this gate.
``compare()`` and ``main(argv, records=...)`` are importable for unit
tests (inject records, skip the suite run).
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: Per-driver wall clamp: a hung driver (TPU relay, runaway compile) must
#: fail the gate, not wedge CI.
DRIVER_TIMEOUT_S = 600.0

_DIRECTIONS = ("higher_better", "lower_better")


def run_suite(
    suite: dict[str, list[str]], timeout_s: float = DRIVER_TIMEOUT_S
) -> dict[str, dict]:
    """Run each micro driver; return {metric: record}. Drivers keep the
    always-one-JSON-line contract, so a crash/timeout becomes an error
    record under the driver's name (which compare() then fails)."""
    records: dict[str, dict] = {}
    for name, args in suite.items():
        # "/" in the suite name addresses a driver package outside
        # micro/ ("load/smoke" -> benchmarks/load/smoke.py).
        parts = name.split("/") if "/" in name else ["micro", name]
        path = os.path.join(REPO, "benchmarks", *parts) + ".py"
        cmd = [sys.executable, path, *[str(a) for a in args]]
        recs: list[dict] = []
        err = ""
        try:
            proc = subprocess.run(
                cmd,
                capture_output=True,
                text=True,
                timeout=timeout_s,
                cwd=REPO,
                env={**os.environ, "JAX_PLATFORMS": "cpu"},
            )
            # Multi-record contract: every parseable '{'-line is one
            # record (the load smoke gates two metrics from one run).
            for ln in proc.stdout.splitlines():
                ln = ln.strip()
                if ln.startswith("{"):
                    try:
                        recs.append(json.loads(ln))
                    except json.JSONDecodeError:
                        continue  # stray '{'-noise; keep scanning
            if not recs:
                err = (proc.stderr or proc.stdout or "").strip()[-300:]
        except subprocess.TimeoutExpired:
            err = f"driver timed out after {timeout_s:.0f}s"
        if not recs:
            recs = [{"metric": name, "value": 0.0, "error": err}]
        for rec in recs:
            records[str(rec.get("metric", name))] = rec
    return records


def compare(
    records: dict[str, dict], baseline_metrics: dict[str, dict]
) -> list[str]:
    """One line per regressed metric (empty = gate passes). ``records``
    maps metric name -> the driver's record (only ``value`` and an
    optional ``error`` are consulted)."""
    regressions: list[str] = []
    for metric in sorted(baseline_metrics):
        spec = baseline_metrics[metric]
        direction = spec.get("direction", "higher_better")
        if direction not in _DIRECTIONS:
            raise ValueError(
                f"{metric}: direction={direction!r}, expected one of "
                f"{_DIRECTIONS}"
            )
        rec = records.get(metric)
        if rec is None:
            # A crashed/hung driver is keyed by its DRIVER name (its
            # metric name was never printed): surface the captured
            # error text instead of a bare "missing".
            errs = "; ".join(
                f"{k}: {r['error']}"
                for k, r in sorted(records.items())
                if r.get("error") and k not in baseline_metrics
            )
            detail = f" (driver errors: {errs})" if errs else (
                " (gated metrics must be measured)"
            )
            regressions.append(
                f"{metric}: missing from the current run{detail}"
            )
            continue
        if rec.get("error"):
            regressions.append(f"{metric}: driver error: {rec['error']}")
            continue
        value = float(rec.get("value", 0.0))
        base = float(spec["value"])
        slack = float(spec.get("abs_tol", 0.0)) + abs(base) * float(
            spec.get("rel_tol", 0.0)
        )
        worse = (base - value) if direction == "higher_better" else (
            value - base
        )
        if worse > slack:
            regressions.append(
                f"{metric}: {value:g} vs baseline {base:g} "
                f"({direction}: worse by {worse:.4g} > tolerance "
                f"{slack:.4g})"
            )
    return regressions


def write_baseline(
    path: str, records: dict[str, dict], old: dict
) -> None:
    """Re-baseline from the current run: measured values replace the old
    ones, per-metric direction/tolerances (and the suite) carry over."""
    metrics = {}
    for metric, spec in old.get("metrics", {}).items():
        rec = records.get(metric)
        new_spec = dict(spec)
        if rec is not None and not rec.get("error"):
            new_spec["value"] = rec.get("value", spec["value"])
        metrics[metric] = new_spec
    out = {
        "description": old.get("description", "perf-regression baseline"),
        "suite": old.get("suite", {}),
        "metrics": metrics,
    }
    with open(path, "w", encoding="utf-8") as f:
        json.dump(out, f, indent=1, sort_keys=True)
        f.write("\n")


def main(argv: list[str] | None = None,
         records: dict[str, dict] | None = None) -> int:
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument(
        "--baseline",
        default=os.path.join(REPO, "benchmarks", "baselines", "seed.json"),
        help="checked-in baseline JSON (suite + per-metric tolerances)",
    )
    p.add_argument(
        "--out", default=None,
        help="also write the full gate report JSON here",
    )
    p.add_argument(
        "--write-baseline", default=None, metavar="PATH",
        help="write a re-baselined file from this run's values "
        "(tolerances carried over) — the gate still runs",
    )
    args = p.parse_args(argv)

    with open(args.baseline, encoding="utf-8") as f:
        baseline = json.load(f)
    if records is None:
        records = run_suite(baseline.get("suite", {}))
    regressions = compare(records, baseline.get("metrics", {}))
    report = {
        "metric": "ci_gate_regressions",
        "value": float(len(regressions)),
        "unit": "regressed metrics",
        "vs_baseline": 0.0 - len(regressions),
        "ok": not regressions,
        "baseline": args.baseline,
        "regressions": regressions,
        "results": records,
    }
    print(json.dumps(report), flush=True)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(report, f, indent=1)
            f.write("\n")
    if args.write_baseline:
        write_baseline(args.write_baseline, records, baseline)
    for line in regressions:
        print(f"REGRESSION: {line}", file=sys.stderr)
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
