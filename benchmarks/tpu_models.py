"""Single-chip TPU throughput for the non-headline model families.

BASELINE.md configs reference ResNet-50 (headline, repo-root ``bench.py``)
plus ViT-B/16 and EfficientNet-B4; this driver measures those two on the
real chip with the same timed region as ``bench.py``
(``benchmarks.common.measure_scan_throughput``: on-device ``lax.scan``
with a data-dependent carry, timed around a host fetch — see bench.py's
docstring for why a host-side dispatch loop over-reports in this image)
and the same robustness contract: the parent imports no JAX, the
measurement runs in a subprocess under a hard timeout (backend init
through the TPU tunnel can HANG), and the driver always prints one JSON
line and exits 0.

Usage: ``python benchmarks/tpu_models.py --model vit_b16``
       ``python benchmarks/tpu_models.py --model efficientnet_b4``

vs_baseline compares against a single A100's framework-level fp16
throughput for the same model/batch (~1600 img/s ViT-B/16 bs=32,
~400 img/s EfficientNet-B4 bs=16 — same XLA/TF-class framing as
bench.py's ResNet-50 constant).
"""

from __future__ import annotations

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.common import (  # noqa: E402  (imports no JAX)
    int_flag,
    run_child_json,
    str_flag,
)

TPU_V5E_PEAK_FLOPS = 197e12  # bf16
#: v5e HBM bandwidth — the MBU denominator (the same 819 GB/s the
#: decode-MBU model in benchmarks/README.md uses). The serving tier's
#: roofline gauges (`adapt_tpu.utils.profiling.ROOFLINE_PEAKS`) mirror
#: this pair; keep them in sync.
TPU_V5E_PEAK_HBM_BYTES_S = 8.19e11

#: model -> (batch, fwd FLOPs/image (mul+add as 2, matching bench.py's
#: ResNet convention of 8.2e9 = 2 x 4.1 GMACs), A100 img/s baseline);
#: input h/w come from the model registry.
#:
#: ViT-B/16: the widely-quoted "17.6 GFLOPs" is the MAC count (paper
#: convention). Derivation at S=197, d=768, mlp=3072, 12 layers:
#: per layer QKV 197*768*2304 = 348.6M + scores+AV 2*12*197*197*64 =
#: 59.6M + out 197*768*768 = 116.2M + MLP 2*197*768*3072 = 929.7M
#: ~= 1.454 GMACs; x12 + patch embed 196*768*768 ~= 17.57 GMACs
#: -> 35.2e9 FLOPs at mul+add-as-2. (Rounds 1-3 used 17.6e9 here and
#: under-reported ViT MFU 2x — the "0.293 MFU" in r03 artifacts is
#: really 0.59, in line with ResNet's 0.575 batch-sweep peak.)
#: EfficientNet-B4: 8.8e9 = 2 x 4.4 GMACs (the paper's "4.2B FLOPs"
#: is likewise a MAC count) — already on the right convention.
MODELS = {
    "vit_b16": (32, 35.2e9, 1600.0),
    "efficientnet_b4": (16, 8.8e9, 400.0),
}


def _child(
    model: str, batch: int, iters: int, trials: int, attn: str | None,
    resident: str | None,
) -> None:
    import jax
    import jax.numpy as jnp

    from adapt_tpu.models import MODEL_REGISTRY
    from benchmarks.common import measure_scan_throughput

    _, flops, a100 = MODELS[model]
    factory, (h, w, c) = MODEL_REGISTRY[model]
    kwargs = {"attn_prefer": attn} if attn else {}
    graph = factory(num_classes=1000, dtype=jnp.bfloat16, **kwargs)
    x0 = jax.random.normal(
        jax.random.PRNGKey(0), (batch, h, w, c), jnp.float32
    )
    images_per_sec, times = measure_scan_throughput(
        graph, x0, iters, trials,
        param_dtype="bfloat16" if resident == "bf16" else None,
    )
    record = {
        "metric": f"{model}_bs{batch}_images_per_sec_per_chip"
        + (f"_attn_{attn}" if attn else "")
        + (f"_res_{resident}" if resident else ""),
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(images_per_sec / a100, 4),
        "baseline": f"single A100 fp16 ~{a100:.0f} img/s (framework-level)",
        "platform": jax.devices()[0].platform,
        "device": str(jax.devices()[0]),
        "batch": batch,
        "iters": iters,
        "trials": trials,
        "trial_seconds": [round(t, 4) for t in times],
    }
    if record["platform"] != "cpu":
        record["mfu"] = round(images_per_sec * flops / TPU_V5E_PEAK_FLOPS, 4)
    print(json.dumps(record), flush=True)


def main() -> int:
    model = (
        sys.argv[sys.argv.index("--model") + 1]
        if "--model" in sys.argv
        else "vit_b16"
    )
    if model not in MODELS:
        print(json.dumps({"metric": f"{model}_images_per_sec_per_chip",
                          "value": 0.0, "unit": "images/sec",
                          "vs_baseline": 0.0,
                          "error": f"unknown model; have {sorted(MODELS)}"}))
        return 0
    default_batch = MODELS[model][0]
    batch = int_flag(sys.argv, "--batch", default_batch)
    iters = int_flag(sys.argv, "--iters", 50)
    trials = int_flag(sys.argv, "--trials", 5)
    # End-to-end attention A/B knob (vit only): force "pallas" or "xla";
    # default "" follows ops.attention's measured dispatch.
    attn = str_flag(sys.argv, "--attn", "", choices=("", "pallas", "xla"))
    # bf16-RESIDENT weights (vs flax's default f32 residency + per-use
    # cast): halves the weight bytes each iteration streams.
    resident = str_flag(sys.argv, "--resident", "", choices=("", "bf16"))
    if attn and model != "vit_b16":
        print(json.dumps({"metric": f"{model}_bs{batch}_images_per_sec_per_chip"
                                    f"_attn_{attn}",
                          "value": 0.0, "unit": "images/sec",
                          "vs_baseline": 0.0,
                          "error": "--attn applies only to vit_b16 "
                                   "(the other models have no attention)"}))
        return 0
    if "--child" in sys.argv:
        _child(model, batch, iters, trials, attn or None, resident or None)
        return 0

    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--model", model, "--batch", str(batch),
           "--iters", str(iters), "--trials", str(trials)]
    if attn:
        cmd += ["--attn", attn]
    if resident:
        cmd += ["--resident", resident]
    return run_child_json(
        cmd,
        # Same suffixes the child uses on success, so a failed A/B run
        # emits its error row under the A/B metric, never the baseline's.
        metric=f"{model}_bs{batch}_images_per_sec_per_chip"
        + (f"_attn_{attn}" if attn else "")
        + (f"_res_{resident}" if resident else ""),
        unit="images/sec",
        timeout_s=900,
    )


if __name__ == "__main__":
    sys.exit(main())
