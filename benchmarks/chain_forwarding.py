"""Chain forwarding vs hub routing: the DCN-hop A/B.

Hub routing moves every stage boundary twice (worker→hub→worker: 2·S
transfers per request, SURVEY §3.2's critique of the reference Gen-2
topology); chain mode forwards activations worker→worker directly
(reference Gen-1, ``/root/reference/src/node.py:163-179``) so the hub
link carries only the final logits — S+1 data-plane transfers and no
hub NIC on the activation path.

Measured hermetically over localhost TCP (the reference's own test
affordance): 3 real worker processes serve ViT-tiny split in 3 stages;
the same request stream runs once hub-routed and once chained.
``vs_baseline`` = chain req/s ÷ hub req/s (>1 = direct hops win), and the
hub's measured result-frame bytes are reported for both modes — the
chained run's hub traffic must be exactly the final outputs.

CPU-backend by design: the topology cost being measured is
per-hop/transport, not device compute, and the TPU relay admits one
process at a time (the queue owns it). Artifact:
``results/<round>/chain_forwarding.json`` (append-only JSONL).

Usage: ``python benchmarks/chain_forwarding.py [--requests 64] [--batch 8]``
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.common import int_flag, out_path  # noqa: E402  (no JAX)

OUT = out_path("chain_forwarding.json")
PORTS = (17741, 17742, 17743)


def metric_name(n_stages: int) -> str:
    return f"chain_forward_{n_stages}stage_req_per_sec"


def _spawn_worker(port: int):
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["PYTHONPATH"] = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))
    )
    return subprocess.Popen(
        [
            sys.executable,
            "-m",
            "adapt_tpu.comm.remote",
            "--port",
            str(port),
            "--heartbeat",
            "0.2",
        ],
        env=env,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )


def _child(n_requests: int, batch: int) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from adapt_tpu.comm.remote import RemoteWorkerProxy
    from adapt_tpu.config import FaultConfig, ServeConfig
    from adapt_tpu.control.dispatcher import Dispatcher
    from adapt_tpu.graph import partition
    from adapt_tpu.models.vit import vit_block_cuts, vit_tiny

    g = vit_tiny()
    x = jnp.ones((batch, 32, 32, 3), jnp.float32)
    variables = g.init(jax.random.PRNGKey(0), x)
    cuts = vit_block_cuts(4, 3)
    plan = partition(g, cuts)
    y_ref = np.asarray(g.apply(variables, x))

    cfg = ServeConfig(
        fault=FaultConfig(
            lease_ttl_s=5.0,
            heartbeat_s=0.2,
            task_deadline_s=60.0,
            watchdog_period_s=0.5,
            startup_wait_s=20.0,
            configure_timeout_s=120.0,
        )
    )
    disp = Dispatcher(plan, variables, config=cfg)
    procs = [_spawn_worker(p) for p in PORTS]
    try:
        proxies = []
        for i, p in enumerate(PORTS):
            pr = RemoteWorkerProxy(
                f"chain-{i}",
                ("127.0.0.1", p),
                disp.registry,
                disp.result_queue,
                model_config={
                    "model": "vit_tiny",
                    "num_classes": 10,
                    "cuts": cuts,
                    "input_shape": [batch, 32, 32, 3],
                },
                fault=cfg.fault,
            )
            disp.attach_worker(pr)
            proxies.append(pr)
        disp.start()
        for pr in proxies:
            pr.start()
        # Pin each stage to its worker and pay every compile before either
        # timed phase (both modes then run the same warm executables).
        for i, pr in enumerate(proxies):
            pr.configure(i, None, plan.extract_variables(variables)[i])
        disp.serve_stream([x] * 3, timeout_per_request=120.0)

        def run(tag: str) -> tuple[float, int]:
            before = sum(p.result_bytes_received for p in proxies)
            t0 = time.perf_counter()
            outs = disp.serve_stream([x] * n_requests, 120.0)
            dt = time.perf_counter() - t0
            for y in outs:
                np.testing.assert_allclose(
                    np.asarray(y), y_ref, rtol=1e-5, atol=1e-5
                )
            return dt, sum(p.result_bytes_received for p in proxies) - before

        hub_s, hub_bytes = run("hub")
        disp.setup_chain([pr.worker_id for pr in proxies])
        disp.serve_stream([x] * 3, timeout_per_request=120.0)  # warm chain
        chain_s, chain_bytes = run("chain")
        assert disp._chain is not None, "chain fell back mid-measurement"

        hub_rps = n_requests / hub_s
        chain_rps = n_requests / chain_s
        print(
            json.dumps(
                {
                    "metric": metric_name(plan.num_stages),
                    "value": round(chain_rps, 2),
                    "unit": "req/sec",
                    "vs_baseline": round(chain_rps / hub_rps, 4),
                    "baseline": f"hub routing, same pool ({hub_rps:.1f} req/s)",
                    "platform": jax.devices()[0].platform,
                    "requests": n_requests,
                    "batch": batch,
                    "stages": plan.num_stages,
                    "hub_s": round(hub_s, 3),
                    "chain_s": round(chain_s, 3),
                    # Hub-link result-frame bytes: hub mode hauls every
                    # stage boundary; chain mode only the final logits.
                    "hub_result_bytes": hub_bytes,
                    "chain_result_bytes": chain_bytes,
                }
            ),
            flush=True,
        )
    finally:
        disp.shutdown()
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait(timeout=10)


def main() -> int:
    n_requests = int_flag(sys.argv, "--requests", 64)
    batch = int_flag(sys.argv, "--batch", 8)
    if "--child" in sys.argv:
        _child(n_requests, batch)
        return 0
    env = dict(os.environ)
    env.pop("PYTHONPATH", None)
    env["JAX_PLATFORMS"] = "cpu"
    metric = metric_name(3)
    cmd = [
        sys.executable,
        os.path.abspath(__file__),
        "--child",
        "--requests",
        str(n_requests),
        "--batch",
        str(batch),
    ]
    try:
        proc = subprocess.run(
            cmd,
            capture_output=True,
            text=True,
            timeout=1800,
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        record = None
        for ln in proc.stdout.splitlines():
            if ln.strip().startswith("{"):
                try:
                    record = json.loads(ln)
                    break
                except json.JSONDecodeError:
                    continue
        if proc.returncode != 0 or record is None:
            record = {
                "metric": metric,
                "value": 0.0,
                "unit": "req/sec",
                "vs_baseline": 0.0,
                "error": (proc.stderr or proc.stdout or "")[-300:],
            }
    except subprocess.TimeoutExpired:
        record = {
            "metric": metric,
            "value": 0.0,
            "unit": "req/sec",
            "vs_baseline": 0.0,
            "error": "child timed out",
        }
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    mode = "a" if os.path.exists(OUT) else "w"
    with open(OUT, mode) as f:
        json.dump(record, f)
        f.write("\n")
    print(json.dumps(record), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
