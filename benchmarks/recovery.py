"""Recovery-to-serve benchmark: kill one stage worker mid-stream.

BASELINE.md config 5 ("ViT encoder split by transformer block,
kill-one-stage fault-injection") and the second headline target:
recovery-to-serve < 2 s after one node kill.

Two configs:

  --config vit-tiny          4-stage ViT-tiny (control-plane floor: stage
                             weights are KB-scale, so the number isolates
                             detection + scheduling latency)
  --config resnet152-8stage  ResNet-152 in 8 balanced stages — the scale
                             BASELINE.md's <2 s budget was written for:
                             a failover re-bind pays a real multi-MB
                             stage-weight device_put, not a toy one

Runs on the virtual CPU mesh: recovery time is a *control-plane + weight
movement* metric, not an MXU metric, and only the CPU backend gives honest
``block_until_ready`` semantics in this image (see benchmarks/common.py).

Definition measured: from the moment a worker is killed (crash mode: the
exec loop dies and stops heartbeating — the reference's machine death)
until EVERY request that was in flight at kill time has completed
successfully. Crash detection is EVENT-driven: the dying exec loop
deregisters immediately (the reference evicts on socket error, not
timeout, ``/root/reference/src/dispatcher.py:153-161``); the lease TTL
remains as the backstop for the failure modes with no event (process
SIGKILL'd between instructions, network partition), so detect_s here
measures the event path, with the TTL as its ceiling.

Breakdown per trial (also written to ``--out`` as a JSON artifact):
  detect_s    kill -> membership 'leave' event (crash eviction; TTL
              expiry is the no-event backstop)
  rebind_s    kill -> first stage configure completed on a surviving worker
              after the kill (the weight device_put failover actually paid)
  total_s     kill -> all in-flight requests completed
  control_s   drain time of an identical burst with NO kill (same trial)
  overhead_s  (submit->done with kill) - control_s: what the kill actually
              cost end-to-end. On the CPU mesh total_s is dominated by
              re-running real stage compute on shared host cores; on
              per-stage TPU chips that replay is milliseconds, so
              detect+rebind+overhead is the hardware-transferable number.

Phase attribution (r4 verdict #8: one r04 trial carried overhead_s=2.6
against a <2 s budget with no diagnosis): each trial also records every
configure's (start, duration, worker, stage) after the kill, the
dispatcher counter deltas over the kill burst (redispatched / stale /
deadline strikes — was the overhead a replay storm?), accumulated GC
pause seconds inside the burst (was it the collector?), and the
completion watermarks' largest gap (was it ONE straggler request, e.g. a
second replay after a task deadline?). An outlier trial is then
attributable from the artifact alone instead of deserving a shrug.

Prints one JSON line; vs_baseline = 2.0 / median_total_s (>1 beats the
<2 s target).
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time

sys.path.insert(0, ".")  # repo root

from benchmarks.common import distinct_inputs, emit, force_cpu_mesh  # noqa: E402

TARGET_S = 2.0

CONFIGS = {
    # name: (n_devices, n_stages, burst, trials)
    "vit-tiny": (8, 4, 8, 4),
    # >= 10 trials: the overhead decomposition subtracts a same-trial
    # control burst whose noise on shared CPU cores is ~±0.3 s — enough
    # trials to bound it (r3's 3-trial run even produced one negative
    # overhead).
    "resnet152-8stage": (8, 8, 6, 10),
}


def _build(config: str):
    import jax

    if config == "vit-tiny":
        from adapt_tpu.models.vit import vit_tiny

        graph = vit_tiny()
        x0 = jax.numpy.ones((1, 32, 32, 3), jax.numpy.float32)
        cuts = [f"encoder_block_{i}" for i in range(1, CONFIGS[config][1])]
    else:
        from adapt_tpu.graph.partition import balanced_cuts
        from adapt_tpu.models.resnet import resnet152

        graph = resnet152(num_classes=1000, dtype=jax.numpy.float32)
        x0 = jax.numpy.ones((1, 224, 224, 3), jax.numpy.float32)
        cuts = balanced_cuts(graph, CONFIGS[config][1])
    return graph, x0, cuts


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", default="vit-tiny", choices=sorted(CONFIGS))
    parser.add_argument("--out", default=None, help="write per-trial JSON here")
    parser.add_argument(
        "--trials", type=int, default=None, help="override the config's trials"
    )
    args = parser.parse_args()
    n_devices, n_stages, burst, trials = CONFIGS[args.config]
    if args.trials is not None:
        trials = args.trials

    force_cpu_mesh(n_devices)
    import jax

    from adapt_tpu.config import FaultConfig, ServeConfig
    from adapt_tpu.control.worker import WorkerState
    from adapt_tpu.graph.partition import partition
    from adapt_tpu.runtime.pipeline import ServingPipeline

    graph, x0, cuts = _build(args.config)
    variables = jax.jit(graph.init)(jax.random.PRNGKey(0), x0)
    plan = partition(graph, cuts)

    # Production-shaped fault config: sub-second failure detection, the
    # task deadline safely above per-request latency (ResNet-152 stages on
    # CPU take real time per request).
    config = ServeConfig(
        max_inflight=burst * 2,
        fault=FaultConfig(
            lease_ttl_s=0.5,
            heartbeat_s=0.1,
            task_deadline_s=30.0,
            watchdog_period_s=0.05,
            startup_wait_s=10.0,
            max_retries=3,
            configure_timeout_s=120.0,
        ),
    )

    trials_out = []
    for trial in range(trials):
        pipe = ServingPipeline(
            plan, variables, devices=jax.devices()[:n_devices], config=config
        ).start()
        try:
            # Breakdown hooks: membership 'leave' times + configure
            # completion times (a configure after the kill = the failover
            # re-bind paying its weight transfer). ALL leaves are
            # recorded with (time, worker): under heavy host load a
            # healthy worker's heartbeat can starve past the TTL and
            # briefly lapse-then-rejoin, and grabbing that first
            # spurious leave instead of the victim's would corrupt
            # detect_s (observed: negative detects).
            events = {"leaves": [], "configures": []}

            def on_member(event, wid, _ev=events):
                if event == "leave":
                    _ev["leaves"].append((time.monotonic(), wid))

            pipe.registry.watch(on_member)
            for w in pipe.workers:
                orig = w.configure

                def timed(
                    *a, _orig=orig, _w=w, _ev=events, **kw
                ):
                    t_start = time.monotonic()
                    r = _orig(*a, **kw)
                    _ev["configures"].append(
                        (t_start, time.monotonic(), _w.worker_id, a[0])
                    )
                    return r

                w.configure = timed

            pipe.warmup(x0)
            # Control burst: identical load, no kill — isolates the cost
            # of the failure from the cost of the compute itself.
            xs_ctrl = distinct_inputs(
                jax.random.PRNGKey(500 + trial), x0.shape, burst
            )
            t_ctrl = time.monotonic()
            for f in [pipe.dispatcher.submit(x) for x in xs_ctrl]:
                f.result(timeout=300.0)
            control_s = time.monotonic() - t_ctrl

            xs = distinct_inputs(
                jax.random.PRNGKey(100 + trial), x0.shape, burst
            )
            # Phase-attribution hooks for THIS burst: GC pauses and
            # dispatcher counters over exactly the kill window.
            import gc

            gc_pause = {"s": 0.0, "t0": None}

            def on_gc(phase, info, _g=gc_pause):
                if phase == "start":
                    _g["t0"] = time.monotonic()
                elif _g["t0"] is not None:
                    _g["s"] += time.monotonic() - _g["t0"]
                    _g["t0"] = None

            gc.callbacks.append(on_gc)
            from adapt_tpu.utils.metrics import global_metrics

            counters_before = dict(
                global_metrics().snapshot()["counters"]
            )
            t_submit = time.monotonic()
            futures = [pipe.dispatcher.submit(x) for x in xs]
            # Pick a victim that is actually involved: busy or has queued
            # tasks, so its in-flight work must be detected and replayed.
            victim = None
            deadline = time.monotonic() + 10.0
            while victim is None and time.monotonic() < deadline:
                for w in pipe.workers:
                    if w.state is WorkerState.BUSY or w.queue_depth > 0:
                        victim = w
                        break
                time.sleep(0.001)  # don't contend with the mesh under test
            if victim is None:  # burst already drained; any configured worker
                victim = next(
                    w
                    for w in pipe.workers
                    if any(w.is_configured(s) for s in range(n_stages))
                )
            t0 = time.monotonic()
            victim.kill("crash")
            # Completion watermarks: result() in submit order gives a
            # non-decreasing drain curve; its largest gap fingers a
            # straggler (a request replayed late) vs uniform slowdown.
            watermarks = []
            for f in futures:
                f.result(timeout=300.0)
                watermarks.append(time.monotonic())
            t_done = time.monotonic()
            gc.callbacks.remove(on_gc)
            counters_after = global_metrics().snapshot()["counters"]
            total = t_done - t0
            detect = next(
                (
                    t - t0
                    for (t, wid) in events["leaves"]
                    if wid == victim.worker_id and t >= t0
                ),
                None,
            )
            post_kill = [
                (start, end, wid, stage)
                for (start, end, wid, stage) in events["configures"]
                if end > t0
            ]
            rebind = (
                (min(end for (_, end, _, _) in post_kill) - t0)
                if post_kill
                else None
            )
            deltas = {
                k: counters_after.get(k, 0) - counters_before.get(k, 0)
                for k in (
                    "dispatcher.redispatched",
                    "dispatcher.stale_results",
                    "dispatcher.tasks_sent",
                    "dispatcher.probes_ok",
                )
            }
            gaps = [
                b - a for a, b in zip(watermarks, watermarks[1:])
            ]
            trials_out.append(
                {
                    "trial": trial,
                    "victim": victim.worker_id,
                    "detect_s": detect,
                    "rebind_s": rebind,
                    "total_s": total,
                    "control_s": control_s,
                    "overhead_s": (t_done - t_submit) - control_s,
                    # -- phase attribution --
                    "post_kill_configures": [
                        {
                            "at_s": round(start - t0, 4),
                            "dur_s": round(end - start, 4),
                            "worker": wid,
                            "stage": stage,
                        }
                        for (start, end, wid, stage) in sorted(post_kill)
                    ],
                    "counter_deltas": deltas,
                    "gc_pause_s": round(gc_pause["s"], 4),
                    "max_completion_gap_s": round(max(gaps), 4)
                    if gaps
                    else 0.0,
                }
            )
        finally:
            pipe.shutdown()

    med = statistics.median(t["total_s"] for t in trials_out)
    artifact = {
        "config": args.config,
        "n_devices": n_devices,
        "n_stages": n_stages,
        "burst": burst,
        "backend": "cpu-virtual-mesh",
        "lease_ttl_s": config.fault.lease_ttl_s,
        "trials": trials_out,
        "median_total_s": med,
        "median_detect_s": statistics.median(
            t["detect_s"] for t in trials_out if t["detect_s"] is not None
        )
        if any(t["detect_s"] is not None for t in trials_out)
        else None,
        "median_overhead_s": statistics.median(
            t["overhead_s"] for t in trials_out
        ),
        "rebinds_observed": sum(
            1 for t in trials_out if t["rebind_s"] is not None
        ),
        "target_s": TARGET_S,
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(artifact, f, indent=1)
    emit(
        f"recovery_to_serve_{args.config}_s",
        med,
        "seconds",
        TARGET_S / med if med > 0 else float("inf"),
    )


if __name__ == "__main__":
    main()
