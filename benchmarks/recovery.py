"""Recovery-to-serve benchmark: kill one stage worker mid-stream.

BASELINE.md config 5 ("ViT encoder split by transformer block,
kill-one-stage fault-injection") and the second headline target:
recovery-to-serve < 2 s after one node kill.

Runs on the virtual CPU mesh: recovery time is a *control-plane* metric
(failure detection via lease expiry + re-bind + replay of retained
payloads), not a compute metric, and only the CPU backend gives honest
``block_until_ready`` semantics in this image (see benchmarks/common.py).

Definition measured here: from the moment a worker is killed (crash mode:
stops heartbeating AND swallows queued tasks — the reference's machine
death, detected only by lease expiry like etcd's ``/workers/<ip>``,
``/root/reference/src/node_state.py:16-20``) until EVERY request that was
in flight at kill time has completed successfully. That includes the
worst case: tasks sitting in the dead worker's queue must wait out the
lease TTL, be re-dispatched by the membership watcher, and re-run.

Prints one JSON line; vs_baseline = 2.0 / median_recovery_s (>1 beats the
<2 s target).
"""

from __future__ import annotations

import statistics
import sys
import time

sys.path.insert(0, ".")  # repo root

from benchmarks.common import distinct_inputs, emit, force_cpu_mesh  # noqa: E402

N_DEVICES = 8
N_STAGES = 4
BURST = 8
TRIALS = 4
TARGET_S = 2.0


def main() -> None:
    force_cpu_mesh(N_DEVICES)
    import jax

    from adapt_tpu.config import FaultConfig, ServeConfig
    from adapt_tpu.control.worker import WorkerState
    from adapt_tpu.graph.partition import partition
    from adapt_tpu.models.vit import vit_tiny
    from adapt_tpu.runtime.pipeline import ServingPipeline

    graph = vit_tiny()
    x0 = jax.numpy.ones((1, 32, 32, 3), jax.numpy.float32)
    variables = jax.jit(graph.init)(jax.random.PRNGKey(0), x0)
    cuts = [f"encoder_block_{i}" for i in range(1, N_STAGES)]
    plan = partition(graph, cuts)

    # Production-shaped fault config: sub-second failure detection, the
    # task deadline safely above per-request latency.
    config = ServeConfig(
        max_inflight=BURST * 2,
        fault=FaultConfig(
            lease_ttl_s=0.5,
            heartbeat_s=0.1,
            task_deadline_s=5.0,
            watchdog_period_s=0.05,
            startup_wait_s=5.0,
            max_retries=3,
            configure_timeout_s=30.0,
        ),
    )

    recoveries = []
    for trial in range(TRIALS):
        pipe = ServingPipeline(
            plan, variables, devices=jax.devices()[:N_DEVICES], config=config
        ).start()
        try:
            pipe.warmup(x0)
            xs = distinct_inputs(
                jax.random.PRNGKey(100 + trial), x0.shape, BURST
            )
            futures = [pipe.dispatcher.submit(x) for x in xs]
            # Pick a victim that is actually involved: busy or has queued
            # tasks, so its in-flight work must be detected and replayed.
            victim = None
            deadline = time.monotonic() + 5.0
            while victim is None and time.monotonic() < deadline:
                for w in pipe.workers:
                    if w.state is WorkerState.BUSY or w.queue_depth > 0:
                        victim = w
                        break
                time.sleep(0.001)  # don't contend with the mesh under test
            if victim is None:  # burst already drained; any configured worker
                victim = next(
                    w
                    for w in pipe.workers
                    if any(w.is_configured(s) for s in range(N_STAGES))
                )
            t0 = time.monotonic()
            victim.kill("crash")
            for f in futures:
                f.result(timeout=30.0)
            recoveries.append(time.monotonic() - t0)
        finally:
            pipe.shutdown()

    rec = statistics.median(recoveries)
    emit(
        "recovery_to_serve_after_kill_s",
        rec,
        "seconds",
        TARGET_S / rec if rec > 0 else float("inf"),
    )


if __name__ == "__main__":
    main()
