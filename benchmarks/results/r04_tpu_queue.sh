#!/bin/bash
# Round-4 TPU measurement queue — run the moment the relay recovers.
# Serial by design: NEVER two JAX processes through the relay at once.
# Each driver already guards itself (subprocess + hard timeout + one
# JSON line), so a relay re-outage mid-queue degrades to error rows,
# not hangs. Usage: bash benchmarks/r04_tpu_queue.sh
set -u
cd "$(dirname "$0")/../.."
OUT=benchmarks/results/r04
mkdir -p "$OUT"
log() { echo "=== $(date +%H:%M:%S) $*"; }

log "0. probe"
timeout 90 python -c "import jax; print(jax.devices())" || {
  echo "relay still down; aborting queue"; exit 1; }

log "1. headline bench.py (ResNet-50 bs=32)"
# Outer timeout strictly ABOVE the driver's own worst case (3 TPU
# attempts + backoffs), so its error-row handler always gets to run.
timeout 3600 python bench.py | tail -1 | tee "$OUT/bench_preview.json"

log "2. lm_decode default (bs8 steps128 prompt64 maxlen256)"
timeout 1800 python benchmarks/lm_decode.py | tail -1 \
  | tee "$OUT/lm_decode.json"

log "3. int8 KV A/B at long context (cache traffic rivals weights)"
timeout 1800 python benchmarks/lm_decode.py --prompt 1024 --maxlen 2048 \
  --steps 128 | tail -1 | tee "$OUT/lm_decode_long_native.json"
timeout 1800 python benchmarks/lm_decode.py --prompt 1024 --maxlen 2048 \
  --steps 128 --kv int8 | tail -1 | tee "$OUT/lm_decode_long_int8.json"

log "4. ViT-B/16 MFU push: batch x residency sweep"
for BS in 32 64 128; do
  timeout 1500 python benchmarks/tpu_models.py --model vit_b16 \
    --batch "$BS" | tail -1 | tee "$OUT/vit_b16_bs${BS}.json"
  timeout 1500 python benchmarks/tpu_models.py --model vit_b16 \
    --batch "$BS" --resident bf16 | tail -1 \
    | tee "$OUT/vit_b16_bs${BS}_res_bf16.json"
done

log "5. continuous batching at serving scale (GPT-2 width)"
timeout 2700 python benchmarks/continuous_serve.py --slots 8 \
  --requests 32 --chunk 16 | tail -1
# (driver writes results/r04/continuous_serve.json itself)

log "6. speculative decoding mechanism bounds (GPT-2 width)"
timeout 2700 python benchmarks/speculative_decode.py --draft self --k 4 \
  | tail -1
timeout 2700 python benchmarks/speculative_decode.py --draft tiny --k 4 \
  | tail -1
# (driver appends to results/r04/speculative_decode.json)

log "queue done"
