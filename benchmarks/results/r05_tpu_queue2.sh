#!/bin/bash
# Round-5 TPU queue, run 2 — evidence for the decode-MBU gap accounting
# (VERDICT r4 #4) + the remaining serving rows. Run AFTER r05_tpu_queue.sh.
# Serial by design: NEVER two JAX processes through the relay at once.
set -u
cd "$(dirname "$0")/../.."
OUT=benchmarks/results/r05
mkdir -p "$OUT"
log() { echo "=== $(date +%H:%M:%S) $*"; }
export JAX_COMPILATION_CACHE_DIR="$PWD/.jax_cache"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=0
export JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES=-1
export BENCH_ROUND=r05

log "1. decode trace: short context (the MBU 0.43 row's gap accounting)"
timeout 1800 python benchmarks/lm_decode_profile.py \
  | tail -1 | tee -a "$OUT/lm_decode_profile.json"

log "2. decode trace: 2k context (the MBU 0.32 row)"
timeout 1800 python benchmarks/lm_decode_profile.py --prompt 1024 \
  --maxlen 2048 --out "$OUT/trace_decode_2k" | tail -1 \
  | tee -a "$OUT/lm_decode_profile_2k.json"

log "2a. SHORT-context kernel A/B (native 256-cache newly eligible:"
log "    block_k 256) — the headline MBU-0.43 row through the kernel"
timeout 1800 python benchmarks/lm_decode.py --decode-attn pallas \
  | tail -1 | tee -a "$OUT/lm_decode_pallas.json"

log "2b. fixed-overhead separation for the MBU gap: same maxlen,"
log "    steps 128 vs 512 — marginal per-step cost = (t512-t128)/384."
log "    If marginal MBU >> headline MBU, the gap is per-CALL overhead"
log "    (relay dispatch + prefill), not the decode loop itself."
timeout 1800 python benchmarks/lm_decode.py --prompt 64 --maxlen 1024 \
  --steps 128 | tail -1 | tee -a "$OUT/lm_decode_m1024_s128.json"
timeout 1800 python benchmarks/lm_decode.py --prompt 64 --maxlen 1024 \
  --steps 512 | tail -1 | tee -a "$OUT/lm_decode_m1024_s512.json"

log "2c. decode-MBU ablation: measured streaming ceiling + per-component"
log "    cost + additivity residual (the arithmetic gap accounting)"
timeout 1800 python benchmarks/lm_decode_ablate.py | tail -1 \
  | tee -a "$OUT/lm_decode_ablate.json"
timeout 1800 python benchmarks/lm_decode_ablate.py --maxlen 2048 \
  --steps 32 | tail -1 | tee -a "$OUT/lm_decode_ablate_2k.json"

log "3. speculative decoding on-chip row"
timeout 1800 python benchmarks/speculative_decode.py | tail -1

log "queue2 done"
