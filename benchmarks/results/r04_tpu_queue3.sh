#!/bin/bash
# Round-4 TPU queue, run 3: decode-attention kernel A/B.
# 0) compiled smoke of the kernel on the chip (the scale-tile reshape is
#    the one Mosaic-lowering risk — fail fast, cheaply);
# 1) long-context A/B rows: native + int8 caches through the kernel, to
#    stand against run 1's XLA rows (lm_decode_long_{native,int8}.json);
# 2) a 4k-context pair where cache traffic dominates weights ~3:1.
# Serial by design: NEVER two JAX processes through the relay at once.
set -u
cd "$(dirname "$0")/../.."
OUT=benchmarks/results/r04
mkdir -p "$OUT"
log() { echo "=== $(date +%H:%M:%S) $*"; }

log "0. decode kernel compiled smoke (parity vs oracle on-chip)"
timeout 900 python benchmarks/decode_attn_smoke.py \
  | tail -1 | tee -a "$OUT/decode_attn_smoke.json"
# Gate on the LAST row (artifacts append — an old pass must not mask a
# fresh failure).
tail -1 "$OUT/decode_attn_smoke.json" | grep -q '"vs_baseline": 1.0' || {
  echo "decode kernel smoke FAILED on-chip; skipping the A/B"; exit 1; }

log "1. decode-attn A/B at 2k context (vs run 1's XLA rows)"
timeout 1800 python benchmarks/lm_decode.py --prompt 1024 --maxlen 2048 \
  --steps 128 --decode-attn pallas | tail -1 \
  | tee -a "$OUT/lm_decode_long_native_pallas.json"
timeout 1800 python benchmarks/lm_decode.py --prompt 1024 --maxlen 2048 \
  --steps 128 --kv int8 --decode-attn pallas | tail -1 \
  | tee -a "$OUT/lm_decode_long_int8_pallas.json"

log "2. 4k context: cache bytes ~3x weight bytes"
timeout 1800 python benchmarks/lm_decode.py --prompt 3072 --maxlen 4096 \
  --steps 128 | tail -1 | tee -a "$OUT/lm_decode_4k_native.json"
timeout 1800 python benchmarks/lm_decode.py --prompt 3072 --maxlen 4096 \
  --steps 128 --decode-attn pallas | tail -1 \
  | tee -a "$OUT/lm_decode_4k_native_pallas.json"
timeout 1800 python benchmarks/lm_decode.py --prompt 3072 --maxlen 4096 \
  --steps 128 --kv int8 | tail -1 | tee -a "$OUT/lm_decode_4k_int8.json"
timeout 1800 python benchmarks/lm_decode.py --prompt 3072 --maxlen 4096 \
  --steps 128 --kv int8 --decode-attn pallas | tail -1 \
  | tee -a "$OUT/lm_decode_4k_int8_pallas.json"

log "3. continuous batching at serving scale (retry; run 2 hit a relay error)"
timeout 2700 python benchmarks/continuous_serve.py --slots 8 \
  --requests 32 --chunk 16 | tail -1
# (driver appends a JSONL row to results/r04/continuous_serve.json)

log "4. paged layout A/B on the same serving workload (kernel path)"
timeout 2700 python benchmarks/continuous_serve.py --slots 8 \
  --requests 32 --chunk 16 --layout paged | tail -1

log "5. MoE decode: 8 experts top-2 at GPT-2 width (single-chip dense-EP)"
timeout 1800 python benchmarks/lm_decode.py --moe 8 | tail -1 \
  | tee -a "$OUT/lm_decode_moe8.json"

log "6. sliding-window decode at 4k context (vs step 2's full-attention rows)"
timeout 1800 python benchmarks/lm_decode.py --prompt 3072 --maxlen 4096 \
  --steps 128 --window 1024 | tail -1 \
  | tee -a "$OUT/lm_decode_4k_win1024.json"
timeout 1800 python benchmarks/lm_decode.py --prompt 3072 --maxlen 4096 \
  --steps 128 --window 1024 --decode-attn pallas | tail -1 \
  | tee -a "$OUT/lm_decode_4k_win1024_pallas.json"

log "7. prefill interference: chunked-prefill p99 shield at serving scale"
timeout 2700 python benchmarks/prefill_interference.py --long 1536 \
  --chunk 256 | tail -1

log "queue3 done"
