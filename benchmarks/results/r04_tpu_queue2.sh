#!/bin/bash
# Round-4 TPU queue, run 2: the tail that run 1's ViT bs>=64 relay
# hangs ate (queue items 4b-6), plus a solo headline recapture.
# Serial by design: NEVER two JAX processes through the relay at once.
set -u
cd "$(dirname "$0")/../.."
OUT=benchmarks/results/r04
mkdir -p "$OUT"
log() { echo "=== $(date +%H:%M:%S) $*"; }

log "0. probe"
timeout 90 python -c "import jax; print(jax.devices())" || {
  echo "relay still down; aborting queue"; exit 1; }

log "4b. ViT-B/16 bs 64/128 (timed out through the relay in run 1)"
for BS in 64 128; do
  timeout 1200 python benchmarks/tpu_models.py --model vit_b16 \
    --batch "$BS" | tail -1 | tee "$OUT/vit_b16_bs${BS}.json"
done

log "5. continuous batching at serving scale (GPT-2 width)"
timeout 2700 python benchmarks/continuous_serve.py --slots 8 \
  --requests 32 --chunk 16 | tail -1
# (driver writes results/r04/continuous_serve.json itself)

log "6. speculative decoding mechanism bounds (GPT-2 width)"
timeout 2700 python benchmarks/speculative_decode.py --draft self --k 4 \
  | tail -1
timeout 2700 python benchmarks/speculative_decode.py --draft tiny --k 4 \
  | tail -1
# (driver appends to results/r04/speculative_decode.json)

log "queue2 done"
