#!/bin/bash
# Relay-outage babysitter: probe the TPU relay every ~8 min and fire the
# given queue script the moment it answers. The probe is itself a JAX
# process through the relay, so this must only run while NOTHING else
# does (the serial rule). Gives up after MAX_TRIES probes.
# Usage: bash benchmarks/r04_tpu_wait_and_run.sh benchmarks/r04_tpu_queue3.sh
set -u
cd "$(dirname "$0")/../.."
QUEUE="${1:?queue script}"
MAX_TRIES="${2:-25}"
for i in $(seq 1 "$MAX_TRIES"); do
  echo "=== $(date +%H:%M:%S) probe $i/$MAX_TRIES"
  if timeout 120 python -c "import jax; print(jax.devices())"; then
    echo "=== $(date +%H:%M:%S) relay up -> running $QUEUE"
    bash "$QUEUE"
    exit $?
  fi
  sleep 480
done
echo "=== $(date +%H:%M:%S) relay never came back after $MAX_TRIES probes"
exit 1
