#!/bin/bash
# Round-5 TPU queue, run 1 — scoreboard-critical rows first (VERDICT r4
# #1/#2/#3/#8/#9). Serial by design: NEVER two JAX processes through the
# relay at once. Every child under its own timeout; artifacts append
# (JSONL) beside older rows, never over them.
set -u
cd "$(dirname "$0")/../.."
OUT=benchmarks/results/r05
mkdir -p "$OUT"
log() { echo "=== $(date +%H:%M:%S) $*"; }

# Persistent XLA compilation cache, shared with the driver-run bench.py:
# every compile this queue pays is one the driver's degraded-relay shot
# won't (VERDICT r4 #1).
export JAX_COMPILATION_CACHE_DIR="$PWD/.jax_cache"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=0
export JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES=-1
export BENCH_ROUND=r05

log "0. bench.py headline: seed the cache with BOTH child programs the"
log "   driver can run (tiny iters=10 first, then the full iters=100)"
timeout 600 python bench.py --child --platform tpu --iters 10 --trials 2 \
  | tail -1 | tee -a "$OUT/bench_preview.json"
timeout 900 python bench.py --child --platform tpu --iters 100 --trials 5 \
  | tail -1 | tee -a "$OUT/bench_preview.json"
# Gate the rest of the queue on the headline actually executing: if even
# bench.py can't run, every later row would burn its timeout too.
tail -1 "$OUT/bench_preview.json" | grep -q '"platform": "tpu"' || {
  echo "headline preview did not run on tpu; aborting queue"; exit 1; }

log "1. decode-attention kernel compiled smoke (gate for the A/B rows)"
timeout 900 python benchmarks/decode_attn_smoke.py \
  | tail -1 | tee -a "$OUT/decode_attn_smoke.json"
tail -1 "$OUT/decode_attn_smoke.json" | grep -q '"vs_baseline": 1.0' || {
  echo "decode kernel smoke FAILED on-chip; skipping kernel rows"; SKIP_PALLAS=1; }

log "2. decode A/B at 2k context: fresh XLA control + kernel rows"
timeout 1800 python benchmarks/lm_decode.py --prompt 1024 --maxlen 2048 \
  --steps 128 | tail -1 | tee -a "$OUT/lm_decode_long_native.json"
timeout 1800 python benchmarks/lm_decode.py --prompt 1024 --maxlen 2048 \
  --steps 128 --kv int8 | tail -1 | tee -a "$OUT/lm_decode_long_int8.json"
if [ -z "${SKIP_PALLAS:-}" ]; then
  timeout 1800 python benchmarks/lm_decode.py --prompt 1024 --maxlen 2048 \
    --steps 128 --decode-attn pallas | tail -1 \
    | tee -a "$OUT/lm_decode_long_native_pallas.json"
  timeout 1800 python benchmarks/lm_decode.py --prompt 1024 --maxlen 2048 \
    --steps 128 --kv int8 --decode-attn pallas | tail -1 \
    | tee -a "$OUT/lm_decode_long_int8_pallas.json"
fi

log "3. continuous batching at serving scale (GPT-2-small width, mixed mix)"
timeout 2700 python benchmarks/continuous_serve.py --slots 8 \
  --requests 32 --chunk 16 | tail -1
timeout 2700 python benchmarks/continuous_serve.py --slots 8 \
  --requests 32 --chunk 16 --layout paged | tail -1

log "4. short-context decode row (MBU baseline for this round's roofline work)"
timeout 1800 python benchmarks/lm_decode.py | tail -1 \
  | tee -a "$OUT/lm_decode.json"

log "5. ViT rows with the fixed mul+add-as-2 MFU accounting"
timeout 1200 python benchmarks/tpu_models.py --model vit_b16 --batch 32 \
  | tail -1 | tee -a "$OUT/vit_b16_bs32.json"
timeout 1200 python benchmarks/tpu_models.py --model vit_b16 --batch 64 \
  --resident bf16 | tail -1 | tee -a "$OUT/vit_b16_bs64_res_bf16.json"
timeout 1800 python benchmarks/tpu_models.py --model vit_b16 --batch 128 \
  | tail -1 | tee -a "$OUT/vit_b16_bs128.json"

log "6. MoE decode: 8 experts top-2 at GPT-2 width (single-chip dense-EP)"
timeout 1800 python benchmarks/lm_decode.py --moe 8 | tail -1 \
  | tee -a "$OUT/lm_decode_moe8.json"

log "7. sliding-window decode at 4k context"
timeout 1800 python benchmarks/lm_decode.py --prompt 3072 --maxlen 4096 \
  --steps 128 --window 1024 | tail -1 \
  | tee -a "$OUT/lm_decode_4k_win1024.json"
timeout 1800 python benchmarks/lm_decode.py --prompt 3072 --maxlen 4096 \
  --steps 128 | tail -1 | tee -a "$OUT/lm_decode_4k_native.json"
if [ -z "${SKIP_PALLAS:-}" ]; then
  timeout 1800 python benchmarks/lm_decode.py --prompt 3072 --maxlen 4096 \
    --steps 128 --decode-attn pallas | tail -1 \
    | tee -a "$OUT/lm_decode_4k_native_pallas.json"
  timeout 1800 python benchmarks/lm_decode.py --prompt 3072 --maxlen 4096 \
    --steps 128 --kv int8 --decode-attn pallas | tail -1 \
    | tee -a "$OUT/lm_decode_4k_int8_pallas.json"
fi

log "8. prefill interference: chunked-prefill p99 shield at serving scale"
timeout 2700 python benchmarks/prefill_interference.py --long 1536 \
  --chunk 256 | tail -1

log "queue1 done"
