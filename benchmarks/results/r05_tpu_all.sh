#!/bin/bash
# Round-5 combined queue: run 1 (scoreboard-critical) then run 2 (traces
# + MBU sweep). One serial stream through the relay.
cd "$(dirname "$0")"
bash r05_tpu_queue.sh
rc=$?
echo "=== queue1 exited rc=$rc; starting queue2"
bash r05_tpu_queue2.sh
