"""Continuous batching vs batch-synchronous serving.

The workload that motivates continuous batching: requests with VARIED
decode lengths. Batch-synchronous serving (``generate()`` on a full
batch) runs every row to the longest request's end — short requests
occupy dead slots (the convoy effect). The ContinuousBatcher admits the
next request the moment a slot frees.

Measured: total emitted tokens / wall seconds for N requests with decode
lengths drawn round-robin from a short/long mix, served (a) through
``ContinuousBatcher(slots=B)`` and (b) as ceil(N/B) batch-synchronous
``generate()`` rounds padded to each round's longest request (tokens
counted = requested tokens only, both sides). ``vs_baseline`` =
continuous/batch-synchronous tokens-per-sec (>1 means the slot recycling
beats the convoy).

Artifact: results/<round>/continuous_serve.json. Runs on the real chip by
default; ``--cpu`` validates the schedule on the host backend (and is
what CI-grade environments can run). Honest caveat on the CPU number:
with the tiny validation model a decode step is microseconds of real
compute, so per-chunk dispatch overhead dominates and batch-synchronous
fused scans still win (measured 0.83x at chunk=16; 0.42x at chunk=8) —
the convoy-effect thesis is for serving-scale models where a step is
real milliseconds, which only the TPU run can settle.

Usage: ``python benchmarks/continuous_serve.py [--slots 8]
[--requests 32] [--cpu]``
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.common import int_flag, out_path, str_flag  # noqa: E402  (no JAX)

VOCAB, DIM, DEPTH, HEADS, MLP = 50257, 768, 12, 12, 3072
PROMPT_LEN, MAX_LEN = 32, 256


def metric_name(slots: int, layout: str) -> str:
    """ONE metric-name builder for parent and child (the parent's
    error-row metric on child failure must equal the child's success
    metric — same rule as lm_decode.metric_suffix)."""
    suffix = "_paged" if layout == "paged" else ""
    return f"continuous_serve_slots{slots}{suffix}_tokens_per_sec"
STEP_MIX = (16, 96, 32, 128)  # short/long interleave — the convoy case
OUT = out_path("continuous_serve.json")


def _child(slots: int, n_requests: int, small: bool, chunk: int,
           layout: str) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from adapt_tpu.models.transformer_lm import generate, transformer_lm
    from adapt_tpu.runtime.continuous import ContinuousBatcher

    if small:  # CPU schedule validation: shrink the model, keep the mix
        lm = transformer_lm(512, 128, 4, 4, 512, max_len=MAX_LEN)
    else:
        lm = transformer_lm(
            VOCAB, DIM, DEPTH, HEADS, MLP, max_len=MAX_LEN,
            dtype=jnp.bfloat16,
        )
    key = jax.random.PRNGKey(0)
    vocab = lm.vocab
    prompts = [
        np.asarray(
            jax.random.randint(
                jax.random.fold_in(key, i), (PROMPT_LEN,), 0, vocab
            )
        )
        for i in range(n_requests)
    ]
    steps = [STEP_MIX[i % len(STEP_MIX)] for i in range(n_requests)]
    variables = jax.jit(lm.graph.init)(
        jax.random.PRNGKey(1), jnp.asarray(prompts[0])[None]
    )
    total_tokens = sum(steps)

    # -- continuous ------------------------------------------------------
    # layout="paged": the page-pool cache + scalar-prefetch kernels
    # (worst-case pool so the A/B vs the slot layout is throughput
    # apples-to-apples; capacity sizing is a separate knob). At this
    # workload's geometry (max_len 256, page 128) every request needs
    # its full 2 pages, so the interesting number is kernel-path
    # throughput vs the slot layout's einsum, on identical traffic.
    kw = (
        {"kv_layout": "paged", "page_size": 128}
        if layout == "paged"
        else {}
    )
    bat = ContinuousBatcher(lm, variables, slots=slots, chunk=chunk, **kw)
    cache_bytes = bat.stats()["cache_bytes"]
    # Warm the compiled pieces (bucket prefill + step) out of the timed
    # region, mirroring generate()'s warmup below.
    bat.submit(prompts[0], 2)
    bat.run()  # drains the warmup request; timed run starts empty
    prefill_tokens0 = bat.stats()["prefill_tokens"]
    t0 = time.perf_counter()
    for p, s in zip(prompts, steps):
        bat.submit(p, s)
    done = bat.run()
    cont_s = time.perf_counter() - t0
    assert len(done) == n_requests
    # Prefill/decode split: the headline tokens/sec blends decode
    # tokens over a wall that includes prefill work — these two fields
    # separate the rates (exactly the ratio disaggregated serving
    # changes; see docs/SERVING.md "Disaggregated prefill/decode").
    prefill_tokens = bat.stats()["prefill_tokens"] - prefill_tokens0

    # -- batch-synchronous rounds ---------------------------------------
    batch0 = jnp.stack([jnp.asarray(p) for p in prompts[:slots]])
    np.asarray(generate(lm, variables, batch0, 2))  # warm
    t0 = time.perf_counter()
    for lo in range(0, n_requests, slots):
        round_idxs = list(range(lo, min(lo + slots, n_requests)))
        batch = jnp.stack([jnp.asarray(prompts[i]) for i in round_idxs])
        np.asarray(
            generate(
                lm, variables, batch, max(steps[i] for i in round_idxs)
            )
        )
    sync_s = time.perf_counter() - t0

    cont_tps = total_tokens / cont_s
    sync_tps = total_tokens / sync_s
    print(
        json.dumps(
            {
                "metric": metric_name(slots, layout),
                "value": round(cont_tps, 2),
                "unit": "tokens/sec",
                "vs_baseline": round(cont_tps / sync_tps, 4),
                "baseline": "batch-synchronous generate() rounds on the "
                f"same workload ({sync_tps:.1f} tok/s useful tokens; "
                "rounds pad to their longest request)",
                "platform": jax.devices()[0].platform,
                "requests": n_requests,
                "slots": slots,
                "chunk": chunk,
                "kv_layout": layout,
                "cache_bytes": cache_bytes,
                "step_mix": list(STEP_MIX),
                "continuous_s": round(cont_s, 3),
                "batch_sync_s": round(sync_s, 3),
                "prefill_tokens_per_sec": round(
                    prefill_tokens / cont_s, 2
                ),
                "decode_tokens_per_sec": round(
                    total_tokens / cont_s, 2
                ),
            }
        ),
        flush=True,
    )


def main() -> int:
    slots = int_flag(sys.argv, "--slots", 8)
    n_requests = int_flag(sys.argv, "--requests", 32)
    chunk = int_flag(sys.argv, "--chunk", 8)
    layout = str_flag(sys.argv, "--layout", "slots",
                      choices=("slots", "paged"))
    cpu = "--cpu" in sys.argv
    if "--child" in sys.argv:
        _child(slots, n_requests, cpu, chunk, layout)
        return 0
    env = dict(os.environ)
    if cpu:
        env.pop("PYTHONPATH", None)
        env["JAX_PLATFORMS"] = "cpu"
    metric = metric_name(slots, layout)
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--slots", str(slots), "--requests", str(n_requests),
           "--chunk", str(chunk), "--layout", layout]
    if cpu:
        cmd.append("--cpu")
    try:
        proc = subprocess.run(
            cmd, capture_output=True, text=True, timeout=2400, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        record = None
        for ln in proc.stdout.splitlines():
            if ln.strip().startswith("{"):
                try:
                    record = json.loads(ln)
                    break
                except json.JSONDecodeError:
                    continue
        if proc.returncode != 0 or record is None:
            record = {"metric": metric, "value": 0.0, "unit": "tokens/sec",
                      "vs_baseline": 0.0,
                      "error": (proc.stderr or proc.stdout or "")[-300:]}
        elif not cpu and record.get("platform") == "cpu":
            record = {"metric": metric, "value": 0.0, "unit": "tokens/sec",
                      "vs_baseline": 0.0,
                      "error": "TPU run fell back to the CPU backend"}
    except subprocess.TimeoutExpired:
        record = {"metric": metric, "value": 0.0, "unit": "tokens/sec",
                  "vs_baseline": 0.0, "error": "child timed out"}
    os.makedirs(os.path.dirname(OUT), exist_ok=True)
    # Append (JSONL, one row per run) like speculative_decode.py: a
    # failed TPU attempt must land BESIDE earlier measurements, never
    # clobber them (r04 lesson: a relay error stub overwrote the only
    # CPU datapoint).
    mode = "a" if os.path.exists(OUT) else "w"
    with open(OUT, mode) as f:
        json.dump(record, f)
        f.write("\n")
    print(json.dumps(record), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
