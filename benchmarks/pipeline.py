"""Partitioned-pipeline throughput drivers: BASELINE.md configs 2-4.

  --config resnet50-3stage    ResNet-50 cut at conv3_block1/conv4_block1
                              into 3 stages (config 2)
  --config resnet152-8stage   ResNet-152, 8 balanced stages, int8
                              activation quantization at every hop
                              (config 3, the zfpy-style codec)
  --config effnetb4-dag       EfficientNet-B4, 8 balanced stages through
                              the multi-branch DAG (config 4)

Runs on the virtual CPU mesh (one device per stage) — the honest
multi-device environment this image has (the TPU tunnel exposes ONE chip
and over-reports async timing; see benchmarks/common.py). vs_baseline is
streamed pipeline req/s over single-device req/s on the same backend —
the A/B the reference runs by hand (``test/test.py`` vs
``test/local_infer.py``). NOTE: virtual CPU devices share one host's
cores, so unlike real per-stage chips there is no extra compute to win;
~1.0 is the ceiling and the number reads as "throughput retained while
paying all stage-boundary costs" (values >1 mean the pipeline's
cross-device overlap beats single-program XLA parallelism on this host).

Prints one JSON line.
"""

from __future__ import annotations

import argparse
import sys
import time

sys.path.insert(0, ".")  # repo root

from benchmarks.common import distinct_inputs, emit, force_cpu_mesh  # noqa: E402

REQUESTS = 12
BATCH = 1


def build(config: str):
    import jax.numpy as jnp

    from adapt_tpu.graph.partition import balanced_cuts

    if config == "resnet50-3stage":
        from adapt_tpu.models.resnet import resnet50

        graph = resnet50(num_classes=1000, dtype=jnp.float32)
        cuts = ["conv3_block1_out", "conv4_block1_out"]
        hop = None
    elif config == "resnet152-8stage":
        from adapt_tpu.models.resnet import resnet152

        graph = resnet152(num_classes=1000, dtype=jnp.float32)
        cuts = balanced_cuts(graph, 8)
        hop = _int8_hop()
    elif config == "effnetb4-dag":
        from adapt_tpu.models.efficientnet import efficientnet_b4

        graph = efficientnet_b4(num_classes=1000, dtype=jnp.float32)
        cuts = balanced_cuts(graph, 8)
        hop = None
    else:
        raise SystemExit(f"unknown --config {config!r}")
    return graph, cuts, hop


def _int8_hop():
    """Int8 quantization round-trip on every activation hop — what the
    reference pays with zfp+lz4 on every socket hop (``src/dispatcher.py:
    92-98``), expressed through the framework's own codec routing."""
    from adapt_tpu.config import CodecConfig
    from adapt_tpu.runtime.pipeline import codec_hop_transform

    return codec_hop_transform(CodecConfig(name="int8"))


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--config", default="resnet50-3stage")
    parser.add_argument("--requests", type=int, default=REQUESTS)
    parser.add_argument("--batch", type=int, default=BATCH)
    args = parser.parse_args()

    graph, cuts, hop = build(args.config)
    n_stages = len(cuts) + 1
    force_cpu_mesh(n_stages)
    import jax
    import numpy as np

    from adapt_tpu.graph.partition import partition
    from adapt_tpu.runtime.pipeline import LocalPipeline

    hw = 380 if args.config == "effnetb4-dag" else 224
    x0 = jax.numpy.ones((args.batch, hw, hw, 3), jax.numpy.float32)
    variables = jax.jit(graph.init)(jax.random.PRNGKey(0), x0)
    plan = partition(graph, cuts)
    pipe = LocalPipeline(
        plan, variables, devices=jax.devices()[:n_stages], hop_transform=hop
    )
    pipe.warmup(x0)
    xs = distinct_inputs(jax.random.PRNGKey(7), x0.shape, args.requests)

    outputs, dt = pipe.throughput(xs)
    assert len(outputs) == args.requests
    np.asarray(outputs[-1])
    pipeline_req_s = args.requests / dt

    # Single-device denominator (reference test/local_infer.py semantics).
    full = jax.jit(graph.apply)
    dev0 = jax.devices()[0]
    v0 = jax.device_put(variables, dev0)
    np.asarray(full(v0, jax.device_put(xs[0], dev0)))
    t0 = time.perf_counter()
    for x in xs:
        y = full(v0, jax.device_put(x, dev0))
    np.asarray(y)
    single_req_s = args.requests / (time.perf_counter() - t0)

    emit(
        f"{args.config}_pipeline_req_per_s",
        pipeline_req_s,
        "req/s",
        pipeline_req_s / single_req_s,
    )


if __name__ == "__main__":
    main()
