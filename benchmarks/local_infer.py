"""Single-device baseline: BASELINE.md config 1.

The reference's ``test/local_infer.py`` (``/root/reference/test/
local_infer.py:19-28``): ResNet-50, single device, `predict` loop,
req/s — the denominator every distributed number is compared against.
Here: one real TPU chip, jitted forward, batch=1 requests.

Same measurement methodology as the repo-root bench.py (on-device
lax.scan with a data-dependent carry, timed around a host fetch) because
the remote-execution tunnel dedups repeated dispatches and returns from
``block_until_ready`` early.

Prints one JSON line; vs_baseline shares bench.py's A100 denominator
(single-image requests underutilize any accelerator — this is the
latency-bound number, by design).
"""

from __future__ import annotations

import statistics
import sys
import time

sys.path.insert(0, ".")  # repo root

from benchmarks.common import emit  # noqa: E402

A100_IMAGES_PER_SEC = 3000.0
ITERS = 100
TRIALS = 3


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from adapt_tpu.models.resnet import resnet50

    graph = resnet50(num_classes=1000, dtype=jnp.bfloat16)
    x0 = jax.random.normal(jax.random.PRNGKey(0), (1, 224, 224, 3), jnp.float32)
    variables = jax.jit(graph.init)(jax.random.PRNGKey(0), x0)

    def bench_fn(variables, x):
        def body(x, _):
            y = graph.apply(variables, x)
            x = x * 0.999 + (jnp.mean(y) * 1e-6).astype(x.dtype)
            return x, y[0, 0]

        x, ys = lax.scan(body, x, None, length=ITERS)
        return jnp.mean(ys)

    fwd = jax.jit(bench_fn)
    np.asarray(fwd(variables, x0))  # compile + warm

    times = []
    for i in range(TRIALS):
        x_trial = x0 + (i + 1) * 1e-6  # distinct per trial (dedup)
        t0 = time.perf_counter()
        np.asarray(fwd(variables, x_trial))
        times.append(time.perf_counter() - t0)

    req_s = ITERS / statistics.median(times)
    emit(
        "local_infer_resnet50_bs1_req_per_s",
        req_s,
        "req/s",
        req_s / A100_IMAGES_PER_SEC,
        platform=jax.devices()[0].platform,
        device=str(jax.devices()[0]),
        batch=1,
        iters=ITERS,
        trials=TRIALS,
        trial_seconds=[round(t, 4) for t in times],
    )


if __name__ == "__main__":
    main()
