"""Decoder LM generation throughput on the real chip.

Beyond the reference's CNN configs (BASELINE.md): tokens/sec for the
KV-cache ``generate()`` loop of ``models/transformer_lm`` at a
GPT-2-small-ish width. ``vs_baseline`` is the model-bandwidth-utilization
(MBU): measured decode steps/sec divided by the bandwidth-bound ceiling
(HBM bytes/sec over bf16 param bytes — each decode step must stream every
weight once), the standard honesty metric for decode throughput. An
uncached full-forward-per-token comparator was tried and dropped: its
scan program (full 12-block forward per emitted token) would not finish
XLA compilation through this image's remote-compile relay in 25 minutes —
recorded here rather than silently shrunk.

Same robustness contract as ``bench.py``/``tpu_models.py``: parent
imports no JAX, child runs under a hard timeout, exactly one JSON line,
exit 0. The decode loop lives on-device (scan), timed around a host
fetch, with distinct prompts per trial (the tunnel dedups identical
dispatches).

``--kv int8`` runs the same measurement with the quantized KV cache
(``kv_cache_dtype="int8"``), the A/B that settles whether the cache
bandwidth claim (~2x fewer cache bytes than the native bf16 cache)
survives XLA's fusion of the dequant — measure at a long context
(``--prompt 1024 --maxlen 2048``) where cache traffic rivals weight
traffic, or the weights term hides the difference. The MBU denominator
counts weight bytes + per-step mean cache bytes actually resident, so
vs_baseline stays honest across cache dtypes.

``--decode-attn pallas`` swaps the per-step attention for the streaming
Pallas decode kernel (``ops/decode_attention``), which dequantizes int8
caches in VMEM — the A/B that decides ``decode_kernel_wins``'s measured
dispatch rule.

Usage: ``python benchmarks/lm_decode.py [--batch 8] [--steps 128]
[--prompt 64] [--maxlen 256] [--kv native|int8]
[--decode-attn auto|xla|pallas]``
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.common import (  # noqa: E402  (imports no JAX)
    int_flag,
    run_child_json,
    str_flag,
)

VOCAB, DIM, DEPTH, HEADS, MLP = 50257, 768, 12, 12, 3072
TPU_V5E_HBM_BYTES_PER_S = 819e9


def metric_suffix(kv: str, decode_attn: str, moe: int, window: int) -> str:
    """ONE metric-name builder for parent and child: the parent's
    error-row metric (on child failure) must equal the child's
    success-row metric or A/B rows fork across keys."""
    s = "_kv_int8" if kv == "int8" else ""
    if decode_attn != "auto":
        s += f"_attn_{decode_attn}"
    if moe > 0:
        s += f"_moe{moe}"
    if window > 0:
        s += f"_win{window}"
    return s


def _child(
    batch: int, steps: int, trials: int, prompt_len: int, max_len: int,
    kv: str, decode_attn: str, moe: int, window: int,
) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from adapt_tpu.models.transformer_lm import generate, transformer_lm

    # --moe E swaps every block's MLP for a dropless top-2 mixture of E
    # experts (models/moe.MoEDecoderMlp). Single chip = the dense-EP
    # degenerate case: every step streams ALL expert weights, so
    # param_bytes (and the MBU ceiling) below scale with E
    # automatically — the honest single-chip MoE number; the E/ep
    # division shows up only on a real ep mesh.
    # --window W bands attention Mistral-style: decode masks (and with
    # the Pallas decode path, compute-SKIPS) everything behind the
    # window — the A/B against the full-attention row shows what the
    # serving path buys at long context.
    lm = transformer_lm(
        VOCAB, DIM, DEPTH, HEADS, MLP, max_len=max_len,
        dtype=jnp.bfloat16,
        moe_experts=moe if moe > 0 else None,
        moe_top_k=2 if moe > 0 else 1,
        window=window if window > 0 else None,
    )
    key = jax.random.PRNGKey(0)
    prompt = jax.random.randint(key, (batch, prompt_len), 0, VOCAB)
    variables = jax.jit(lm.graph.init)(jax.random.PRNGKey(1), prompt)
    # Serving weights are bf16-resident (decode is bandwidth-bound; f32
    # residency would double the bytes every step streams). param_bytes
    # below counts actual itemsize, so the MBU denominator follows.
    variables = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16)
        if x.dtype == jnp.float32
        else x,
        variables,
    )

    def timed(fn, *args, trials=trials):
        np.asarray(fn(*args))  # compile + warm
        times = []
        for t in range(trials):
            p = (args[0] + t + 1) % VOCAB  # distinct prompt (dedup)
            t0 = time.perf_counter()
            np.asarray(fn(p, *args[1:]))
            times.append(time.perf_counter() - t0)
        return statistics.median(times)

    kv_dtype = "int8" if kv == "int8" else "native"
    attn = None if decode_attn == "auto" else decode_attn
    if attn == "pallas":
        # Ask the op itself (ONE source of truth for eligibility — a
        # re-encoded literal here drifted once already): decode_attention
        # silently serves the oracle when the cache length is not
        # kernel-eligible, and an A/B row labeled `_attn_pallas` that
        # actually measured XLA would corrupt the measured dispatch rule.
        from adapt_tpu.ops.decode_attention import (
            _supported,
            default_block_k,
        )

        q8 = kv_dtype == "int8"
        if not _supported(max_len, default_block_k(max_len, q8), q8):
            raise SystemExit(
                f"--decode-attn pallas: maxlen {max_len} with "
                f"kv={kv_dtype} is not kernel-eligible (native needs "
                "%256==0, int8 %1024==0): the kernel would fall back "
                "to XLA and the artifact label would lie"
            )
    cached_s = timed(
        lambda p: generate(
            lm, variables, p, steps, kv_cache_dtype=kv_dtype,
            decode_attn=attn,
        ),
        prompt,
    )
    cached_tok_s = batch * steps / cached_s

    # Bandwidth-bound ceiling: every decode step streams all params once
    # PLUS the K+V cache entries. Counting actual itemsize keeps the
    # weight term honest whatever the residency above is set to; the
    # cache term follows the cache dtype (bf16 native here; int8 stores
    # 1 byte/elem + one f32 scale per vector), evaluated at the padded
    # cache length the decode attention actually streams every step.
    param_bytes = sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(variables)
    )
    head_dim = DIM // HEADS
    vec_bytes = (
        head_dim * 1 + 4 if kv_dtype == "int8" else head_dim * 2
    )  # per K or V vector
    # Sliding window: the IDEAL per-step cache traffic is the window,
    # not the buffer — the ceiling must reflect it or the windowed
    # pallas row (whose kernel really does skip dead blocks) reports an
    # inflated MBU while the XLA row (which streams the whole buffer)
    # hides its overhead. One window-bounded ceiling keeps both honest:
    # the kernel approaches it, the einsum path shows the gap.
    eff_len = min(max_len, window) if window > 0 else max_len
    cache_bytes = 2 * DEPTH * batch * HEADS * eff_len * vec_bytes
    ceiling_steps_s = TPU_V5E_HBM_BYTES_PER_S / (param_bytes + cache_bytes)
    mbu = (cached_tok_s / batch) / ceiling_steps_s

    suffix = metric_suffix(kv_dtype, decode_attn, moe, window)
    print(
        json.dumps(
            {
                "metric": f"lm_decode_bs{batch}_tokens_per_sec{suffix}",
                "value": round(cached_tok_s, 2),
                "unit": "tokens/sec",
                "vs_baseline": round(mbu, 4),
                "baseline": "vs_baseline is MBU: measured decode steps/s "
                f"over the HBM-bandwidth ceiling ({ceiling_steps_s:.0f} "
                "steps/s for these param+cache bytes at 819 GB/s)",
                "platform": jax.devices()[0].platform,
                "device": str(jax.devices()[0]),
                "config": f"vocab{VOCAB} d{DIM} L{DEPTH} h{HEADS} "
                f"prompt{prompt_len} steps{steps} max_len{max_len} bf16 "
                f"kv={kv_dtype}"
                + (f" moe{moe}top2" if moe > 0 else "")
                + (f" window{window}" if window > 0 else ""),
                "param_bytes": param_bytes,
                "kv_cache_bytes": cache_bytes,
                "cached_s_per_trial": round(cached_s, 4),
            }
        ),
        flush=True,
    )


def main() -> int:
    batch = int_flag(sys.argv, "--batch", 8)
    steps = int_flag(sys.argv, "--steps", 128)
    trials = int_flag(sys.argv, "--trials", 3)
    prompt_len = int_flag(sys.argv, "--prompt", 64)
    max_len = int_flag(sys.argv, "--maxlen", 256)
    kv = str_flag(sys.argv, "--kv", "native", choices=("native", "int8"))
    decode_attn = str_flag(
        sys.argv, "--decode-attn", "auto", choices=("auto", "xla", "pallas")
    )
    moe = int_flag(sys.argv, "--moe", 0)
    window = int_flag(sys.argv, "--window", 0)
    if "--child" in sys.argv:
        _child(batch, steps, trials, prompt_len, max_len, kv, decode_attn,
               moe, window)
        return 0
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--batch", str(batch), "--steps", str(steps),
           "--trials", str(trials), "--prompt", str(prompt_len),
           "--maxlen", str(max_len), "--kv", kv,
           "--decode-attn", decode_attn, "--moe", str(moe),
           "--window", str(window)]
    suffix = metric_suffix(kv, decode_attn, moe, window)
    return run_child_json(
        cmd,
        metric=f"lm_decode_bs{batch}_tokens_per_sec{suffix}",
        unit="tokens/sec",
        timeout_s=1500,
    )


if __name__ == "__main__":
    sys.exit(main())
