"""Decoder LM generation throughput on the real chip.

Beyond the reference's CNN configs (BASELINE.md): tokens/sec for the
KV-cache ``generate()`` loop of ``models/transformer_lm`` at a
GPT-2-small-ish width. ``vs_baseline`` is the model-bandwidth-utilization
(MBU): measured decode steps/sec divided by the bandwidth-bound ceiling
(HBM bytes/sec over bf16 param bytes — each decode step must stream every
weight once), the standard honesty metric for decode throughput. An
uncached full-forward-per-token comparator was tried and dropped: its
scan program (full 12-block forward per emitted token) would not finish
XLA compilation through this image's remote-compile relay in 25 minutes —
recorded here rather than silently shrunk.

Same robustness contract as ``bench.py``/``tpu_models.py``: parent
imports no JAX, child runs under a hard timeout, exactly one JSON line,
exit 0. The decode loop lives on-device (scan), timed around a host
fetch, with distinct prompts per trial (the tunnel dedups identical
dispatches).

Usage: ``python benchmarks/lm_decode.py [--batch 8] [--steps 128]``
"""

from __future__ import annotations

import json
import os
import statistics
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from benchmarks.common import int_flag, run_child_json  # noqa: E402  (no JAX)

VOCAB, DIM, DEPTH, HEADS, MLP = 50257, 768, 12, 12, 3072
PROMPT_LEN, MAX_LEN = 64, 256
TPU_V5E_HBM_BYTES_PER_S = 819e9


def _child(batch: int, steps: int, trials: int) -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from adapt_tpu.models.transformer_lm import generate, transformer_lm

    lm = transformer_lm(
        VOCAB, DIM, DEPTH, HEADS, MLP, max_len=MAX_LEN, dtype=jnp.bfloat16
    )
    key = jax.random.PRNGKey(0)
    prompt = jax.random.randint(key, (batch, PROMPT_LEN), 0, VOCAB)
    variables = jax.jit(lm.graph.init)(jax.random.PRNGKey(1), prompt)
    # Serving weights are bf16-resident (decode is bandwidth-bound; f32
    # residency would double the bytes every step streams). param_bytes
    # below counts actual itemsize, so the MBU denominator follows.
    variables = jax.tree.map(
        lambda x: x.astype(jnp.bfloat16)
        if x.dtype == jnp.float32
        else x,
        variables,
    )

    def timed(fn, *args, trials=trials):
        np.asarray(fn(*args))  # compile + warm
        times = []
        for t in range(trials):
            p = (args[0] + t + 1) % VOCAB  # distinct prompt (dedup)
            t0 = time.perf_counter()
            np.asarray(fn(p, *args[1:]))
            times.append(time.perf_counter() - t0)
        return statistics.median(times)

    cached_s = timed(lambda p: generate(lm, variables, p, steps), prompt)
    cached_tok_s = batch * steps / cached_s

    # Bandwidth-bound ceiling: every decode step streams all params once.
    # Counting actual itemsize keeps the denominator honest whatever the
    # residency above is set to (bf16 after the cast; f32 if it's ever
    # removed).
    param_bytes = sum(
        x.size * x.dtype.itemsize for x in jax.tree.leaves(variables)
    )
    ceiling_steps_s = TPU_V5E_HBM_BYTES_PER_S / param_bytes
    mbu = (cached_tok_s / batch) / ceiling_steps_s

    print(
        json.dumps(
            {
                "metric": f"lm_decode_bs{batch}_tokens_per_sec",
                "value": round(cached_tok_s, 2),
                "unit": "tokens/sec",
                "vs_baseline": round(mbu, 4),
                "baseline": "vs_baseline is MBU: measured decode steps/s "
                f"over the HBM-bandwidth ceiling ({ceiling_steps_s:.0f} "
                "steps/s for these param bytes at 819 GB/s)",
                "platform": jax.devices()[0].platform,
                "device": str(jax.devices()[0]),
                "config": f"vocab{VOCAB} d{DIM} L{DEPTH} h{HEADS} "
                f"prompt{PROMPT_LEN} steps{steps} max_len{MAX_LEN} bf16",
                "param_bytes": param_bytes,
                "cached_s_per_trial": round(cached_s, 4),
            }
        ),
        flush=True,
    )


def main() -> int:
    batch = int_flag(sys.argv, "--batch", 8)
    steps = int_flag(sys.argv, "--steps", 128)
    trials = int_flag(sys.argv, "--trials", 3)
    if "--child" in sys.argv:
        _child(batch, steps, trials)
        return 0
    cmd = [sys.executable, os.path.abspath(__file__), "--child",
           "--batch", str(batch), "--steps", str(steps),
           "--trials", str(trials)]
    return run_child_json(
        cmd,
        metric=f"lm_decode_bs{batch}_tokens_per_sec",
        unit="tokens/sec",
        timeout_s=1500,
    )


if __name__ == "__main__":
    sys.exit(main())
