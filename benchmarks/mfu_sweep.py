"""Batch sweep + profile evidence for the headline MFU number.

VERDICT r2 weak #7: "MFU 0.478 is good, not proven optimal — no batch
sweep, no trace, no roofline argument." This driver runs the headline
bench (repo-root ``bench.py``, same scan methodology, same subprocess
isolation) at several batch sizes and writes one JSON artifact with the
full table, plus (best-effort) a ``jax.profiler`` trace of the winning
configuration. Run on the real chip; takes several minutes.

Usage: ``python benchmarks/mfu_sweep.py --out benchmarks/results/r03/mfu_sweep.json``
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
BATCHES = [16, 32, 64, 128, 256]
#: Must exceed bench.py's worst-case attempt schedule (2370s, see below).
PER_BATCH_TIMEOUT_S = 2700


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--out", required=True)
    p.add_argument("--trace-dir", default=None, help="profiler trace output")
    args = p.parse_args()

    rows = []
    for batch in BATCHES:
        t0 = time.time()
        # Timeout must exceed bench.py's own worst-case attempt schedule
        # (600s tpu + 30s + 420s retry + 300s backoff + 420s retry +
        # 600s cpu fallback = 2370s); a breach is recorded as a row,
        # never allowed to lose the sweep.
        try:
            proc = subprocess.run(
                [sys.executable, os.path.join(REPO, "bench.py"), "--batch", str(batch)],
                capture_output=True,
                text=True,
                timeout=PER_BATCH_TIMEOUT_S,
                cwd=REPO,
            )
            line = next(
                (
                    ln
                    for ln in proc.stdout.splitlines()
                    if ln.strip().startswith("{")
                ),
                None,
            )
            row = json.loads(line) if line else {"error": proc.stderr[-300:]}
        except subprocess.TimeoutExpired:
            row = {"error": f"sweep-level timeout ({PER_BATCH_TIMEOUT_S}s)"}
        row["batch"] = row.get("batch", batch)
        row["wall_s"] = round(time.time() - t0, 1)
        rows.append(row)
        print(f"bs={batch}: {row.get('value')} img/s mfu={row.get('mfu')}")

    best = max(
        (r for r in rows if r.get("platform") == "tpu"),
        key=lambda r: r.get("value", 0),
        default=None,
    )
    artifact = {
        "sweep": rows,
        "best": best,
        "methodology": "bench.py on-device lax.scan, data-dependent carry, "
        "median of trials; one subprocess per batch size",
    }
    if args.trace_dir and best is not None:
        artifact["trace"] = _trace(best["batch"], args.trace_dir)
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=1)
    print(json.dumps({"metric": "mfu_sweep_best_images_per_sec",
                      "value": best.get("value") if best else 0.0,
                      "unit": "images/sec",
                      "vs_baseline": best.get("vs_baseline") if best else 0.0}))


def _trace(batch: int, trace_dir: str) -> dict:
    """Best-effort jax.profiler trace of the headline forward at ``batch``
    (the TPU relay in this image may not support profiling; failure is
    recorded, not fatal)."""
    code = f"""
import sys, json
sys.path.insert(0, {REPO!r})
import jax, jax.numpy as jnp, numpy as np
from adapt_tpu.models.resnet import resnet50
graph = resnet50(num_classes=1000, dtype=jnp.bfloat16)
x = jax.random.normal(jax.random.PRNGKey(0), ({batch}, 224, 224, 3), jnp.float32)
variables = jax.jit(graph.init)(jax.random.PRNGKey(0), x)
fwd = jax.jit(graph.apply)
np.asarray(fwd(variables, x))  # warm
with jax.profiler.trace({trace_dir!r}):
    for _ in range(10):
        y = fwd(variables, x)
    y.block_until_ready()
print("TRACE_OK")
"""
    try:
        proc = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True,
            text=True,
            timeout=600,
            cwd=REPO,
        )
        ok = "TRACE_OK" in proc.stdout
        files = []
        for root, _, names in os.walk(trace_dir):
            files += [os.path.relpath(os.path.join(root, n), trace_dir) for n in names]
        return {"ok": ok, "dir": trace_dir, "files": files[:20],
                "note": None if ok else (proc.stderr or proc.stdout)[-300:]}
    except Exception as e:  # noqa: BLE001
        return {"ok": False, "note": str(e)[:300]}


if __name__ == "__main__":
    main()
