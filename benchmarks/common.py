"""Shared helpers for the benchmark drivers.

Every driver prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
matching the repo-root ``bench.py`` contract, so results are machine
comparable across configs (BASELINE.md "configs to reproduce").

Measurement caveat baked in here (see bench.py's module docstring for the
full story): under this image's remote-execution tunnel,
``jax.block_until_ready`` can return before execution completes and repeat
executions of identical (fn, args) are deduplicated. Honest wall-clock
therefore requires (a) distinct inputs per request and (b) timing around a
host fetch (``np.asarray``) of real outputs.
"""

from __future__ import annotations

import json


def force_cpu_mesh(n_devices: int) -> None:
    """Force an ``n_devices`` virtual CPU mesh (post-import safe). Thin
    wrapper over ``__graft_entry__._force_virtual_cpu`` — the drivers put
    the repo root on sys.path, so the one implementation is shared."""
    from __graft_entry__ import _force_virtual_cpu

    _force_virtual_cpu(n_devices)


def distinct_inputs(key, shape, n: int):
    """``n`` device-resident inputs, each unique (defeats execution dedup)."""
    import jax

    return [
        jax.device_put(jax.random.normal(jax.random.fold_in(key, i), shape))
        for i in range(n)
    ]


def emit(metric: str, value: float, unit: str, vs_baseline: float) -> None:
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(value, 4),
                "unit": unit,
                "vs_baseline": round(vs_baseline, 4),
            }
        )
    )
