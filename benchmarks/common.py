"""Shared helpers for the benchmark drivers.

Every driver prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
matching the repo-root ``bench.py`` contract, so results are machine
comparable across configs (BASELINE.md "configs to reproduce").

Measurement caveat baked in here (see bench.py's module docstring for the
full story): under this image's remote-execution tunnel,
``jax.block_until_ready`` can return before execution completes and repeat
executions of identical (fn, args) are deduplicated. Honest wall-clock
therefore requires (a) distinct inputs per request and (b) timing around a
host fetch (``np.asarray``) of real outputs.
"""

from __future__ import annotations

import json
import re


def force_cpu_mesh(n_devices: int) -> None:
    """Force an ``n_devices`` virtual CPU mesh (post-import safe). Same
    mechanism as ``__graft_entry__._force_virtual_cpu``; duplicated because
    benchmark drivers must stay runnable standalone from the repo root."""
    import os

    import jax

    flag = "--xla_force_host_platform_device_count"
    flags = os.environ.get("XLA_FLAGS", "")
    m = re.search(rf"{flag}=(\d+)", flags)
    if m is None:
        os.environ["XLA_FLAGS"] = f"{flags} {flag}={n_devices}".strip()
    elif int(m.group(1)) < n_devices:
        os.environ["XLA_FLAGS"] = re.sub(
            rf"{flag}=\d+", f"{flag}={n_devices}", flags
        )
    jax.config.update("jax_platforms", "cpu")
    devs = jax.devices()
    if len(devs) < n_devices:
        raise RuntimeError(
            f"could not get {n_devices} virtual CPU devices "
            f"(have {len(devs)} {devs[0].platform})"
        )


def distinct_inputs(key, shape, n: int):
    """``n`` device-resident inputs, each unique (defeats execution dedup)."""
    import jax

    return [
        jax.device_put(jax.random.normal(jax.random.fold_in(key, i), shape))
        for i in range(n)
    ]


def emit(metric: str, value: float, unit: str, vs_baseline: float) -> None:
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(value, 4),
                "unit": unit,
                "vs_baseline": round(vs_baseline, 4),
            }
        )
    )
