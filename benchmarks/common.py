"""Shared helpers for the benchmark drivers.

Every driver prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
matching the repo-root ``bench.py`` contract, so results are machine
comparable across configs (BASELINE.md "configs to reproduce").

Measurement caveat baked in here (see bench.py's module docstring for the
full story): under this image's remote-execution tunnel,
``jax.block_until_ready`` can return before execution completes and repeat
executions of identical (fn, args) are deduplicated. Honest wall-clock
therefore requires (a) distinct inputs per request and (b) timing around a
host fetch (``np.asarray``) of real outputs.
"""

from __future__ import annotations

import json
import os


#: Current round's artifact directory (drivers append JSONL rows here).
#: Env-overridable so old rows can be regenerated in place if needed.
ROUND = os.environ.get("BENCH_ROUND", "r05")


def out_path(name: str) -> str:
    """``benchmarks/results/<round>/<name>`` for the append-only JSONL
    artifact convention (error rows land BESIDE good rows, never over
    them)."""
    return os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "results", ROUND, name
    )


def force_cpu_mesh(n_devices: int) -> None:
    """Force an ``n_devices`` virtual CPU mesh (post-import safe). Thin
    wrapper over ``__graft_entry__._force_virtual_cpu`` — the drivers put
    the repo root on sys.path, so the one implementation is shared."""
    from __graft_entry__ import _force_virtual_cpu

    _force_virtual_cpu(n_devices)


def distinct_inputs(key, shape, n: int):
    """``n`` device-resident inputs, each unique (defeats execution dedup)."""
    import jax

    return [
        jax.device_put(jax.random.normal(jax.random.fold_in(key, i), shape))
        for i in range(n)
    ]


def emit(
    metric: str, value: float, unit: str, vs_baseline: float, **extra
) -> None:
    """The one-JSON-line contract; ``extra`` fields (platform, device,
    trial timings, notes) append after the four required keys."""
    print(
        json.dumps(
            {
                "metric": metric,
                "value": round(value, 4),
                "unit": unit,
                "vs_baseline": round(vs_baseline, 4),
                **extra,
            }
        ),
        flush=True,
    )


def measure_scan_throughput(
    graph, x0, iters: int, trials: int, param_dtype: str | None = None
) -> tuple[float, list[float]]:
    """The one honest timed region for this image (shared by ``bench.py``,
    ``local_infer.py`` and ``tpu_models.py``): ITERS forward passes of
    ``graph`` inside one jitted ``lax.scan`` whose carry makes every
    iteration data-dependent on the last (defeats LICM and the tunnel's
    (fn, args) dedup), timed around a host fetch. Returns
    (images_per_sec, per-trial wall seconds).

    ``param_dtype="bfloat16"`` makes weights bf16-RESIDENT (flax keeps
    params f32 by default and casts per use — residency halves the
    weight bytes every iteration streams from HBM)."""
    import statistics
    import time

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    variables = jax.jit(graph.init)(jax.random.PRNGKey(0), x0)
    if param_dtype is not None:
        target = jnp.dtype(param_dtype)
        variables = jax.tree.map(
            lambda x: x.astype(target)
            if x.dtype == jnp.float32
            else x,
            variables,
        )

    def bench_fn(variables, x):
        def body(x, _):
            y = graph.apply(variables, x)
            x = x * 0.999 + (jnp.mean(y) * 1e-6).astype(x.dtype)
            return x, y[0, 0]

        x, ys = lax.scan(body, x, None, length=iters)
        return jnp.mean(ys)

    fwd = jax.jit(bench_fn)
    np.asarray(fwd(variables, x0))  # compile + warm

    times = []
    for i in range(trials):
        x_trial = x0 + (i + 1) * 1e-6  # distinct per trial (dedup)
        t0 = time.perf_counter()
        np.asarray(fwd(variables, x_trial))
        times.append(time.perf_counter() - t0)
    dt = statistics.median(times)
    return x0.shape[0] * iters / dt, times


def int_flag(argv: list[str], name: str, default: int) -> int:
    """Parse ``--name N`` from argv; malformed/missing values fall back to
    the default instead of raising — bench.py's 'always print one JSON
    line, exit 0' contract must survive bad CLI input."""
    if name in argv:
        try:
            return int(argv[argv.index(name) + 1])
        except (IndexError, ValueError):
            pass
    return default


def str_flag(
    argv: list[str], name: str, default: str, choices: tuple[str, ...] | None = None
) -> str:
    """Parse ``--name VALUE``; missing values, values that look like the
    next flag, or values outside ``choices`` fall back to the default
    (same always-emit contract as :func:`int_flag`)."""
    if name in argv:
        idx = argv.index(name) + 1
        if idx < len(argv) and not argv[idx].startswith("--"):
            value = argv[idx]
            if choices is None or value in choices:
                return value
    return default


def run_child_json(
    cmd: list,
    metric: str,
    unit: str,
    timeout_s: float,
    *,
    env: dict | None = None,
    allow_cpu: bool = False,
    out_path: str | None = None,
) -> int:
    """The shared parent half of the subprocess measurement contract
    (bench.py's postmortem rules): run ``cmd``, scan stdout for the first
    parseable '{'-line, reject silent CPU fallbacks inside a TPU
    measurement (unless ``allow_cpu`` — an explicit --cpu validation
    run), and ALWAYS print exactly one JSON line + return 0 — on
    failure an error record, never a crash. ``out_path`` additionally
    APPENDS the record as one JSONL row (append, not overwrite: a relay
    error stub must land beside earlier measurements, never over them —
    the r04 lesson). Drivers that need more than one child mode
    (artifact writers like mfu_sweep) keep their own loops; every plain
    one-JSON-line driver should use this."""
    import subprocess

    record, err = None, ""
    try:
        proc = subprocess.run(
            cmd,
            capture_output=True,
            text=True,
            timeout=timeout_s,
            env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        for ln in proc.stdout.splitlines():
            ln = ln.strip()
            if ln.startswith("{"):
                try:
                    record = json.loads(ln)
                    break
                except json.JSONDecodeError:
                    continue  # stray '{'-prefixed noise; keep scanning
        if proc.returncode != 0 or record is None:
            record = None
            err = (proc.stderr or proc.stdout or "").strip()[-300:]
        elif record.get("platform") == "cpu" and not allow_cpu:
            record = None
            err = "TPU run silently fell back to the CPU backend"
    except subprocess.TimeoutExpired:
        err = f"child timed out after {timeout_s:.0f}s (TPU relay hang?)"
    if record is None:
        record = {
            "metric": metric,
            "value": 0.0,
            "unit": unit,
            "vs_baseline": 0.0,
            "error": err,
        }
    if out_path is not None:
        os.makedirs(os.path.dirname(out_path), exist_ok=True)
        with open(out_path, "a") as f:
            json.dump(record, f)
            f.write("\n")
    print(json.dumps(record), flush=True)
    return 0
