"""Headline benchmark: ResNet-50 inference images/sec on one chip.

Reference metric (BASELINE.json): "images/sec/chip (ResNet-50, bs=32)".
The reference never published numbers (BASELINE.md); the baseline constant
here is a single NVIDIA A100's framework-level ResNet-50 fp16 inference
throughput at bs=32 (~3000 images/sec, XLA/TF-class stacks — TensorRT INT8
figures are far higher but not framework-comparable). The north-star target
is v5e-8 aggregate >= one A100; per-chip parity at 1/8th of the baseline is
vs_baseline = 0.125 * 8 = 1.0 when extrapolated linearly across 8 chips —
we report the honest per-chip ratio and let vs_baseline carry it.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

from __future__ import annotations

import json
import sys
import time

A100_IMAGES_PER_SEC = 3000.0  # single-A100 fp16 bs32, framework-level
BATCH = 32
WARMUP = 10
ITERS = 60


def main() -> None:
    import jax
    import jax.numpy as jnp

    from adapt_tpu.models.resnet import resnet50

    graph = resnet50(num_classes=1000, dtype=jnp.bfloat16)
    x = jnp.ones((BATCH, 224, 224, 3), jnp.float32)
    variables = jax.jit(graph.init)(jax.random.PRNGKey(0), x)
    fwd = jax.jit(graph.apply)

    for _ in range(WARMUP):
        y = fwd(variables, x)
    jax.block_until_ready(y)

    t0 = time.perf_counter()
    for _ in range(ITERS):
        y = fwd(variables, x)
    jax.block_until_ready(y)
    dt = time.perf_counter() - t0

    images_per_sec = BATCH * ITERS / dt
    print(
        json.dumps(
            {
                "metric": "resnet50_bs32_images_per_sec_per_chip",
                "value": round(images_per_sec, 2),
                "unit": "images/sec",
                "vs_baseline": round(images_per_sec / A100_IMAGES_PER_SEC, 4),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
