"""Headline benchmark: ResNet-50 inference images/sec on one chip.

Reference metric (BASELINE.json): "images/sec/chip (ResNet-50, bs=32)".
The reference never published numbers (BASELINE.md); the baseline constant
here is a single NVIDIA A100's framework-level ResNet-50 fp16 inference
throughput at bs=32 (~3000 images/sec, XLA/TF-class stacks — TensorRT INT8
figures are far higher but not framework-comparable).

Measurement methodology: the timed region is ONE jitted program that runs
ITERS forward passes in a `lax.scan`, with each iteration's input carrying
a data dependency on the previous iteration's logits. That shape is
deliberate:
- a Python-level dispatch loop under this image's remote-execution tunnel
  over-reports wildly (repeat executions of identical (fn, args) are
  deduplicated, and `block_until_ready` returns before execution
  completes), so the loop must live on-device;
- a loop-invariant body inside `scan` could be hoisted by XLA (LICM),
  so each step's input must depend on the previous step's output.
Wall clock is taken around a host fetch (`np.asarray`) of the scalar
result, which is the only operation that provably waits for execution.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""

from __future__ import annotations

import json
import statistics
import sys
import time

A100_IMAGES_PER_SEC = 3000.0  # single-A100 fp16 bs32, framework-level
BATCH = 32
ITERS = 100  # forwards per timed program; amortizes the tunnel round-trip
TRIALS = 5


def main() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax import lax

    from adapt_tpu.models.resnet import resnet50

    graph = resnet50(num_classes=1000, dtype=jnp.bfloat16)
    x0 = jax.random.normal(
        jax.random.PRNGKey(0), (BATCH, 224, 224, 3), jnp.float32
    )
    variables = jax.jit(graph.init)(jax.random.PRNGKey(0), x0)

    def bench_fn(variables, x):
        def body(x, _):
            y = graph.apply(variables, x)
            # Fold a negligible function of the logits back into the next
            # input: keeps every iteration data-dependent (defeats LICM /
            # cross-call dedup) without changing what is computed.
            x = x * 0.999 + (jnp.mean(y) * 1e-6).astype(x.dtype)
            return x, y[0, 0]

        x, ys = lax.scan(body, x, None, length=ITERS)
        return jnp.mean(ys)

    fwd = jax.jit(bench_fn)
    np.asarray(fwd(variables, x0))  # compile + warm

    times = []
    for i in range(TRIALS):
        # Distinct input per trial: the tunnel dedups repeat executions of
        # identical (fn, args), which would serve trials from cache.
        x_trial = x0 + (i + 1) * 1e-6
        t0 = time.perf_counter()
        np.asarray(fwd(variables, x_trial))
        times.append(time.perf_counter() - t0)

    dt = statistics.median(times)
    images_per_sec = BATCH * ITERS / dt
    print(
        json.dumps(
            {
                "metric": "resnet50_bs32_images_per_sec_per_chip",
                "value": round(images_per_sec, 2),
                "unit": "images/sec",
                "vs_baseline": round(images_per_sec / A100_IMAGES_PER_SEC, 4),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
