"""Headline benchmark: ResNet-50 inference images/sec on one chip.

Reference metric (BASELINE.json): "images/sec/chip (ResNet-50, bs=32)".
The reference never published numbers (BASELINE.md); the baseline constant
here is a single NVIDIA A100's framework-level ResNet-50 fp16 inference
throughput at bs=32 (~3000 images/sec, XLA/TF-class stacks — TensorRT INT8
figures are far higher but not framework-comparable).

Robustness contract (round-1 postmortem: the driver-captured run died at
first JAX op with "Unable to initialize backend 'axon'", rc=1, and zero
perf numbers existed): this script must ALWAYS print exactly one JSON line
and exit 0. The parent process imports no JAX; the measurement runs in a
child subprocess under a hard timeout (backend init through the TPU tunnel
can HANG, not just raise — a timeout is the only reliable guard). TPU is
attempted with retry + backoff; if it never comes up, a CPU-backend
fallback still produces a measured number, flagged "platform": "cpu" with
the TPU failure tail in "note" so the regression is loud, not silent.

Measurement methodology (child): the timed region is ONE jitted program
that runs ITERS forward passes in a `lax.scan`, with each iteration's
input carrying a data dependency on the previous iteration's logits. That
shape is deliberate:
- a Python-level dispatch loop under this image's remote-execution tunnel
  over-reports wildly (repeat executions of identical (fn, args) are
  deduplicated, and `block_until_ready` returns before execution
  completes), so the loop must live on-device;
- a loop-invariant body inside `scan` could be hoisted by XLA (LICM),
  so each step's input must depend on the previous step's output.
Wall clock is taken around a host fetch (`np.asarray`) of the scalar
result, which is the only operation that provably waits for execution.

MFU is reported alongside (VERDICT r1 #1): images/sec x ~8.2 GFLOP/image
(ResNet-50 fwd, multiply+add counted separately) / chip peak bf16 FLOPs
(TPU v5e: 197 TFLOP/s).

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N, ...}
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from benchmarks.common import int_flag, str_flag  # noqa: E402  (no JAX)

A100_IMAGES_PER_SEC = 3000.0  # single-A100 fp16 bs32, framework-level
RESNET50_FLOPS_PER_IMAGE = 8.2e9  # fwd pass @224x224, mul+add as 2
TPU_V5E_PEAK_FLOPS = 197e12  # bf16
BATCH = 32

#: Persistent XLA compilation cache, shared between the in-round benchmark
#: queues and this driver-run script. The r03/r04 postmortem: the driver's
#: TPU shots spent their whole window on FIRST COMPILE through a degraded
#: relay and timed out, so two rounds of real TPU perf never reached the
#: official artifact. The queue seeds this cache with the exact child
#: programs below (both scan lengths); the driver's shots then pay
#: execution, not compile. Best-effort: if the tunnel's PJRT plugin cannot
#: serialize executables, JAX warns and runs uncached — never fails.
CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), ".jax_cache")
CACHE_ENV = {
    "JAX_COMPILATION_CACHE_DIR": CACHE_DIR,
    "JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS": "0",
    "JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES": "-1",
}

#: Healthy-relay schedule: (platform, iters, trials, timeout_s,
#: backoff_before_s). TPU gets three shots (first compile through the
#: tunnel is slow; a flaky relay often recovers within a minute — and
#: r04 saw multi-hour outages, so a final attempt after a 5-minute
#: backoff buys one more recovery window); CPU is the evidence-of-life
#: fallback with a small iteration count — ResNet-50 bs=32 on CPU is
#: ~seconds per batch. A HUNG probe (a wedged runtime, killed with its
#: whole process group) skips TPU entirely and degrades straight to the
#: CPU row in main().
ATTEMPTS = [
    ("tpu", 100, 5, 600, 0),
    ("tpu", 100, 3, 420, 30),
    ("tpu", 100, 3, 420, 300),
    ("cpu", 3, 2, 600, 0),
]


def _child(
    platform: str,
    iters: int,
    trials: int,
    batch: int = BATCH,
    stem: str = "conv7",
) -> None:
    import jax

    # Belt-and-braces with CACHE_ENV (parent may be bypassed: queue scripts
    # invoke --child directly): enable the persistent compilation cache
    # before the first compile. Guarded — cache config must never break a
    # measurement.
    try:
        jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass

    import jax.numpy as jnp

    from adapt_tpu.models.resnet import resnet50
    from benchmarks.common import measure_scan_throughput

    graph = resnet50(num_classes=1000, dtype=jnp.bfloat16, stem=stem)
    x0 = jax.random.normal(
        jax.random.PRNGKey(0), (batch, 224, 224, 3), jnp.float32
    )
    images_per_sec, times = measure_scan_throughput(graph, x0, iters, trials)
    record = {
        # The headline metric name is the bs=32 contract; off-headline
        # sweep rows are labeled by their actual batch (and vs_baseline
        # still divides by the bs=32 A100 constant — noted in-band).
        "metric": f"resnet50_bs{batch}_images_per_sec_per_chip"
        + ("" if stem == "conv7" else f"_{stem}"),
        "value": round(images_per_sec, 2),
        "unit": "images/sec",
        "vs_baseline": round(images_per_sec / A100_IMAGES_PER_SEC, 4),
        "baseline": "single A100 fp16 bs=32 ~3000 img/s (framework-level)",
        "platform": jax.devices()[0].platform,
        "device": str(jax.devices()[0]),
        "batch": batch,
        "iters": iters,
        "trials": trials,
        "trial_seconds": [round(t, 4) for t in times],
    }
    # Gate MFU on the MEASURED platform, not the requested one: if JAX
    # silently fell back to CPU, an "mfu" vs TPU peak would be fabricated.
    if record["platform"] != "cpu":
        record["mfu"] = round(
            images_per_sec * RESNET50_FLOPS_PER_IMAGE / TPU_V5E_PEAK_FLOPS, 4
        )
    print(json.dumps(record), flush=True)


def main() -> int:
    if "--child" in sys.argv:
        platform = sys.argv[sys.argv.index("--platform") + 1]
        iters = int_flag(sys.argv, "--iters", 100)
        trials = int_flag(sys.argv, "--trials", 3)
        batch = int_flag(sys.argv, "--batch", BATCH)
        stem = str_flag(sys.argv, "--stem", "conv7", choices=("conv7", "s2d"))
        _child(platform, iters, trials, batch, stem)
        return 0

    # Optional batch override (default 32 = the headline config; the batch
    # sweep artifact uses this knob, the driver never passes it). Guarded
    # parse: bad CLI input must not break the one-JSON-line contract.
    batch = int_flag(sys.argv, "--batch", BATCH)
    stem = str_flag(sys.argv, "--stem", "conv7", choices=("conv7", "s2d"))
    notes: list[str] = []
    attempts = ATTEMPTS
    # Fast relay probe, PHASED (r06 rebuild): with the relay DOWN,
    # backend init HANGS, so each TPU attempt would burn its full child
    # timeout — three of them plus backoffs is ~40 min, past some
    # driver timeouts (r03's BENCH was rc=124 exactly this way). The
    # r05 Popen/killpg rebuild stopped the lingering process group but
    # the probe itself still HUNG with no evidence of WHERE, so every
    # blind round since r03 has been un-diagnosable from the artifact.
    # The probe now runs two phases and prints one JSON line per phase
    # to a real file as it completes:
    #   1. "devices"    — import jax + enumerate devices (the r03-r05
    #                     hang site: PJRT init through the tunnel);
    #   2. "warm_touch" — a CHEAP first-op device touch (jit add +
    #                     block_until_ready) with its OWN short
    #                     in-child alarm, run only when a TPU is
    #                     present — so the tiny-first measurement
    #                     schedule starts against a warmed runtime and
    #                     a first-op wedge is attributed to THIS phase
    #                     instead of timing out a full 600 s attempt.
    # The TRANSCRIPT (every phase line that landed) is stamped into the
    # BENCH report EITHER WAY — a hang now names its phase, and a
    # completed probe on a TPU-less host proves "hardware absent", the
    # only legitimate tpu_blind cause. In-child alarms are best-effort
    # (a C-level PJRT hang ignores SIGALRM); the parent's
    # process-group SIGKILL remains the hard guard.
    # Probe output goes to real files, not pipes — after a timeout,
    # draining inherited pipe fds to EOF would block (the documented
    # subprocess gotcha), turning the guard itself into a hang.
    import tempfile

    probe_src = r"""
import json, signal, sys, time
t0 = time.time()
def emit(**kw):
    print(json.dumps(kw), flush=True)
def phase(name, timeout_s, fn):
    def onalrm(sig, frm):
        raise TimeoutError(name)
    old = signal.signal(signal.SIGALRM, onalrm)
    signal.alarm(timeout_s)
    try:
        out = fn() or {}
        emit(phase=name, status="ok",
             elapsed_s=round(time.time() - t0, 2), **out)
        return True, out
    except TimeoutError:
        emit(phase=name, status="timeout", timeout_s=timeout_s,
             elapsed_s=round(time.time() - t0, 2))
        return False, {}
    except Exception as e:
        emit(phase=name, status="error", error=repr(e)[-300:],
             elapsed_s=round(time.time() - t0, 2))
        return False, {}
    finally:
        signal.alarm(0)
        signal.signal(signal.SIGALRM, old)
def devices():
    import jax
    devs = jax.devices()
    return {"platform": devs[0].platform, "device_count": len(devs),
            "kinds": sorted({str(getattr(d, "device_kind", "?"))
                             for d in devs})}
ok, info = phase("devices", 90, devices)
if ok and info.get("platform") == "tpu":
    def touch():
        import jax
        import jax.numpy as jnp
        jax.jit(lambda a: a + 1)(
            jnp.zeros((8, 128), jnp.float32)
        ).block_until_ready()
    phase("warm_touch", 45, touch)
else:
    emit(phase="warm_touch", status="skipped",
         reason=("no TPU devices" if ok else "device phase failed"))
emit(phase="done", status="ok", elapsed_s=round(time.time() - t0, 2))
"""

    probe_hung = False  # any non-timeout failure = not hung (ADVICE r4)
    #: Machine-readable probe outcome for the BENCH report: status +
    #: the full phase transcript stamp on EVERY emitted record (the
    #: r03-r05 trajectory was TPU-blind with only prose notes saying
    #: why), so a blind round is diagnosable from the artifact alone.
    probe_status: str | int = "ok"
    probe_stderr_tail = ""
    probe_transcript: list = []
    tpu_present = False
    #: True only when the devices phase COMPLETED (status "ok"): the
    #: hardware_absent conclusion is allowed only then — a devices
    #: phase that timed out or errored is a wedge/failure, which must
    #: never be classified as hardware absence (the acceptance rule:
    #: tpu_blind 'hardware_absent' only on a completed probe).
    devices_ok = False
    with tempfile.TemporaryFile() as probe_err, \
            tempfile.TemporaryFile() as probe_out:
        probe = None

        def _drain_transcript():
            probe_out.seek(0)
            lines = []
            for raw in probe_out.read().decode(errors="replace").splitlines():
                raw = raw.strip()
                if not raw.startswith("{"):
                    continue
                try:
                    lines.append(json.loads(raw))
                except json.JSONDecodeError:
                    continue
            return lines

        try:
            probe = subprocess.Popen(
                [sys.executable, "-c", probe_src],
                stdout=probe_out,
                stderr=probe_err,
                start_new_session=True,
            )
            rc = probe.wait(timeout=150)
            probe_transcript = _drain_transcript()
            if rc != 0:
                probe_status = rc
                probe_err.seek(0)
                probe_stderr_tail = (
                    probe_err.read()[-200:].decode(errors="replace").strip()
                )
                notes.append(f"relay probe rc={rc}: {probe_stderr_tail}")
            elif not any(
                p.get("phase") == "done" for p in probe_transcript
            ):
                probe_status = "incomplete"
                notes.append("relay probe exited 0 without a done phase")
        except subprocess.TimeoutExpired:
            probe_hung = True
            probe_status = "hung"
            try:
                os.killpg(probe.pid, signal.SIGKILL)
            except OSError:  # group already gone / not permitted
                probe.kill()
            try:
                probe.wait(timeout=10)
            except subprocess.TimeoutExpired:
                probe_status = "unkillable"
                notes.append("relay probe unkillable (survived SIGKILL)")
            probe_transcript = _drain_transcript()
            if probe_transcript:
                last = probe_transcript[-1].get("phase", "?")
                notes.append(f"probe hung after phase {last!r}")
            probe_err.seek(0)
            probe_stderr_tail = (
                probe_err.read()[-200:].decode(errors="replace").strip()
            )
        except Exception as exc:  # OSError etc: record, keep full schedule
            probe_status = "error"
            probe_stderr_tail = repr(exc)[-200:]
            notes.append(f"relay probe error: {exc!r}")
            if probe is not None and probe.poll() is None:
                probe.kill()
            # Whatever phase lines landed before the failure are still
            # evidence — never drop them.
            probe_transcript = _drain_transcript()
        for p in probe_transcript:
            if p.get("phase") == "devices" and p.get("status") == "ok":
                devices_ok = True
                tpu_present = p.get("platform") == "tpu"

    cache_warm = os.path.isdir(CACHE_DIR) and bool(os.listdir(CACHE_DIR))

    def _attempt(platform: str, iters: int, trials: int, timeout_s: int):
        """One child measurement; returns the parsed record or None,
        appending the failure reason to ``notes``."""
        env = dict(os.environ)
        env.update(CACHE_ENV)
        if platform == "cpu":
            # Drop the axon relay hook: with the TPU tunnel down, imports
            # through it hang; the CPU run must be hermetic.
            env.pop("PYTHONPATH", None)
            env["JAX_PLATFORMS"] = "cpu"
        cmd = [
            sys.executable,
            os.path.abspath(__file__),
            "--child",
            "--platform",
            platform,
            "--iters",
            str(iters),
            "--trials",
            str(trials),
            "--batch",
            str(batch),
            "--stem",
            stem,
        ]
        t0 = time.time()
        try:
            proc = subprocess.run(
                cmd,
                env=env,
                cwd=os.path.dirname(os.path.abspath(__file__)),
                capture_output=True,
                text=True,
                timeout=timeout_s,
            )
        except subprocess.TimeoutExpired:
            notes.append(f"{platform} iters={iters}: timeout after {timeout_s}s")
            print(
                f"bench attempt on {platform} timed out ({timeout_s}s)",
                file=sys.stderr,
            )
            return None
        if proc.returncode != 0:
            tail = (proc.stderr or proc.stdout or "").strip().splitlines()
            tail = " | ".join(tail[-3:])[-500:]
            notes.append(
                f"{platform}: rc={proc.returncode} after "
                f"{time.time() - t0:.0f}s: {tail}"
            )
            print(f"bench attempt on {platform} failed: {tail}", file=sys.stderr)
            return None
        record = None
        for line in proc.stdout.splitlines():
            line = line.strip()
            if line.startswith("{"):
                try:
                    record = json.loads(line)
                    break
                except json.JSONDecodeError:
                    continue
        if record is None:
            notes.append(f"{platform}: exited 0 but printed no JSON")
        elif platform == "tpu" and record.get("platform") == "cpu":
            # JAX silently fell back to CPU inside a TPU attempt — reject
            # it; a real (flagged) CPU fallback is the last attempt's job.
            notes.append("tpu attempt silently ran on cpu")
            record = None
        return record

    def _emit(record) -> int:
        record["compile_cache"] = "warm" if cache_warm else "cold"
        # Stamp the ACTIVE serving-tier CacheTierConfig (capacity +
        # codecs) into every BENCH record: the trajectory's perf rows
        # are only comparable when the memory hierarchy behind them is
        # known — a row measured with a host spill tier under the
        # Pager is a different serving config from one without.
        # ADAPT_TPU_CACHE_TIER=1 opts serving runs into the default
        # config; unset/0 means off (today's single-tier behavior).
        try:
            if os.environ.get("ADAPT_TPU_CACHE_TIER", "").lower() in (
                "1", "on", "true",
            ):
                import dataclasses as _dc

                from adapt_tpu.config import CacheTierConfig

                record["cache_tier"] = _dc.asdict(CacheTierConfig())
            else:
                record["cache_tier"] = None
        except Exception:  # cache-tier stamping must never break a row
            record["cache_tier"] = None
        if notes:
            record["note"] = "; ".join(notes)
        # TPU-blind stamping, greppable from the artifact alone: ANY
        # record that did not measure on the TPU is blind — the common
        # case is a healthy probe followed by TPU attempts timing out
        # into the CPU fallback, not just a failed probe. The probe's
        # full phase transcript rides on EVERY record (r06): a blind
        # round must be diagnosable — hardware absence (probe completed,
        # no TPU devices) vs a probe hang (transcript names the phase)
        # — from the artifact alone.
        blind = record.get("platform") != "tpu"
        if blind:
            record["tpu_blind"] = True
            if probe_status == "ok" and devices_ok and not tpu_present:
                # Only a COMPLETED devices phase may conclude absence —
                # an in-child timeout on that phase is a wedge, even
                # when the probe process exits cleanly around it.
                record["tpu_blind_cause"] = "hardware_absent"
            elif probe_status in ("hung", "unkillable") or (
                probe_status in ("ok", "incomplete") and not devices_ok
            ):
                record["tpu_blind_cause"] = "probe_hang"
            else:
                record["tpu_blind_cause"] = "tpu_attempts_failed"
        record["tpu_probe"] = {
            "status": probe_status,
            "tpu_present": tpu_present,
            "transcript": probe_transcript,
        }
        if probe_stderr_tail:
            record["tpu_probe"]["stderr_tail"] = probe_stderr_tail
        print(json.dumps(record), flush=True)
        return 0

    if probe_hung:
        # WEDGED runtime, not a merely-slow one: the probe could not even
        # finish its phases in 150 s, so every TPU attempt would burn its
        # full child timeout the same way (r05 postmortem: the
        # tiny-first TPU escalation this branch used to run spent
        # another 300 s timing out before the CPU row landed). Degrade
        # STRAIGHT to the CPU evidence-of-life number — flagged
        # "platform": "cpu" with the hang in "note", loud not silent.
        notes.append("relay probe HUNG (150s); degrading to CPU")
        record = _attempt("cpu", 3, 2, 600)
        if record is not None:
            return _emit(record)
    elif probe_status == "ok" and devices_ok and not tpu_present:
        # Probe COMPLETED and enumerated a TPU-less backend: hardware
        # absence, the one legitimate tpu_blind cause. Burning
        # 600+420+420 s of TPU attempts against a backend the probe
        # just proved has no TPU devices would reproduce the r03-r05
        # blind-with-no-evidence pattern; go straight to the flagged
        # CPU row with the completed transcript as proof.
        notes.append(
            "relay probe completed: no TPU devices (hardware absent); "
            "skipping TPU attempts"
        )
        record = _attempt("cpu", 3, 2, 600)
        if record is not None:
            return _emit(record)
    elif probe_status in ("ok", "incomplete") and not devices_ok:
        # The probe PROCESS exited but its devices phase never
        # completed (the in-child alarm fired at a Python-interruptible
        # point of a wedged init): a wedge wearing a clean exit. Every
        # TPU attempt would burn its full child timeout the same way —
        # degrade straight to the CPU row, transcript naming the phase.
        notes.append(
            "relay probe devices phase did not complete "
            "(wedged init); degrading to CPU"
        )
        record = _attempt("cpu", 3, 2, 600)
        if record is not None:
            return _emit(record)
    else:
        for platform, iters, trials, timeout_s, backoff_s in attempts:
            if backoff_s:
                time.sleep(backoff_s)
            record = _attempt(platform, iters, trials, timeout_s)
            if record is not None:
                return _emit(record)

    # Every attempt failed: still honor the one-JSON-line, rc=0 contract so
    # the driver records a diagnostic instead of a crash.
    record = {
        "metric": f"resnet50_bs{batch}_images_per_sec_per_chip"
        + ("" if stem == "conv7" else f"_{stem}"),
        "value": 0.0,
        "unit": "images/sec",
        "vs_baseline": 0.0,
        "error": "; ".join(notes)[-1000:],
        # No measurement landed at all — the round is TPU-blind by
        # definition; the probe transcript rides along either way.
        "tpu_blind": True,
        "tpu_probe": {
            "status": probe_status,
            "tpu_present": tpu_present,
            "transcript": probe_transcript,
        },
    }
    if probe_stderr_tail:
        record["tpu_probe"]["stderr_tail"] = probe_stderr_tail
    print(json.dumps(record), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
