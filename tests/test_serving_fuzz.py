"""Serving-stack property fuzz: random knobs x random traffic.

The deterministic tests pin fixed scenarios; this fuzz draws random
model configurations (GQA / MoE / sliding window / RoPE), random cache
layouts (slot strips, paged pools sized to random pressure, chunked
prefill), and random traffic (prompt lengths, steps, sampling knobs,
staggered arrivals), then holds every served stream to THE invariant:
token-identical to solo ``generate()`` for that request. Seeded — a
failure reproduces from the printed draw.

This is the serving-side sibling of ``test_stress.py``'s membership
fuzz (SURVEY.md §5's race-detection analog): the interactions it
covers (prefix sharing under eviction under windows under chunked
admissions...) grow combinatorially and deserve randomized coverage,
not just the fixed cases."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adapt_tpu.models.transformer_lm import generate, transformer_lm
from adapt_tpu.runtime.continuous import ContinuousBatcher

VOCAB = 31


def _random_model(rs):
    kv_heads = rs.choice([None, 2])
    window = rs.choice([None, 10, 18])
    pos = rs.choice(["learned", "rope"])
    moe = rs.choice([None, 4])
    lm = transformer_lm(
        VOCAB, 32, 2, 4, 48,
        max_len=96,
        kv_heads=kv_heads,
        moe_experts=moe,
        moe_top_k=2 if moe else 1,
        window=None if window is None else int(window),
        pos=pos,
        name="fuzz_lm",
    )
    desc = dict(kv_heads=kv_heads, window=window, pos=pos, moe=moe)
    variables = lm.graph.init(
        jax.random.PRNGKey(int(rs.randint(1 << 30))),
        jnp.zeros((1, 4), jnp.int32),
    )
    return lm, variables, desc


def _random_batcher(rs, lm, variables):
    layout = rs.choice(["slots", "paged", "paged", "paged"])
    kw = {}
    if layout == "paged":
        kw["kv_layout"] = "paged"
        kw["page_size"] = 16
        pps = -(-lm.max_len // 16)
        slots = int(rs.choice([2, 3]))
        worst = slots * pps + 1
        # Random pool pressure from cozy down to ~60% of worst case.
        kw["pool_pages"] = int(rs.randint(max(3, int(0.6 * worst)), worst + 1))
        if rs.random_sample() < 0.5:
            kw["prefill_chunk"] = 16
        kw["slots"] = slots
    else:
        kw["slots"] = int(rs.choice([2, 3]))
    desc = dict(layout=layout, **{k: v for k, v in kw.items()})
    return ContinuousBatcher(lm, variables, chunk=int(rs.choice([1, 2, 4])),
                             **kw), desc


@pytest.mark.parametrize("seed", [0, 1, 2, 3, 4, 5, 6, 7])
def test_serving_fuzz_streams_match_solo(seed):
    rs = np.random.RandomState(seed)
    lm, variables, mdesc = _random_model(rs)
    bat, bdesc = _random_batcher(rs, lm, variables)
    print(f"fuzz draw: model={mdesc} batcher={bdesc}")

    n_req = 7
    reqs = []
    shared = rs.randint(0, VOCAB, size=int(rs.randint(16, 33))).astype(
        np.int32
    )
    for i in range(n_req):
        if rs.random_sample() < 0.4:  # shared-prefix traffic
            tail = rs.randint(0, VOCAB, size=rs.randint(1, 8)).astype(
                np.int32
            )
            prompt = np.concatenate([shared, tail])
        else:
            prompt = rs.randint(0, VOCAB, size=rs.randint(2, 40)).astype(
                np.int32
            )
        steps = int(rs.randint(2, min(20, lm.max_len - len(prompt))))
        kw = {}
        if rs.random_sample() < 0.4:  # sampled request
            kw = dict(
                temperature=float(rs.uniform(0.5, 1.2)),
                top_k=int(rs.randint(2, VOCAB)),
                rng=jax.random.PRNGKey(1000 + i),
            )
            if rs.random_sample() < 0.5:
                kw["top_p"] = float(rs.uniform(0.5, 1.0))
        reqs.append((prompt, steps, kw))

    ids = {}
    for i, (prompt, steps, kw) in enumerate(reqs):
        ids[bat.submit(prompt, steps, **kw)] = i
        if rs.random_sample() < 0.5:  # staggered arrivals
            bat.tick()
    out = bat.run()
    assert set(out) == set(ids)
    chunked = bdesc.get("prefill_chunk") is not None
    for rid, i in ids.items():
        prompt, steps, kw = reqs[i]
        if chunked and kw.get("temperature"):
            # Chunked prefill's documented contract is greedy-bitwise /
            # sampled-distributional (fp reassociation at chunk
            # boundaries); skip exact comparison for sampled requests.
            assert len(out[rid]) <= steps
            continue
        want = np.asarray(
            generate(lm, variables, jnp.asarray(prompt)[None], steps, **kw)
        )[0]
        got = out[rid]
        # No request sets eos_id, so a short stream IS a truncation bug
        # — never skip the comparison on it.
        assert len(got) == steps, (
            f"req {i} truncated: {len(got)}/{steps} tokens "
            f"(model={mdesc}, batcher={bdesc})"
        )
        np.testing.assert_array_equal(
            got, want,
            err_msg=f"req {i} diverged (model={mdesc}, "
            f"batcher={bdesc}, kw={kw})",
        )
    assert bat.stats()["pages_in_use" if bdesc["layout"] == "paged"
                       else "active"] == 0