"""Paged KV cache: allocator, kernel parity, and batcher equivalence.

The contract stack, bottom-up: the ``Pager`` free-list bookkeeping, the
``ops/paged_attention`` kernel against its gather oracle (which itself
reduces to the contiguous decode oracle), and the ``ContinuousBatcher``
with ``kv_layout="paged"`` emitting token-for-token what ``generate()``
emits for each request alone — the same invisibility bar the slot
layout is held to — including under a pool small enough to force
requests to wait for pages."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adapt_tpu.models.transformer_lm import generate, lm_tiny
from adapt_tpu.ops.paged_attention import (
    paged_attention,
    paged_attention_reference,
)
from adapt_tpu.runtime.continuous import ContinuousBatcher
from adapt_tpu.runtime.paged import Pager, insert_prefill_pages


# -- allocator ---------------------------------------------------------------


def test_pager_alloc_free_cycle():
    p = Pager(num_pages=8, slots=3, pages_per_slot=4)
    assert p.alloc(0, 3) and p.alloc(1, 4)
    assert p.stats().in_use == 7 and p.stats().free == 0
    assert not p.alloc(2, 1)  # exhausted (page 0 never handed out)
    assert 0 not in p.owned(0) + p.owned(1)
    t = p.table()
    assert t.shape == (3, 4)
    assert set(t[0, :3]) == set(p.owned(0)) and t[0, 3] == 0
    assert (t[2] == 0).all()
    p.free_slot(1)
    assert p.stats().free == 4
    assert p.alloc(2, 4)  # reuses freed pages


def test_pager_validation():
    with pytest.raises(ValueError, match="num_pages"):
        Pager(1, 1, 1)
    p = Pager(8, 2, 2)
    with pytest.raises(ValueError, match="table width"):
        p.alloc(0, 3)


def test_pager_radix_probe_and_books():
    """``radix_probe`` walks the deepest resident token-block path
    READ-ONLY (no counters move, nothing acquired),
    ``record_prefix_match`` books token-weighted hits and flags
    matches ending strictly inside the prompt's shareable run as
    partial, ``lookup_share`` heat feeds back into the probe, and
    eviction prunes the radix in lockstep with the byte registry."""
    P = 4
    p = Pager(num_pages=8, slots=2, pages_per_slot=4, page_tokens=P)
    toks = np.arange(12, dtype=np.int32)
    assert p.alloc(0, 2)
    pages = p.owned(0)
    for j, page in enumerate(pages):
        p.register(page, Pager.prefix_key(toks, (j + 1) * P))
    assert p.stats().radix_nodes == 2
    # The walk caps at (len-1)//P pages: the last-token page is never
    # shareable, so a 12-token prompt matches at most 2 pages.
    assert p.radix_probe(toks) == (2, 8, 0)
    longer = np.concatenate([toks, np.arange(6, dtype=np.int32)])
    assert p.radix_probe(longer)[:2] == (2, 8)  # shared-prefix match
    assert p.radix_probe(np.ones(12, np.int32))[0] == 0  # diverges
    st = p.stats()
    assert (st.prefix_hits, st.radix_hit_tokens) == (0, 0)  # read-only
    # Full-cap match on the 12-token prompt: a hit, NOT partial.
    p.record_prefix_match(2, 12)
    st = p.stats()
    assert (st.radix_hit_tokens, st.radix_partial_hits) == (8, 0)
    # The same 2 pages against the longer prompt end strictly inside
    # its shareable run — the case whole-run keying scores as a miss.
    p.record_prefix_match(2, len(longer))
    st = p.stats()
    assert (st.radix_hit_tokens, st.radix_partial_hits) == (16, 1)
    # Heat: lookup_share bumps the node, the probe sums the path.
    p.free_slot(0)  # registered pages park rc=0 in the LRU
    assert p.lookup_share(1, Pager.prefix_key(toks, P)) == pages[0]
    assert p.radix_probe(toks)[2] == 1
    # Eviction drops radix nodes with their keys and counts it.
    p.free_slot(1)
    assert p.evict_cached() == 2
    assert p.stats().radix_nodes == 0 and p.radix_evictions == 2
    assert p.radix_probe(toks) == (0, 0, 0)


# -- kernel vs oracle --------------------------------------------------------


def test_paged_kernel_matches_oracle(rng):
    b, kvh, g, hd, page, npages, pps = 2, 2, 3, 64, 128, 16, 4
    q = jax.random.normal(rng, (b, kvh, g, hd))
    kp = jax.random.normal(jax.random.fold_in(rng, 1), (npages, kvh, page, hd))
    vp = jax.random.normal(jax.random.fold_in(rng, 2), (npages, kvh, page, hd))
    table = jnp.asarray([[3, 7, 1, 0], [5, 2, 9, 4]], jnp.int32)
    index = jnp.asarray([300, 200], jnp.int32)
    for vf in (None, jnp.asarray([10, 0], jnp.int32)):
        ref = paged_attention_reference(q, kp, vp, table, index, vf)
        out = paged_attention(q, kp, vp, table, index, vf, prefer="pallas")
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )


def test_paged_chunk_kernel_matches_oracle(rng):
    """Chunk-query kernel (per-row causal over a paged window) vs its
    gather oracle: GQA folding, non-zero pos0, and pow2 trash padding."""
    from adapt_tpu.ops.paged_attention import (
        paged_chunk_attention,
        paged_chunk_attention_reference,
    )

    kvh, g, chunk, hd, page, npages = 2, 3, 32, 64, 128, 12
    q = jax.random.normal(rng, (1, kvh, g * chunk, hd))
    kp = jax.random.normal(
        jax.random.fold_in(rng, 1), (npages, kvh, page, hd)
    )
    vp = jax.random.normal(
        jax.random.fold_in(rng, 2), (npages, kvh, page, hd)
    )
    for pos0, pages in [(128, [3, 7, 0, 0]), (0, [5, 0]),
                        (256, [2, 4, 9, 0])]:
        pages = jnp.asarray(pages, jnp.int32)
        ref = paged_chunk_attention_reference(q, kp, vp, pages, pos0, chunk)
        out = paged_chunk_attention(
            q, kp, vp, pages, pos0, chunk, prefer="pallas"
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5,
            err_msg=f"pos0={pos0}",
        )


def test_paged_verify_kernel_matches_oracle(rng):
    """Batched verify kernel (per-SLOT base positions, per-row causal
    diagonal — the speculative tick's attention) vs its gather oracle:
    desynchronized indices, GQA folding, and a sliding window."""
    from adapt_tpu.ops.paged_attention import (
        paged_verify_attention,
        paged_verify_attention_reference,
    )

    b, kvh, g, chunk, hd, page, npages = 2, 2, 2, 5, 64, 128, 16
    q = jax.random.normal(rng, (b, kvh, g * chunk, hd))
    kp = jax.random.normal(
        jax.random.fold_in(rng, 1), (npages, kvh, page, hd)
    )
    vp = jax.random.normal(
        jax.random.fold_in(rng, 2), (npages, kvh, page, hd)
    )
    table = jnp.asarray([[3, 7, 1, 0], [5, 2, 9, 4]], jnp.int32)
    index = jnp.asarray([301, 77], jnp.int32)  # rows desynchronized
    for window in (None, 130):
        ref = paged_verify_attention_reference(
            q, kp, vp, table, index, chunk, window=window
        )
        out = paged_verify_attention(
            q, kp, vp, table, index, chunk, prefer="pallas",
            window=window,
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5,
            err_msg=f"window={window}",
        )


def _quantized_pool(key, npages, kvh, page, hd):
    """Random native pool quantized with THE shared per-vector scheme —
    (int8 values, f32 scales (npages, kvh, page, 1)) pair."""
    from adapt_tpu.ops.quantize import quantize_kv_vectors

    return quantize_kv_vectors(
        jax.random.normal(key, (npages, kvh, page, hd))
    )


def test_paged_kernel_quantized_matches_oracle(rng):
    """Quantized ``_paged_kernel``: scale tiles ride the scalar-prefetch
    pipeline (table-addressed like the int8 payload) into the shared
    ``_decode_kernel`` quantized branch — interpreter parity vs the
    gather oracle (which itself reduces to the contiguous quantized
    decode oracle), with and without ragged valid_from."""
    b, kvh, g, hd, page, npages = 2, 2, 3, 64, 128, 16
    q = jax.random.normal(rng, (b, kvh, g, hd))
    kp = _quantized_pool(jax.random.fold_in(rng, 1), npages, kvh, page, hd)
    vp = _quantized_pool(jax.random.fold_in(rng, 2), npages, kvh, page, hd)
    table = jnp.asarray([[3, 7, 1, 0], [5, 2, 9, 4]], jnp.int32)
    index = jnp.asarray([300, 200], jnp.int32)
    for vf in (None, jnp.asarray([10, 0], jnp.int32)):
        ref = paged_attention_reference(q, kp, vp, table, index, vf)
        out = paged_attention(q, kp, vp, table, index, vf, prefer="pallas")
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )


def test_paged_verify_kernel_quantized_matches_oracle(rng):
    """Quantized ``_verify_kernel`` (the int8 speculative verify over a
    paged cache): desynchronized per-slot indices, GQA folding, sliding
    window — interpreter parity vs the gather oracle."""
    from adapt_tpu.ops.paged_attention import (
        paged_verify_attention,
        paged_verify_attention_reference,
    )

    b, kvh, g, chunk, hd, page, npages = 2, 2, 2, 5, 64, 128, 16
    q = jax.random.normal(rng, (b, kvh, g * chunk, hd))
    kp = _quantized_pool(jax.random.fold_in(rng, 1), npages, kvh, page, hd)
    vp = _quantized_pool(jax.random.fold_in(rng, 2), npages, kvh, page, hd)
    table = jnp.asarray([[3, 7, 1, 0], [5, 2, 9, 4]], jnp.int32)
    index = jnp.asarray([301, 77], jnp.int32)
    for window in (None, 130):
        ref = paged_verify_attention_reference(
            q, kp, vp, table, index, chunk, window=window
        )
        out = paged_verify_attention(
            q, kp, vp, table, index, chunk, prefer="pallas", window=window
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5,
            err_msg=f"window={window}",
        )


def test_paged_chunk_kernel_quantized_matches_oracle(rng):
    """Quantized ``_chunk_kernel`` (int8 chunked prefill): the chunk's
    rows attend the quantized window with fused scale application —
    interpreter parity vs the gather oracle, incl. trash padding."""
    from adapt_tpu.ops.paged_attention import (
        paged_chunk_attention,
        paged_chunk_attention_reference,
    )

    kvh, g, chunk, hd, page, npages = 2, 3, 32, 64, 128, 12
    q = jax.random.normal(rng, (1, kvh, g * chunk, hd))
    kp = _quantized_pool(jax.random.fold_in(rng, 1), npages, kvh, page, hd)
    vp = _quantized_pool(jax.random.fold_in(rng, 2), npages, kvh, page, hd)
    for pos0, pages in [(128, [3, 7, 0, 0]), (0, [5, 0])]:
        pages = jnp.asarray(pages, jnp.int32)
        ref = paged_chunk_attention_reference(q, kp, vp, pages, pos0, chunk)
        out = paged_chunk_attention(
            q, kp, vp, pages, pos0, chunk, prefer="pallas"
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5,
            err_msg=f"pos0={pos0}",
        )


def test_paged_kernel_unsupported_page_size_falls_back(rng):
    # page 16 is not a lane multiple: prefer="pallas" serves the oracle.
    b, kvh, g, hd, page, npages = 1, 2, 1, 64, 16, 8
    q = jax.random.normal(rng, (b, kvh, g, hd))
    kp = jax.random.normal(jax.random.fold_in(rng, 1), (npages, kvh, page, hd))
    vp = jax.random.normal(jax.random.fold_in(rng, 2), (npages, kvh, page, hd))
    table = jnp.asarray([[2, 5, 1]], jnp.int32)
    out = paged_attention(q, kp, vp, table, 30, prefer="pallas")
    ref = paged_attention_reference(q, kp, vp, table, 30)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


def test_insert_prefill_pages_roundtrip(rng):
    kvh, page, hd, npages = 2, 16, 8, 10
    pool = jnp.zeros((npages, kvh, page, hd))
    kv = jax.random.normal(rng, (1, kvh, 40, hd))  # 40 -> 3 pages (pad 8)
    pages = jnp.asarray([4, 7, 2], jnp.int32)
    pool = insert_prefill_pages(pool, pages, kv)
    got = np.concatenate(
        [np.asarray(pool)[p] for p in [4, 7, 2]], axis=1
    )  # (kvh, 48, hd)
    np.testing.assert_allclose(got[:, :40], np.asarray(kv)[0], rtol=1e-6)
    assert (got[:, 40:] == 0).all()
    assert (np.asarray(pool)[[0, 1, 3, 5, 6, 8, 9]] == 0).all()


# -- batcher equivalence -----------------------------------------------------


@pytest.fixture(scope="module")
def lm_setup():
    lm = lm_tiny(vocab=37, max_len=48)
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    return lm, variables


@pytest.fixture(scope="module")
def lm_setup_64():
    lm = lm_tiny(vocab=37, max_len=64)
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    return lm, variables


@pytest.fixture(scope="module")
def lm_setup_256():
    lm = lm_tiny(vocab=37, max_len=256)
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    return lm, variables


def _solo(lm, variables, prompt, steps, **kw):
    return np.asarray(
        generate(lm, variables, jnp.asarray(prompt)[None], steps, **kw)
    )[0]


def test_paged_staggered_requests_match_generate(lm_setup):
    """Mixed greedy/sampled staggered traffic through paged slots ==
    per-request solo generate, and pages drain back to the pool."""
    lm, variables = lm_setup
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, 37, size=n).astype(np.int32)
               for n in (3, 9, 5, 12, 7)]
    steps = [6, 4, 8, 3, 5]
    kw = [
        {},
        {"temperature": 0.9, "top_k": 5, "rng": jax.random.PRNGKey(7)},
        {},
        {"temperature": 1.3, "rng": jax.random.PRNGKey(9)},
        {},
    ]
    bat = ContinuousBatcher(
        lm, variables, slots=3, chunk=4, kv_layout="paged", page_size=16
    )
    ids = {}
    for i in range(2):
        ids[bat.submit(prompts[i], steps[i], **kw[i])] = i
    bat.tick()
    for i in range(2, 5):
        ids[bat.submit(prompts[i], steps[i], **kw[i])] = i
    out = bat.run()
    assert set(out) == set(ids)
    for rid, i in ids.items():
        solo_kw = dict(kw[i])
        want = _solo(lm, variables, prompts[i], steps[i], **solo_kw)
        np.testing.assert_array_equal(out[rid], want, err_msg=f"req {i}")
    st = bat.stats()
    assert st["pages_in_use"] == 0 and st["pages_free"] == st["pool_pages"] - 1


def test_paged_small_pool_forces_waiting_but_completes(lm_setup):
    """A pool too small for all slots at once: admission stalls on pages
    (not slots), later requests run after earlier ones free theirs, and
    every output still matches solo generate."""
    lm, variables = lm_setup
    rng = np.random.RandomState(4)
    prompts = [rng.randint(0, 37, size=n).astype(np.int32)
               for n in (11, 12, 13, 14)]
    steps = [6, 6, 6, 6]
    # Each request needs ceil(max(16, s0+6)/16) = 2 pages (spans 17..20).
    # Pool of 5 = trash + 4: TWO requests resident max, though there are
    # 3 slots.
    bat = ContinuousBatcher(
        lm, variables, slots=3, chunk=4, kv_layout="paged", page_size=16,
        pool_pages=5,
    )
    ids = {bat.submit(p, s): i
           for i, (p, s) in enumerate(zip(prompts, steps))}
    bat.tick()
    st = bat.stats()
    assert st["active"] == 2 and st["pages_in_use"] == 4  # page-bound
    out = bat.run()
    for rid, i in ids.items():
        want = _solo(lm, variables, prompts[i], steps[i])
        np.testing.assert_array_equal(out[rid], want, err_msg=f"req {i}")


def test_prefix_cache_reuses_pages_across_requests(lm_setup):
    """Same prompt served twice: the second admission shares the first's
    registered full pages (prefix hits, fewer fresh allocations) and
    still emits exactly the solo generate() stream — suffix-only
    prefill must be invisible in outputs."""
    lm, variables = lm_setup
    rng = np.random.RandomState(7)
    prompt = rng.randint(0, 37, size=37).astype(np.int32)  # 2 full pages
    bat = ContinuousBatcher(
        lm, variables, slots=2, chunk=4, kv_layout="paged", page_size=16
    )
    r1 = bat.submit(prompt, 5)
    out1 = bat.run()
    assert bat._pager.stats().cached == 2  # two full pages registered
    r2 = bat.submit(prompt, 5)
    out2 = bat.run()
    want = _solo(lm, variables, prompt, 5)
    np.testing.assert_array_equal(out1[r1], want)
    np.testing.assert_array_equal(out2[r2], want)
    st = bat._pager.stats()
    assert st.prefix_hits == 2 and st.cached == 2


def test_prefix_cache_shared_system_prompt_live(lm_setup):
    """Two DIFFERENT requests sharing a long system prefix, resident
    simultaneously: the common full pages are shared in flight (rc=2 —
    observable as fewer pages in use than two solo windows) and both
    streams match solo generate()."""
    lm, variables = lm_setup
    rng = np.random.RandomState(8)
    system = rng.randint(0, 37, size=32).astype(np.int32)  # 2 full pages
    p1 = np.concatenate([system, rng.randint(0, 37, size=4).astype(np.int32)])
    p2 = np.concatenate([system, rng.randint(0, 37, size=7).astype(np.int32)])
    bat = ContinuousBatcher(
        lm, variables, slots=2, chunk=4, kv_layout="paged", page_size=16
    )
    r1 = bat.submit(p1, 4)
    bat.tick()  # admit + register p1's prefix pages
    r2 = bat.submit(p2, 4,
                    temperature=0.8, top_k=6, rng=jax.random.PRNGKey(11))
    bat.tick()  # p2 admits against p1's live pages
    st = bat._pager.stats()
    # Window per request = ceil(max(bucket=48? (36/43 -> 64), s0+4)/16)
    # pages; sharing saves 2 of them while both are live.
    assert bat._pager.prefix_hits == 2
    out = bat.run()
    np.testing.assert_array_equal(out[r1], _solo(lm, variables, p1, 4))
    np.testing.assert_array_equal(
        out[r2],
        _solo(lm, variables, p2, 4, temperature=0.8, top_k=6,
              rng=jax.random.PRNGKey(11)),
    )
    assert st.in_use < 2 * (-(-max(64, p1.shape[0] + 4) // 16))


def test_prefix_cache_eviction_under_pressure(lm_setup):
    """A pool with no spare room: cached (rc=0) prefix pages are evicted
    to admit an unrelated request, and serving stays correct."""
    lm, variables = lm_setup
    rng = np.random.RandomState(9)
    p_a = rng.randint(0, 37, size=33).astype(np.int32)
    p_b = rng.randint(0, 37, size=33).astype(np.int32)
    # Window: bucket 48? buckets are powers of two + max_len: 8,16,32,48
    # -> 33 fits bucket 48 (max_len); span max(48, 39) = 48 -> 3 pages.
    bat = ContinuousBatcher(
        lm, variables, slots=1, chunk=4, kv_layout="paged", page_size=16,
        pool_pages=4,  # exactly one window + trash: b must evict a's pages
    )
    ra = bat.submit(p_a, 5)
    out_a = bat.run()
    assert bat._pager.stats().cached == 2
    rb = bat.submit(p_b, 5)
    out_b = bat.run()
    np.testing.assert_array_equal(out_a[ra], _solo(lm, variables, p_a, 5))
    np.testing.assert_array_equal(out_b[rb], _solo(lm, variables, p_b, 5))
    # a's cached pages were evicted to make room; b's now sit in cache.
    assert bat._pager.stats().cached == 2
    # And a THIRD submit of p_a must recompute (its pages are gone) yet
    # still match.
    ra2 = bat.submit(p_a, 5)
    out_a2 = bat.run()
    np.testing.assert_array_equal(out_a2[ra2], _solo(lm, variables, p_a, 5))


def test_prefix_hit_suffix_bucket_rounds_past_span(lm_setup_64):
    """Regression: a short prefix hit (m=1) whose SUFFIX bucket
    re-rounds past the request's own span page count — the reservation
    must cover the suffix prefill's working strip, or _admit crashes
    (or silently corrupts shared pages under -O). s0=49, steps=5,
    P=16: span 64 -> 4 pages, but suffix 33 -> bucket 64 -> strip
    needs 5."""
    lm, variables = lm_setup_64
    rng = np.random.RandomState(11)
    first = rng.randint(0, 37, size=49).astype(np.int32)
    second = first.copy()
    second[20] = (second[20] + 1) % 37  # shares ONLY the first page
    bat = ContinuousBatcher(
        lm, variables, slots=1, chunk=4, kv_layout="paged", page_size=16
    )
    r1 = bat.submit(first, 5)
    out1 = bat.run()
    r2 = bat.submit(second, 5)
    out2 = bat.run()
    assert bat._pager.prefix_hits == 1  # page 0 shared, page 1 missed
    np.testing.assert_array_equal(
        out1[r1], _solo(lm, variables, first, 5)
    )
    np.testing.assert_array_equal(
        out2[r2], _solo(lm, variables, second, 5)
    )


def test_chunked_prefill_matches_generate_and_interleaves(lm_setup_64):
    """A long prompt admitted with prefill_chunk=16 prefills one
    page-chunk per tick while an already-running request keeps
    decoding — the long admission must not stall it — and the chunked
    request's GREEDY output equals solo generate(). (Greedy is the
    contract: chunk boundaries change fp contraction widths, so the
    cached K/V can differ from the one-pass values at ulp scale —
    invisible to argmax, but able to flip a high-temperature
    categorical draw at a near-tie. The sampled stream's equivalence
    is distributional, not bitwise — documented on prefill_chunk.)"""
    lm, variables = lm_setup_64
    rng = np.random.RandomState(12)
    short = rng.randint(0, 37, size=4).astype(np.int32)
    long_p = rng.randint(0, 37, size=50).astype(np.int32)
    bat = ContinuousBatcher(
        lm, variables, slots=2, chunk=2, kv_layout="paged", page_size=16,
        prefill_chunk=16,
    )
    r_short = bat.submit(short, 8,
                         temperature=0.9, top_k=5,
                         rng=jax.random.PRNGKey(13))
    bat.tick()  # short decoding
    emitted_before = len(bat.slots[0].tokens)
    r_long = bat.submit(long_p, 4)
    bat.tick()  # long prefills its first chunk; short keeps decoding
    assert bat.slots[1].pf_done >= 0  # still mid-prefill
    assert len(bat.slots[0].tokens) > emitted_before  # no stall
    out = bat.run()
    np.testing.assert_array_equal(
        out[r_short],
        _solo(lm, variables, short, 8, temperature=0.9, top_k=5,
              rng=jax.random.PRNGKey(13)),
    )
    np.testing.assert_array_equal(
        out[r_long], _solo(lm, variables, long_p, 4)
    )


def test_chunked_prefill_composes_with_prefix_cache(lm_setup_64):
    """Chunked prefill starts AFTER the shared prefix: a second long
    request with a cached 32-token prefix prefills only its remaining
    pages chunk by chunk, and matches solo generate()."""
    lm, variables = lm_setup_64
    rng = np.random.RandomState(13)
    system = rng.randint(0, 37, size=32).astype(np.int32)
    p1 = np.concatenate([system, rng.randint(0, 37, size=18).astype(np.int32)])
    p2 = np.concatenate([system, rng.randint(0, 37, size=20).astype(np.int32)])
    bat = ContinuousBatcher(
        lm, variables, slots=1, chunk=2, kv_layout="paged", page_size=16,
        prefill_chunk=16,
    )
    r1 = bat.submit(p1, 4)
    out1 = bat.run()
    hits_before = bat._pager.prefix_hits
    r2 = bat.submit(p2, 4)
    bat.tick()
    # p2 shares the two system pages and chunk-prefills from there.
    assert bat._pager.prefix_hits == hits_before + 2
    out2 = bat.run()
    np.testing.assert_array_equal(out1[r1], _solo(lm, variables, p1, 4))
    np.testing.assert_array_equal(out2[r2], _solo(lm, variables, p2, 4))


def test_decode_during_chunked_prefill_cannot_corrupt_prompt_pages(
    lm_setup_256,
):
    """Regression: while a slot is mid-chunked-prefill it still rides
    the lockstep decode batch as a dead row — and a dead row OWNS real
    pages, so its garbage write must go to the trash page, not
    table[row, 0] (= the prompt's first page). Before the negative-pos
    sentinel, concurrent decode overwrote prompt positions 0..chunk-1
    every tick and the chunked request's stream diverged from token
    one."""
    lm, variables = lm_setup_256
    rng = np.random.RandomState(14)
    short = rng.randint(0, 37, size=5).astype(np.int32)
    long_p = rng.randint(0, 37, size=124).astype(np.int32)
    bat = ContinuousBatcher(
        lm, variables, slots=2, chunk=2, kv_layout="paged", page_size=16,
        prefill_chunk=32,
    )
    r_short = bat.submit(short, 40)  # still decoding through the prefill
    bat.tick()
    r_long = bat.submit(long_p, 5)
    bat.tick()
    assert bat.slots[1].pf_done >= 0  # mid-prefill with decode running
    out = bat.run()
    np.testing.assert_array_equal(
        out[r_short], _solo(lm, variables, short, 40)
    )
    np.testing.assert_array_equal(
        out[r_long], _solo(lm, variables, long_p, 5)
    )


def test_chunked_prefill_validation(lm_setup):
    lm, variables = lm_setup
    with pytest.raises(ValueError, match="paged"):
        ContinuousBatcher(lm, variables, prefill_chunk=16)
    with pytest.raises(ValueError, match="multiple"):
        ContinuousBatcher(lm, variables, kv_layout="paged", page_size=16,
                          prefill_chunk=24)


def test_paged_validation(lm_setup):
    lm, variables = lm_setup
    with pytest.raises(ValueError, match="kv_layout"):
        ContinuousBatcher(lm, variables, kv_layout="vram")
    # Paged + int8 is a supported COMPOSITION (tests/test_quant_serving
    # pins its behavior); construction must succeed with pool pairs.
    q = ContinuousBatcher(
        lm, variables, slots=2, kv_layout="paged", kv_cache_dtype="int8"
    )
    assert isinstance(q._caches[0][0], tuple)  # (int8 values, f32 scales)
    bat = ContinuousBatcher(
        lm, variables, slots=2, kv_layout="paged", page_size=16,
        pool_pages=2,  # one allocatable page = 16 positions
    )
    with pytest.raises(ValueError, match="pages"):
        bat.submit(np.arange(10, dtype=np.int32), steps=20)  # needs 2
