"""Membership-fuzz stress test: randomized worker kills and joins under
sustained request load.

SURVEY.md §5 names "race detection / sanitizers" as absent from the
reference (manual locking only); our analog is this deterministic-seed
fuzz of membership events against the control plane's invariants:

  1. every submitted request either completes with the correct value or
     fails loudly — none lost, none duplicated (exactly-once);
  2. the pipeline keeps serving as long as >= 1 worker survives;
  3. the dispatcher's in-flight registry drains to empty.

Also exercises the tracing hook (stage_exec spans) under concurrency.
"""

import random
import threading
import time

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from adapt_tpu.config import FaultConfig, ServeConfig
from adapt_tpu.control.worker import StageWorker, WorkerState
from adapt_tpu.graph import INPUT, LayerGraph, partition
from adapt_tpu.runtime import ServingPipeline
from adapt_tpu.utils.tracing import global_tracer


def _graph(width=8, depth=3):
    g = LayerGraph("stress")
    prev = INPUT
    for i in range(depth):
        prev = g.add(f"dense{i}", nn.Dense(width), prev)
    return g


def test_membership_fuzz_exactly_once(rng, devices):
    random.seed(1234)
    g = _graph()
    x0 = jnp.ones((2, 8))
    variables = g.init(rng, x0)
    plan = partition(g, ["dense0", "dense1"])  # 3 stages
    config = ServeConfig(
        max_inflight=16,
        fault=FaultConfig(
            lease_ttl_s=0.4,
            heartbeat_s=0.1,
            task_deadline_s=1.5,
            watchdog_period_s=0.05,
            startup_wait_s=2.0,
            max_retries=4,
            configure_timeout_s=10.0,
        ),
    )
    pipe = ServingPipeline(plan, variables, devices=devices[:6], config=config)
    tracer = global_tracer()
    tracer.clear()
    tracer.enabled = True
    try:
        pipe.start()
        pipe.warmup(x0)
        expected = {}
        futures = {}
        stop_chaos = threading.Event()
        spawned = []

        def chaos():
            """Kill a random live worker (crash or hang) every ~150 ms and
            occasionally add a fresh worker — but always keep >= 2 alive."""
            idx = len(pipe.workers)
            while not stop_chaos.is_set():
                time.sleep(random.uniform(0.1, 0.2))
                live = [
                    w
                    for w in pipe.workers + spawned
                    if w.state is not WorkerState.DEAD and not w._hung.is_set()
                ]
                if len(live) > 2 and random.random() < 0.7:
                    victim = random.choice(live)
                    victim.kill(random.choice(["crash", "hang"]))
                elif random.random() < 0.5:
                    w = StageWorker(
                        worker_id=f"joined-{idx}",
                        device=devices[idx % 6],
                        registry=pipe.registry,
                        result_queue=pipe.dispatcher.result_queue,
                        fault=config.fault,
                    )
                    idx += 1
                    pipe.dispatcher.attach_worker(w)
                    w.start()
                    spawned.append(w)

        chaos_t = threading.Thread(target=chaos, daemon=True)
        chaos_t.start()

        full = jax.jit(g.apply)
        n_requests = 60
        for i in range(n_requests):
            x = jnp.full((2, 8), float(i % 7) - 3.0)
            futures[i] = pipe.dispatcher.submit(x)
            expected[i] = np.asarray(full(variables, x))
            time.sleep(random.uniform(0.0, 0.02))

        completed, failed = 0, 0
        for i, f in futures.items():
            try:
                y = f.result(timeout=60.0)
                np.testing.assert_allclose(
                    np.asarray(y), expected[i], rtol=1e-5, atol=1e-5
                )
                completed += 1
            except Exception:
                failed += 1
        stop_chaos.set()
        chaos_t.join(timeout=2.0)

        # Invariant 1: everything accounted for.
        assert completed + failed == n_requests
        # Invariant 2: the pool never dropped below 2 live workers, so the
        # overwhelming majority must complete (failures only possible if a
        # request burned all retries on freshly-killed workers).
        assert completed >= n_requests * 0.9, (completed, failed)
        # Invariant 3: in-flight registry drains.
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            with pipe.dispatcher._inflight_lock:
                if not pipe.dispatcher._inflight:
                    break
            time.sleep(0.05)
        with pipe.dispatcher._inflight_lock:
            assert not pipe.dispatcher._inflight
        # Tracing hook saw real concurrent execution.
        spans = tracer.spans("stage_exec")
        assert len(spans) >= completed * 3  # >= one span per stage per req
        # Request-latency histogram populated.
        snap = pipe.metrics()
        assert snap["histograms"]["request.latency_s"]["count"] >= completed
    finally:
        tracer.enabled = False
        pipe.shutdown()
