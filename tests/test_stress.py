"""Membership-fuzz stress test: randomized worker kills and joins under
sustained request load.

SURVEY.md §5 names "race detection / sanitizers" as absent from the
reference (manual locking only); our analog is this deterministic-seed
fuzz of membership events against the control plane's invariants:

  1. every submitted request either completes with the correct value or
     fails loudly — none lost, none duplicated (exactly-once);
  2. the pipeline keeps serving as long as >= 1 worker survives;
  3. the dispatcher's in-flight registry drains to empty.

Also exercises the tracing hook (stage_exec spans) under concurrency.
"""

import random
import threading
import time

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np

from adapt_tpu.config import FaultConfig, ServeConfig
from adapt_tpu.control.worker import StageWorker, WorkerState
from adapt_tpu.graph import INPUT, LayerGraph, partition
from adapt_tpu.runtime import ServingPipeline
from adapt_tpu.utils.tracing import global_tracer


def _graph(width=8, depth=3):
    g = LayerGraph("stress")
    prev = INPUT
    for i in range(depth):
        prev = g.add(f"dense{i}", nn.Dense(width), prev)
    return g


def test_membership_fuzz_exactly_once(rng, devices):
    random.seed(1234)
    g = _graph()
    x0 = jnp.ones((2, 8))
    variables = g.init(rng, x0)
    plan = partition(g, ["dense0", "dense1"])  # 3 stages
    config = ServeConfig(
        max_inflight=16,
        fault=FaultConfig(
            lease_ttl_s=0.4,
            heartbeat_s=0.1,
            task_deadline_s=1.5,
            watchdog_period_s=0.05,
            startup_wait_s=2.0,
            max_retries=4,
            configure_timeout_s=10.0,
        ),
    )
    pipe = ServingPipeline(plan, variables, devices=devices[:6], config=config)
    tracer = global_tracer()
    tracer.clear()
    tracer.enabled = True
    try:
        pipe.start()
        pipe.warmup(x0)
        expected = {}
        futures = {}
        stop_chaos = threading.Event()
        spawned = []

        def chaos():
            """Kill a random live worker (crash or hang) every ~150 ms and
            occasionally add a fresh worker — but always keep >= 2 alive."""
            idx = len(pipe.workers)
            while not stop_chaos.is_set():
                time.sleep(random.uniform(0.1, 0.2))
                live = [
                    w
                    for w in pipe.workers + spawned
                    if w.state is not WorkerState.DEAD and not w._hung.is_set()
                ]
                if len(live) > 2 and random.random() < 0.7:
                    victim = random.choice(live)
                    victim.kill(random.choice(["crash", "hang"]))
                elif random.random() < 0.5:
                    w = StageWorker(
                        worker_id=f"joined-{idx}",
                        device=devices[idx % 6],
                        registry=pipe.registry,
                        result_queue=pipe.dispatcher.result_queue,
                        fault=config.fault,
                    )
                    idx += 1
                    pipe.dispatcher.attach_worker(w)
                    w.start()
                    spawned.append(w)

        chaos_t = threading.Thread(target=chaos, daemon=True)
        chaos_t.start()

        full = jax.jit(g.apply)
        n_requests = 60
        for i in range(n_requests):
            x = jnp.full((2, 8), float(i % 7) - 3.0)
            futures[i] = pipe.dispatcher.submit(x)
            expected[i] = np.asarray(full(variables, x))
            time.sleep(random.uniform(0.0, 0.02))

        completed, failed = 0, 0
        for i, f in futures.items():
            try:
                y = f.result(timeout=60.0)
                np.testing.assert_allclose(
                    np.asarray(y), expected[i], rtol=1e-5, atol=1e-5
                )
                completed += 1
            except Exception:
                failed += 1
        stop_chaos.set()
        chaos_t.join(timeout=2.0)

        # Invariant 1: everything accounted for.
        assert completed + failed == n_requests
        # Invariant 2: the pool never dropped below 2 live workers, so the
        # overwhelming majority must complete (failures only possible if a
        # request burned all retries on freshly-killed workers).
        assert completed >= n_requests * 0.9, (completed, failed)
        # Invariant 3: in-flight registry drains.
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            with pipe.dispatcher._inflight_lock:
                if not pipe.dispatcher._inflight:
                    break
            time.sleep(0.05)
        with pipe.dispatcher._inflight_lock:
            assert not pipe.dispatcher._inflight
        # Tracing hook saw real concurrent execution.
        spans = tracer.spans("stage_exec")
        assert len(spans) >= completed * 3  # >= one span per stage per req
        # Request-latency histogram populated.
        snap = pipe.metrics()
        assert snap["histograms"]["request.latency_s"]["count"] >= completed
    finally:
        tracer.enabled = False
        pipe.shutdown()


def test_membership_fuzz_with_cross_host_join(rng, devices):
    """Exactly-once must hold while the pool GROWS across hosts: mid-burst,
    a remote worker process joins through the WorkerGateway while local
    workers are being killed (the reference's scheduling pool grew and
    shrank the same way, src/dispatcher.py:176-201 + node_state.py:17-20)."""
    from adapt_tpu.comm.remote import WorkerGateway
    from adapt_tpu.config import CodecConfig
    from adapt_tpu.models.vit import vit_tiny

    random.seed(99)
    g = vit_tiny()
    x0 = jnp.ones((2, 32, 32, 3), jnp.float32)
    variables = g.init(rng, x0)
    from adapt_tpu.graph import partition as partition_fn

    plan = partition_fn(g, ["encoder_block_1"])
    config = ServeConfig(
        max_inflight=8,
        fault=FaultConfig(
            lease_ttl_s=0.6,
            heartbeat_s=0.15,
            task_deadline_s=8.0,
            watchdog_period_s=0.1,
            startup_wait_s=5.0,
            max_retries=4,
            configure_timeout_s=30.0,
        ),
        codec=CodecConfig(name="bf16", weights="lz"),
    )
    from adapt_tpu.control.dispatcher import Dispatcher

    disp = Dispatcher(plan, variables, config=config)
    local = disp.spawn_workers(devices[:3])
    gateway = WorkerGateway(
        disp,
        model_config={
            "model": "vit_tiny",
            "num_classes": 10,
            "cuts": ["encoder_block_1"],
            "input_shape": [2, 32, 32, 3],
        },
    )
    full = jax.jit(g.apply)
    y_ref = np.asarray(full(variables, x0))
    procs = []
    try:
        disp.start()
        gateway.start()
        disp.warmup(x0)

        futures = {}
        n_requests = 24
        for i in range(n_requests):
            futures[i] = disp.submit(x0)
            if i == 4:
                # Pool grows: remote joiner dials in mid-burst.
                from conftest import spawn_worker_proc

                procs.append(
                    spawn_worker_proc(
                        "--connect", f"127.0.0.1:{gateway.port}",
                        "--worker-id", "fuzz-joiner", "--heartbeat", "0.1",
                    )
                )
            if i == 10:
                # Pool shrinks: one local worker crashes, one hangs.
                local[0].kill("crash")
                local[1].kill("hang")
            time.sleep(random.uniform(0.0, 0.05))

        completed = failed = 0
        for i, f in futures.items():
            try:
                y = f.result(timeout=120.0)
                # bf16 activation codec on the remote hop: loose tolerance.
                np.testing.assert_allclose(
                    np.asarray(y), y_ref, rtol=0.1, atol=0.1
                )
                completed += 1
            except Exception:
                failed += 1
        # Invariant 1: everything accounted for, none lost/duplicated.
        assert completed + failed == n_requests
        # Invariant 2: >= 1 worker always lived, so the stream survives.
        assert completed >= n_requests * 0.9, (completed, failed)
        # Invariant 3: the joiner actually became a member. The deadline
        # covers a cold `python -m` child (jax+flax import) on a LOADED
        # machine — 20 s flaked when the full suite ran alongside other
        # work; registration itself is milliseconds once the process is
        # up.
        deadline = time.monotonic() + 60.0
        while "fuzz-joiner" not in disp.registry.alive():
            assert time.monotonic() < deadline, "joiner never registered"
            time.sleep(0.05)
        # Invariant 4: in-flight registry drains.
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline:
            with disp._inflight_lock:
                if not disp._inflight:
                    break
            time.sleep(0.05)
        with disp._inflight_lock:
            assert not disp._inflight
    finally:
        for p in procs:
            p.terminate()
            p.wait(timeout=10)
        gateway.stop()
        disp.shutdown()
