"""ISSUE-12 kernel push: flash-split decode, int4 KV, tree-draft verify.

Interpreter-mode parity for every new kernel branch (split decode
dense/paged x native/int8/int4, ragged last split, split=1 degenerate
== the unsplit kernel bit-exact; tree-mask verify vs a jnp oracle),
the batcher-level invariants under `KernelConfig` split dispatch
(bit-identical greedy streams, 0 h2d/steady tick, frozen compile
footprint), tree-draft losslessness + the > 5.0 accepted-per-pass
claim, int4 composition (top-1 agreement vs int8, prefix cache, disagg
handoff, tp=2 sharding, recovery migration), the kernel-dispatch
gauges, and the per-generation roofline peak table."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adapt_tpu.config import (
    KernelConfig,
    ParallelConfig,
    SpeculativeConfig,
)
from adapt_tpu.models.transformer_lm import (
    generate,
    lm_tiny,
    transformer_lm,
)
from adapt_tpu.ops.decode_attention import (
    decode_attention,
    decode_attention_reference,
    default_decode_split,
    kernel_dispatch_stats,
    verify_attention,
)
from adapt_tpu.ops.paged_attention import (
    paged_attention,
    paged_attention_reference,
    paged_verify_attention,
    paged_verify_attention_reference,
)
from adapt_tpu.ops.quantize import (
    pack_int4,
    quantize_kv_vectors,
    unpack_int4,
)
from adapt_tpu.runtime.continuous import ContinuousBatcher

VOCAB = 37


def _solo(lm, variables, prompt, steps, **kw):
    return np.asarray(
        generate(lm, variables, jnp.asarray(prompt)[None], steps, **kw)
    )[0]


@pytest.fixture(scope="module")
def lm_setup():
    lm = lm_tiny(vocab=VOCAB, max_len=96)
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    return lm, variables


# -- ops: packing ------------------------------------------------------------


def test_int4_pack_roundtrip():
    rng = np.random.RandomState(0)
    q = rng.randint(-8, 8, size=(3, 5, 16)).astype(np.int32)
    rt = np.asarray(unpack_int4(pack_int4(jnp.asarray(q))))
    np.testing.assert_array_equal(rt, q)


def test_int4_quantize_kv_vectors_shapes_and_error():
    t = jnp.asarray(np.random.RandomState(1).randn(2, 3, 16), jnp.float32)
    v8, s8 = quantize_kv_vectors(t, "int8")
    v4, s4 = quantize_kv_vectors(t, "int4")
    assert v8.shape == (2, 3, 16) and v4.shape == (2, 3, 8)
    assert s8.shape == s4.shape == (2, 3, 1)
    # int4 dequant stays within one lattice step of the input
    deq = np.asarray(unpack_int4(v4)) * np.asarray(s4)
    assert np.abs(deq - np.asarray(t)).max() <= np.asarray(s4).max() * 0.51
    with pytest.raises(ValueError, match="even head_dim"):
        quantize_kv_vectors(t[..., :15], "int4")


def test_default_decode_split_rule():
    assert [default_decode_split(n) for n in (1, 2, 3, 4, 8, 16, 64)] == [
        1, 1, 1, 2, 4, 8, 8,
    ]


# -- ops: interpreter parity, every new branch -------------------------------


def _quant(pool, dt):
    return quantize_kv_vectors(pool, dt)


@pytest.mark.parametrize("dtype", ["native", "int8", "int4"])
@pytest.mark.parametrize("split", [1, 2, 3, 4])
def test_split_decode_dense_parity(dtype, split):
    """Dense split kernel vs the einsum oracle, every dtype, including
    the RAGGED split=3 over 4 blocks and a ragged valid_from window."""
    rng = np.random.RandomState(0)
    b, kvh, g, hd, L = 2, 2, 4, 16, 1024
    q = jnp.asarray(rng.randn(b, kvh, g, hd), jnp.float32)
    k = jnp.asarray(rng.randn(b, kvh, L, hd), jnp.float32)
    v = jnp.asarray(rng.randn(b, kvh, L, hd), jnp.float32)
    idx = jnp.asarray([700, 130], jnp.int32)
    vf = jnp.asarray([3, 0], jnp.int32)
    if dtype == "native":
        ck, cv = k, v
    else:
        ck, cv = _quant(k, dtype), _quant(v, dtype)
    ref = decode_attention_reference(q, ck, cv, idx, vf)
    out = decode_attention(
        q, ck, cv, idx, vf, prefer="pallas", split=split
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5
    )


def test_split1_degenerate_bit_exact():
    """split=1 must be the ORIGINAL single-stream kernel bit-for-bit
    (it IS that code path; the combine never runs)."""
    rng = np.random.RandomState(2)
    q = jnp.asarray(rng.randn(1, 2, 4, 16), jnp.float32)
    k = jnp.asarray(rng.randn(1, 2, 512, 16), jnp.float32)
    v = jnp.asarray(rng.randn(1, 2, 512, 16), jnp.float32)
    idx = jnp.asarray([200], jnp.int32)
    a = decode_attention(q, k, v, idx, prefer="pallas", split=1)
    b = decode_attention(q, k, v, idx, prefer="pallas")  # auto off-TPU -> 1
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.parametrize("dtype", ["native", "int8", "int4"])
@pytest.mark.parametrize("split", [2, 3])
def test_split_decode_paged_parity(dtype, split):
    rng = np.random.RandomState(1)
    b, kvh, g, hd, P, pps = 2, 2, 4, 16, 128, 5
    npages = b * pps + 1
    kp = jnp.asarray(rng.randn(npages, kvh, P, hd), jnp.float32)
    vp = jnp.asarray(rng.randn(npages, kvh, P, hd), jnp.float32)
    table = jnp.asarray(
        np.arange(1, 1 + b * pps).reshape(b, pps), jnp.int32
    )
    q = jnp.asarray(rng.randn(b, kvh, g, hd), jnp.float32)
    idx = jnp.asarray([500, 60], jnp.int32)
    if dtype != "native":
        kp, vp = _quant(kp, dtype), _quant(vp, dtype)
    ref = paged_attention_reference(q, kp, vp, table, idx)
    out = paged_attention(
        q, kp, vp, table, idx, prefer="pallas", split=split
    )
    np.testing.assert_allclose(
        np.asarray(out), np.asarray(ref), atol=2e-5
    )


@pytest.mark.parametrize("dtype", ["native", "int8", "int4"])
@pytest.mark.parametrize("split,tree_tail", [(1, 0), (2, 0), (1, 2), (3, 2)])
def test_split_verify_paged_parity(dtype, split, tree_tail):
    """Batched paged verify: split x tree-mask x dtype vs the gather
    oracle, with a DEAD (negative-index) row in the batch (compared on
    the live row only — dead rows emit finite garbage by contract)."""
    rng = np.random.RandomState(4)
    b, kvh, g, hd, P, pps, K = 2, 2, 4, 16, 128, 5, 6
    npages = b * pps + 1
    kp = jnp.asarray(rng.randn(npages, kvh, P, hd), jnp.float32)
    vp = jnp.asarray(rng.randn(npages, kvh, P, hd), jnp.float32)
    table = jnp.asarray(
        np.arange(1, 1 + b * pps).reshape(b, pps), jnp.int32
    )
    q = jnp.asarray(rng.randn(b, kvh, g * K, hd), jnp.float32)
    idx = jnp.asarray([300, -7], jnp.int32)  # row 1 dead
    if dtype != "native":
        kp, vp = _quant(kp, dtype), _quant(vp, dtype)
    ref = paged_verify_attention_reference(
        q, kp, vp, table, idx, K, tree_tail=tree_tail
    )
    out = paged_verify_attention(
        q, kp, vp, table, idx, K, prefer="pallas",
        tree_tail=tree_tail, split=split,
    )
    np.testing.assert_allclose(
        np.asarray(out[0]), np.asarray(ref[0]), atol=2e-5
    )


def test_tree_mask_verify_vs_jnp_oracle():
    """The tree mask's semantics pinned against a hand-built oracle:
    chain rows keep their diagonal, each leaf row attends the chain
    plus ONLY its own slot (never a sibling's)."""
    rng = np.random.RandomState(5)
    b, kvh, g, hd, L, K, w = 2, 2, 2, 16, 64, 6, 2
    ck = jnp.asarray(rng.randn(b, kvh, L, hd), jnp.float32)
    cv = jnp.asarray(rng.randn(b, kvh, L, hd), jnp.float32)
    q = jnp.asarray(rng.randn(b, kvh, g * K, hd), jnp.float32)
    idx = np.asarray([10, 20], np.int32)
    out = np.asarray(
        verify_attention(q, ck, cv, jnp.asarray(idx), K, tree_tail=w)
    )
    s = np.einsum(
        "bhqd,bhkd->bhqk", np.asarray(q), np.asarray(ck)
    ) / np.sqrt(hd)
    chain = K - 1 - w
    rows = np.arange(g * K) % K
    man = np.zeros_like(out)
    for bi in range(b):
        for r in range(g * K):
            t = rows[r]
            live = np.arange(L) <= idx[bi] + min(t, chain)
            live |= np.arange(L) == idx[bi] + t
            srow = np.where(live, s[bi, :, r, :], -1e30)
            e = np.exp(srow - srow.max(-1, keepdims=True))
            p = e / e.sum(-1, keepdims=True)
            man[bi, :, r, :] = np.einsum(
                "hk,hkd->hd", p, np.asarray(cv)[bi]
            )
    np.testing.assert_allclose(out, man, atol=2e-5)


# -- kernel-dispatch gauges --------------------------------------------------


def test_kernel_dispatch_gauges_surface_fallback():
    """Every dispatcher records pallas-vs-oracle at trace time and the
    engine collector exports the gauges — the silent `_kernel_supported`
    fallback is now observable."""
    from adapt_tpu.utils.metrics import global_metrics

    rng = np.random.RandomState(6)
    q = jnp.asarray(rng.randn(1, 2, 4, 16), jnp.float32)
    kp = jnp.asarray(rng.randn(5, 2, 8, 16), jnp.float32)  # page 8:
    vp = jnp.asarray(rng.randn(5, 2, 8, 16), jnp.float32)  # unsupported
    table = jnp.asarray([[1, 2]], jnp.int32)
    paged_attention(q, kp, vp, table, jnp.asarray([9], jnp.int32))
    st = kernel_dispatch_stats()
    assert st["paged_decode"]["last"] == 0.0  # oracle (page not lane-mult)
    assert st["paged_decode"]["xla"] >= 1
    kp2 = jnp.asarray(rng.randn(3, 2, 128, 16), jnp.float32)
    vp2 = jnp.asarray(rng.randn(3, 2, 128, 16), jnp.float32)
    paged_attention(
        q, kp2, vp2, jnp.asarray([[1, 2]], jnp.int32),
        jnp.asarray([100], jnp.int32), prefer="pallas",
    )
    st = kernel_dispatch_stats()
    assert st["paged_decode"]["last"] == 1.0
    assert st["paged_decode"]["pallas"] >= 1
    snap = global_metrics().snapshot()
    gauges = snap["gauges"]
    assert gauges["engine.kernel_dispatch.paged_decode"] == 1.0
    assert gauges["engine.kernel_dispatch.paged_decode.xla_total"] >= 1


def test_roofline_peaks_per_generation(monkeypatch):
    """The peak table resolves by device KIND (v4/v5e/v5p/v6e rows) and
    the env override beats everything — the documented knob order."""
    from adapt_tpu.utils import profiling

    assert {"tpu v4", "tpu v5e", "tpu v5p", "tpu v6e"} <= set(
        profiling.ROOFLINE_PEAKS
    )
    # distinct generations carry distinct peaks
    assert (
        profiling.ROOFLINE_PEAKS["tpu v4"]
        != profiling.ROOFLINE_PEAKS["tpu v5p"]
    )
    monkeypatch.setenv("ADAPT_TPU_PEAK_FLOPS", "1e12")
    monkeypatch.setenv("ADAPT_TPU_PEAK_BYTES_S", "1e11")
    assert profiling.roofline_peaks() == (1e12, 1e11)
    monkeypatch.delenv("ADAPT_TPU_PEAK_FLOPS")
    monkeypatch.delenv("ADAPT_TPU_PEAK_BYTES_S")
    # CPU backend, no override: no honest peak
    assert profiling.roofline_peaks() is None


# -- batcher: split dispatch invariants --------------------------------------


@pytest.mark.slow
@pytest.mark.parametrize("layout", ["dense", "paged"])
def test_batcher_split_streams_bit_identical(layout):
    """Greedy streams are BIT-IDENTICAL across split in {1, 2, 4} and
    vs the default XLA path on both layouts, across staggered
    admits/retires/cancels; 0 h2d per steady tick and a frozen compile
    footprint hold under the split kernels (sentinel-pinned)."""
    from adapt_tpu.utils.profiling import global_compile_sentinel

    max_len = 255 if layout == "dense" else 256
    lm = transformer_lm(VOCAB, 32, 2, 2, 64, max_len=max_len,
                        name=f"split_{layout}")
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    rng = np.random.RandomState(0)
    prompts = [rng.randint(0, VOCAB, size=n).astype(np.int32)
               for n in (4, 7, 3)]
    sentinel = global_compile_sentinel()
    streams = {}
    for tag, kern in (
        ("xla", None),
        ("s1", KernelConfig(attn_impl="pallas", decode_split=1)),
        ("s2", KernelConfig(attn_impl="pallas", decode_split=2)),
        ("s4", KernelConfig(attn_impl="pallas", decode_split=4)),
    ):
        kw: dict = dict(chunk=2)
        if layout == "paged":
            kw.update(kv_layout="paged", page_size=128, pool_pages=9)
        bat = ContinuousBatcher(
            lm, variables, slots=2, kernel=kern, **kw
        )
        # staggered admits, then a steady-state window with BOTH slots
        # mid-flight (steps sized to outlive it — a retirement's
        # row-clear is a legitimate +1, not a violation)
        r1 = bat.submit(prompts[0], 20)
        bat.tick()
        r2 = bat.submit(prompts[1], 20)
        bat.tick()
        bat.tick()
        h2d0 = bat.stats()["h2d_transfers"]
        c0 = sentinel.compiles("continuous.step_chunk")
        bat.tick()
        assert bat.stats()["h2d_transfers"] == h2d0  # 0 h2d/steady tick
        assert sentinel.compiles("continuous.step_chunk") == c0
        # a queued cancel rides the drain, exercising the churn path
        rc = bat.submit(prompts[2], 8)
        bat.cancel(rc)
        out = bat.run()
        streams[tag] = {0: out[r1], 1: out[r2]}
        bat.close()
    for tag in ("s1", "s2", "s4"):
        for i in (0, 1):
            np.testing.assert_array_equal(
                streams[tag][i], streams["xla"][i],
                err_msg=f"{layout}/{tag} req {i} diverged",
            )


@pytest.mark.slow
def test_batcher_split_speculative_int8():
    """Split dispatch composes with speculative mode over int8 pools:
    the spec stream under (pallas, split=2) equals the XLA-path spec
    stream AND solo generate(int8)."""
    lm = transformer_lm(VOCAB, 32, 2, 2, 64, max_len=256,
                        name="split_spec")
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    p = np.asarray([1, 2, 3, 4, 5], np.int32)
    outs = {}
    for tag, kern in (
        ("xla", None),
        ("s2", KernelConfig(attn_impl="pallas", decode_split=2)),
    ):
        bat = ContinuousBatcher(
            lm, variables, slots=2, kv_layout="paged", page_size=128,
            kv_cache_dtype="int8", draft_lm=lm, draft_variables=variables,
            speculative=SpeculativeConfig(draft_k=3), kernel=kern,
        )
        r = bat.submit(p, 10)
        outs[tag] = bat.run()[r]
        bat.close()
    solo = _solo(lm, variables, p, 10, kv_cache_dtype="int8")
    np.testing.assert_array_equal(outs["s2"], outs["xla"])
    np.testing.assert_array_equal(outs["s2"], solo)


# -- tree drafts -------------------------------------------------------------


@pytest.mark.parametrize("layout", ["dense", "paged"])
def test_tree_spec_lossless_and_beats_chain(lm_setup, layout):
    """tree_width=1: the emitted stream is STILL exactly the target's
    greedy stream (lossless, staggered admits + a cancel), and the
    perfect-draft arm commits > 5.0 tokens per verify pass at
    draft_k=4 (the chain's ceiling)."""
    lm, variables = lm_setup
    rng = np.random.RandomState(3)
    prompts = [rng.randint(0, VOCAB, size=n).astype(np.int32)
               for n in (4, 6)]
    kw: dict = {}
    if layout == "paged":
        kw.update(kv_layout="paged", page_size=8)
    bat = ContinuousBatcher(
        lm, variables, slots=2, draft_lm=lm, draft_variables=variables,
        speculative=SpeculativeConfig(draft_k=4, tree_width=1), **kw,
    )
    r1 = bat.submit(prompts[0], 40)
    bat.tick()
    r2 = bat.submit(prompts[1], 30)
    rc = bat.submit(prompts[0], 5)
    bat.cancel(rc)
    bat.tick()
    # steady-state acceptance window (both slots decoding)
    e0 = sum(len(s.tokens) for s in bat.slots if s.req is not None)
    for _ in range(3):
        bat.tick()
    e1 = sum(len(s.tokens) for s in bat.slots if s.req is not None)
    per_pass = (e1 - e0) / (3 * 2)
    out = bat.run()
    np.testing.assert_array_equal(out[r1], _solo(lm, variables, prompts[0], 40))
    np.testing.assert_array_equal(out[r2], _solo(lm, variables, prompts[1], 30))
    assert out[rc].size == 0 or len(out[rc]) < 5  # cancelled
    assert per_pass > 5.0, per_pass
    assert bat.stats()["spec_acceptance"] == 1.0
    bat.close()


def test_tree_spec_adversarial_draft_still_lossless(lm_setup):
    """A wrong draft (acceptance ~1/vocab) with tree_width=2: the tree
    machinery must never corrupt the stream — worst case it commits 1
    token per round like chain speculation."""
    lm, variables = lm_setup
    adv = transformer_lm(VOCAB, 16, 1, 1, 32, max_len=96,
                         name="tree_adv")
    avars = adv.graph.init(
        jax.random.PRNGKey(9), jnp.zeros((1, 4), jnp.int32)
    )
    p = np.asarray([5, 6, 7], np.int32)
    bat = ContinuousBatcher(
        lm, variables, slots=2, draft_lm=adv, draft_variables=avars,
        speculative=SpeculativeConfig(draft_k=3, tree_width=2),
    )
    r = bat.submit(p, 16)
    out = bat.run()
    np.testing.assert_array_equal(out[r], _solo(lm, variables, p, 16))
    bat.close()


# -- int4 composition --------------------------------------------------------


def test_int4_top1_agreement_vs_int8():
    """Teacher-forced per-step top-1 agreement between int4 and int8
    caches >= 0.95: both caches serve the SAME committed stream (the
    int8 greedy stream) and the next-token argmaxes are compared at
    every step — the quantization perturbation alone, no free-running
    divergence compounding. Seeds are PINNED (untrained toy models'
    argmax gaps vary widely across inits; this deterministic
    configuration measures 1.0/0.988 across the two pinned prompts —
    the gate guards the quantization scheme, i.e. a packing or scale
    regression would crater it, not the toy model's luck)."""
    lm = transformer_lm(13, 64, 2, 2, 128, max_len=96, name="i4_agree")
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    rng = np.random.RandomState(11)
    g = lm.graph
    embed = g.node("embed").module
    head = g.node("head").module
    blocks = [g.node(n).module for n in lm.block_names]

    def preds(dt, prompt, stream):
        quant = dt if dt != "native" else False
        h = embed.apply(variables["embed"], prompt)
        caches = []
        for name, block in zip(lm.block_names, blocks):
            h, ck, cv = block.apply(
                variables[name], h, lm.max_len, None, quant,
                method="prefill",
            )
            caches.append((ck, cv))
        out = [int(jnp.argmax(
            head.apply(variables["head"], h[:, -1:, :])[:, 0], -1
        )[0])]
        idx = prompt.shape[1]
        for t in stream:
            x = embed.apply(
                variables["embed"], jnp.asarray([[t]], jnp.int32), idx,
                method="embed_at",
            )
            new = []
            for name, block, (ck, cv) in zip(
                lm.block_names, blocks, caches
            ):
                x, ck, cv = block.apply(
                    variables[name], x, ck, cv, idx, None, False,
                    method="decode_step",
                )
                new.append((ck, cv))
            caches = new
            out.append(int(jnp.argmax(
                head.apply(variables["head"], x)[:, 0], -1
            )[0]))
            idx += 1
        return out

    agree = total = 0
    for trial in range(2):
        p = jnp.asarray(rng.randint(0, lm.vocab, (1, 6)), jnp.int32)
        stream = [int(t) for t in np.asarray(
            generate(lm, variables, p, 20, kv_cache_dtype="int8")
        )[0][:-1]]
        a = preds("int8", p, stream)
        b = preds("int4", p, stream)
        agree += sum(x == y for x, y in zip(a, b))
        total += len(a)
    assert agree / total >= 0.95, f"top-1 agreement {agree}/{total}"


def test_int4_batcher_lossless_and_prefix_cache(lm_setup):
    """int4 batcher streams equal solo generate(kv_cache_dtype='int4')
    on both layouts, and a re-submitted prompt enters through the
    prefix cache (its int4 pages + scale planes are reused)."""
    lm, variables = lm_setup
    p = np.asarray(list(range(1, 19)), np.int32)  # 2 full 8-pages
    solo = _solo(lm, variables, p, 6, kv_cache_dtype="int4")
    for kw in ({}, {"kv_layout": "paged", "page_size": 8}):
        bat = ContinuousBatcher(
            lm, variables, slots=2, kv_cache_dtype="int4", **kw
        )
        r1 = bat.submit(p, 6)
        out1 = bat.run()[r1]
        np.testing.assert_array_equal(out1, solo)
        if kw:
            hits0 = bat._pager.prefix_hits
            r2 = bat.submit(p, 6)
            out2 = bat.run()[r2]
            assert bat._pager.prefix_hits > hits0
            np.testing.assert_array_equal(out2, solo)
        bat.close()


@pytest.mark.slow
def test_int4_disagg_handoff():
    """A disaggregated prefill over int4 pools streams packed pages +
    scale planes over the wire (kv_dtype in the annex) and the decode
    side's stream equals the collocated int4 stream."""
    from adapt_tpu.config import DisaggConfig
    from adapt_tpu.runtime.disagg import DisaggServer, PrefillWorker

    lm = transformer_lm(61, 32, 2, 2, 64, max_len=96, name="i4_disagg")
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    PAGE = 8
    prompt = np.arange(1, 2 * PAGE + 4, dtype=np.int32)  # > threshold

    def decode_bat():
        return ContinuousBatcher(
            lm, variables, slots=2, chunk=4, kv_layout="paged",
            page_size=PAGE, kv_cache_dtype="int4",
        )

    solo_bat = decode_bat()
    r = solo_bat.submit(prompt, 6)
    collocated = solo_bat.run()[r]
    solo_bat.close()

    decode = decode_bat()
    worker = PrefillWorker(
        lm, variables, page_size=PAGE, prefill_chunk=2 * PAGE,
        kv_cache_dtype="int4",
    )
    srv = DisaggServer(
        decode, worker,
        DisaggConfig(prompt_threshold=2 * PAGE,
                     busy_prompt_threshold=2 * PAGE),
    )
    rid = srv.submit(prompt, 6)
    out = srv.run()
    np.testing.assert_array_equal(out[rid], collocated)
    assert srv.stats()["disaggregated"] == 1
    assert decode._pager.prefix_hits > 0  # landed through the cache
    decode.close()


@pytest.mark.slow
def test_int4_tp2_and_recovery_migration(sim_mesh):
    """int4 pools head-shard under tp=2 (both pytree members at
    logical/2 per device) and a chip loss migrates them live: the
    post-kill stream equals solo generate(kv_cache_dtype='int4')."""
    from adapt_tpu.control.registry import DeviceHealthMonitor
    from adapt_tpu.utils.profiling import device_local_nbytes

    lm = transformer_lm(37, 32, 2, 8, 64, max_len=48, kv_heads=4,
                        name="i4_rec")
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    p = np.asarray([1, 2, 3], np.int32)
    solo = _solo(lm, variables, p, 10, kv_cache_dtype="int4")
    mon = DeviceHealthMonitor()
    bat = ContinuousBatcher(
        lm, variables, slots=2, chunk=2, mesh=sim_mesh(2),
        parallel=ParallelConfig(tp=2), kv_cache_dtype="int4",
        kv_layout="paged", page_size=8, health=mon,
    )
    # sharded: both members at logical/2 per device
    for ck, cv in bat._caches:
        for member in (*ck, *cv):
            assert device_local_nbytes(member) * 2 == member.nbytes
    r = bat.submit(p, 10)
    bat.tick()
    mon.kill(list(bat._mesh.devices.flat)[1])
    out = bat.run()
    st = bat.stats()
    assert st["tp"] == 1 and st["recoveries"] == 1
    np.testing.assert_array_equal(out[r], solo)
    bat.close()
