"""MoE + expert-parallelism tests.

Correctness oracle: with capacity >= N every token reaches its chosen
expert(s), so routed output must equal a dense per-token loop over the
same expert MLPs. EP test: expert-sharded forward == replicated forward.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adapt_tpu.core.mesh import MeshSpec, build_mesh
from adapt_tpu.models.moe import MoEMlp
from adapt_tpu.parallel.expert import (
    expert_shardings,
    expert_utilization,
    place_experts,
)

B, S, D, E, H = 2, 16, 8, 4, 32


def _dense_oracle(variables, x, top_k):
    """Route every token through its top-k experts with full capacity."""
    p = variables["params"]
    n = B * S
    tokens = np.asarray(x.reshape(n, D), np.float32)
    gates = jax.nn.softmax(
        jnp.asarray(tokens) @ p["gate"], axis=-1
    )
    gates = np.asarray(gates)
    out = np.zeros_like(tokens)
    for t in range(n):
        order = np.argsort(-gates[t])
        for choice in order[:top_k]:
            hidden = np.asarray(
                jax.nn.gelu(
                    jnp.asarray(tokens[t] @ np.asarray(p["w1"][choice]))
                    + jnp.asarray(p["b1"][choice])
                )
            )
            y = hidden @ np.asarray(p["w2"][choice]) + np.asarray(
                p["b2"][choice]
            )
            out[t] += gates[t, choice] * y
    return out.reshape(B, S, D)


@pytest.mark.parametrize("top_k", [1, 2])
def test_moe_matches_dense_oracle_full_capacity(rng, top_k):
    moe = MoEMlp(
        num_experts=E, hidden_dim=H, top_k=top_k, capacity_factor=float(E)
    )  # capacity >= N: nothing dropped
    x = jax.random.normal(rng, (B, S, D))
    variables = moe.init(jax.random.PRNGKey(1), x)
    y = moe.apply(variables, x)
    ref = _dense_oracle(variables, x, top_k)
    np.testing.assert_allclose(np.asarray(y), ref, rtol=2e-4, atol=2e-4)


def test_moe_capacity_drops_tokens(rng):
    moe = MoEMlp(num_experts=E, hidden_dim=H, top_k=1, capacity_factor=0.05)
    x = jax.random.normal(rng, (B, S, D))
    variables = moe.init(jax.random.PRNGKey(1), x)
    y, state = moe.apply(variables, x, mutable=["intermediates"])
    # capacity ~ 1 slot/expert: most tokens dropped -> many zero outputs.
    zero_rows = np.sum(
        np.all(np.asarray(y).reshape(-1, D) == 0.0, axis=-1)
    )
    assert zero_rows > 0
    aux = state["intermediates"]["aux_loss"][0]
    assert np.isfinite(float(aux)) and float(aux) >= 1.0 - 1e-3


def test_moe_aux_loss_uniform_is_one():
    # Perfectly uniform gates -> aux loss == 1 (its minimum).
    from adapt_tpu.models.moe import _one_hot_routing

    gates = jnp.full((8, 4), 0.25)
    _, _, aux = _one_hot_routing(gates, capacity=8, top_k=1)
    assert abs(float(aux) - 1.0) < 1e-5


def test_expert_parallel_matches_replicated(rng, devices):
    mesh = build_mesh(MeshSpec((("ep", 4),)), devices[:4])
    moe = MoEMlp(num_experts=E, hidden_dim=H, top_k=1, capacity_factor=2.0)
    x = jax.random.normal(rng, (B, S, D))
    variables = moe.init(jax.random.PRNGKey(1), x)
    ref = moe.apply(variables, x)

    shardings = expert_shardings(variables, mesh, num_experts=E)
    # gate [D, E]: not expert-stacked -> replicated; w1 [E, D, H]: sharded.
    flat = jax.tree_util.tree_leaves_with_path(shardings)
    specs = {
        jax.tree_util.keystr(path): s.spec for path, s in flat
    }
    assert any(spec == jax.sharding.PartitionSpec("ep", None, None)
               for spec in specs.values())
    placed = place_experts(variables, mesh, num_experts=E)
    y = jax.jit(moe.apply)(placed, x)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(ref), rtol=2e-5, atol=2e-5
    )


def test_expert_utilization_sums_to_one(rng):
    gates = jax.nn.softmax(jax.random.normal(rng, (64, E)), axis=-1)
    util = expert_utilization(gates)
    assert util.shape == (E,)
    assert abs(util.sum() - 1.0) < 1e-6


# -- MoE decoder LM (dropless per-token routing) ------------------------------


def _moe_lm():
    from adapt_tpu.models.transformer_lm import transformer_lm

    return transformer_lm(
        53, 32, 2, 4, 48, max_len=48, moe_experts=8, moe_top_k=2,
        name="moe_lm",
    )


def test_moe_decoder_mlp_is_per_token_independent(rng):
    """The parity-enabling property: each token's output depends only on
    its own hidden state — a batch of two rows equals the two rows
    computed separately (capacity routing would fail this)."""
    from adapt_tpu.models.moe import MoEDecoderMlp

    m = MoEDecoderMlp(num_experts=8, hidden_dim=16, top_k=2)
    x = jax.random.normal(rng, (2, 8, 8))
    variables = m.init(jax.random.PRNGKey(0), x)
    both = m.apply(variables, x)
    one = m.apply(variables, x[:1])
    two = m.apply(variables, x[1:])
    np.testing.assert_allclose(
        np.asarray(both), np.concatenate([one, two]), rtol=1e-6, atol=1e-6
    )


def test_moe_lm_cached_decode_matches_full_forward():
    """KV-cached greedy generate on the MoE decoder == stepwise argmax
    of the full causal forward — the same parity bar as the dense LM
    (dropless routing is what makes it reachable)."""
    from adapt_tpu.models.transformer_lm import generate, logits_full

    lm = _moe_lm()
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (2, 6), 0, 53, jnp.int32
    )
    got = np.asarray(generate(lm, variables, prompt, steps=5))
    ids = prompt
    for _ in range(5):
        nxt = jnp.argmax(logits_full(lm, variables, ids)[:, -1], -1)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(got, np.asarray(ids)[:, 6:])


def test_moe_lm_serves_through_paged_batcher():
    from adapt_tpu.models.transformer_lm import generate
    from adapt_tpu.runtime.continuous import ContinuousBatcher

    lm = _moe_lm()
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    rng = np.random.RandomState(21)
    prompts = [rng.randint(0, 53, size=n).astype(np.int32)
               for n in (5, 9, 3)]
    bat = ContinuousBatcher(
        lm, variables, slots=2, chunk=2, kv_layout="paged", page_size=16
    )
    ids = {bat.submit(p, 4): p for p in prompts}
    out = bat.run()
    for rid, p in ids.items():
        want = np.asarray(
            generate(lm, variables, jnp.asarray(p)[None], 4)
        )[0]
        np.testing.assert_array_equal(out[rid], want)


def test_moe_lm_expert_sharded_generate_matches(devices):
    """Experts placed over an 8-device ep mesh: generate() under GSPMD
    equals the replicated run token-for-token."""
    from adapt_tpu.models.transformer_lm import generate

    lm = _moe_lm()
    variables = lm.graph.init(
        jax.random.PRNGKey(0), jnp.zeros((1, 4), jnp.int32)
    )
    prompt = jax.random.randint(
        jax.random.PRNGKey(2), (2, 5), 0, 53, jnp.int32
    )
    want = np.asarray(generate(lm, variables, prompt, steps=4))
    mesh = build_mesh(MeshSpec(axes=(("ep", len(devices)),)))
    placed = place_experts(variables, mesh, num_experts=8)
    got = np.asarray(generate(lm, placed, prompt, steps=4))
    np.testing.assert_array_equal(got, want)


def test_both_moe_layers_sow_one_aux_convention(rng):
    """The refactor's invariant: MoEMlp (capacity-routed) and
    MoEDecoderMlp (dropless) sow the SAME Switch-style aux_loss for the
    same inputs — one scale, one threshold, as the docstrings promise."""
    from adapt_tpu.models.moe import MoEDecoderMlp

    x = jax.random.normal(rng, (B, S, D))
    train = MoEMlp(num_experts=E, hidden_dim=H, top_k=1,
                   capacity_factor=float(E))
    serve = MoEDecoderMlp(num_experts=E, hidden_dim=H, top_k=1)
    tv = train.init(jax.random.PRNGKey(1), x)
    # Same gate weights -> same routing distribution for both layers.
    sv = jax.tree.map(lambda a: a, serve.init(jax.random.PRNGKey(1), x))
    sv["params"]["gate"] = tv["params"]["gate"]
    _, ts = train.apply(tv, x, mutable=["intermediates"])
    _, ss = serve.apply(sv, x, mutable=["intermediates"])
    np.testing.assert_allclose(
        float(ts["intermediates"]["aux_loss"][0]),
        float(ss["intermediates"]["aux_loss"][0]),
        rtol=1e-6,
    )
