"""Control-plane tests: membership, late binding, pipelined serving,
fault injection (crash + hang), exactly-once under re-dispatch.

This is the test coverage the reference never had for its headline feature
(SURVEY.md §2.7, §4): kill one stage worker mid-stream and assert recovery
with no lost or duplicated requests.
"""

import dataclasses
import time

import flax.linen as nn
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from adapt_tpu.config import FaultConfig, ServeConfig
from adapt_tpu.control import WorkerRegistry
from adapt_tpu.control.dispatcher import RequestFailed
from adapt_tpu.graph import INPUT, LayerGraph, partition
from adapt_tpu.graph.ir import Lambda
from adapt_tpu.runtime import LocalPipeline, ServingPipeline
from adapt_tpu.utils.metrics import global_metrics


def chain_graph(width=8, depth=4):
    g = LayerGraph("chain")
    prev = INPUT
    for i in range(depth):
        prev = g.add(f"dense{i}", nn.Dense(width), prev)
    g.add("head", Lambda(lambda x: x * 2.0, "double"), prev)
    return g


@pytest.fixture
def small_model(rng):
    g = chain_graph()
    x = jnp.ones((2, 8))
    variables = g.init(rng, x)
    plan = partition(g, ["dense0", "dense2"])  # 3 stages
    return g, variables, plan, x


FAST_FAULT = FaultConfig(
    lease_ttl_s=0.4,
    heartbeat_s=0.1,
    task_deadline_s=1.0,
    watchdog_period_s=0.05,
    startup_wait_s=2.0,
)


# -- registry ---------------------------------------------------------------


def test_registry_lease_expiry():
    reg = WorkerRegistry(default_ttl_s=0.2, reap_period_s=0.02).start()
    events = []
    reg.watch(lambda ev, w: events.append((ev, w)))
    reg.register("w0")
    assert reg.alive() == ["w0"]
    # Heartbeats keep it alive past one TTL.
    for _ in range(5):
        time.sleep(0.05)
        assert reg.heartbeat("w0")
    assert reg.alive() == ["w0"]
    # Stop heartbeating -> reaped.
    time.sleep(0.4)
    assert reg.alive() == []
    assert not reg.heartbeat("w0")  # expired lease cannot renew
    assert ("leave", "w0") in events
    reg.stop()


def test_registry_bounded_startup_wait():
    reg = WorkerRegistry().start()
    t0 = time.monotonic()
    assert not reg.wait_for_workers(1, timeout_s=0.3)
    assert 0.25 < time.monotonic() - t0 < 1.0
    reg.stop()


# -- serving happy path -----------------------------------------------------


def test_local_pipeline_matches_model(small_model, devices):
    g, variables, plan, x = small_model
    pipe = LocalPipeline(plan, variables, devices[:3])
    y = pipe.infer(x)
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(g.apply(variables, x)), rtol=1e-6
    )


def test_local_pipeline_stream_order(small_model, devices):
    g, variables, plan, _ = small_model
    pipe = LocalPipeline(plan, variables, devices[:3])
    inputs = [jnp.full((2, 8), float(i)) for i in range(12)]
    outputs = pipe.stream(inputs)
    assert len(outputs) == 12
    for x, y in zip(inputs, outputs):
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(g.apply(variables, x)), rtol=1e-5
        )


def test_serving_pipeline_basic(small_model, devices):
    g, variables, plan, x = small_model
    global_metrics().reset()
    cfg = ServeConfig(fault=FAST_FAULT)
    with ServingPipeline(plan, variables, devices[:4], cfg) as pipe:
        y = pipe.infer(x)
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(g.apply(variables, x)), rtol=1e-6
        )
        outs = pipe.stream([x] * 8)
        assert len(outs) == 8


def test_no_workers_clean_shutdown(small_model):
    _, variables, plan, _ = small_model
    cfg = ServeConfig(fault=FaultConfig(startup_wait_s=0.3))
    pipe = ServingPipeline(plan, variables, devices=[], config=cfg)
    # No devices -> no workers ever register -> bounded-wait abort
    # (reference behavior at src/dispatcher.py:290-295).
    with pytest.raises(RequestFailed, match="no workers"):
        pipe.start()


# -- fault injection --------------------------------------------------------


def test_crash_recovery_no_lost_requests(small_model, devices):
    """Kill a worker mid-stream (crash: heartbeats stop). All requests must
    still complete with correct values — membership eviction triggers
    immediate re-dispatch of its in-flight tasks."""
    g, variables, plan, _ = small_model
    global_metrics().reset()
    cfg = ServeConfig(max_inflight=4, fault=FAST_FAULT)
    pipe = ServingPipeline(plan, variables, devices[:4], cfg)
    with pipe:
        inputs = [jnp.full((2, 8), float(i)) for i in range(20)]
        futures = []
        for i, x in enumerate(inputs):
            futures.append(pipe.dispatcher.submit(x))
            if i == 5:
                pipe.kill_worker(0, mode="crash")
        results = [f.result(30.0) for f in futures]
    for x, y in zip(inputs, results):
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(g.apply(variables, x)), rtol=1e-5
        )


def test_hang_recovery_via_watchdog(small_model, devices):
    """Hung worker keeps heartbeating — only the task-deadline watchdog can
    recover (the reference's _task_watchdog scenario)."""
    g, variables, plan, x = small_model
    global_metrics().reset()
    cfg = ServeConfig(max_inflight=2, fault=FAST_FAULT)
    pipe = ServingPipeline(plan, variables, devices[:3], cfg)
    with pipe:
        # Prime all workers with configs so the hung worker is a candidate.
        pipe.infer(x)
        pipe.kill_worker(1, mode="hang")
        t0 = time.monotonic()
        outs = pipe.stream([x] * 6, timeout_per_request=30.0)
        elapsed = time.monotonic() - t0
    assert len(outs) == 6
    m = global_metrics().snapshot()["counters"]
    # If the hung worker ever swallowed a task, the watchdog must have fired.
    # (It may have been idle-skipped; either way all requests completed.)
    assert m.get("dispatcher.completed", 0) >= 6
    assert elapsed < 25.0


def test_all_workers_dead_fails_requests(small_model, devices):
    _, variables, plan, x = small_model
    cfg = ServeConfig(
        fault=FaultConfig(
            lease_ttl_s=0.3,
            heartbeat_s=0.1,
            task_deadline_s=0.5,
            watchdog_period_s=0.05,
            startup_wait_s=1.0,
            max_retries=2,
        )
    )
    pipe = ServingPipeline(plan, variables, devices[:2], cfg)
    with pipe:
        pipe.infer(x)  # healthy first
        for w in pipe.workers:
            w.kill("crash")
        time.sleep(0.5)  # let leases expire
        with pytest.raises(RequestFailed):
            pipe.dispatcher.submit(x).result(10.0)


def test_exactly_once_under_redispatch(small_model, devices):
    """A late result from a presumed-dead attempt must not complete the
    future twice nor corrupt a newer attempt (SURVEY §7.4 exactly-once)."""
    g, variables, plan, x = small_model
    global_metrics().reset()
    cfg = ServeConfig(max_inflight=8, fault=FAST_FAULT)
    pipe = ServingPipeline(plan, variables, devices[:4], cfg)
    with pipe:
        # Hang one worker, push load through, then assert completions ==
        # submissions exactly.
        pipe.infer(x)
        pipe.kill_worker(2, mode="hang")
        outs = pipe.stream([x] * 10, timeout_per_request=30.0)
        assert len(outs) == 10
    m = global_metrics().snapshot()["counters"]
    assert m.get("dispatcher.completed", 0) == 11  # 1 warmup + 10
    assert m.get("dispatcher.failed", 0) == 0


def test_stream_surfaces_stage_error(small_model, devices):
    """A failing stage must raise, not hang the stream (regression)."""
    g, variables, plan, x = small_model
    pipe = LocalPipeline(plan, variables, devices[:3])
    bad = jnp.ones((2, 5))  # wrong feature dim
    with pytest.raises(RuntimeError, match="stage 0 failed"):
        pipe.stream([bad])


def test_single_error_budget_allows_retries(small_model, devices):
    """With max_retries=1 a single transient error must still get one
    re-dispatch (regression: double-counted retry budget)."""
    _, variables, plan, x = small_model
    cfg = ServeConfig(
        fault=FaultConfig(
            lease_ttl_s=0.4,
            heartbeat_s=0.1,
            task_deadline_s=1.0,
            watchdog_period_s=0.05,
            startup_wait_s=2.0,
            max_retries=1,
        )
    )
    pipe = ServingPipeline(plan, variables, devices[:2], cfg)
    with pipe:
        pipe.infer(x)
        # Inject one transient failure: unconfigure stage 0 on one worker by
        # submitting a malformed payload through worker 0 directly is messy;
        # instead kill worker 0 with 'hang' and verify a request that lands
        # there still completes within a single retry.
        pipe.kill_worker(0, mode="hang")
        outs = pipe.stream([x] * 4, timeout_per_request=30.0)
        assert len(outs) == 4


def test_shutdown_fails_pending_futures(small_model, devices):
    _, variables, plan, x = small_model
    cfg = ServeConfig(fault=FAST_FAULT)
    pipe = ServingPipeline(plan, variables, devices[:3], cfg)
    pipe.start()
    pipe.infer(x)
    for w in pipe.workers:
        w.kill("hang")  # requests will never complete
    f = pipe.dispatcher.submit(x)
    pipe.shutdown()
    t0 = time.monotonic()
    with pytest.raises(RequestFailed, match="shut down|retries|no live"):
        f.result(10.0)
    assert time.monotonic() - t0 < 5.0  # prompt failure, not timeout sleep


def test_stream_error_no_thread_leak(small_model, devices):
    """After a failed stream, no stage/feeder threads may linger blocked
    (regression: leaked producers on the error path)."""
    import threading as _threading

    g, variables, plan, x = small_model
    pipe = LocalPipeline(plan, variables, devices[:3])
    before = _threading.active_count()
    bad_inputs = [x] * 2 + [jnp.ones((2, 5))] + [x] * 50
    with pytest.raises(RuntimeError, match="failed during stream"):
        pipe.stream(bad_inputs)
    time.sleep(0.5)
    assert _threading.active_count() <= before + 1


def test_throughput_empty_inputs(small_model, devices):
    _, variables, plan, _ = small_model
    pipe = LocalPipeline(plan, variables, devices[:3])
    outs, dt = pipe.throughput([])
    assert outs == [] and dt >= 0


def test_hung_worker_still_scheduled_and_recovered(small_model, devices):
    """A hung worker stays schedulable (it heartbeats like a healthy one);
    a request routed to it must be recovered by the deadline watchdog —
    the true _task_watchdog path. Deterministic force-route: only the
    victim is configured for any stage, so configured-first rank must pick
    it for the first dispatch; canary probes are disabled so recovery can
    only come from the real-task deadline."""
    g, variables, plan, x = small_model
    global_metrics().reset()
    fault = dataclasses.replace(FAST_FAULT, probe_silence_s=600.0)
    cfg = ServeConfig(max_inflight=2, fault=fault)
    pipe = ServingPipeline(plan, variables, devices[:2], cfg)
    with pipe:
        victim = pipe.workers[0]
        for s in range(plan.num_stages):
            pipe.dispatcher._configure_with_timeout(victim, s)
        pipe.kill_worker(0, mode="hang")
        from adapt_tpu.control.worker import WorkerState

        assert victim.state is not WorkerState.DEAD
        outs = pipe.stream([x] * 4, timeout_per_request=30.0)
        assert len(outs) == 4
        for y in outs:
            np.testing.assert_allclose(
                np.asarray(y), np.asarray(g.apply(variables, x)), rtol=1e-5
            )
    m = global_metrics().snapshot()["counters"]
    # The hung worker swallowed at least one task -> watchdog re-dispatched.
    assert m.get("dispatcher.redispatched", 0) >= 1


# -- prewarm (precompiled re-shard plans) -----------------------------------


def test_warmup_prewarms_all_stage_device_pairs(small_model, devices):
    g, variables, plan, x = small_model
    before = global_metrics().counter("dispatcher.prewarmed")
    with ServingPipeline(
        plan, variables, devices=devices[:4], config=ServeConfig(fault=FAST_FAULT)
    ) as pipe:
        pipe.warmup(x)
        prewarmed = global_metrics().counter("dispatcher.prewarmed") - before
        # 3 stages x 4 devices = 12 pairs, minus pairs already compiled by
        # the warmup request itself (those were seeded before prewarm ran,
        # but still counted only if prewarm executed them).
        assert prewarmed >= 3 * 4 - 3
        # The real no-recompile evidence: failover re-binds must be jit
        # cache hits — the per-stage cache must not grow when a kill
        # forces stages onto new devices.
        sizes = [fn._cache_size() for fn in pipe.dispatcher._stage_fns]
        pipe.kill_worker(0)
        y = pipe.infer(x, timeout=10.0)
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(g.apply(variables, x)), rtol=1e-5
        )
        assert [
            fn._cache_size() for fn in pipe.dispatcher._stage_fns
        ] == sizes, "recovery triggered an XLA recompile despite prewarm"


def test_local_pipeline_hop_transform(small_model, devices):
    g, variables, plan, x = small_model
    calls = []

    def hop(a, stage_index):
        calls.append(stage_index)
        return np.asarray(a)  # host round-trip, like a codec would

    pipe = LocalPipeline(plan, variables, devices=devices[:3], hop_transform=hop)
    y = pipe.infer(x)
    assert calls == [0, 1, 2]
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(g.apply(variables, x)), rtol=1e-5
    )
    calls.clear()
    outs = pipe.stream([x, x])
    assert len(outs) == 2 and sorted(calls) == [0, 0, 1, 1, 2, 2]


def test_hung_worker_quarantined_after_strikes(small_model, devices):
    """A hang (heartbeats alive, swallows tasks) must be quarantined after
    `quarantine_strikes` missed deadlines — later requests never touch it."""
    g, variables, plan, x = small_model
    config = ServeConfig(
        fault=FaultConfig(
            lease_ttl_s=5.0,  # leases never expire: only deadlines catch it
            heartbeat_s=0.1,
            task_deadline_s=0.5,
            watchdog_period_s=0.05,
            startup_wait_s=2.0,
            max_retries=4,
            quarantine_strikes=2,
        )
    )
    with ServingPipeline(
        plan, variables, devices=devices[:3], config=config
    ) as pipe:
        pipe.warmup(x)
        victim = pipe.workers[0]
        victim.kill("hang")
        # Serving continues throughout; strikes accrue against the hung
        # worker from real-task deadline misses and — deterministically,
        # even when rank routes all real traffic away from it — from the
        # watchdog's canary probes, until quarantine.
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            y = pipe.infer(x, timeout=30.0)
            with pipe.dispatcher._health_lock:
                if victim.worker_id in pipe.dispatcher._quarantined:
                    break
        np.testing.assert_allclose(
            np.asarray(y), np.asarray(g.apply(variables, x)), rtol=1e-5
        )
        assert victim.worker_id in pipe.dispatcher._quarantined
        assert (
            global_metrics().counter("dispatcher.quarantined") >= 1
        )
        # Quarantined worker is skipped while healthy workers exist.
        w = pipe.dispatcher._acquire(0, exclude=set())
        assert w.worker_id != victim.worker_id
        # Self-healing: once the hang clears, the queued/next canary probe
        # is answered and the quarantine lifts without operator action.
        victim.revive()
        deadline = time.monotonic() + 20.0
        while time.monotonic() < deadline:
            with pipe.dispatcher._health_lock:
                if victim.worker_id not in pipe.dispatcher._quarantined:
                    break
            time.sleep(0.05)
        with pipe.dispatcher._health_lock:
            assert victim.worker_id not in pipe.dispatcher._quarantined


def test_timed_out_configure_cannot_install_late_binding(rng, devices):
    """A configure that exceeds the handshake timeout is *cancelled*, not
    just abandoned: when the slow transfer finally completes, the abort
    token blocks the install, so the worker neither pins stage weights in
    device memory nor reports is_configured for a binding the dispatcher
    gave up on."""
    import threading

    from adapt_tpu.control.dispatcher import Dispatcher
    from adapt_tpu.control.worker import StageWorker

    g = LayerGraph("slowcfg")
    g.add("dense0", nn.Dense(4), INPUT)
    x = jnp.ones((1, 4))
    variables = g.init(rng, x)
    plan = partition(g, [])

    config = ServeConfig(
        fault=FaultConfig(
            configure_timeout_s=0.3,
            startup_wait_s=5.0,
        )
    )
    disp = Dispatcher(plan, variables, config=config)

    release = threading.Event()

    class SlowWorker(StageWorker):
        def configure(self, stage_index, fn, host_variables, spec=None, abort=None):
            release.wait(5.0)  # simulate a weight transfer >> timeout
            super().configure(
                stage_index, fn, host_variables, spec=spec, abort=abort
            )

    w = SlowWorker(
        worker_id="slow-0",
        device=devices[0],
        registry=disp.registry,
        result_queue=disp.result_queue,
        fault=config.fault,
    )
    disp.attach_worker(w)
    disp.start()
    try:
        with pytest.raises(RequestFailed, match="timed out|no worker"):
            disp.infer(x, timeout=5.0)
        # Let the abandoned configure thread finish its slow transfer...
        release.set()
        time.sleep(0.5)
        # ...and assert it did NOT install the binding afterwards.
        assert not w.is_configured(0)
    finally:
        disp.shutdown()


def test_registry_lease_tokens_protect_replacement():
    """A stale holder's deregister must not evict a replacement that
    re-registered the same worker id (etcd lease-id semantics): the
    ownership token decides, under the same lock that deletes."""
    reg = WorkerRegistry(default_ttl_s=5.0)
    old_token = reg.register("w", ttl_s=5.0)
    new_token = reg.register("w", ttl_s=5.0)  # replacement takes the id
    assert new_token != old_token
    reg.deregister("w", token=old_token)  # stale holder dies late
    assert "w" in reg.alive(), "stale deregister evicted the replacement"
    reg.deregister("w", token=new_token)  # owner may always deregister
    assert "w" not in reg.alive()
    # Tokenless deregister stays unconditional (in-process workers).
    reg.register("w2")
    reg.deregister("w2")
    assert "w2" not in reg.alive()


def test_unconfigure_generation_scoped(rng, devices):
    """A revoke is scoped to the configure that earned it: undoing an
    abandoned handshake must not drop a newer configure's binding."""
    import queue as queue_mod

    from adapt_tpu.control.worker import StageWorker

    reg = WorkerRegistry()
    w = StageWorker(
        worker_id="w0",
        device=devices[0],
        registry=reg,
        result_queue=queue_mod.Queue(),
    )
    g = LayerGraph("ucfg")
    g.add("dense0", nn.Dense(4), INPUT)
    variables = g.init(rng, jnp.ones((1, 4)))
    plan = partition(g, [])
    fn = plan.stage_apply(plan.stages[0])

    gen1 = w.configure(0, fn, variables)
    gen2 = w.configure(0, fn, variables)  # newer configure, same stage
    assert gen2 > gen1
    w.unconfigure(0, gen1)  # stale revoke: must be a no-op
    assert w.is_configured(0)
    w.unconfigure(0, gen2)  # owning revoke: drops the binding
    assert not w.is_configured(0)
    # Unconditional revoke works regardless of generation.
    gen3 = w.configure(0, fn, variables)
    assert gen3 > gen2
    w.unconfigure(0)
    assert not w.is_configured(0)


def test_local_pipeline_from_config_codec_hop(small_model, devices):
    """ServeConfig.codec drives LocalPipeline hops: with a lossy int8
    codec the pipeline output differs from exact but stays within
    quantization error; with 'none' there is no transform at all."""
    from adapt_tpu.config import CodecConfig
    from adapt_tpu.runtime.pipeline import LocalPipeline

    g, variables, plan, x = small_model
    exact = np.asarray(g.apply(variables, x))

    cfg = ServeConfig(codec=CodecConfig(name="int8"))
    pipe = LocalPipeline.from_config(plan, variables, devices[:3], cfg)
    assert pipe.hop_transform is not None
    y = np.asarray(pipe.infer(x))
    assert np.max(np.abs(y - exact)) < 0.1 * max(1.0, np.max(np.abs(exact)))

    pipe_none = LocalPipeline.from_config(
        plan, variables, devices[:3], ServeConfig()
    )
    assert pipe_none.hop_transform is None
    np.testing.assert_allclose(
        np.asarray(pipe_none.infer(x)), exact, rtol=1e-6
    )


def test_crash_eviction_is_event_driven_hang_is_not(devices):
    """A crashed worker's exec loop deregisters it IMMEDIATELY (the
    reference evicts on socket error, not timeout, dispatcher.py:153-161)
    — the lease TTL is only the backstop for event-less deaths. A hung
    worker keeps heartbeating and MUST keep its lease: only the task
    watchdog may call that failure."""
    import queue as _queue
    import time as _time

    from adapt_tpu.config import FaultConfig
    from adapt_tpu.control.registry import WorkerRegistry
    from adapt_tpu.control.worker import StageWorker

    # TTL deliberately huge: any eviction within the assert window must
    # have come from the crash event, not expiry.
    fault = FaultConfig(lease_ttl_s=60.0, heartbeat_s=0.05)
    registry = WorkerRegistry(default_ttl_s=60.0)
    rq: "_queue.Queue" = _queue.Queue()
    crash_w = StageWorker("ev-crash", devices[0], registry, rq, fault).start()
    hang_w = StageWorker("ev-hang", devices[1], registry, rq, fault).start()
    try:
        assert set(registry.alive()) >= {"ev-crash", "ev-hang"}
        t0 = _time.monotonic()
        crash_w.kill("crash")
        hang_w.kill("hang")
        while "ev-crash" in registry.alive():
            assert _time.monotonic() - t0 < 2.0, (
                "crash eviction waited on something other than the event"
            )
            _time.sleep(0.005)
        detect_s = _time.monotonic() - t0
        assert detect_s < 1.0, f"event-driven eviction took {detect_s:.2f}s"
        _time.sleep(0.2)
        assert "ev-hang" in registry.alive(), (
            "a hang must not be evicted from membership (it heartbeats; "
            "only the watchdog may catch it)"
        )
    finally:
        hang_w.stop()
        crash_w.stop()


# -- dispatcher crash recovery (journal) -------------------------------------


def test_dispatcher_crash_recovery_exactly_once(tmp_path):
    """Kill the dispatcher mid-stream (hard_stop = SIGKILL's leftovers):
    a NEW dispatcher recovered from the journal re-adopts the still-
    running worker processes and completes every accepted request exactly
    once — requests done before the crash are not replayed, requests in
    flight complete with correct outputs, and the journal drains to
    empty. The reference's etcd-outlives-the-dispatcher property
    (``src/start_etcd.sh:81-94``) rebuilt as a WAL."""
    from conftest import spawn_worker_proc

    from adapt_tpu.comm.remote import RemoteWorkerProxy
    from adapt_tpu.control.dispatcher import Dispatcher
    from adapt_tpu.control.journal import DispatcherJournal
    from adapt_tpu.models.vit import vit_tiny

    g = vit_tiny()
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    variables = g.init(jax.random.PRNGKey(0), x)
    cuts = ["encoder_block_1"]
    plan = partition(g, cuts)
    y_ref = np.asarray(g.apply(variables, x))
    cfg = ServeConfig(
        fault=FaultConfig(
            lease_ttl_s=2.0,
            heartbeat_s=0.2,
            task_deadline_s=60.0,
            watchdog_period_s=0.5,
            startup_wait_s=15.0,
            configure_timeout_s=120.0,
        )
    )
    model_config = {
        "model": "vit_tiny",
        "num_classes": 10,
        "cuts": cuts,
        "input_shape": [2, 32, 32, 3],
    }
    ports = [17681, 17682]
    procs = [
        spawn_worker_proc("--port", str(p), "--heartbeat", "0.2")
        for p in ports
    ]
    root = str(tmp_path / "journal")
    disp_b = None
    try:
        journal = DispatcherJournal(root)
        disp = Dispatcher(plan, variables, config=cfg, journal=journal)
        for i, p in enumerate(ports):
            disp.attach_worker(
                RemoteWorkerProxy(
                    f"jw-{i}",
                    ("127.0.0.1", p),
                    disp.registry,
                    disp.result_queue,
                    model_config=model_config,
                    fault=cfg.fault,
                )
            )
        disp.start()
        disp.warmup(x)
        futures = [disp.submit(x) for _ in range(6)]
        # Let at least one complete (its done mark lands), then crash
        # with whatever remains in flight.
        np.testing.assert_allclose(
            np.asarray(futures[0].result(60.0)), y_ref, rtol=1e-5, atol=1e-5
        )
        disp.hard_stop()
        # Two requests whose dispatch raced the crash: journaled as
        # accepted, never dispatched — guarantees the recovery set is
        # non-empty regardless of how fast the pool drained the six.
        all_ids = {f.request_id for f in futures}
        raced = [max(all_ids) + 1, max(all_ids) + 2]
        for rid in raced:
            journal.record_submit(rid, np.asarray(x))
        journal.close()

        # The journal, not a racy in-process snapshot, defines what must
        # replay (completion and its done mark are NOT atomic — the
        # documented at-least-once window).
        _, pending_at_crash, _ = DispatcherJournal(root).load()
        assert set(raced) <= set(pending_at_crash)
        assert set(pending_at_crash) <= (all_ids | set(raced))

        disp_b, recovered = Dispatcher.recover(
            plan, variables, DispatcherJournal(root), config=cfg
        )
        # Re-adoption: the SAME worker processes serve the new dispatcher.
        assert {"jw-0", "jw-1"} <= set(disp_b.registry.alive())
        # Replay covers exactly the journal's pending set.
        assert set(recovered) == set(pending_at_crash)
        for rid, fut in recovered.items():
            np.testing.assert_allclose(
                np.asarray(fut.result(120.0)), y_ref, rtol=1e-5, atol=1e-5
            )
        # Exactly-once, durably: nothing left to replay.
        _, pending_after, _ = DispatcherJournal(root).load()
        assert pending_after == {}
        # The recovered dispatcher serves new traffic with fresh ids.
        fut = disp_b.submit(x)
        assert fut.request_id > max(all_ids)
        np.testing.assert_allclose(
            np.asarray(fut.result(60.0)), y_ref, rtol=1e-5, atol=1e-5
        )
    finally:
        if disp_b is not None:
            disp_b.shutdown()
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait(timeout=10)


def test_journal_composes_with_chain_forwarding(tmp_path):
    """Journal on + chain forwarding on: chain-dispatched requests are
    journaled like any other, and after the dispatcher completes them —
    including any that replayed through the hub when the chain broke —
    the journal has nothing pending."""
    from conftest import chain_cfg, chain_pool

    from adapt_tpu.control.dispatcher import Dispatcher
    from adapt_tpu.control.journal import DispatcherJournal
    from adapt_tpu.models.vit import vit_block_cuts, vit_tiny

    g = vit_tiny()
    x = jnp.ones((2, 32, 32, 3), jnp.float32)
    variables = g.init(jax.random.PRNGKey(0), x)
    cuts = vit_block_cuts(4, 3)
    plan = partition(g, cuts)
    y_ref = np.asarray(g.apply(variables, x))
    cfg = chain_cfg(configure_timeout_s=120.0)
    root = str(tmp_path / "cj")
    disp = Dispatcher(
        plan, variables, config=cfg, journal=DispatcherJournal(root)
    )
    procs, proxies = chain_pool(
        disp, cfg, cuts, [17685, 17686, 17687], prefix="jc"
    )
    try:
        disp.start()
        for pr in proxies:
            pr.start()
        disp.setup_chain([pr.worker_id for pr in proxies])
        futures = [disp.submit(x) for _ in range(6)]
        proxies[1].kill("crash")  # mid-chain death while journaled work flies
        for f in futures:
            np.testing.assert_allclose(
                np.asarray(f.result(180.0)), y_ref, rtol=1e-5, atol=1e-5
            )
        # The break really happened: the mid worker's death (link drop ->
        # membership leave) disabled the chain even if every request had
        # already drained — otherwise this test silently covers only the
        # no-failure path.
        deadline = time.monotonic() + 10.0
        while disp._chain is not None:
            assert time.monotonic() < deadline, "chain never disabled"
            time.sleep(0.05)
    finally:
        disp.shutdown()
        for p in procs:
            p.terminate()
        for p in procs:
            p.wait(timeout=10)
    _, pending, _ = DispatcherJournal(root).load()
    assert pending == {}  # every journaled request reached a done mark


def test_journal_compaction_bounds_history(tmp_path):
    """The WAL rewrites itself to live state every compact_every appends:
    size is bounded by pending work + pool size, not all-time history,
    and the id horizon survives compaction without falsely completing a
    still-pending request."""
    from adapt_tpu.control.journal import DispatcherJournal

    import os

    root = str(tmp_path / "j")
    j = DispatcherJournal(root, compact_every=20)
    j.record_worker("w0", "127.0.0.1", 1234, meta={"codec": "none"})
    for rid in range(300):
        j.record_submit(rid, np.zeros((2, 2), np.float32))
        if rid != 150:  # one request stays pending across compactions
            j.record_done(rid)
    j.close()
    with open(root + "/wal.jsonl", encoding="utf-8") as f:
        n_lines = sum(1 for _ in f)
    assert n_lines < 30  # ~600 appends compacted away
    # Payload reclaim (group-commit + compaction sweep) bounds disk too:
    # the pending payload survives, done payloads don't accumulate.
    payloads = [n for n in os.listdir(root) if n.startswith("req_")]
    assert "req_150.npy" in payloads
    assert len(payloads) < 100
    workers, pending, next_id = DispatcherJournal(root).load()
    assert set(workers) == {"w0"}
    assert workers["w0"]["port"] == 1234
    assert set(pending) == {150}
    assert next_id == 300
    # A dispatcher built OVER this journal must not recycle ids 0..299
    # (a fresh counter would clear pending id 150 with its done marks).
    assert DispatcherJournal(root).next_request_id == 300
